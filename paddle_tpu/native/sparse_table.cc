// Native sparse embedding table (host KV) — C++ core of
// paddle_tpu.distributed.ps.MemorySparseTable.
//
// TPU-native counterpart of the reference PS table runtime
// (reference: paddle/fluid/distributed/ps/table/memory_sparse_table.h:39
// hash-grown rows; ps/table/sparse_sgd_rule.cc server-side optimizer
// rules). The reference runs this inside brpc PS server processes; on
// TPU hosts it runs in-process beside the device runtime, feeding
// batched pulls to HBM. Exposed as a plain C ABI for ctypes (no
// pybind11 in the image).
//
// Concurrency: a shared mutex around the id->row map; pull/push copy
// row data outside Python (callers pass numpy buffers), so the GIL is
// released for the whole operation.

#include <cstdint>
#include <cstring>
#include <cmath>
#include <mutex>
#include <random>
#include <unordered_map>
#include <vector>

namespace {

struct Table {
  int64_t dim;
  int rule;          // 0 = sgd, 1 = adagrad
  float lr;
  float init_scale;  // rows init ~ N(0, init_scale)
  float g0;          // adagrad initial accumulator
  float eps;
  std::unordered_map<int64_t, int64_t> rows;
  std::vector<float> data;   // (nrows, dim)
  std::vector<float> slots;  // (nrows, slot_dim)
  std::mt19937_64 rng;
  std::mutex mu;

  int64_t slot_dim() const { return rule == 1 ? 1 : 0; }

  int64_t ensure(int64_t id) {
    auto it = rows.find(id);
    if (it != rows.end()) return it->second;
    int64_t r = static_cast<int64_t>(rows.size());
    rows.emplace(id, r);
    std::normal_distribution<float> nd(0.f, init_scale);
    for (int64_t j = 0; j < dim; ++j) data.push_back(nd(rng));
    for (int64_t j = 0; j < slot_dim(); ++j) slots.push_back(g0);
    return r;
  }
};

}  // namespace

extern "C" {

void* pt_table_create(int64_t dim, int rule, float lr, float init_scale,
                      float g0, float eps, uint64_t seed) {
  auto* t = new Table();
  t->dim = dim;
  t->rule = rule;
  t->lr = lr;
  t->init_scale = init_scale;
  t->g0 = g0;
  t->eps = eps;
  t->rng.seed(seed);
  return t;
}

void pt_table_destroy(void* h) { delete static_cast<Table*>(h); }

int64_t pt_table_size(void* h) {
  auto* t = static_cast<Table*>(h);
  std::lock_guard<std::mutex> g(t->mu);
  return static_cast<int64_t>(t->rows.size());
}

// out: (n, dim) float32, caller-allocated
void pt_table_pull(void* h, const int64_t* ids, int64_t n, float* out) {
  auto* t = static_cast<Table*>(h);
  std::lock_guard<std::mutex> g(t->mu);
  for (int64_t i = 0; i < n; ++i) {
    int64_t r = t->ensure(ids[i]);
    std::memcpy(out + i * t->dim, t->data.data() + r * t->dim,
                sizeof(float) * t->dim);
  }
}

// grads: (n, dim). Duplicate ids are accumulated before ONE rule
// application (reference push-dedup semantics).
void pt_table_push(void* h, const int64_t* ids, int64_t n,
                   const float* grads) {
  auto* t = static_cast<Table*>(h);
  std::lock_guard<std::mutex> g(t->mu);
  std::unordered_map<int64_t, std::vector<float>> acc;
  acc.reserve(n);
  for (int64_t i = 0; i < n; ++i) {
    auto& buf = acc[ids[i]];
    if (buf.empty()) buf.assign(t->dim, 0.f);
    const float* gi = grads + i * t->dim;
    for (int64_t j = 0; j < t->dim; ++j) buf[j] += gi[j];
  }
  for (auto& kv : acc) {
    int64_t r = t->ensure(kv.first);
    float* row = t->data.data() + r * t->dim;
    const float* gacc = kv.second.data();
    if (t->rule == 1) {  // adagrad: per-row mean-squared accumulator
      float g2 = 0.f;
      for (int64_t j = 0; j < t->dim; ++j) g2 += gacc[j] * gacc[j];
      g2 /= static_cast<float>(t->dim);
      float* slot = t->slots.data() + r;  // slot_dim == 1
      *slot += g2;
      float scale = t->lr / (std::sqrt(*slot) + t->eps);
      for (int64_t j = 0; j < t->dim; ++j) row[j] -= scale * gacc[j];
    } else {  // sgd
      for (int64_t j = 0; j < t->dim; ++j) row[j] -= t->lr * gacc[j];
    }
  }
}

// Checkpoint export: ids (size,), data (size*dim), slots (size*slot_dim)
void pt_table_export(void* h, int64_t* ids_out, float* data_out,
                     float* slots_out) {
  auto* t = static_cast<Table*>(h);
  std::lock_guard<std::mutex> g(t->mu);
  for (const auto& kv : t->rows) {
    ids_out[kv.second] = kv.first;
  }
  std::memcpy(data_out, t->data.data(), sizeof(float) * t->data.size());
  if (t->slot_dim() > 0 && !t->slots.empty())
    std::memcpy(slots_out, t->slots.data(),
                sizeof(float) * t->slots.size());
}

void pt_table_import(void* h, const int64_t* ids, int64_t n,
                     const float* data, const float* slots) {
  auto* t = static_cast<Table*>(h);
  std::lock_guard<std::mutex> g(t->mu);
  t->rows.clear();
  t->rows.reserve(n);
  t->data.assign(data, data + n * t->dim);
  if (t->slot_dim() > 0 && slots)
    t->slots.assign(slots, slots + n * t->slot_dim());
  else
    t->slots.clear();
  for (int64_t i = 0; i < n; ++i) t->rows.emplace(ids[i], i);
}

}  // extern "C"
