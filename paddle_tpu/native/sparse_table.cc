// Native sparse embedding table (host KV) — C++ core of
// paddle_tpu.distributed.ps.MemorySparseTable.
//
// TPU-native counterpart of the reference PS table runtime
// (reference: paddle/fluid/distributed/ps/table/memory_sparse_table.h:39
// — SHARD-partitioned hash maps with per-shard locks and a thread pool;
// ps/table/sparse_sgd_rule.cc server-side optimizer rules: naive SGD,
// AdaGrad, Adam; ps/table/ctr_accessor.cc show/click feature management
// with time-decay scoring and eviction via Table::Shrink). The reference
// runs this inside brpc PS server processes; on TPU hosts it runs
// in-process beside the device runtime, feeding batched pulls to HBM.
// Exposed as a plain C ABI for ctypes (no pybind11 in the image).
//
// Concurrency: the id space is split over NB = 64 bucket shards, each
// with its own mutex + hash map + row storage (the reference's
// shard-locked layout). pull/push release the GIL at the ctypes
// boundary; large batches additionally fan out across a std::thread
// pool — pull splits the output range (row writes are disjoint),
// push pre-deduplicates then splits the unique range; every row touch
// takes only its bucket's lock, so concurrent callers on different
// buckets do not serialize.

#include <atomic>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <functional>
#include <mutex>
#include <random>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

constexpr int kBuckets = 64;
constexpr int64_t kMtThreshold = 4096;  // batch size that buys threads

struct Bucket {
  std::mutex mu;
  std::unordered_map<int64_t, int64_t> rows;  // id -> local row index
  std::vector<float> data;    // (n, dim)
  std::vector<float> slots;   // (n, slot_dim)
  std::vector<float> meta;    // (n, 3): show, click, unseen  (accessor)
  std::vector<int64_t> ids;   // (n,) reverse map for export/shrink
};

struct Table {
  int64_t dim;
  int rule;          // 0 = sgd, 1 = adagrad, 2 = adam
  float lr;
  float init_scale;  // rows init ~ N(0, init_scale)
  float g0;          // adagrad initial accumulator
  float eps;
  float beta1, beta2;  // adam
  int accessor;        // 1 = CTR show/click meta tracked per row
  uint64_t seed;
  Bucket buckets[kBuckets];

  int64_t slot_dim() const {
    if (rule == 1) return 1;
    if (rule == 2) return 2 * dim + 1;  // m[dim], v[dim], t
    return 0;
  }

  static int bucket_of(int64_t id) {
    // golden-ratio mix: consecutive ids spread across buckets
    uint64_t h = static_cast<uint64_t>(id) * 0x9e3779b97f4a7c15ull;
    return static_cast<int>(h >> 58) & (kBuckets - 1);
  }

  // caller holds b.mu
  int64_t ensure(Bucket& b, int64_t id) {
    auto it = b.rows.find(id);
    if (it != b.rows.end()) return it->second;
    int64_t r = static_cast<int64_t>(b.rows.size());
    b.rows.emplace(id, r);
    b.ids.push_back(id);
    // per-id deterministic init (seed ^ id): identical across shard
    // counts and insertion orders, like the python id-aware initializer
    std::mt19937_64 rng(seed ^ static_cast<uint64_t>(id));
    std::normal_distribution<float> nd(0.f, init_scale);
    for (int64_t j = 0; j < dim; ++j) b.data.push_back(nd(rng));
    int64_t sd = slot_dim();
    for (int64_t j = 0; j < sd; ++j) b.slots.push_back(rule == 1 ? g0 : 0.f);
    if (accessor) {
      b.meta.push_back(0.f);  // show
      b.meta.push_back(0.f);  // click
      b.meta.push_back(0.f);  // unseen rounds
    }
    return r;
  }

  // caller holds b.mu; applies ONE accumulated gradient to one row
  void apply(Bucket& b, int64_t r, const float* gacc) {
    float* row = b.data.data() + r * dim;
    if (rule == 2) {  // adam (reference SparseAdamSGDRule)
      float* m = b.slots.data() + r * slot_dim();
      float* v = m + dim;
      float* t = v + dim;
      *t += 1.f;
      float b1t = 1.f - std::pow(beta1, *t);
      float b2t = 1.f - std::pow(beta2, *t);
      for (int64_t j = 0; j < dim; ++j) {
        m[j] = beta1 * m[j] + (1.f - beta1) * gacc[j];
        v[j] = beta2 * v[j] + (1.f - beta2) * gacc[j] * gacc[j];
        row[j] -= lr * (m[j] / b1t) / (std::sqrt(v[j] / b2t) + eps);
      }
    } else if (rule == 1) {  // adagrad: per-row mean-squared accumulator
      float g2 = 0.f;
      for (int64_t j = 0; j < dim; ++j) g2 += gacc[j] * gacc[j];
      g2 /= static_cast<float>(dim);
      float* slot = b.slots.data() + r * 1;
      *slot += g2;
      float scale = lr / (std::sqrt(*slot) + eps);
      for (int64_t j = 0; j < dim; ++j) row[j] -= scale * gacc[j];
    } else {  // sgd
      for (int64_t j = 0; j < dim; ++j) row[j] -= lr * gacc[j];
    }
    if (accessor) b.meta[r * 3 + 2] = 0.f;  // touched: reset unseen
  }
};

void parallel_for(int64_t n, int64_t grain,
                  const std::function<void(int64_t, int64_t)>& fn) {
  unsigned hw = std::thread::hardware_concurrency();
  int64_t nt = static_cast<int64_t>(hw ? (hw > 8 ? 8 : hw) : 1);
  if (n < grain || nt <= 1) {
    fn(0, n);
    return;
  }
  if (nt > n) nt = n;
  std::vector<std::thread> ts;
  int64_t chunk = (n + nt - 1) / nt;
  for (int64_t t = 0; t < nt; ++t) {
    int64_t lo = t * chunk, hi = std::min(n, lo + chunk);
    if (lo >= hi) break;
    ts.emplace_back([&fn, lo, hi] { fn(lo, hi); });
  }
  for (auto& th : ts) th.join();
}

}  // namespace

extern "C" {

void* pt_table_create(int64_t dim, int rule, float lr, float init_scale,
                      float g0, float eps, float beta1, float beta2,
                      int accessor, uint64_t seed) {
  auto* t = new Table();
  t->dim = dim;
  t->rule = rule;
  t->lr = lr;
  t->init_scale = init_scale;
  t->g0 = g0;
  t->eps = eps;
  t->beta1 = beta1;
  t->beta2 = beta2;
  t->accessor = accessor;
  t->seed = seed;
  return t;
}

void pt_table_destroy(void* h) { delete static_cast<Table*>(h); }

int64_t pt_table_size(void* h) {
  auto* t = static_cast<Table*>(h);
  int64_t n = 0;
  for (auto& b : t->buckets) {
    std::lock_guard<std::mutex> g(b.mu);
    n += static_cast<int64_t>(b.rows.size());
  }
  return n;
}

// out: (n, dim) float32, caller-allocated. Threaded over the id range —
// each out row is written by exactly one index; row creation/read takes
// the row's bucket lock only.
void pt_table_pull(void* h, const int64_t* ids, int64_t n, float* out) {
  auto* t = static_cast<Table*>(h);
  parallel_for(n, kMtThreshold, [&](int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; ++i) {
      Bucket& b = t->buckets[Table::bucket_of(ids[i])];
      std::lock_guard<std::mutex> g(b.mu);
      int64_t r = t->ensure(b, ids[i]);
      std::memcpy(out + i * t->dim, b.data.data() + r * t->dim,
                  sizeof(float) * t->dim);
      if (t->accessor) b.meta[r * 3 + 2] = 0.f;
    }
  });
}

// grads: (n, dim). Duplicate ids are accumulated before ONE rule
// application (reference push-dedup semantics); the unique set is then
// applied in parallel under bucket locks.
void pt_table_push(void* h, const int64_t* ids, int64_t n,
                   const float* grads) {
  auto* t = static_cast<Table*>(h);
  std::unordered_map<int64_t, int64_t> first;  // id -> slot in acc
  first.reserve(n);
  std::vector<int64_t> uniq;
  std::vector<float> acc;
  for (int64_t i = 0; i < n; ++i) {
    auto ins = first.emplace(ids[i], static_cast<int64_t>(uniq.size()));
    const float* gi = grads + i * t->dim;
    if (ins.second) {
      uniq.push_back(ids[i]);
      acc.insert(acc.end(), gi, gi + t->dim);
    } else {
      float* buf = acc.data() + ins.first->second * t->dim;
      for (int64_t j = 0; j < t->dim; ++j) buf[j] += gi[j];
    }
  }
  int64_t u = static_cast<int64_t>(uniq.size());
  parallel_for(u, kMtThreshold, [&](int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; ++i) {
      Bucket& b = t->buckets[Table::bucket_of(uniq[i])];
      std::lock_guard<std::mutex> g(b.mu);
      int64_t r = t->ensure(b, uniq[i]);
      t->apply(b, r, acc.data() + i * t->dim);
    }
  });
}

// --- CTR accessor (reference ctr_accessor.cc) ------------------------

// shows/clicks: (n,) float32 event counts for each id (a batch's label
// statistics). Creates rows on first touch, resets unseen.
void pt_table_update_show_click(void* h, const int64_t* ids, int64_t n,
                                const float* shows, const float* clicks) {
  auto* t = static_cast<Table*>(h);
  if (!t->accessor) return;
  for (int64_t i = 0; i < n; ++i) {
    Bucket& b = t->buckets[Table::bucket_of(ids[i])];
    std::lock_guard<std::mutex> g(b.mu);
    int64_t r = t->ensure(b, ids[i]);
    b.meta[r * 3 + 0] += shows[i];
    b.meta[r * 3 + 1] += clicks[i];
    b.meta[r * 3 + 2] = 0.f;
  }
}

// One maintenance round (reference Table::Shrink via CtrCommonAccessor
// ::Shrink + ::Save filtering): decay show/click, age every row one
// round, then evict rows whose score = click + nonclk_coeff·(show −
// click) falls below delete_threshold AND whose unseen age exceeds
// delete_after_unseen rounds. Buckets compact independently (parallel).
// Returns the number of evicted rows.
int64_t pt_table_shrink(void* h, float decay, float nonclk_coeff,
                        float delete_threshold,
                        float delete_after_unseen) {
  auto* t = static_cast<Table*>(h);
  if (!t->accessor) return 0;
  std::atomic<int64_t> evicted{0};
  int64_t sd = t->slot_dim();
  parallel_for(kBuckets, kBuckets,  // always single-thread per bucket
               [&](int64_t lo, int64_t hi) {
    for (int64_t bi = lo; bi < hi; ++bi) {
      Bucket& b = t->buckets[bi];
      std::lock_guard<std::mutex> g(b.mu);
      int64_t n = static_cast<int64_t>(b.ids.size());
      Bucket keep;
      keep.rows.reserve(n);
      for (int64_t r = 0; r < n; ++r) {
        float show = b.meta[r * 3 + 0] * decay;
        float click = b.meta[r * 3 + 1] * decay;
        float unseen = b.meta[r * 3 + 2] + 1.f;
        float score = click + nonclk_coeff * (show - click);
        if (score < delete_threshold && unseen > delete_after_unseen) {
          evicted.fetch_add(1, std::memory_order_relaxed);
          continue;
        }
        int64_t nr = static_cast<int64_t>(keep.ids.size());
        keep.rows.emplace(b.ids[r], nr);
        keep.ids.push_back(b.ids[r]);
        keep.data.insert(keep.data.end(), b.data.begin() + r * t->dim,
                         b.data.begin() + (r + 1) * t->dim);
        if (sd)
          keep.slots.insert(keep.slots.end(), b.slots.begin() + r * sd,
                            b.slots.begin() + (r + 1) * sd);
        keep.meta.push_back(show);
        keep.meta.push_back(click);
        keep.meta.push_back(unseen);
      }
      b.rows.swap(keep.rows);
      b.ids.swap(keep.ids);
      b.data.swap(keep.data);
      b.slots.swap(keep.slots);
      b.meta.swap(keep.meta);
    }
  });
  return evicted.load();
}

// --- checkpoint ------------------------------------------------------

// Export order: bucket-major, insertion order within bucket. meta_out
// may be null when the accessor is off.
void pt_table_export(void* h, int64_t* ids_out, float* data_out,
                     float* slots_out, float* meta_out) {
  auto* t = static_cast<Table*>(h);
  int64_t base = 0;
  int64_t sd = t->slot_dim();
  for (auto& b : t->buckets) {
    std::lock_guard<std::mutex> g(b.mu);
    int64_t n = static_cast<int64_t>(b.ids.size());
    if (!n) continue;
    std::memcpy(ids_out + base, b.ids.data(), sizeof(int64_t) * n);
    std::memcpy(data_out + base * t->dim, b.data.data(),
                sizeof(float) * n * t->dim);
    if (sd)
      std::memcpy(slots_out + base * sd, b.slots.data(),
                  sizeof(float) * n * sd);
    if (t->accessor && meta_out)
      std::memcpy(meta_out + base * 3, b.meta.data(),
                  sizeof(float) * n * 3);
    base += n;
  }
}

void pt_table_import(void* h, const int64_t* ids, int64_t n,
                     const float* data, const float* slots,
                     const float* meta) {
  auto* t = static_cast<Table*>(h);
  int64_t sd = t->slot_dim();
  for (auto& b : t->buckets) {
    std::lock_guard<std::mutex> g(b.mu);
    b.rows.clear();
    b.ids.clear();
    b.data.clear();
    b.slots.clear();
    b.meta.clear();
  }
  for (int64_t i = 0; i < n; ++i) {
    Bucket& b = t->buckets[Table::bucket_of(ids[i])];
    std::lock_guard<std::mutex> g(b.mu);
    int64_t r = static_cast<int64_t>(b.ids.size());
    b.rows.emplace(ids[i], r);
    b.ids.push_back(ids[i]);
    b.data.insert(b.data.end(), data + i * t->dim,
                  data + (i + 1) * t->dim);
    if (sd && slots)
      b.slots.insert(b.slots.end(), slots + i * sd, slots + (i + 1) * sd);
    if (t->accessor) {
      if (meta)
        b.meta.insert(b.meta.end(), meta + i * 3, meta + (i + 1) * 3);
      else
        b.meta.insert(b.meta.end(), {0.f, 0.f, 0.f});
    }
  }
}

}  // extern "C"
