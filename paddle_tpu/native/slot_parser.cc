// Slot-line parser — the host ingest hot loop for PS/CTR workloads.
//
// TPU-native counterpart of the reference's C++ data feed
// (reference: paddle/fluid/framework/data_feed.cc MultiSlotDataFeed —
// thread-pooled line parsing feeding trainer scopes). Here the parse is
// a single tight strtof loop over a buffer (called with the GIL
// released via ctypes), producing one dense [rows, n_slots] float32
// matrix the Python dataset facade slices into samples.

#include <cstdlib>

extern "C" {

// Parse whitespace-separated numeric slot lines; one sample per line.
// buf MUST be NUL-terminated (the Python wrapper appends one). CRLF and
// whitespace-only lines are handled (blank lines are skipped). Returns
// the number of rows parsed, or -(row_index+1) on a malformed row
// (short line / extra slots / non-numeric token), where row_index
// counts parsed (non-blank) rows.
long long pt_parse_slots(const char* buf, long long n_slots, float* out,
                         long long max_rows) {
  const char* p = buf;
  long long rows = 0;
  while (*p && rows < max_rows) {
    // skip blank / whitespace-only lines (also leading spaces of a row)
    while (*p == '\n' || *p == '\r' || *p == ' ' || *p == '\t') ++p;
    if (!*p) break;
    for (long long s = 0; s < n_slots; ++s) {
      if (!*p || *p == '\n' || *p == '\r') return -(rows + 1);  // short
      char* q;
      float v = strtof(p, &q);
      if (q == p) return -(rows + 1);  // non-numeric token
      out[rows * n_slots + s] = v;
      p = q;
      while (*p == ' ' || *p == '\t') ++p;
    }
    while (*p == ' ' || *p == '\t' || *p == '\r') ++p;
    if (*p && *p != '\n') return -(rows + 1);  // extra slots
    ++rows;
  }
  return rows;
}

}  // extern "C"
