"""paddle_tpu.native — C++ runtime components, loaded via ctypes.

The reference implements its host runtime (PS tables, data feed,
allocator) in C++; this package is the TPU-native equivalent for the
pieces that stay on the host: the sparse-table KV core and the
DataLoader batch assembler (see the .cc files for reference pointers).

Build model: one shared library compiled from the .cc sources with the
system g++ on first import, cached next to the sources keyed by a
source hash (no pip, no pybind11 — plain C ABI + ctypes). If no
compiler is available the callers fall back to their pure-python
paths; `is_available()` reports which world you're in.
"""
import ctypes
import hashlib
import os
import subprocess
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_SOURCES = ["sparse_table.cc", "batch_assemble.cc", "slot_parser.cc"]

_lib = None
_tried = False
_lock = threading.Lock()


def _source_hash():
    h = hashlib.sha1()
    for s in _SOURCES:
        with open(os.path.join(_DIR, s), "rb") as f:
            h.update(f.read())
    return h.hexdigest()[:12]


def _build(out_path):
    cmd = ["g++", "-O2", "-fPIC", "-shared", "-std=c++17", "-pthread",
           "-o", out_path] + [os.path.join(_DIR, s) for s in _SOURCES]
    subprocess.run(cmd, check=True, capture_output=True, text=True)


def _load():
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        path = os.path.join(_DIR, f"libpaddle_tpu_{_source_hash()}.so")
        try:
            if not os.path.exists(path):
                tmp = path + f".tmp{os.getpid()}"
                _build(tmp)
                os.replace(tmp, path)
            lib = ctypes.CDLL(path)
        except Exception:
            return None
        # ---- signatures ----
        lib.pt_table_create.restype = ctypes.c_void_p
        lib.pt_table_create.argtypes = [
            ctypes.c_int64, ctypes.c_int, ctypes.c_float, ctypes.c_float,
            ctypes.c_float, ctypes.c_float, ctypes.c_float, ctypes.c_float,
            ctypes.c_int, ctypes.c_uint64]
        lib.pt_table_destroy.argtypes = [ctypes.c_void_p]
        lib.pt_table_size.restype = ctypes.c_int64
        lib.pt_table_size.argtypes = [ctypes.c_void_p]
        lib.pt_table_pull.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
            ctypes.c_void_p]
        lib.pt_table_push.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
            ctypes.c_void_p]
        lib.pt_table_update_show_click.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
            ctypes.c_void_p, ctypes.c_void_p]
        lib.pt_table_shrink.restype = ctypes.c_int64
        lib.pt_table_shrink.argtypes = [
            ctypes.c_void_p, ctypes.c_float, ctypes.c_float,
            ctypes.c_float, ctypes.c_float]
        lib.pt_table_export.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_void_p, ctypes.c_void_p]
        lib.pt_table_import.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p]
        lib.pt_assemble_batch.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_void_p, ctypes.c_int]
        lib.pt_parse_slots.restype = ctypes.c_int64
        lib.pt_parse_slots.argtypes = [
            ctypes.c_char_p, ctypes.c_int64, ctypes.c_void_p,
            ctypes.c_int64]
        _lib = lib
        return _lib


def get_lib():
    """The loaded CDLL, building it if needed; None when unavailable."""
    return _load()


def is_available():
    return _load() is not None


# ------------------------------------------------------------ wrappers

class NativeSparseTable:
    """ctypes wrapper over the C++ table (same contract as the python
    MemorySparseTable storage engine: pull creates rows, push applies
    the optimizer rule with dedup). rule ∈ {sgd, adagrad, adam}
    (reference sparse_sgd_rule.cc's naive/adagrad/adam); accessor="ctr"
    tracks per-row show/click with `update_show_click` and decay-scored
    eviction via `shrink` (reference ctr_accessor.cc)."""

    RULES = {"sgd": 0, "adagrad": 1, "adam": 2}

    def __init__(self, dim, rule="adagrad", lr=0.05, init_scale=None,
                 g0=0.0, eps=1e-8, beta1=0.9, beta2=0.999, accessor=None,
                 seed=0):
        import numpy as np

        lib = get_lib()
        if lib is None:
            raise RuntimeError("native library unavailable (no g++?)")
        self._lib = lib
        self.dim = int(dim)
        self.rule = rule
        self.accessor = accessor
        if accessor not in (None, "ctr"):
            raise ValueError(f"accessor={accessor!r}: expected None/'ctr'")
        if init_scale is None:
            init_scale = 1.0 / float(np.sqrt(dim))
        self._h = ctypes.c_void_p(lib.pt_table_create(
            self.dim, self.RULES[rule], float(lr), float(init_scale),
            float(g0), float(eps), float(beta1), float(beta2),
            1 if accessor == "ctr" else 0, int(seed)))

    @property
    def slot_dim(self):
        return {"sgd": 0, "adagrad": 1, "adam": 2 * self.dim + 1}[self.rule]

    def __len__(self):
        return int(self._lib.pt_table_size(self._h))

    def __del__(self):
        try:
            if getattr(self, "_h", None):
                self._lib.pt_table_destroy(self._h)
                self._h = None
        except Exception:  # ptlint: disable=PTL804 (__del__ must never raise)
            pass

    def pull(self, ids):
        import numpy as np

        ids = np.ascontiguousarray(np.asarray(ids).reshape(-1), np.int64)
        out = np.empty((len(ids), self.dim), np.float32)
        self._lib.pt_table_pull(self._h, ids.ctypes.data, len(ids),
                                out.ctypes.data)
        return out

    def push(self, ids, grads):
        import numpy as np

        ids = np.ascontiguousarray(np.asarray(ids).reshape(-1), np.int64)
        grads = np.ascontiguousarray(
            np.asarray(grads, np.float32).reshape(len(ids), self.dim))
        self._lib.pt_table_push(self._h, ids.ctypes.data, len(ids),
                                grads.ctypes.data)

    def update_show_click(self, ids, shows, clicks):
        """Accumulate per-row show/click event counts (reference
        CtrCommonAccessor::UpdateStatAfterSave path feeding shrink)."""
        import numpy as np

        if self.accessor != "ctr":
            raise RuntimeError("table created without accessor='ctr'")
        ids = np.ascontiguousarray(np.asarray(ids).reshape(-1), np.int64)
        shows = np.ascontiguousarray(
            np.asarray(shows, np.float32).reshape(-1))
        clicks = np.ascontiguousarray(
            np.asarray(clicks, np.float32).reshape(-1))
        if not len(ids) == len(shows) == len(clicks):
            raise ValueError("ids/shows/clicks length mismatch")
        self._lib.pt_table_update_show_click(
            self._h, ids.ctypes.data, len(ids), shows.ctypes.data,
            clicks.ctypes.data)

    def shrink(self, decay=0.98, nonclk_coeff=0.1, delete_threshold=0.8,
               delete_after_unseen=7):
        """One maintenance round: decay show/click, age rows, evict
        low-score long-unseen rows (reference Table::Shrink +
        ctr_accessor.cc ShowClickScore). Returns evicted row count."""
        if self.accessor != "ctr":
            raise RuntimeError("table created without accessor='ctr'")
        return int(self._lib.pt_table_shrink(
            self._h, float(decay), float(nonclk_coeff),
            float(delete_threshold), float(delete_after_unseen)))

    def state_dict(self):
        import numpy as np

        n = len(self)
        ids = np.empty((n,), np.int64)
        data = np.empty((n, self.dim), np.float32)
        slots = np.empty((n, self.slot_dim), np.float32)
        meta = (np.empty((n, 3), np.float32)
                if self.accessor == "ctr" else None)
        if n:
            self._lib.pt_table_export(
                self._h, ids.ctypes.data, data.ctypes.data,
                slots.ctypes.data,
                meta.ctypes.data if meta is not None else None)
        sd = {"ids": ids, "data": data, "slots": slots}
        if self.accessor == "ctr":
            sd["meta"] = meta
        return sd

    def set_state_dict(self, sd):
        import numpy as np

        ids = np.ascontiguousarray(_np_of(sd["ids"]).reshape(-1), np.int64)
        data = np.ascontiguousarray(_np_of(sd["data"]), np.float32)
        slots = np.ascontiguousarray(_np_of(sd["slots"]), np.float32)
        # validate BEFORE crossing the ctypes boundary — the C++ side
        # trusts the sizes and would read past a mismatched buffer
        n = len(ids)
        if data.shape != (n, self.dim):
            raise ValueError(
                f"table state 'data' has shape {data.shape}, expected "
                f"({n}, {self.dim}) — checkpoint from a different table?")
        if self.slot_dim and slots.shape != (n, self.slot_dim):
            raise ValueError(
                f"table state 'slots' has shape {slots.shape}, expected "
                f"({n}, {self.slot_dim})")
        meta = None
        if self.accessor == "ctr" and "meta" in sd:
            meta = np.ascontiguousarray(_np_of(sd["meta"]), np.float32)
            if meta.shape != (n, 3):
                raise ValueError(
                    f"table state 'meta' has shape {meta.shape}, "
                    f"expected ({n}, 3)")
        self._lib.pt_table_import(
            self._h, ids.ctypes.data, n, data.ctypes.data,
            slots.ctypes.data if slots.size else None,
            meta.ctypes.data if meta is not None else None)


def _np_of(x):
    import numpy as np

    return np.asarray(x._value if hasattr(x, "_value") else x)


def assemble_batch(samples, out=None, n_threads=0):
    """Stack N equal-shaped contiguous numpy samples into one batch
    array using the native thread pool (GIL released). Falls back to
    np.stack when the library is missing."""
    import numpy as np

    lib = get_lib()
    samples = [np.ascontiguousarray(s) for s in samples]
    if lib is None:
        return np.stack(samples)
    n = len(samples)
    if n == 0:
        raise ValueError("empty batch")
    shape, dtype = samples[0].shape, samples[0].dtype
    if dtype.hasobject:
        # raw memcpy of PyObject* would skip increfs → refcount corruption
        return np.stack(samples)
    for s in samples[1:]:
        if s.shape != shape or s.dtype != dtype:
            return np.stack(samples)  # ragged: numpy's error/semantics
    if out is None:
        out = np.empty((n,) + shape, dtype)
    ptrs = (ctypes.c_void_p * n)(
        *[s.ctypes.data for s in samples])
    lib.pt_assemble_batch(ptrs, n, samples[0].nbytes, out.ctypes.data,
                          n_threads)
    return out


def parse_slots(text, n_slots):
    """Parse numeric slot lines to a [rows, n_slots] float32 matrix
    (reference: data_feed.cc MultiSlotDataFeed). `text`: str or bytes;
    raises ValueError naming the first malformed line. Falls back to a
    python parse when the native library is unavailable."""
    import numpy as np

    if isinstance(text, str):
        text = text.encode()
    n_slots = int(n_slots)
    lib = get_lib()
    if lib is None:
        # pure-python fallback with the SAME error contract: row index
        # counts parsed (non-blank) rows, like the native path
        rows = []
        for line in text.decode().splitlines():
            if not line.strip():
                continue
            toks = line.split()
            r = len(rows)
            if len(toks) != n_slots:
                raise ValueError(
                    f"slot parse error on line {r}: wrong slot count or "
                    "non-numeric token")
            try:
                rows.append([float(t) for t in toks])
            except ValueError:
                raise ValueError(
                    f"slot parse error on line {r}: wrong slot count or "
                    "non-numeric token") from None
        return np.asarray(rows, np.float32).reshape(-1, n_slots)
    max_rows = text.count(b"\n") + 1
    out = np.empty((max_rows, int(n_slots)), np.float32)
    n = lib.pt_parse_slots(text + b"\0", int(n_slots), out.ctypes.data,
                           max_rows)
    if n < 0:
        raise ValueError(
            f"slot parse error on line {-n - 1}: wrong slot count or "
            "non-numeric token")
    return out[:n].copy()
