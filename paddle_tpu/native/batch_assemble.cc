// Native batch assembler — C++ core of the DataLoader collate hot path.
//
// TPU-native counterpart of the reference's C++ data feed
// (reference: paddle/fluid/framework/data_feed.cc MultiSlotDataFeed /
// InMemoryDataFeed — batch assembly off the Python interpreter). The
// DataLoader's worker threads call this through ctypes, which drops the
// GIL for the duration: N sample buffers are memcpy'd into one
// contiguous batch buffer by a small thread pool, so collate no longer
// serializes on the interpreter for large samples.

#include <algorithm>
#include <atomic>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

extern "C" {

// srcs: n pointers, each `bytes_per_sample` long; dst: n*bytes contiguous
void pt_assemble_batch(const void** srcs, int64_t n,
                       int64_t bytes_per_sample, void* dst,
                       int n_threads) {
  if (n <= 0) return;
  char* out = static_cast<char*>(dst);
  int hw = static_cast<int>(std::thread::hardware_concurrency());
  int nt = n_threads > 0 ? n_threads : std::max(1, hw / 2);
  nt = static_cast<int>(
      std::min<int64_t>(nt, n));
  // small batches: one thread beats spawn overhead
  if (n * bytes_per_sample < (1 << 20)) nt = 1;
  if (nt == 1) {
    for (int64_t i = 0; i < n; ++i)
      std::memcpy(out + i * bytes_per_sample, srcs[i], bytes_per_sample);
    return;
  }
  std::atomic<int64_t> next(0);
  std::vector<std::thread> pool;
  pool.reserve(nt);
  for (int t = 0; t < nt; ++t) {
    pool.emplace_back([&]() {
      int64_t i;
      while ((i = next.fetch_add(1)) < n) {
        std::memcpy(out + i * bytes_per_sample, srcs[i],
                    bytes_per_sample);
      }
    });
  }
  for (auto& th : pool) th.join();
}

}  // extern "C"
