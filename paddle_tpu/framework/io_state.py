"""paddle.save / paddle.load (reference: python/paddle/framework/io.py:574,791).

Pickle-based nested state_dict I/O, with Tensors converted to numpy on save
and rehydrated as Tensors on load. (The sharded/async distributed checkpoint
path lives in paddle_tpu.distributed.checkpoint — this is the single-process
object I/O the reference exposes as paddle.save.)
"""
import os
import pickle

import numpy as np

from ..tensor_core import Parameter, Tensor

__all__ = ["save", "load"]

_PROTO = 4


class _TensorPayload:
    """Pickle-stable tensor wrapper recording trainable-ness."""

    def __init__(self, array, trainable=None, name=None):
        self.array = array
        self.trainable = trainable
        self.name = name


def _pack(obj):
    if isinstance(obj, Parameter):
        return _TensorPayload(np.asarray(obj._value), obj.trainable, obj.name)
    if isinstance(obj, Tensor):
        return _TensorPayload(np.asarray(obj._value), None, obj.name)
    if isinstance(obj, dict):
        return {k: _pack(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        return t(_pack(v) for v in obj)
    return obj


def _unpack(obj, return_numpy=False):
    if isinstance(obj, _TensorPayload):
        if return_numpy:
            return obj.array
        if obj.trainable is not None:
            return Parameter(obj.array, trainable=obj.trainable, name=obj.name)
        return Tensor(obj.array, name=obj.name)
    if isinstance(obj, dict):
        return {k: _unpack(v, return_numpy) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        return t(_unpack(v, return_numpy) for v in obj)
    return obj


def save(obj, path, protocol=_PROTO, **configs):
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(_pack(obj), f, protocol=protocol)


def load(path, **configs):
    return_numpy = configs.get("return_numpy", False)
    with open(path, "rb") as f:
        obj = pickle.load(f)
    return _unpack(obj, return_numpy)
