"""Seed control (reference: python/paddle/framework/random.py)."""
from ..core import rng

__all__ = ["seed", "get_cuda_rng_state", "set_cuda_rng_state"]


def seed(s):
    return rng.seed(s)


def get_cuda_rng_state():
    return [rng.default_generator().get_state()]


def set_cuda_rng_state(states):
    if states:
        rng.default_generator().set_state(states[0])
