"""paddle.framework parity surface (reference: python/paddle/framework/)."""
from . import io_state  # noqa: F401
from . import random  # noqa: F401
from .io_state import load, save  # noqa: F401
from .random import get_cuda_rng_state, seed, set_cuda_rng_state  # noqa: F401
