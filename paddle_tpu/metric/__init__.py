"""Metrics (reference: python/paddle/metric/metrics.py)."""
import numpy as np

from ..tensor_core import Tensor

__all__ = ["Metric", "Accuracy", "Precision", "Recall", "Auc", "accuracy"]


def _np(x):
    return x.numpy() if isinstance(x, Tensor) else np.asarray(x)


class Metric:
    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        raise NotImplementedError

    def compute(self, *args):
        return args


class Accuracy(Metric):
    def __init__(self, topk=(1,), name=None):
        self.topk = (topk,) if isinstance(topk, int) else tuple(topk)
        self.maxk = max(self.topk)
        self._name = name or "acc"
        self.reset()

    def reset(self):
        self.total = [0.0] * len(self.topk)
        self.count = [0] * len(self.topk)

    def compute(self, pred, label, *args):
        pred = _np(pred)
        label = _np(label)
        if label.ndim == pred.ndim and label.shape[-1] > 1:
            label = label.argmax(-1)  # one-hot → index
        label = label.reshape(label.shape[0], -1)
        topk_idx = np.argsort(-pred, axis=-1)[..., : self.maxk]
        correct = topk_idx == label[..., :1]
        return Tensor(correct.astype("float32"))

    def update(self, correct, *args):
        correct = _np(correct)
        num = correct.shape[0]
        for i, k in enumerate(self.topk):
            c = correct[..., :k].sum()
            self.total[i] += float(c)
            self.count[i] += num
        acc = self.total[0] / max(self.count[0], 1)
        return acc

    def accumulate(self):
        res = [t / max(c, 1) for t, c in zip(self.total, self.count)]
        return res[0] if len(res) == 1 else res

    def name(self):
        if len(self.topk) == 1:
            return self._name
        return [f"{self._name}_top{k}" for k in self.topk]


class Precision(Metric):
    def __init__(self, name="precision"):
        self._name = name
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        preds = (_np(preds) > 0.5).astype("int32").reshape(-1)
        labels = _np(labels).astype("int32").reshape(-1)
        self.tp += int(((preds == 1) & (labels == 1)).sum())
        self.fp += int(((preds == 1) & (labels == 0)).sum())

    def accumulate(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Recall(Metric):
    def __init__(self, name="recall"):
        self._name = name
        self.reset()

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        preds = (_np(preds) > 0.5).astype("int32").reshape(-1)
        labels = _np(labels).astype("int32").reshape(-1)
        self.tp += int(((preds == 1) & (labels == 1)).sum())
        self.fn += int(((preds == 0) & (labels == 1)).sum())

    def accumulate(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Auc(Metric):
    """Thresholded-bucket AUC (reference: metrics.py Auc — same bucketed
    trapezoid estimator the C++ fleet metric uses)."""

    def __init__(self, curve="ROC", num_thresholds=4095, name="auc"):
        self.num_thresholds = num_thresholds
        self._name = name
        self.reset()

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1)
        self._stat_neg = np.zeros(self.num_thresholds + 1)

    def update(self, preds, labels):
        preds = _np(preds)
        labels = _np(labels).reshape(-1)
        if preds.ndim == 2 and preds.shape[1] == 2:
            pos_prob = preds[:, 1]
        else:
            pos_prob = preds.reshape(-1)
        bins = (pos_prob * self.num_thresholds).astype("int64")
        bins = np.clip(bins, 0, self.num_thresholds)
        pos = labels != 0
        np.add.at(self._stat_pos, bins[pos], 1)
        np.add.at(self._stat_neg, bins[~pos], 1)

    def accumulate(self):
        tot_pos = tot_neg = 0.0
        auc = 0.0
        for i in range(self.num_thresholds, -1, -1):
            new_pos = tot_pos + self._stat_pos[i]
            new_neg = tot_neg + self._stat_neg[i]
            auc += (new_pos + tot_pos) * (new_neg - tot_neg) / 2.0
            tot_pos, tot_neg = new_pos, new_neg
        denom = tot_pos * tot_neg
        return float(auc / denom) if denom else 0.0

    def name(self):
        return self._name


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    """Functional top-k accuracy (paddle.metric.accuracy)."""
    pred = _np(input)
    lab = _np(label).reshape(-1, 1)
    topk_idx = np.argsort(-pred, axis=-1)[:, :k]
    acc = (topk_idx == lab).any(-1).mean()
    return Tensor(np.asarray(acc, dtype="float32"))
