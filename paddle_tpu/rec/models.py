"""DeepFM / FM for CTR prediction.

Reference counterpart: the PaddleRec DeepFM config that BASELINE.md
names as the recommendation baseline, trained on the reference's PS
runtime (the_one_ps.py). Here the model is a plain Layer whose
embedding backend is pluggable:

- dense (default): one device table, ids must be < vocab_size; works
  under jit/DistributedTrainStep (ShardedEmbedding for big vocabs).
- sparse=True: PS-backed `SparseEmbedding` host tables with unbounded
  vocab and server-side optimizer rules — the reference's async-PS
  training shape (eager loop; the dense math still compiles).

DeepFM = linear (first-order) + FM pairwise interactions + DNN over the
concatenated field embeddings, sharing ONE embedding space keyed by
globally-offset feature ids (the standard single-table CTR layout the
PS tables use).
"""
import numpy as np

import jax.numpy as jnp

from .. import nn
from ..tensor_core import Tensor

__all__ = ["FM", "DeepFM"]


class _DenseBackend:
    def __init__(self, vocab_size, dim):
        self.emb = nn.Embedding(vocab_size, dim)

    def __call__(self, ids):
        return self.emb(ids)

    def layers(self):
        return [self.emb]


class _SparseBackend:
    def __init__(self, dim, rule=None, table_fn=None):
        from ..distributed.ps import SparseEmbedding

        table = table_fn(dim) if table_fn is not None else None
        self.emb = SparseEmbedding(dim, table=table, rule=rule)

    def __call__(self, ids):
        return self.emb(ids)

    def layers(self):
        return []


class FM(nn.Layer):
    """Factorization machine: w0 + sum_i w_i + 0.5 * sum_k ((Σv)² − Σv²).
    ids: (B, F) int64 globally-offset feature ids."""

    def __init__(self, vocab_size=None, embed_dim=8, sparse=False,
                 sparse_rule=None, sparse_table_fn=None):
        super().__init__()
        if sparse:
            # sparse_table_fn(dim) -> table: inject e.g. a multi-host
            # ShardedSparseTable (distributed/ps.py) instead of the
            # default per-process table
            self._first = _SparseBackend(1, rule=sparse_rule,
                                         table_fn=sparse_table_fn)
            self._embed = _SparseBackend(embed_dim, rule=sparse_rule,
                                         table_fn=sparse_table_fn)
        else:
            assert vocab_size is not None, "dense FM needs vocab_size"
            self._first = _DenseBackend(vocab_size, 1)
            self._embed = _DenseBackend(vocab_size, embed_dim)
        for i, lyr in enumerate(self._first.layers()
                                + self._embed.layers()):
            setattr(self, f"_t{i}", lyr)  # register dense tables
        self.bias = self.create_parameter([1], is_bias=True)

    def _terms(self, ids):
        first = self._first(ids).squeeze(-1).sum(axis=-1)   # (B,)
        v = self._embed(ids)                                # (B, F, K)
        s = v.sum(axis=1)
        pair = 0.5 * ((s * s).sum(axis=-1)
                      - (v * v).sum(axis=2).sum(axis=-1))   # (B,)
        return first, pair, v

    def forward(self, ids):
        first, pair, _ = self._terms(ids)
        return first + pair + self.bias


class DeepFM(nn.Layer):
    """DeepFM: FM terms + DNN over concatenated field embeddings,
    sharing the same embedding table."""

    def __init__(self, num_fields, vocab_size=None, embed_dim=8,
                 hidden=(64, 32), sparse=False, sparse_rule=None,
                 sparse_table_fn=None):
        super().__init__()
        self.fm = FM(vocab_size=vocab_size, embed_dim=embed_dim,
                     sparse=sparse, sparse_rule=sparse_rule,
                     sparse_table_fn=sparse_table_fn)
        dims = [num_fields * embed_dim] + list(hidden)
        layers = []
        for i in range(len(hidden)):
            layers += [nn.Linear(dims[i], dims[i + 1]), nn.ReLU()]
        layers.append(nn.Linear(dims[-1], 1))
        self.dnn = nn.Sequential(*layers)

    def forward(self, ids):
        first, pair, v = self.fm._terms(ids)
        b = v.shape[0]
        deep = self.dnn(v.reshape([b, -1])).squeeze(-1)
        return first + pair + deep + self.fm.bias

    def predict(self, ids):
        from ..nn import functional as F

        return F.sigmoid(self.forward(ids))
