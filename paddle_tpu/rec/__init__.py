"""Recommendation model family (the reference ships these via PaddleRec
on top of its PS runtime; DeepFM is the BASELINE.md recommendation
config)."""
from .models import DeepFM, FM  # noqa: F401

__all__ = ["DeepFM", "FM"]
