"""paddle_tpu.geometric — graph learning primitives.

TPU-native re-design of the reference geometric package (reference:
python/paddle/geometric/ — message_passing/send_recv.py send_u_recv:27,
send_ue_recv:165, send_uv:335; math.py segment_sum/mean/max/min;
reindex.py graph_reindex).

Message passing lowers to gather + `jax.ops.segment_sum`-family scatter
— both XLA primitives that fuse well; `num_segments` (paddle's
out_size) keeps shapes static for jit, which is why every op threads
it through.
"""
import numpy as np

import jax
import jax.numpy as jnp

from ..ops._helpers import apply_jfn, ensure_tensor, value_of

__all__ = [
    "segment_sum", "segment_mean", "segment_max", "segment_min",
    "send_u_recv", "send_ue_recv", "send_uv", "graph_reindex",
]


def _nseg(index, out_size):
    if out_size is not None:
        return int(out_size)
    return int(np.asarray(value_of(ensure_tensor(index))).max()) + 1


def _segment(name, jfn_seg):
    def op(data, segment_ids, out_size=None, name_=None):
        n = _nseg(segment_ids, out_size)
        ids_t = ensure_tensor(segment_ids)

        def jfn(v):
            return jfn_seg(v, value_of(ids_t), n)

        return apply_jfn(f"segment_{name}", jfn, data)

    op.__name__ = f"segment_{name}"
    return op


segment_sum = _segment("sum", lambda v, i, n: jax.ops.segment_sum(
    v, i, num_segments=n))
segment_max = _segment("max", lambda v, i, n: jax.ops.segment_max(
    v, i, num_segments=n))
segment_min = _segment("min", lambda v, i, n: jax.ops.segment_min(
    v, i, num_segments=n))


def _seg_mean(v, i, n):
    s = jax.ops.segment_sum(v, i, num_segments=n)
    cnt = jax.ops.segment_sum(jnp.ones((v.shape[0],), v.dtype), i,
                              num_segments=n)
    return s / jnp.maximum(cnt, 1.0).reshape((-1,) + (1,) * (v.ndim - 1))


segment_mean = _segment("mean", _seg_mean)

_REDUCERS = {
    "sum": lambda v, i, n: jax.ops.segment_sum(v, i, num_segments=n),
    "add": lambda v, i, n: jax.ops.segment_sum(v, i, num_segments=n),
    "mean": _seg_mean,
    "max": lambda v, i, n: jax.ops.segment_max(v, i, num_segments=n),
    "min": lambda v, i, n: jax.ops.segment_min(v, i, num_segments=n),
}


def send_u_recv(x, src_index, dst_index, reduce_op="sum", out_size=None,
                name=None):
    """Gather x[src] → scatter-reduce onto dst
    (reference send_recv.py:27)."""
    n = _nseg(dst_index, out_size if out_size is not None
              else value_of(ensure_tensor(x)).shape[0])
    src_t, dst_t = ensure_tensor(src_index), ensure_tensor(dst_index)
    red = _REDUCERS[reduce_op]

    def jfn(v):
        msgs = jnp.take(v, value_of(src_t), axis=0)
        return red(msgs, value_of(dst_t), n)

    return apply_jfn("send_u_recv", jfn, x)


def send_ue_recv(x, y, src_index, dst_index, message_op="add",
                 reduce_op="sum", out_size=None, name=None):
    """Combine node features with EDGE features, then reduce
    (reference send_recv.py:165). message_op: add/sub/mul/div."""
    comb = {"add": jnp.add, "sub": jnp.subtract, "mul": jnp.multiply,
            "div": jnp.divide}[message_op]
    n = _nseg(dst_index, out_size if out_size is not None
              else value_of(ensure_tensor(x)).shape[0])
    src_t, dst_t = ensure_tensor(src_index), ensure_tensor(dst_index)
    red = _REDUCERS[reduce_op]

    def jfn(v, e):
        msgs = comb(jnp.take(v, value_of(src_t), axis=0), e)
        return red(msgs, value_of(dst_t), n)

    return apply_jfn("send_ue_recv", jfn, x, ensure_tensor(y))


def send_uv(x, y, src_index, dst_index, message_op="add", name=None):
    """Per-edge message from both endpoints (reference
    send_recv.py:335)."""
    comb = {"add": jnp.add, "sub": jnp.subtract, "mul": jnp.multiply,
            "div": jnp.divide}[message_op]
    src_t, dst_t = ensure_tensor(src_index), ensure_tensor(dst_index)

    def jfn(xv, yv):
        return comb(jnp.take(xv, value_of(src_t), axis=0),
                    jnp.take(yv, value_of(dst_t), axis=0))

    return apply_jfn("send_uv", jfn, x, ensure_tensor(y))


def graph_reindex(x, neighbors, count, name=None):
    """Compact global ids to local ids (reference reindex.py). Host-side
    (hash-map semantics, data-dependent sizes — not a jit shape)."""
    from ..tensor_core import Tensor

    xv = np.asarray(value_of(ensure_tensor(x)))
    nb = np.asarray(value_of(ensure_tensor(neighbors)))
    uniq = {}
    for i in xv.tolist():
        uniq.setdefault(int(i), len(uniq))
    out_nodes = list(uniq)
    reindexed = []
    for i in nb.tolist():
        if int(i) not in uniq:
            uniq[int(i)] = len(uniq)
            out_nodes.append(int(i))
        reindexed.append(uniq[int(i)])
    return (Tensor(jnp.asarray(reindexed)),
            Tensor(jnp.asarray(out_nodes)),
            ensure_tensor(count))
