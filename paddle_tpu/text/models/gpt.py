"""GPT — decoder-only causal language model, the flagship transformer.

The reference ships GPT through PaddleNLP on top of the fleet TP/PP layers
(reference capability: fleet/layers/mpu/mp_layers.py + the GPT-3 hybrid
configs named in BASELINE.json); here the model is built directly on the
framework's tensor-parallel layers so ONE model definition runs serial,
DP, TP, ZeRO, and sequence-parallel — the mesh axes and PartitionSpecs
decide, not the model code (GSPMD-first design).

TPU-first choices:
- attention runs through F.scaled_dot_product_attention → the Pallas
  flash-attention kernel on TPU (ops/pallas_kernels/flash_attention.py);
- qkv is ONE fused ColumnParallelLinear (3·d_model output, mp-sharded) so
  the MXU sees one big matmul;
- the LM head is tied to the vocab-sharded embedding; the loss is
  ParallelCrossEntropy (vocab-parallel softmax-CE, reference
  c_softmax_with_cross_entropy_op).
"""
import math

from ... import nn
from ...distributed.fleet.meta_parallel.mp_layers import (
    ColumnParallelLinear,
    ParallelCrossEntropy,
    RowParallelLinear,
    VocabParallelEmbedding,
    shard_activation,
    split_fused_qkv,
)
from ...nn import functional as F
from ...ops import manipulation as manip

__all__ = [
    "GPTConfig", "GPTDecoderLayer", "GPTModel", "GPTForCausalLM",
    "GPTPretrainingCriterion", "gpt_tiny", "gpt_small", "gpt_medium",
    "gpt_1p3b",
]


class GPTConfig:
    def __init__(self, vocab_size=50304, hidden_size=768, num_layers=12,
                 num_heads=12, ffn_size=None, max_seq_len=1024,
                 dropout=0.0, tie_embeddings=True):
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.num_heads = num_heads
        self.ffn_size = ffn_size or 4 * hidden_size
        self.max_seq_len = max_seq_len
        self.dropout = dropout
        self.tie_embeddings = tie_embeddings


def gpt_tiny(**kw):
    return GPTConfig(vocab_size=2048, hidden_size=128, num_layers=2,
                     num_heads=4, max_seq_len=256, **kw)


def gpt_small(**kw):
    return GPTConfig(vocab_size=50304, hidden_size=768, num_layers=12,
                     num_heads=12, max_seq_len=1024, **kw)


def gpt_medium(**kw):
    return GPTConfig(vocab_size=50304, hidden_size=1024, num_layers=24,
                     num_heads=16, max_seq_len=1024, **kw)


def gpt_1p3b(**kw):
    return GPTConfig(vocab_size=50304, hidden_size=2048, num_layers=24,
                     num_heads=32, max_seq_len=2048, **kw)


class GPTDecoderLayer(nn.Layer):
    """Pre-LN decoder block: LN → fused-qkv attn → residual, LN → MLP →
    residual. Column/Row parallel pairs keep the intermediate activations
    mp-sharded with zero manual collectives."""

    def __init__(self, config):
        super().__init__()
        d = config.hidden_size
        self.nh = config.num_heads
        self.hd = d // config.num_heads
        self.ln1 = nn.LayerNorm(d)
        self.qkv = ColumnParallelLinear(d, 3 * d, gather_output=False)
        self.proj = RowParallelLinear(d, d, input_is_parallel=True)
        self.ln2 = nn.LayerNorm(d)
        self.fc1 = ColumnParallelLinear(d, config.ffn_size,
                                        gather_output=False)
        self.fc2 = RowParallelLinear(config.ffn_size, d,
                                     input_is_parallel=True)
        self.dropout = nn.Dropout(config.dropout)

    def forward(self, x):
        b = x.shape[0]
        s = x.shape[1]
        h = self.ln1(x)
        qkv = self.qkv(h)  # [b, s, 3d] (mp-sharded last dim)
        q, k, v = split_fused_qkv(qkv, b, s, self.nh, self.hd)
        attn = F.scaled_dot_product_attention(q, k, v, is_causal=True)
        attn = manip.reshape(attn, [b, s, self.nh * self.hd])
        x = x + self.dropout(self.proj(attn))
        h = self.ln2(x)
        x = x + self.dropout(self.fc2(F.gelu(self.fc1(h))))
        return x


class GPTModel(nn.Layer):
    """Token + position embeddings, N decoder layers, final LN."""

    def __init__(self, config):
        super().__init__()
        self.config = config
        self.wte = VocabParallelEmbedding(config.vocab_size,
                                          config.hidden_size)
        self.wpe = nn.Embedding(config.max_seq_len, config.hidden_size)
        self.drop = nn.Dropout(config.dropout)
        self.layers = nn.LayerList(
            [GPTDecoderLayer(config) for _ in range(config.num_layers)])
        self.ln_f = nn.LayerNorm(config.hidden_size)

    def forward(self, input_ids):
        s = input_ids.shape[1]
        from ...ops.creation import arange

        pos = arange(0, s, dtype="int64")
        x = self.wte(input_ids) + self.wpe(pos)
        x = self.drop(x)
        x = shard_activation(x, "dp", "sp", None)
        for layer in self.layers:
            x = layer(x)
        return self.ln_f(x)


class GPTForCausalLM(nn.Layer):
    """LM head tied to the (vocab-sharded) embedding by default."""

    def __init__(self, config):
        super().__init__()
        self.config = config
        self.gpt = GPTModel(config)
        if config.tie_embeddings:
            self.lm_head = None
        else:
            self.lm_head = ColumnParallelLinear(
                config.hidden_size, config.vocab_size, has_bias=False,
                gather_output=False)

    def forward(self, input_ids):
        x = self.gpt(input_ids)
        if self.lm_head is not None:
            return self.lm_head(x)
        w = self.gpt.wte.weight  # [vocab, d], mp-sharded on vocab
        logits = F.linear(x, manip.transpose(w, [1, 0]))
        return shard_activation(logits, "dp", "sp", "mp")


class GPTPretrainingCriterion(nn.Layer):
    """Shifted next-token vocab-parallel cross entropy."""

    def __init__(self):
        super().__init__()
        self.ce = ParallelCrossEntropy()

    def forward(self, logits, labels):
        from ...ops.math import mean

        shift_logits = manip.slice(
            logits, [1], [0], [logits.shape[1] - 1])
        shift_labels = manip.slice(labels, [1], [1], [labels.shape[1]])
        loss = self.ce(shift_logits, shift_labels)
        return mean(loss)
