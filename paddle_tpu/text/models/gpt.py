"""GPT — decoder-only causal language model, the flagship transformer.

The reference ships GPT through PaddleNLP on top of the fleet TP/PP layers
(reference capability: fleet/layers/mpu/mp_layers.py + the GPT-3 hybrid
configs named in BASELINE.json); here the model is built directly on the
framework's tensor-parallel layers so ONE model definition runs serial,
DP, TP, ZeRO, and sequence-parallel — the mesh axes and PartitionSpecs
decide, not the model code (GSPMD-first design).

TPU-first choices:
- attention runs through F.scaled_dot_product_attention → the Pallas
  flash-attention kernel on TPU (ops/pallas_kernels/flash_attention.py);
- qkv is ONE fused ColumnParallelLinear (3·d_model output, mp-sharded) so
  the MXU sees one big matmul;
- the LM head is tied to the vocab-sharded embedding; the loss is
  ParallelCrossEntropy (vocab-parallel softmax-CE, reference
  c_softmax_with_cross_entropy_op).
"""
import math

from ... import nn
from ...distributed.fleet.meta_parallel.mp_layers import (
    ColumnParallelLinear,
    ParallelCrossEntropy,
    RowParallelLinear,
    VocabParallelEmbedding,
    shard_activation,
    split_fused_qkv,
)
from ...nn import functional as F
from ...ops import manipulation as manip

__all__ = [
    "GPTConfig", "GPTDecoderLayer", "GPTModel", "GPTForCausalLM",
    "GPTPretrainingCriterion", "gpt_tiny", "gpt_small", "gpt_medium",
    "gpt_1p3b", "sample_tokens",
]


class GPTConfig:
    def __init__(self, vocab_size=50304, hidden_size=768, num_layers=12,
                 num_heads=12, ffn_size=None, max_seq_len=1024,
                 dropout=0.0, tie_embeddings=True, recompute=False):
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.num_heads = num_heads
        self.ffn_size = ffn_size or 4 * hidden_size
        self.max_seq_len = max_seq_len
        self.dropout = dropout
        self.tie_embeddings = tie_embeddings
        # per-LAYER activation recompute for the serial/dp path (the
        # big-model-on-few-chips lever; PP has its own ring-buffer remat).
        # False | True (keep nothing) | policy name ('dots_saveable', ...)
        self.recompute = recompute


def gpt_tiny(**kw):
    return GPTConfig(vocab_size=2048, hidden_size=128, num_layers=2,
                     num_heads=4, max_seq_len=256, **kw)


def gpt_small(**kw):
    return GPTConfig(vocab_size=50304, hidden_size=768, num_layers=12,
                     num_heads=12, max_seq_len=1024, **kw)


def gpt_medium(**kw):
    return GPTConfig(vocab_size=50304, hidden_size=1024, num_layers=24,
                     num_heads=16, max_seq_len=1024, **kw)


def gpt_1p3b(**kw):
    return GPTConfig(vocab_size=50304, hidden_size=2048, num_layers=24,
                     num_heads=32, max_seq_len=2048, **kw)


class GPTDecoderLayer(nn.Layer):
    """Pre-LN decoder block: LN → fused-qkv attn → residual, LN → MLP →
    residual. Column/Row parallel pairs keep the intermediate activations
    mp-sharded with zero manual collectives."""

    def __init__(self, config):
        super().__init__()
        d = config.hidden_size
        self.nh = config.num_heads
        self.hd = d // config.num_heads
        self.ln1 = nn.LayerNorm(d)
        self.qkv = ColumnParallelLinear(d, 3 * d, gather_output=False)
        self.proj = RowParallelLinear(d, d, input_is_parallel=True)
        self.ln2 = nn.LayerNorm(d)
        self.fc1 = ColumnParallelLinear(d, config.ffn_size,
                                        gather_output=False)
        self.fc2 = RowParallelLinear(config.ffn_size, d,
                                     input_is_parallel=True)
        self.dropout = nn.Dropout(config.dropout)

    def forward(self, x):
        b = x.shape[0]
        s = x.shape[1]
        h = self.ln1(x)
        qkv = self.qkv(h)  # [b, s, 3d] (mp-sharded last dim)
        q, k, v = split_fused_qkv(qkv, b, s, self.nh, self.hd)
        attn = F.scaled_dot_product_attention(q, k, v, is_causal=True)
        attn = manip.reshape(attn, [b, s, self.nh * self.hd])
        x = x + self.dropout(self.proj(attn))
        h = self.ln2(x)
        x = x + self.dropout(self.fc2(F.gelu(self.fc1(h))))
        return x


class GPTModel(nn.Layer):
    """Token + position embeddings, N decoder layers, final LN."""

    def __init__(self, config):
        super().__init__()
        self.config = config
        self.wte = VocabParallelEmbedding(config.vocab_size,
                                          config.hidden_size)
        self.wpe = nn.Embedding(config.max_seq_len, config.hidden_size)
        self.drop = nn.Dropout(config.dropout)
        self.layers = nn.LayerList(
            [GPTDecoderLayer(config) for _ in range(config.num_layers)])
        self.ln_f = nn.LayerNorm(config.hidden_size)

    def forward(self, input_ids):
        s = input_ids.shape[1]
        from ...ops.creation import arange

        pos = arange(0, s, dtype="int64")
        x = self.wte(input_ids) + self.wpe(pos)
        x = self.drop(x)
        x = shard_activation(x, "dp", "sp", None)
        rc = self.config.recompute
        if rc:
            from ...distributed.fleet.recompute import recompute as _rc

            # checkpoint_policy() normalizes True -> keep-nothing
            for layer in self.layers:
                x = _rc(layer, x, policy=rc)
        else:
            for layer in self.layers:
                x = layer(x)
        return self.ln_f(x)


# ------------------------------------------------------------ generation

def _cached_attention(q, k_new, v_new, cache_k, cache_v, index,
                      pad_lens=None):
    """Write k/v into the static cache at `index` and attend q against
    the valid prefix (TPU decode pattern: fixed-size buffers +
    dynamic_update_slice, no shape changes step to step).

    pad_lens: optional [b] int32 LEFT-pad counts per example (ragged
    prompts padded on the left so every row's generation frontier is
    aligned); columns < pad_lens[b] are masked out."""
    import math as _math

    import jax
    import jax.numpy as jnp
    from jax import lax

    from ...ops._helpers import apply_jfn

    def jfn(qv, kn, vn, ck, cv, idx, *rest):
        idx = idx.astype(jnp.int32)
        zero = jnp.asarray(0, idx.dtype)  # all start indices same dtype
        starts = (zero, idx, zero, zero)
        ck = lax.dynamic_update_slice(ck, kn.astype(ck.dtype), starts)
        cv = lax.dynamic_update_slice(cv, vn.astype(cv.dtype), starts)
        qt = jnp.swapaxes(qv, 1, 2)
        kt = jnp.swapaxes(ck, 1, 2)
        vt = jnp.swapaxes(cv, 1, 2)
        d = qv.shape[-1]
        sc = jnp.einsum("bhqd,bhkd->bhqk", qt, kt) / _math.sqrt(d)
        s_new, L = qv.shape[1], ck.shape[1]
        allowed = (jnp.arange(L)[None, :]
                   <= (idx + jnp.arange(s_new))[:, None])[None, None]
        if rest:  # left-pad mask: [b,1,1,L] AND the causal window
            pads = rest[0].astype(jnp.int32)
            allowed = jnp.logical_and(
                allowed,
                (jnp.arange(L)[None, :]
                 >= pads[:, None])[:, None, None, :])
        sc = jnp.where(allowed, sc, jnp.float32(-1e30))
        # softmax statistics in f32 even for bf16 caches
        w = jax.nn.softmax(sc.astype(jnp.float32), axis=-1).astype(
            vt.dtype)
        out = jnp.einsum("bhqk,bhkd->bhqd", w, vt).astype(qv.dtype)
        return jnp.swapaxes(out, 1, 2), ck, cv

    tensors = [q, k_new, v_new, cache_k, cache_v, index]
    if pad_lens is not None:
        tensors.append(pad_lens)
    return apply_jfn("cached_attention", jfn, *tensors)


def _layer_forward_cached(layer, x, cache, index, pad_lens=None):
    """Functional: returns (x_out, new_cache) — no mutation, so the whole
    decode step can be captured by to_static and dispatched as ONE
    compiled program per token."""
    b, s = x.shape[0], x.shape[1]
    h = layer.ln1(x)
    qkv = layer.qkv(h)
    q, k, v = split_fused_qkv(qkv, b, s, layer.nh, layer.hd)
    attn, ck, cv = _cached_attention(q, k, v, cache["k"], cache["v"],
                                     index, pad_lens=pad_lens)
    attn = manip.reshape(attn, [b, s, layer.nh * layer.hd])
    x = x + layer.proj(attn)
    h = layer.ln2(x)
    return x + layer.fc2(F.gelu(layer.fc1(h))), {"k": ck, "v": cv}


def _paged_cache_write(k_pool, v_pool, k_new, v_new, write_idx):
    """Scatter per-token k/v rows into the paged KV pool.

    k_pool/v_pool [num_pages, page_size, heads, head_dim]; k_new/v_new
    [T, heads, head_dim]; write_idx [T] int32 flat destination rows
    (page_id * page_size + offset). Page 0 is the engine's trash page:
    padding tokens all target row 0, where collisions are harmless —
    trash content is never attended with nonzero weight."""
    import jax.numpy as jnp

    from ...ops._helpers import apply_jfn

    def jfn(kp, vp, kn, vn, idx):
        shape = kp.shape
        flat = (shape[0] * shape[1],) + shape[2:]
        idx = idx.astype(jnp.int32)
        kp2 = kp.reshape(flat).at[idx].set(
            kn.astype(kp.dtype)).reshape(shape)
        vp2 = vp.reshape(flat).at[idx].set(
            vn.astype(vp.dtype)).reshape(shape)
        return kp2, vp2

    return apply_jfn("paged_cache_write", jfn, k_pool, v_pool, k_new,
                     v_new, write_idx)


def _paged_cache_write_quant(k_pool, v_pool, k_scales, v_scales, k_new,
                             v_new, write_idx):
    """Int8/int4 variant of `_paged_cache_write`: each incoming k/v row
    is quantized per (token, head) absmax (quantization.runtime
    `quantize_kv_rows` / `quantize_kv_rows_int4`) and scattered into
    the quantized pools, with its fp32 scale scattered into the
    page-shaped scale planes at the same flat row. A row is quantized
    exactly once with its own scale, so later writes to the same page
    never invalidate earlier tokens.

    The pool's last dim picks the codec: head_dim → int8 rows,
    head_dim/2 → PACKED int4 (two nibbles per byte, `kv_dtype="int4"`
    — the shape mismatch is unambiguous, so the compiled step needs no
    extra bits argument threaded through)."""
    import jax.numpy as jnp

    from ...ops._helpers import apply_jfn
    from ...quantization import runtime as _qrt

    packed4 = int(k_pool.shape[-1]) * 2 == int(k_new.shape[-1])
    quant_rows = (_qrt.quantize_kv_rows_int4 if packed4
                  else _qrt.quantize_kv_rows)

    def jfn(kp, vp, ks, vs, kn, vn, idx):
        shape = kp.shape
        flat = (shape[0] * shape[1],) + shape[2:]
        sflat = (shape[0] * shape[1],) + ks.shape[2:]
        idx = idx.astype(jnp.int32)
        kq, kscale = quant_rows(kn)
        vq, vscale = quant_rows(vn)
        kp2 = kp.reshape(flat).at[idx].set(kq).reshape(shape)
        vp2 = vp.reshape(flat).at[idx].set(vq).reshape(shape)
        ks2 = ks.reshape(sflat).at[idx].set(kscale).reshape(ks.shape)
        vs2 = vs.reshape(sflat).at[idx].set(vscale).reshape(vs.shape)
        return kp2, vp2, ks2, vs2

    return apply_jfn("paged_cache_write_int4" if packed4
                     else "paged_cache_write_int8", jfn, k_pool, v_pool,
                     k_scales, v_scales, k_new, v_new, write_idx)


def _layer_forward_paged(layer, x, cache_k, cache_v, write_idx,
                         page_tables, slot_ids, kv_lens,
                         k_scales=None, v_scales=None,
                         frontier_offset=None, max_q_per_slot=None):
    """Paged-cache decoder block over the FLAT token layout [1, T, d] —
    the continuous-batching analog of `_layer_forward_cached`: write the
    step's k/v into pool pages, then ragged paged attention against each
    token's own sequence prefix. Functional (returns new pools), so the
    whole engine step compiles to ONE program.

    With `k_scales`/`v_scales` (int8 pools) the write quantizes each row
    and attention dequantizes on gather; returns the new scale planes
    after the pools. `frontier_offset` is the fused-decode window's
    per-iteration scalar: kv_lens stays the window-invariant BASE
    length and attention adds the offset to every nonzero row.
    `max_q_per_slot` is the speculative-verify grid hint: a caller that
    packs at most that many query tokens per slot (the verify step:
    exactly k+1) lets attention size its slot grid [S, k+1] instead of
    the worst-case [S, T]."""
    T = x.shape[1]
    h = layer.ln1(x)
    qkv = layer.qkv(h)
    q, k, v = split_fused_qkv(qkv, 1, T, layer.nh, layer.hd)
    q = manip.reshape(q, [T, layer.nh, layer.hd])
    k = manip.reshape(k, [T, layer.nh, layer.hd])
    v = manip.reshape(v, [T, layer.nh, layer.hd])
    if k_scales is None:
        ck, cv = _paged_cache_write(cache_k, cache_v, k, v, write_idx)
        attn = F.paged_attention(q, ck, cv, page_tables, slot_ids,
                                 kv_lens,
                                 frontier_offset=frontier_offset,
                                 max_tokens_per_slot=max_q_per_slot)
        cks = cvs = None
    else:
        ck, cv, cks, cvs = _paged_cache_write_quant(
            cache_k, cache_v, k_scales, v_scales, k, v, write_idx)
        attn = F.paged_attention(q, ck, cv, page_tables, slot_ids,
                                 kv_lens, k_scales=cks, v_scales=cvs,
                                 frontier_offset=frontier_offset,
                                 max_tokens_per_slot=max_q_per_slot)
    attn = manip.reshape(attn, [1, T, layer.nh * layer.hd])
    x = x + layer.proj(attn)
    h = layer.ln2(x)
    out = x + layer.fc2(F.gelu(layer.fc1(h)))
    if k_scales is None:
        return out, ck, cv
    return out, ck, cv, cks, cvs


def sample_tokens(logits, temps, top_ps, streams, positions, key,
                  allowed=None):
    """Greedy / temperature / top-p next-token sampler — pure jnp,
    shared by the engine's host tick (first tokens after prefill) and
    the fused decode window's in-executable scan, so both paths pick
    identical tokens from identical logits.

    logits [S, vocab] f32; temps/top_ps [S] f32; streams/positions [S]
    int32; key uint32[2] (the engine-owned PRNG key, threaded as a step
    ARGUMENT so reseeding never recompiles). allowed (optional)
    [S, vocab] bool — the structured-decoding grammar mask: False
    entries are excluded BEFORE both the greedy argmax and the top-p
    truncation, so a constrained row's pick is always grammar-legal
    under either decode mode. An all-True row is a value-level no-op:
    unconstrained rows pick bit-identically to `allowed=None` (the
    engine's mask-identity contract rides on this).

    Rows with temps <= 0 take the greedy argmax (the generate()/engine
    default pick, bit-identical to the host argmax path). Sampling rows
    draw from the temperature-scaled, top-p-truncated distribution with
    a per-row key `fold_in(fold_in(key, stream), position)` — the draw
    depends ONLY on (engine seed, request stream, token position), so a
    request's sampled continuation is invariant to the window size k,
    to batch composition, and to preemption replays (the same
    determinism contract greedy decode gets for free). The grammar mask
    reshapes the distribution but not the key: constrained +
    speculative composes losslessly because acceptance is exact-match
    against this same keyed pick, masked or not."""
    import jax
    import jax.numpy as jnp

    if allowed is not None:
        logits = jnp.where(allowed, logits, jnp.float32(-1e30))
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    def drawn(_):
        scaled = logits / jnp.maximum(temps, 1e-6)[:, None]
        # top-p: keep the smallest prefix of the descending-prob list
        # whose EXCLUSIVE cumulative mass is < top_p (always keeps the
        # top-1)
        srt = jnp.sort(scaled, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(srt, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        keep = (cum - probs) < top_ps[:, None]
        thresh = jnp.min(jnp.where(keep, srt, jnp.inf), axis=-1)
        masked = jnp.where(scaled >= thresh[:, None], scaled,
                           jnp.float32(-1e30))
        keys = jax.vmap(
            lambda s, p: jax.random.fold_in(jax.random.fold_in(key, s),
                                            p)
        )(streams.astype(jnp.uint32), positions.astype(jnp.uint32))
        pick = jax.vmap(jax.random.categorical)(keys, masked)
        return jnp.where(temps > 0, pick, greedy).astype(jnp.int32)

    # all-greedy batches skip the whole sort/cumsum/draw branch at RUN
    # time (lax.cond executes one side): the fused scan calls this every
    # iteration, and a vocab-wide sort per tick would tax exactly the
    # dispatch-bound serving the fused window exists to speed up
    return jax.lax.cond(jnp.any(temps > 0), drawn,
                        lambda _: greedy, None)


def grammar_allowed(gmask, gstate, vocab):
    """Expand grammar-arena mask bitsets to a boolean logits mask:
    gmask [G, ceil(vocab/32)] uint32, gstate [R] int32 (arena-absolute
    DFA state per row) → [R, vocab] bool for `sample_tokens(allowed=)`.
    Pure jnp — runs inside the fused/verify executables
    (inference/structured has the arena contract: row 0 is the
    mask-identity every unconstrained row carries)."""
    import jax.numpy as jnp

    words = gmask[gstate]                       # [R, W] uint32
    v = jnp.arange(int(vocab), dtype=jnp.int32)
    bits = words[:, v // 32] >> (v % 32).astype(jnp.uint32)
    return (bits & jnp.uint32(1)).astype(jnp.bool_)


class GPTGenerationMixin:
    """Greedy / temperature / top-k decoding with a static KV cache
    (reference capability: PaddleNLP generate() on GPT; here designed
    for XLA — fixed-length cache buffers, dynamic_update_slice writes,
    every step the same compiled shape)."""

    def _forward_cached(self, input_ids, caches, index, pad_lens=None):
        from ...ops.creation import arange

        model = self.gpt
        s = input_ids.shape[1]
        pos = arange(0, s, dtype="int64") + index
        if pad_lens is not None:
            # left-padded rows start their position ids AFTER the pads
            # (clamped at 0 for the pad slots themselves, which attention
            # masks out anyway)
            pos = (pos.unsqueeze(0) - pad_lens.unsqueeze(1)).clip(
                0, self.config.max_seq_len - 1)
        x = model.wte(input_ids) + model.wpe(pos)
        new_caches = []
        for layer, cache in zip(model.layers, caches):
            x, nc = _layer_forward_cached(layer, x, cache, index,
                                          pad_lens=pad_lens)
            new_caches.append(nc)
        x = model.ln_f(x)
        return self._logits_from_hidden(x, shard=False), new_caches

    def _decode_core(self, tok, idx, pad_lens, kv):
        L = self.config.num_layers
        caches = [{"k": kv[2 * i], "v": kv[2 * i + 1]} for i in range(L)]
        logits, new = self._forward_cached(tok, caches, idx,
                                           pad_lens=pad_lens)
        flat = []
        for c in new:
            flat += [c["k"], c["v"]]
        return (logits, *flat)

    # two impls so to_static sees two distinct signatures (the padded
    # step threads pad_lens as a traced argument)
    def _decode_step_impl(self, tok, idx, *kv):
        return self._decode_core(tok, idx, None, kv)

    def _decode_step_padded_impl(self, tok, idx, pad_lens, *kv):
        return self._decode_core(tok, idx, pad_lens, kv)

    def _make_step(self, padded=False):
        """ONE to_static-wrapped step per INSTANCE: the trace cache
        persists across generate() calls but dies with the model (a
        class-level cache would pin every instance's weights forever —
        the traced closures capture them). Invoked as a bound Layer
        method, so weights are threaded as jit ARGUMENTS, not baked
        into each executable as constants."""
        key = "_decode_step_static_padded" if padded else \
            "_decode_step_static"
        if key not in self.__dict__:
            from ... import jit as jit_mod

            impl = (type(self)._decode_step_padded_impl if padded
                    else type(self)._decode_step_impl)
            self.__dict__[key] = jit_mod.to_static(impl)
        return self.__dict__[key].__get__(self, type(self))

    # ---- paged-cache ragged decode (continuous-batching serving) ----

    def _paged_decode_core(self, tok, pos_ids, slot_ids, write_idx,
                           page_tables, kv_lens, sample_idx, kv,
                           kv_scales=None, frontier_offset=None,
                           max_q_per_slot=None):
        """One ragged engine step over flat tokens: tok/pos_ids/slot_ids/
        write_idx/kv_lens [T], page_tables [S, MP], sample_idx [S] (the
        flat row holding each slot's sampling frontier; stale slots
        point anywhere — their logits are ignored), kv = 2·num_layers
        pool arrays. Returns (logits [1, S, vocab], *new_pools).
        The vocab head — the step's single biggest matmul — runs ONLY
        on the S gathered frontier rows, never on prefill tokens.
        Compiled ONCE by inference/llm_engine.py's _CompiledPagedStep —
        the TrainStep-style executable behind every scheduler tick
        (weights as jit arguments, pools donated).

        kv_scales: for int8 pools (kv_dtype="int8"), the 2·num_layers
        page-shaped fp32 scale planes; the new planes are returned
        AFTER the new pools: (logits, *new_pools, *new_scales).

        frontier_offset: optional scalar added to every NONZERO kv_len
        (the fused decode window passes iteration i here so the base
        kv_lens vector stays window-invariant).

        max_q_per_slot: the speculative-verify grid hint (see
        `_layer_forward_paged`) — the caller guarantees no slot owns
        more than this many flat tokens this step."""
        model = self.gpt
        x = model.wte(tok.unsqueeze(0)) + model.wpe(pos_ids)
        flat, scale_flat = [], []
        for i, layer in enumerate(model.layers):
            if kv_scales is None:
                x, ck, cv = _layer_forward_paged(
                    layer, x, kv[2 * i], kv[2 * i + 1], write_idx,
                    page_tables, slot_ids, kv_lens,
                    frontier_offset=frontier_offset,
                    max_q_per_slot=max_q_per_slot)
            else:
                x, ck, cv, cks, cvs = _layer_forward_paged(
                    layer, x, kv[2 * i], kv[2 * i + 1], write_idx,
                    page_tables, slot_ids, kv_lens,
                    k_scales=kv_scales[2 * i],
                    v_scales=kv_scales[2 * i + 1],
                    frontier_offset=frontier_offset,
                    max_q_per_slot=max_q_per_slot)
                scale_flat += [cks, cvs]
            flat += [ck, cv]
        x = model.ln_f(x)
        x = manip.gather(x, sample_idx, axis=1)  # [1, S, d] frontiers
        return (self._logits_from_hidden(x, shard=False), *flat,
                *scale_flat)

    def _paged_decode_fused(self, k, page_size, tok0, pos0, rem, fin0,
                            eos_ids, temps, top_ps, streams,
                            page_tables, kv, kv_scales, key,
                            lag=None, frontier=None, gstate0=None,
                            gtrans=None, gmask=None):
        """k decode ticks fused into ONE `lax.scan` over the paged step
        — the body of the engine's fused executable (`_CompiledFusedStep`
        in inference/llm_engine.py): per iteration, write the frontier
        token's KV, ragged paged attention over each slot's own prefix,
        vocab head on the S frontier rows, and sampling (greedy /
        temperature / top-p via `sample_tokens`) IN-EXECUTABLE, so the
        host syncs once per k tokens instead of once per token.

        Raw jax values in and out (the jit wrapper owns the Tensor
        boundary): tok0/pos0/rem/streams [S] int32 (frontier token, its
        write position, tokens the row may still emit, sampling stream
        id), fin0 [S] bool (True = empty/ignored slot), eos_ids [S]
        int32 (-1 = no eos), temps/top_ps [S] f32, page_tables [S, MP],
        kv / kv_scales the pool pytree, key the engine PRNG key.

        In-executable EOS + budget masking: a row that samples its eos
        or exhausts `rem` mid-window flips finished — later iterations
        write its KV to the trash row, skip its attention (kv_len 0),
        and emit the pad sentinel -1 — no host sync. Page capacity for
        every live iteration is reserved by the engine BEFORE dispatch
        (`rem` is pre-clamped to the reserved window), so in-scan write
        indices never leave the request's own pages. Returns
        (emitted [k, S] int32, new_kv, new_scales) — the key passes
        through the donated pytree untouched (sampling folds per-row
        (stream, position) into it instead of splitting, which is what
        makes the draw window-size-invariant).

        lag/frontier (speculative draft PROPOSE mode — both [S] or
        both None): a row with lag 1 starts the scan ONE position
        early at pos0-1 — `tok0` then carries the token AT pos0-1 —
        so its missing draft-KV row (the previous window's k-th
        accepted token, which the propose scan never wrote) is
        replayed inside this same dispatch instead of costing a
        separate catch-up tick; iteration 0's carry is FORCED to
        `frontier` (the already-known token at pos0) for lag rows, so
        the later proposals condition on the true sequence, not on
        the draft's guess of a token the engine already holds.

        gstate0/gtrans/gmask (structured decoding — all three or
        none): gstate0 [S] int32 arena-absolute grammar DFA states,
        gtrans [G, vocab] int32 / gmask [G, ceil(vocab/32)] uint32 the
        engine's grammar-arena tables. The DFA state rides the scan
        carry like the token does: each iteration masks the live rows'
        logits through `grammar_allowed` BEFORE sampling and advances
        `gs2 = gtrans[gs, nxt]`. Arena row 0 is the mask identity, so
        unconstrained rows sample bit-identically — and a whole-window
        `lax.cond` on `any(gstate0 > 0)` skips the gather/expand
        entirely when no constrained row is resident (same discipline
        as the all-greedy fast path in `sample_tokens`). The tables
        are plain arguments at engine-static shapes: grammar churn is
        a value swap, never a retrace."""
        import jax
        import jax.numpy as jnp

        from ...tensor_core import Tensor

        S = tok0.shape[0]
        sl = jnp.arange(S, dtype=jnp.int32)
        pt = jnp.asarray(page_tables, jnp.int32)
        start = pos0 if lag is None else pos0 - lag
        klen0 = start + 1
        pad = jnp.asarray(-1, jnp.int32)
        structured = gtrans is not None
        if structured:
            any_g = jnp.any(gstate0 > 0)

        def t(v):
            return Tensor(v, stop_gradient=True)

        def body(carry, i):
            if structured:
                tok, fin, gs, kv_c, kvs_c = carry
            else:
                tok, fin, kv_c, kvs_c = carry
            live = ~fin
            tok_in = jnp.where(live, tok, 0)
            pos_in = jnp.where(live, start + i, 0)
            klen = jnp.where(live, klen0, 0)  # + i rides the offset
            page = pt[sl, pos_in // page_size]
            widx = jnp.where(live,
                             page * page_size + pos_in % page_size, 0)
            out = self._paged_decode_core(
                t(tok_in), t(pos_in), t(sl), t(widx), t(pt), t(klen),
                t(sl), [t(v) for v in kv_c],
                kv_scales=([t(s) for s in kvs_c] if kvs_c else None),
                frontier_offset=t(i))
            logits, *new = out
            n = len(kv_c)
            kv2 = [x._value for x in new[:n]]
            kvs2 = [x._value for x in new[n:]]
            lv = logits._value[0].astype(jnp.float32)  # [S, vocab]
            allowed = None
            if structured:
                V = lv.shape[1]
                allowed = jax.lax.cond(
                    any_g,
                    lambda s: grammar_allowed(gmask, s, V),
                    lambda s: jnp.ones((S, V), jnp.bool_), gs)
            nxt = sample_tokens(lv, temps, top_ps, streams, pos_in + 1,
                                key, allowed=allowed)
            if lag is not None:
                # propose mode: a lag row's iteration-0 output IS the
                # already-known frontier token — force it so later
                # proposals condition on the true sequence
                nxt = jnp.where((i == 0) & (lag > 0), frontier, nxt)
            emit = jnp.where(live, nxt, pad)
            fin2 = (fin | (live & (eos_ids >= 0) & (nxt == eos_ids))
                    | (live & (i + 1 >= rem)))
            tok2 = jnp.where(live, nxt, tok)
            if structured:
                gs2 = jnp.where(live, gtrans[gs, nxt], gs)
                return (tok2, fin2, gs2, kv2, kvs2), emit
            return (tok2, fin2, kv2, kvs2), emit

        init = ((tok0, fin0, gstate0, list(kv), list(kv_scales or []))
                if structured
                else (tok0, fin0, list(kv), list(kv_scales or [])))
        carry_f, emits = jax.lax.scan(
            body, init, jnp.arange(int(k), dtype=jnp.int32))
        kv_f, kvs_f = carry_f[-2], carry_f[-1]
        return emits, kv_f, kvs_f

    def _paged_verify_fused(self, k, page_size, tok0, pos0, drafts,
                            width, rem, fin0, eos_ids, temps, top_ps,
                            streams, page_tables, kv, kv_scales, key,
                            gstate0=None, gtrans=None, gmask=None):
        """Speculative-decoding verify: score ALL k+1 positions of every
        slot — the real frontier token plus k draft proposals — in ONE
        ragged batched step, then accept the longest prefix of drafts
        that matches the target model's own keyed picks
        (inference/speculative.py has the window orchestration;
        docs/SERVING.md "Speculative decoding" the contract).

        Lossless by construction: `sample_tokens` keys every draw on
        (engine seed, stream, position) only, so the target pick at a
        position is a deterministic function of the accepted prefix —
        greedy AND sampled outputs are token-identical to the
        non-speculative engine, and invariant to spec_k. Acceptance is
        therefore exact-match against the target pick (for greedy rows
        that IS longest-prefix argmax match; for sampled rows the
        rejection test degenerates to equality because the keyed
        categorical draw is the target sample itself — couple the draft
        to the same key and agreement is high whenever the
        distributions are close).

        Raw jax values in and out (the jit wrapper in speculative.py
        owns the Tensor boundary): tok0/pos0 [S] int32 (frontier token
        + its write position), drafts [S, k] int32 (draft proposals —
        entries at or past `width` are ignored), width [S] int32
        (drafts actually processed this window: positions
        pos0+1..pos0+width get KV written; pre-clamped by the engine to
        the reserved pages), rem [S] int32 (emit budget: at most this
        many tokens may be emitted), fin0 [S] bool (True = dead slot),
        eos_ids/temps/top_ps/streams [S], page_tables [S, MP], kv /
        kv_scales the pool pytree, key the engine PRNG key (passes
        through untouched — same contract as the fused scan).

        Flat layout is slot-major [S*(k+1)]: row s*(k+1)+j carries the
        token at position pos0[s]+j with kv_len pos0[s]+j+1, so ragged
        paged attention lets every draft attend to the earlier drafts
        written in this same dispatch and never to later ones. Invalid
        rows (dead slots, j > width) write the trash page at kv_len 0.
        Rejected-draft KV rows stay in the pool as stale garbage past
        the accepted frontier — never attended (kv_len masks them) and
        overwritten by position when the real tokens arrive: rollback
        is positional, no cleanup pass (the draft pool relies on the
        same property — tests pin it).

        gstate0/gtrans/gmask (structured decoding — all three or
        none): same arena tables the fused scan threads. The k+1
        per-position DFA states are chained HYPOTHETICALLY through the
        draft tokens (`st_{j+1} = gtrans[st_j, drafts[:, j]]` — a
        static k-step chain, no scan) and each flat row's logits are
        masked through `grammar_allowed` before the keyed pick.
        Lossless composition falls out: up to the first rejected
        draft the hypothetical states ARE the true states, so every
        accepted pick saw exactly the mask the non-speculative fused
        scan would have applied; states past the first mismatch are
        garbage but their picks are never emitted (acceptance is the
        exact-match prefix). Arena row 0 keeps unconstrained rows
        bit-identical, and the whole-window `lax.cond` on
        `any(gstate0 > 0)` skips the expansion when no constrained
        row is resident.

        Returns (emits [k+1, S] int32, new_kv, new_scales): column s
        holds the accepted target picks — between 1 and k+1 tokens —
        then -1 padding; EOS and budget masking applied in-executable
        (the emitted eos is kept, nothing after it)."""
        import jax
        import jax.numpy as jnp

        from ...tensor_core import Tensor

        S = tok0.shape[0]
        Q = int(k) + 1
        T = S * Q
        live = ~fin0
        j = jnp.arange(Q, dtype=jnp.int32)
        pt = jnp.asarray(page_tables, jnp.int32)
        drafts = drafts.astype(jnp.int32)
        tok_mat = jnp.concatenate([tok0[:, None], drafts], axis=1)
        valid = live[:, None] & (j[None, :] <= width[:, None])  # [S, Q]
        pos_mat = pos0[:, None] + j[None, :]
        sid = jnp.repeat(jnp.arange(S, dtype=jnp.int32), Q)
        tokf = jnp.where(valid, tok_mat, 0).reshape(T)
        posf = jnp.where(valid, pos_mat, 0).reshape(T)
        validf = valid.reshape(T)
        page = pt[sid, posf // page_size]
        widx = jnp.where(validf,
                         page * page_size + posf % page_size, 0)
        klen = jnp.where(validf, posf + 1, 0)

        def t(v):
            return Tensor(v, stop_gradient=True)

        out = self._paged_decode_core(
            t(tokf), t(posf), t(sid), t(widx), t(pt), t(klen),
            t(jnp.arange(T, dtype=jnp.int32)), [t(v) for v in kv],
            kv_scales=([t(s) for s in kv_scales] if kv_scales
                       else None),
            max_q_per_slot=Q)
        logits, *new = out
        n = len(kv)
        kv2 = [x._value for x in new[:n]]
        kvs2 = [x._value for x in new[n:]]
        lv = logits._value[0].astype(jnp.float32)       # [T, vocab]
        allowed = None
        if gtrans is not None:
            # hypothetical DFA state per (slot, position): chain the
            # draft tokens through the arena table (static k steps)
            sts = [gstate0]
            for jj in range(int(k)):
                sts.append(gtrans[sts[-1], drafts[:, jj]])
            st_flat = jnp.stack(sts, axis=1).reshape(T)
            V = lv.shape[1]
            allowed = jax.lax.cond(
                jnp.any(gstate0 > 0),
                lambda s: grammar_allowed(gmask, s, V),
                lambda s: jnp.ones((T, V), jnp.bool_), st_flat)
        picks = sample_tokens(
            lv, jnp.repeat(temps, Q), jnp.repeat(top_ps, Q),
            jnp.repeat(streams, Q), posf + 1, key,
            allowed=allowed).reshape(S, Q)
        # longest matching draft prefix, clamped to the window width
        match = (drafts == picks[:, :k]) & (
            jnp.arange(int(k), dtype=jnp.int32)[None, :]
            < width[:, None])
        acc = jnp.cumprod(match.astype(jnp.int32), axis=1)
        a = jnp.sum(acc, axis=1)                        # [S] accepted
        n_emit = jnp.where(live, jnp.minimum(a + 1, rem), 0)
        # in-executable EOS masking: the emitted eos is kept, every
        # later pick in the window is suppressed (exclusive cumsum)
        is_eos = ((eos_ids[:, None] >= 0)
                  & (picks == eos_ids[:, None])).astype(jnp.int32)
        eos_before = jnp.cumsum(is_eos, axis=1) - is_eos
        emit_mask = (j[None, :] < n_emit[:, None]) & (eos_before == 0)
        emits = jnp.where(emit_mask, picks,
                          jnp.asarray(-1, jnp.int32))
        return jnp.swapaxes(emits, 0, 1), kv2, kvs2     # [Q, S]

    def generate(self, input_ids, max_new_tokens=32, temperature=1.0,
                 top_k=None, do_sample=False, attention_mask=None,
                 eos_token_id=None, pad_token_id=None):
        """input_ids [b, prompt] → [b, min(prompt + max_new_tokens,
        max_seq_len)].

        attention_mask: optional [b, prompt] keep-mask for RAGGED
        prompts, LEFT-padded (zeros first — every row's last prompt
        token sits at the same column, so one uniform decode loop
        serves the whole batch); pad columns are masked out of
        attention and position ids start after the pads.

        eos_token_id: optional early-stop contract (shared with the
        continuous-batching engine, inference/llm_engine.py): a row
        that GENERATES eos is finished — it emits `pad_token_id`
        (default: eos_token_id) for every later step instead of fresh
        tokens, and the loop exits as soon as every row is finished, so
        the result can be shorter than max_new_tokens. Prompt tokens
        never count as eos. NOTE: the all-finished check syncs one bool
        per step, trading the decode loop's async dispatch for early
        exit — only pay it when stopping is actually wanted.
        """
        import jax
        import jax.numpy as jnp
        import numpy as np

        from ... import to_tensor
        from ...autograd import no_grad
        from ...core import rng as rng_mod
        from ...tensor_core import Tensor

        cfg = self.config
        b, prompt = int(input_ids.shape[0]), int(input_ids.shape[1])
        pad_lens = None
        if attention_mask is not None:
            mask_np = np.asarray(attention_mask._value if isinstance(
                attention_mask, Tensor) else attention_mask)
            if mask_np.shape != (b, prompt):
                raise ValueError(
                    f"attention_mask shape {mask_np.shape} != "
                    f"{(b, prompt)}")
            pads_np = (mask_np == 0).sum(axis=1)
            # generate() is a host loop, so left-contiguity is checkable
            # eagerly — reject ambiguous (non-left-padded) masks
            expect = (np.arange(prompt)[None, :] >= pads_np[:, None])
            if not np.array_equal(mask_np != 0, expect):
                raise ValueError(
                    "generate() requires LEFT-padded prompts: "
                    "attention_mask must be 0s followed by 1s per row")
            if (pads_np >= prompt).any():
                raise ValueError(
                    "attention_mask has an all-zero row (empty prompt): "
                    "every example needs at least one real token")
            if pads_np.any():
                pad_lens = to_tensor(pads_np.astype(np.int32))
        if prompt > cfg.max_seq_len:
            raise ValueError(
                f"prompt length {prompt} exceeds max_seq_len "
                f"{cfg.max_seq_len}")
        total = min(prompt + max_new_tokens, cfg.max_seq_len)
        if total <= prompt:  # no budget: nothing to generate
            return Tensor(input_ids._value.astype(jnp.int64),
                          stop_gradient=True)
        # bucket the cache length so different max_new_tokens reuse the
        # SAME compiled decode program (each distinct shape is a fresh
        # XLA compile)
        cache_len = min(-(-total // 128) * 128, cfg.max_seq_len)
        nh, hd = cfg.num_heads, cfg.hidden_size // cfg.num_heads

        def pick(logits_row):
            lv = logits_row._value[:, -1, :].astype(jnp.float32)
            if not do_sample or temperature == 0:
                return jnp.argmax(lv, axis=-1)
            lv = lv / max(temperature, 1e-6)
            if top_k is not None:
                k_eff = min(int(top_k), lv.shape[-1])
                kth = jnp.sort(lv, axis=-1)[:, -k_eff][:, None]
                lv = jnp.where(lv < kth, -1e30, lv)
            return jax.random.categorical(rng_mod.next_key(), lv, axis=-1)

        with no_grad():
            # cache in the model's compute dtype: decode is HBM-bound,
            # an fp32 cache for a bf16 model doubles the traffic
            cache_dt = self.gpt.wte.weight._value.dtype
            flat_kv = []
            for _ in range(cfg.num_layers):
                flat_kv += [
                    to_tensor(jnp.zeros((b, cache_len, nh, hd),
                                        cache_dt)),
                    to_tensor(jnp.zeros((b, cache_len, nh, hd),
                                        cache_dt))]
            step = self._make_step(padded=pad_lens is not None)

            def run_step(tok_t, idx_t, kv):
                if pad_lens is not None:
                    return step(tok_t, idx_t, pad_lens, *kv)
                return step(tok_t, idx_t, *kv)

            finished = None
            if eos_token_id is not None:
                pad_id = (eos_token_id if pad_token_id is None
                          else pad_token_id)
                finished = jnp.zeros((b,), bool)

            def stop_update(tok):
                # finished rows emit pad; a fresh eos marks its row
                # finished (the emitted eos itself is kept)
                nonlocal finished
                if finished is None:
                    return tok
                tok = jnp.where(finished,
                                jnp.asarray(pad_id, tok.dtype), tok)
                finished = finished | (tok == eos_token_id)
                return tok

            idx0 = to_tensor(jnp.asarray(0, jnp.int32))
            logits, *flat_kv = run_step(input_ids, idx0, flat_kv)
            out = [input_ids._value.astype(jnp.int64)]
            tok = stop_update(pick(logits))
            out.append(tok[:, None].astype(jnp.int64))
            for t in range(1, total - prompt):
                if finished is not None and bool(finished.all()):
                    break  # every row hit eos: stop early
                step_idx = to_tensor(jnp.asarray(prompt + t - 1, jnp.int32))
                logits, *flat_kv = run_step(
                    Tensor(tok[:, None], stop_gradient=True), step_idx,
                    flat_kv)
                tok = stop_update(pick(logits))
                out.append(tok[:, None].astype(jnp.int64))
        return Tensor(jnp.concatenate(out, axis=1), stop_gradient=True)



class GPTForCausalLM(GPTGenerationMixin, nn.Layer):
    """LM head tied to the (vocab-sharded) embedding by default."""

    def __init__(self, config):
        super().__init__()
        self.config = config
        self.gpt = GPTModel(config)
        if config.tie_embeddings:
            self.lm_head = None
        else:
            self.lm_head = ColumnParallelLinear(
                config.hidden_size, config.vocab_size, has_bias=False,
                gather_output=False)

    def _logits_from_hidden(self, x, shard=True):
        """ONE head projection shared by training forward and cached
        decode (shard hints only matter on a mesh)."""
        if self.lm_head is not None:
            return self.lm_head(x)
        w = self.gpt.wte.weight  # [vocab, d], mp-sharded on vocab
        logits = F.linear(x, manip.transpose(w, [1, 0]))
        if shard:
            logits = shard_activation(logits, "dp", "sp", "mp")
        return logits

    def forward(self, input_ids):
        return self._logits_from_hidden(self.gpt(input_ids))

    def fused_head_loss(self, input_ids, labels=None, block_size=4096):
        """Shifted next-token loss with the head projection and softmax-CE
        fused (F.fused_linear_cross_entropy): the [b, s, vocab] logits are
        never materialized in HBM — the dominant activation slab of the
        step (docs/PERF_NOTES.md hypothesis 1). Single-chip / dp / sp
        path; vocab-sharded TP training should keep forward() +
        ParallelCrossEntropy (the vocab-parallel reduction lives there).
        """
        from ...distributed import mesh as mesh_mod

        if mesh_mod.has_mesh() and mesh_mod.axis_size("mp") > 1:
            raise ValueError(
                "fused_head_loss computes softmax over the FULL vocab; "
                "with mp>1 the tied head weight is vocab-sharded and the "
                "result would be silently wrong. Use forward() + "
                "GPTPretrainingCriterion (ParallelCrossEntropy) under TP.")
        if labels is None:
            labels = input_ids
        x = self.gpt(input_ids)  # [b, s, d]
        shift_x = manip.slice(x, [1], [0], [x.shape[1] - 1])
        shift_labels = manip.slice(labels, [1], [1], [labels.shape[1]])
        # sum/total-count, NOT mean-over-valid: GPTPretrainingCriterion
        # means over ALL positions (ignored ones contribute 0), and the
        # two paths must stay loss- and grad-scale identical for the
        # BENCH_GPT_FUSED_HEAD A/B to be meaningful
        total = shift_labels.shape[0] * shift_labels.shape[1]
        if self.lm_head is not None:
            s = F.fused_linear_cross_entropy(
                shift_x, self.lm_head.weight, shift_labels,
                reduction="sum", block_size=block_size)
        else:
            s = F.fused_linear_cross_entropy(
                shift_x, self.gpt.wte.weight, shift_labels,
                transpose_weight=True, reduction="sum",
                block_size=block_size)
        return s / float(total)


class GPTPretrainingCriterion(nn.Layer):
    """Shifted next-token vocab-parallel cross entropy."""

    def __init__(self):
        super().__init__()
        self.ce = ParallelCrossEntropy()

    def forward(self, logits, labels):
        from ...ops.math import mean

        shift_logits = manip.slice(
            logits, [1], [0], [logits.shape[1] - 1])
        shift_labels = manip.slice(labels, [1], [1], [labels.shape[1]])
        loss = self.ce(shift_logits, shift_labels)
        return mean(loss)


