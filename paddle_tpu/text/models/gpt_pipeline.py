"""Pipeline-parallel GPT: heterogeneous embedding/head stages + uniform
decoder stack on the 1F1B SPMD schedule, with Megatron tensor parallelism
COMPOSED INSIDE each stage (the reference's hybrid TP+PP+DP flagship).

(reference: fleet/meta_parallel/parallel_layers/pp_layers.py — GPT built as
PipelineLayer([SharedLayerDesc(embedding), LayerDesc(decoder)×L,
SharedLayerDesc(head)]) and run by pipeline_parallel.py:105's 1F1B with
ColumnParallel/RowParallel mpu layers inside each LayerDesc
(fleet/layers/mpu/mp_layers.py:155/:293) and ParallelCrossEntropy
(mp_layers.py:438) on the last stage. Here the same decomposition maps onto
pipeline_1f1b: embedding runs in the outer program (its grad arrives
through the pipeline's input cotangents), the L decoder layers live as
STACKED parameters [L, ...] sharded over 'pp' — and, per-leaf, over 'mp'
in the Megatron column/row pattern — and the tied head + final LN ride as
post_params (head weight vocab-sharded over 'mp') into the last stage's
loss. Weight tying needs no shared-weight allreduce: the two grad paths
meet in outer autodiff. Tensor-parallel collectives inside the stage body
are the explicit custom_vjp pairs from mp_ops.py (identity/allreduce —
reference mpu/mp_ops.py `_c_identity`/`_mp_allreduce`); data parallelism
shards the within-micro batch dim and pmeans grads — all in ONE compiled
SPMD program over the (dp, pp, mp) mesh.)

QKV weight layout is HEAD-MAJOR: the fused qkv matmul's output columns are
ordered [head, (q|k|v), head_dim] so a contiguous 'mp' shard of the column
dim is a whole number of heads with their q, k AND v — the same per-head
partitioning Megatron uses. ([q-block, k-block, v-block] column order would
make an mp shard slice across the q/k/v boundary.)
"""
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ... import nn
from ...ops._helpers import apply_jfn
from ...distributed import mesh as mesh_mod
from ...distributed.fleet.meta_parallel.pipeline_1f1b import (
    PipelineSpecs, pipeline_1f1b)
from ...distributed.fleet.meta_parallel.mp_ops import (
    allreduce_mp, copy_to_mp)
from .gpt import GPTConfig

__all__ = ["PipelinedGPTForCausalLM"]


def _layernorm(x, w, b, eps=1e-5):
    mu = x.mean(-1, keepdims=True)
    var = ((x - mu) ** 2).mean(-1, keepdims=True)
    return (x - mu) * jax.lax.rsqrt(var + eps) * w + b


def _attention(q, k, v):
    """Causal attention [b, s, h, hd]; Pallas kernel when eligible, else
    the shared dense formulation from nn/functional/attention.py."""
    from ...nn.functional.attention import (_pallas_eligible,
                                            dense_attention_bshd)

    if _pallas_eligible(q, k):
        from ...ops.pallas_kernels.flash_attention import (
            flash_attention_bshd)

        return flash_attention_bshd(q, k, v, causal=True)
    return dense_attention_bshd(q, k, v, is_causal=True)


def _decoder_fwd(p, x, nh, mp=1, sp=1, ep=1, moe_cf=1.25, dp=1,
                 moe_topk=1):
    """One pre-LN decoder block as a pure function of its param dict.
    Returns (x, aux) — aux is the MoE load-balancing term (0.0 for the
    dense FFN), pre-scaled by 1/sp so the pipeline's sum_axes psum
    yields the mean over sequence shards.

    With mp > 1 the dict's leaves are the LOCAL Megatron shards (qkv/fc1
    column-sharded, proj/fc2 row-sharded, LN + output biases replicated)
    and the body brackets each parallel pair with the explicit
    identity/allreduce custom_vjp collectives. At mp == 1 the collectives
    are no-ops over a size-1 axis (outside shard_map they must not run at
    all, so the mp==1 call skips them entirely — same math). With sp > 1
    the SEQUENCE dim is sharded over 'sp' and attention runs as a
    causal RING over the K/V shards (sequence_parallel.ring_attention);
    LN and the MLP are per-token, so only attention needs the ring.
    """
    b, s, d = x.shape
    nh_loc = nh // mp
    hd = d // nh
    ident = (lambda t: t) if mp == 1 else copy_to_mp
    reduce_ = (lambda t: t) if mp == 1 else allreduce_mp

    h = _layernorm(x, p["ln1_w"], p["ln1_b"])
    qkv = ident(h) @ p["qkv_w"] + p["qkv_b"]       # [b, s, 3·d/mp]
    qkv = qkv.reshape(b, s, nh_loc, 3, hd)          # head-major layout
    q, k, v = qkv[:, :, :, 0], qkv[:, :, :, 1], qkv[:, :, :, 2]
    if sp > 1:
        from ...nn.functional.attention import _pallas_eligible
        from ...distributed.sequence_parallel import (
            ring_attention, ring_flash_attention)

        if _pallas_eligible(q, k):
            # flash kernel per K/V shard + causal block skip (TPU);
            # the dense ring stays the CPU/test path
            attn = ring_flash_attention(q, k, v, causal=True,
                                        axis_name="sp")
        else:
            attn = ring_attention(q, k, v, causal=True, axis_name="sp")
    else:
        attn = _attention(q, k, v)
    attn = attn.reshape(b, s, nh_loc * hd)
    x = x + reduce_(attn @ p["proj_w"]) + p["proj_b"]
    h = _layernorm(x, p["ln2_w"], p["ln2_b"])
    if "gate_w" in p:   # MoE FFN (experts sharded over 'ep')
        out, aux = _moe_ffn(p, h, p["gate_w"].shape[-1], ep, moe_cf,
                            dp=dp, sp=sp, topk=moe_topk)
        # aux is the GLOBAL-batch value on every rank; 1/sp makes the
        # pipeline's sum_axes psum recover it (the pmean over dp is a
        # no-op on a replicated value)
        return x + out, aux / sp
    part = jax.nn.gelu(ident(h) @ p["fc1_w"] + p["fc1_b"]) @ p["fc2_w"]
    return x + reduce_(part) + p["fc2_b"], jnp.zeros([], jnp.float32)


def _moe_ffn(p, h, n_experts, ep, cf=1.25, dp=1, sp=1, topk=1):
    """Top-k MoE feed-forward (topk=1 switch, topk=2 the reference
    GShardGate default) with experts sharded over 'ep' and
    TOKEN-SHARDED all-to-all dispatch (reference incubate
    moe_layer.py:244 MoEScatter/MoEGather over global_scatter_op.cc /
    global_gather_op.cc). Each ep rank takes a 1/ep slice of this
    shard's tokens, capacity-buckets them locally (GShard grouped
    capacity), exchanges buckets with `lax.all_to_all`, runs only its
    E/ep resident experts, and all-gathers the combined outputs —
    per-rank dispatch traffic and routing FLOPs are O(tokens/ep). Gate
    statistics for the returned load-balancing aux term are psum'd over
    'ep', so aux matches the full-local-batch (serial) value exactly.

    Returns (out [b, s, d], aux scalar).

    Capacity note: overflow-dropping is per GROUP — each (dp, sp, ep)
    shard's local token slice (the GShard formulation). With dp/sp/ep
    sharding the groups shrink vs the serial full-batch cumsum, so drop
    decisions can differ from serial once an expert overflows; with
    capacity_factor high enough that nothing drops, parity is exact.
    """
    b, s, d = h.shape
    x = h.reshape(b * s, d)
    # gate statistics reduce over ALL token-sharding axes so the aux
    # term is the exact global-batch value (serial parity under ep×dp)
    stat_axes = tuple(n for n, sz in (("dp", dp), ("sp", sp), ("ep", ep))
                      if sz > 1)
    n_shards = dp * sp * ep

    def expert_fn(expert_in):   # [E_loc, ·, d], local expert shards
        hmid = jax.nn.gelu(
            jnp.einsum("ecd,edh->ech", expert_in, p["moe_w1"])
            + p["moe_b1"])
        return jnp.einsum("ech,ehd->ecd", hmid, p["moe_w2"]) + p["moe_b2"]

    if ep > 1:
        from ...distributed.moe import moe_a2a_dispatch_combine

        out, aux = moe_a2a_dispatch_combine(
            x, p["gate_w"], expert_fn, n_experts, ep,
            capacity_factor=cf, axis="ep", stat_axes=stat_axes,
            n_stat_shards=n_shards, topk=topk)
        return out.reshape(b, s, d), aux

    # ep == 1: dense local dispatch over this shard's whole token set
    from ...distributed.moe import (moe_a2a_capacity, switch_dispatch,
                                    topk_rounds)

    logits = x @ p["gate_w"]
    probs = jax.nn.softmax(logits.astype(jnp.float32), -1)
    capacity = moe_a2a_capacity(x.shape[0], 1, n_experts, cf * topk)
    me = probs.mean(axis=0)
    if stat_axes:
        me = allreduce_mp(me, stat_axes) / n_shards
    out = jnp.zeros_like(x)
    aux = jnp.zeros([], jnp.float32)
    for round_probs in topk_rounds(probs, topk):
        disp, top_p, onehot = switch_dispatch(round_probs, n_experts,
                                              capacity, x.dtype)
        ce = onehot.mean(axis=0)
        if stat_axes:
            ce = allreduce_mp(ce, stat_axes) / n_shards
        aux = aux + n_experts * jnp.sum(me * ce)
        expert_in = jnp.einsum("etc,td->ecd", disp, x)
        expert_out = expert_fn(expert_in)
        partial = jnp.einsum("etc,ecd->td", disp, expert_out)
        out = out + partial * top_p[:, None].astype(x.dtype)
    return out.reshape(b, s, d), aux


def _vocab_parallel_ce(sh, wte_loc, sl, mp):
    """Per-token CE over a vocab-sharded head: [N, d] @ [d, V/mp] local
    logits, LSE reduced across 'mp' (reference mp_layers.py:438
    ParallelCrossEntropy → c_softmax_with_cross_entropy_op: per-rank max /
    masked pick / two allreduces — same algorithm, psum via the explicit
    vjp pairs)."""
    logits = jnp.dot(copy_to_mp(sh), wte_loc.T,
                     preferred_element_type=jnp.float32)   # [N, V/mp]
    m = lax.pmax(lax.stop_gradient(jnp.max(logits, -1)), "mp")
    ssum = allreduce_mp(jnp.sum(jnp.exp(logits - m[:, None]), -1))
    lse = m + jnp.log(ssum)
    v_loc = logits.shape[-1]
    li = sl - lax.axis_index("mp") * v_loc
    hit = (li >= 0) & (li < v_loc)
    li_c = jnp.clip(li, 0, v_loc - 1)
    picked_loc = jnp.where(
        hit, jnp.take_along_axis(logits, li_c[:, None], -1)[:, 0], 0.0)
    return lse - allreduce_mp(picked_loc)


class PipelinedGPTForCausalLM(nn.Layer):
    """GPT whose decoder parameters are stacked [num_layers, ...] and
    sharded over the 'pp' mesh axis, with per-leaf 'mp' sharding in the
    Megatron pattern and optional dp sharding of the micro-batch.
    `forward` runs the serial scan (eval / single device); `loss(ids)`
    runs the 1F1B pipeline schedule over whatever (dp, pp, mp) mesh is
    active.

    MoE (`moe_experts > 0`): switch FFN with token-sharded all-to-all
    dispatch over 'ep'; `loss()` returns loss + moe_aux_weight·aux and
    stores the aux metric in `self.aux_loss`. Overflow-dropping is per
    (dp, sp, ep) token group: with the default moe_capacity_factor the
    dropped set depends on the mesh (standard GShard semantics); set
    moe_capacity_factor ≥ num_experts for lossless dispatch and exact
    serial parity. The aux term itself is always the global-batch value
    (gate statistics psum'd over every token-sharding axis)."""

    # dp-axis gradient all-reduce in block-scaled int8 (EQuARX in-XLA,
    # distributed.quant_collective): None = follow the
    # PT_QUANT_ALLREDUCE_XLA env; set True/False explicitly via
    # HybridTrainStep(quant_allreduce=...) / Hybrid3DConfig
    quant_allreduce = None

    def __init__(self, config: GPTConfig, n_micro=4, remat="stage",
                 n_virtual=1, moe_experts=0, moe_hidden=None,
                 moe_aux_weight=0.01, moe_capacity_factor=1.25,
                 moe_topk=1, schedule="1f1b"):
        super().__init__()
        self.config = config
        self.n_micro = n_micro
        # schedule: "1f1b" (lockstep, O(pp) activations — default) or
        # "gpipe" (all-forward-then-all-backward serialized halves,
        # O(M) activations; distributed.hybrid3d.schedule). Both share
        # PipelineSpecs, so tp/dp/sp composition is identical.
        if schedule not in ("1f1b", "gpipe"):
            raise ValueError(
                f"schedule={schedule!r}: expected '1f1b' or 'gpipe'")
        if schedule == "gpipe" and n_virtual != 1:
            raise ValueError(
                "interleaved virtual stages are a 1F1B refinement; "
                "gpipe runs n_virtual=1")
        self.schedule = schedule
        # moe_experts > 0: the dense FFN becomes a switch (top-1) MoE
        # with experts sharded over the 'ep' mesh axis and token-sharded
        # all-to-all dispatch (see _moe_ffn). The load-balancing aux
        # term rides the 1F1B aux channel: loss() returns
        # loss + moe_aux_weight·aux and stores the aux value in
        # self.aux_loss (reference moe gates always train with it).
        # Overflow-dropping is per (dp, sp, ep) token group —
        # capacity_factor ≥ num_experts makes dispatch lossless.
        self.moe_experts = int(moe_experts)
        self.moe_hidden = moe_hidden or config.ffn_size
        self.moe_aux_weight = float(moe_aux_weight)
        self.moe_capacity_factor = float(moe_capacity_factor)
        # moe_topk=2 is the reference GShardGate default; 1 = switch
        self.moe_topk = int(moe_topk)
        # aux metric rides a persistable buffer so the jitted TrainStep
        # surfaces it through the frozen-value channel (the same path BN
        # running stats take) — readable after each step as a concrete
        # value, never a leaked tracer
        self.register_buffer("aux_loss", jnp.zeros([], jnp.float32))
        # n_virtual > 1: tick-interleaved virtual stages — each device
        # owns n_virtual NON-contiguous chunks of the layer stack
        # (round-robin placement, reference PipelineParallelWithInterleave)
        if not isinstance(n_virtual, int) or n_virtual < 1:
            raise ValueError(
                f"n_virtual={n_virtual!r}: expected an int >= 1")
        self.n_virtual = n_virtual
        # remat: "stage" = 1F1B ring buffer keeps only stage INPUTS and
        # re-linearizes the whole stage per backward tick (default);
        # "layer" = jax.checkpoint around every decoder layer inside the
        # stage scan (the reference's per-layer recompute —
        # distributed/fleet/utils/recompute.py); False = keep everything.
        if remat is True:
            remat = "stage"
        if remat not in ("stage", "layer", False):
            raise ValueError(
                f"remat={remat!r}: expected 'stage', 'layer', or False")
        self.remat = remat
        d, L, ffn = config.hidden_size, config.num_layers, config.ffn_size
        mk = self.create_parameter
        normal = nn.initializer.Normal(0.0, 0.02)
        self.wte = mk([config.vocab_size, d], default_initializer=normal)
        self.wpe = mk([config.max_seq_len, d], default_initializer=normal)
        from ...distributed.fleet.meta_parallel.mp_layers import (
            mark_sharding)

        mark_sharding(self.wte, "mp", None)   # vocab-sharded head/embed
        # stacked decoder params, leading dim = num_layers (sharded 'pp');
        # Megatron 'mp' sharding per leaf: qkv/fc1 column (last dim),
        # proj/fc2 row (middle dim), LN + output biases replicated.
        self._stack_names = []
        self._stack_specs = {}
        ones = nn.initializer.Constant(1.0)

        def stacked(name, shape, is_bias=False, init=None, mp_dim=None,
                    ep_dim=None):
            p = mk([L] + shape, is_bias=is_bias,
                   default_initializer=init or (
                       nn.initializer.Constant(0.0) if is_bias else normal))
            spec = ["pp"] + [None] * len(shape)
            if mp_dim is not None:
                spec[1 + mp_dim] = "mp"
            if ep_dim is not None:
                spec[1 + ep_dim] = "ep"
            mark_sharding(p, *spec)
            self._stack_specs[name] = P(*spec)
            setattr(self, "stk_" + name, p)
            self._stack_names.append(name)
            return p

        stacked("ln1_w", [d], init=ones); stacked("ln1_b", [d], True)
        stacked("qkv_w", [d, 3 * d], mp_dim=1)
        stacked("qkv_b", [3 * d], True, mp_dim=0)
        stacked("proj_w", [d, d], mp_dim=0); stacked("proj_b", [d], True)
        stacked("ln2_w", [d], init=ones); stacked("ln2_b", [d], True)
        if self.moe_experts:
            E, dh = self.moe_experts, self.moe_hidden
            stacked("gate_w", [d, E])
            stacked("moe_w1", [E, d, dh], ep_dim=0)
            stacked("moe_b1", [E, 1, dh], True, ep_dim=0)
            stacked("moe_w2", [E, dh, d], ep_dim=0)
            stacked("moe_b2", [E, 1, d], True, ep_dim=0)
        else:
            stacked("fc1_w", [d, ffn], mp_dim=1)
            stacked("fc1_b", [ffn], True, mp_dim=0)
            stacked("fc2_w", [ffn, d], mp_dim=0)
            stacked("fc2_b", [d], True)
        self.lnf_w = mk([d], default_initializer=ones)
        self.lnf_b = mk([d], is_bias=True)

    def shard_storage(self):
        """ZeRO-style parameter storage sharding composed with the
        pipeline (reference: GroupSharded stage-3 param sharding,
        fleet/meta_parallel/sharding/group_sharded_stage3.py — composed
        with pp the way the reference composes sharding+pp in its
        hybrid configs). Each stacked weight (and the tied embedding)
        gains the `axis` mesh axis on a free divisible dim; the 1F1B
        shard_map's in_specs don't mention `axis`, so XLA all-gathers
        params at the boundary and reduce-scatters grads back — the
        optimizer then updates SHARDED storage (params + moments /axis).
        The axis is the mesh's 'sharding' axis (the shared
        `_zero_spec` policy). Call after construction, before the
        first step."""
        from ...distributed.fleet.meta_parallel.mp_layers import (
            mark_sharding)
        from ...distributed.parallel_step import _zero_spec

        if mesh_mod.axis_size("sharding") <= 1:
            return self
        # the ONE ZeRO placement policy (largest divisible free dim,
        # warning on forced replication) — shared with
        # DistributedTrainStep/shard_params_and_opt; each param's
        # existing _pspec (set by __init__'s mark_sharding) is the base
        for p in self._param_tensors():
            spec = _zero_spec(p._value, "p_g_os",
                              getattr(p, "_pspec", None))
            mark_sharding(p, *spec)
        return self

    # ---- pure pieces ----
    def _embed(self, wte, wpe, ids):
        return wte[ids] + wpe[jnp.arange(ids.shape[-1])]

    def _block_fn(self, mp, sp=1, ep=1, dp=1):
        nh = self.config.num_heads
        cf = self.moe_capacity_factor
        tk = self.moe_topk
        has_aux = bool(self.moe_experts)
        layer = lambda p, x: _decoder_fwd(p, x, nh, mp, sp, ep, cf, dp,
                                          tk)
        if self.remat == "layer":
            layer = jax.checkpoint(layer)

        def block(stage_params, x):
            def body(x, p):
                x2, aux = layer(p, x)
                return x2, aux

            out, auxs = jax.lax.scan(body, x, stage_params)
            if has_aux:
                # per-stage sum over this stage's layers; the pipeline's
                # pp-psum assembles the whole stack's aux
                return out, jnp.sum(auxs).astype(jnp.float32)
            return out

        return block

    def _loss_fn(self, mp, sp=1):
        def per_token(sh, sl, post):
            if mp == 1:
                # fused blocked head CE (nn/functional/loss.py
                # linear_ce_raw): never materializes [micro·s, vocab]
                # logits — the head vjp inside the 1F1B head-tick cond
                # stays memory-lean
                from ...nn.functional.loss import linear_ce_raw

                return linear_ce_raw(sh, post["wte"].T, sl)
            return _vocab_parallel_ce(sh, post["wte"], sl, mp)

        def loss_fn(y_pred, labels, post):
            h = _layernorm(y_pred, post["lnf_w"], post["lnf_b"])
            if sp == 1:
                sh = h[:, :-1].reshape(-1, h.shape[-1])
                sl = labels[:, 1:].reshape(-1)
                return jnp.mean(per_token(sh, sl, post))
            # sequence-sharded: labels arrive PRE-SHIFTED by the outer
            # program (position t carries token t+1; the globally-last
            # position carries -1). No collective here — a ppermute in
            # this head-gated branch would deadlock the other stages'
            # devices, which never enter it. Each shard returns a
            # PARTIAL of the global mean (masked_sum / global_valid),
            # summed by the pipeline's sum_axes=('sp',) psum.
            b, s_loc = labels.shape
            valid = (labels >= 0).astype(jnp.float32).reshape(-1)
            sl = jnp.clip(labels, 0, None).reshape(-1)
            tok = per_token(h.reshape(-1, h.shape[-1]), sl, post)
            n_valid_global = b * (s_loc * sp - 1)
            return jnp.sum(tok * valid) / n_valid_global

        return loss_fn

    def _param_tensors(self):
        stk = [getattr(self, "stk_" + n) for n in self._stack_names]
        return [self.wte, self.wpe, self.lnf_w, self.lnf_b] + stk

    def _hybrid_specs(self, mp, dp, micro_bsz, sp=1, ep=1):
        """PipelineSpecs for the active mesh (None when pure pp×replica).
        ep MUST be included: expert leaves carry 'ep' in their stored
        specs, and replicating them while _moe_ffn slices per rank
        would silently einsum-broadcast the size-1 expert dim into
        wrong math (caught by the MoE parity tests)."""
        if mp == 1 and dp == 1 and sp == 1 and ep == 1:
            return None
        names = self._stack_names
        stacked_tree = {n: self._stack_specs[n] for n in names}
        stacked = tuple(
            jax.tree_util.tree_leaves(
                stacked_tree, is_leaf=lambda s: isinstance(s, P)))
        post = {"lnf_b": P(None), "lnf_w": P(None),
                "wte": P("mp", None) if mp > 1 else P(None, None)}
        post = tuple(jax.tree_util.tree_leaves(
            post, is_leaf=lambda s: isinstance(s, P)))
        dp_axis = None
        seq = "sp" if sp > 1 else None
        x_spec = P(None, None, seq, None) if sp > 1 else None
        y_spec = P(None, None, seq) if sp > 1 else None
        if dp > 1:
            if micro_bsz % dp:
                # silent replication would burn dp× the FLOPs — match the
                # mp divisibility errors instead
                raise ValueError(
                    f"per-micro batch {micro_bsz} not divisible by "
                    f"dp={dp}; pick batch/n_micro so each dp shard gets "
                    "an equal slice")
            dp_axis = "dp"
            x_spec = P(None, "dp", seq, None)
            y_spec = P(None, "dp", seq)
        # quantized dp grad all-reduce: the model attribute is set by
        # HybridTrainStep(quant_allreduce=...)/Hybrid3DConfig; None
        # falls back to the PT_QUANT_ALLREDUCE_XLA env opt-in. Read at
        # TRACE time, so extract_schedule/collective_schedule see the
        # same program the step dispatches.
        quant = self.quant_allreduce
        if quant is None:
            from ...distributed.quant_collective import xla_quant_enabled

            quant = xla_quant_enabled()
        return PipelineSpecs(stacked=stacked, post=post, x=x_spec,
                             y=y_spec, dp_axis=dp_axis,
                             sum_axes=("sp",) if sp > 1 else None,
                             quant_dp=bool(quant) and dp_axis is not None)

    # ---- API ----
    def forward(self, input_ids):
        """Serial (non-pipelined) forward to logits — eval path."""
        tensors = self._param_tensors()
        names = self._stack_names
        nh = self.config.num_heads

        def jfn(wte, wpe, lnf_w, lnf_b, *stk):
            ids = input_ids._value
            x = self._embed(wte, wpe, ids)
            p = dict(zip(names, stk))

            def body(x, pl):
                x2, _aux = _decoder_fwd(pl, x, nh,
                                        moe_cf=self.moe_capacity_factor,
                                        moe_topk=self.moe_topk)
                return x2, None

            x, _ = jax.lax.scan(body, x, p)
            h = _layernorm(x, lnf_w, lnf_b)
            return h @ wte.T

        return apply_jfn("pipelined_gpt_forward", jfn, *tensors)

    def loss(self, input_ids, labels=None):
        """Mean LM loss via the 1F1B pipeline schedule (forward-only
        fill-drain when grad is disabled — eval loops skip the backward
        machinery). The global batch is split into `n_micro` micro-batches
        on axis 0; with an active 'mp'/'dp' mesh axis, tensor parallelism
        runs inside every stage and the within-micro batch dim is
        data-sharded — the hybrid TP+PP+DP program."""
        from ...autograd import engine
        from ...distributed.fleet.meta_parallel.pipeline_1f1b import (
            pipeline_forward_loss)

        mesh = mesh_mod.global_mesh()
        pp, mp, dp, sp = (mesh.shape["pp"], mesh.shape["mp"],
                          mesh.shape["dp"], mesh.shape["sp"])
        ep = mesh.shape["ep"] if self.moe_experts else 1
        if pp == 1:
            if sp > 1:
                # mp/dp fall back to GSPMD annotations on the degenerate
                # path, but nothing annotates the sequence dim — silent
                # sp-fold replication would burn sp× the FLOPs
                raise ValueError(
                    "sequence parallelism in PipelinedGPTForCausalLM "
                    "needs pp > 1 (use DistributedTrainStep with a "
                    "seq-sharded batch_specs for GSPMD-only sp)")
            mp = 1   # degenerate path runs outside shard_map: GSPMD
            dp = 1   # annotations (mark_sharding) cover mp/dp instead
            ep = 1
        cfg = self.config
        if mp > 1:
            dims = [(cfg.num_heads, "num_heads"),
                    (cfg.vocab_size, "vocab_size")]
            if not self.moe_experts:
                # the dense fc pair is mp-sharded; MoE experts are not
                dims.append((cfg.ffn_size, "ffn_size"))
            for dim, what in dims:
                if dim % mp:
                    raise ValueError(
                        f"{what}={dim} not divisible by mp={mp}")
        labels = input_ids if labels is None else labels
        if ep > 1 and self.moe_experts % ep:
            raise ValueError(
                f"moe_experts={self.moe_experts} not divisible by "
                f"ep={ep}")
        if sp > 1 and input_ids.shape[1] % sp:
            raise ValueError(
                f"sequence length {input_ids.shape[1]} not divisible by "
                f"sp={sp}")
        if ep > 1:
            # the a2a dispatch slices each shard's tokens into ep groups
            b_sh = input_ids.shape[0] // self.n_micro // max(dp, 1)
            toks = b_sh * (input_ids.shape[1] // max(sp, 1))
            if toks % ep:
                raise ValueError(
                    f"tokens per shard {toks} not divisible by ep={ep} "
                    "(adjust batch/n_micro/dp/sp so each ep group is "
                    "equal)")
        tensors = self._param_tensors()
        names = self._stack_names
        M = self.n_micro
        block_fn = self._block_fn(mp, sp, ep, dp)
        loss_fn = self._loss_fn(mp, sp)
        fwd_only = not engine.is_grad_enabled()

        V = self.n_virtual if pp > 1 else 1
        if V > 1 and self.config.num_layers % (pp * V):
            raise ValueError(
                f"num_layers={self.config.num_layers} not divisible by "
                f"pp*n_virtual={pp}*{V}")

        def jfn(wte, wpe, lnf_w, lnf_b, *stk):
            ids = input_ids._value
            lbl = labels._value
            if sp > 1:
                # pre-shift for the sequence-sharded loss: position t
                # carries token t+1, the last position carries -1
                # (masked). Done HERE, where the full sequence is in
                # one piece — inside the pipeline the shift would need
                # a cross-shard collective in a stage-gated branch.
                # NOTE: jnp.pad, NOT jnp.concatenate — on jax 0.4.x
                # XLA:CPU the spmd partitioner mis-shards a concatenate
                # result entering shard_map through a partial in_spec
                # (values arrive summed across the unmentioned mesh
                # axes: labels DOUBLED at pp=2, then OOB vocab indices
                # take_along_axis-fill as NaN — the whole-suite sp NaN).
                # Pad partitions correctly; pinned by
                # test_label_shift_survives_partial_shard_spec.
                lbl = jnp.pad(lbl[:, 1:], ((0, 0), (0, 1)),
                              constant_values=-1)
            B = ids.shape[0]
            assert B % M == 0, f"batch {B} not divisible by n_micro {M}"
            specs = self._hybrid_specs(mp, dp, B // M, sp, ep)
            ids_m = ids.reshape(M, B // M, ids.shape[1])
            lbl_m = lbl.reshape(M, B // M, lbl.shape[1])
            x_m = self._embed(wte, wpe, ids_m)
            stacked = dict(zip(names, stk))
            post = {"wte": wte, "lnf_w": lnf_w, "lnf_b": lnf_b}
            if V > 1:
                # round-robin chunking: [L, ...] → [pp·V, L/(pp·V), ...]
                # rows reordered so each stage's shard is its V chunks
                # (interleaved_stacking_order); grads flow back through
                # the gather+reshape via outer AD. Specs gain the chunk
                # dim after 'pp'.
                from ...distributed.fleet.meta_parallel.pipeline_1f1b \
                    import interleaved_stacking_order

                L = self.config.num_layers
                order = jnp.asarray(
                    interleaved_stacking_order(pp, V))
                stacked = {
                    n: a.reshape((pp * V, L // (pp * V)) + a.shape[1:])[
                        order]
                    for n, a in stacked.items()}
                if specs is not None:
                    specs = specs._replace(stacked=tuple(
                        P(*((s[0], None) + tuple(s[1:])))
                        for s in specs.stacked))
            aux_w = self.moe_aux_weight if self.moe_experts else None
            if fwd_only and V == 1:
                return pipeline_forward_loss(block_fn, loss_fn, stacked,
                                             post, (x_m, lbl_m),
                                             specs=specs,
                                             aux_weight=aux_w)
            # "layer" remat lives inside block_fn already — the schedule
            # must not double-checkpoint the stage (fwd_only with V > 1
            # also lands here: the fill-drain path has no virtual-stage
            # schedule, and the 1F1B loss is identical, just costlier)
            remat = self.remat == "stage"
            if self.schedule == "gpipe" and pp > 1 and not fwd_only:
                from ...distributed.hybrid3d.schedule import pipeline_gpipe

                return pipeline_gpipe(block_fn, loss_fn, stacked, post,
                                      (x_m, lbl_m), remat=remat,
                                      specs=specs, aux_weight=aux_w)
            return pipeline_1f1b(block_fn, loss_fn, stacked, post,
                                 (x_m, lbl_m), remat=remat,
                                 num_virtual=V, specs=specs,
                                 aux_weight=aux_w)

        if not self.moe_experts:
            return apply_jfn("pipelined_gpt_loss", jfn, *tensors)
        # MoE: the pipeline returns (loss + aux_weight·aux, aux); the
        # aux value is a detached metric surfaced as self.aux_loss
        # (reference MoELayer stores the gate's balance loss the same
        # way — moe_layer.py gates)
        total, aux = apply_jfn("pipelined_gpt_loss", jfn, *tensors)
        self.aux_loss._value = lax.stop_gradient(aux._value)
        return total
