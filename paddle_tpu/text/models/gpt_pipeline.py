"""Pipeline-parallel GPT: heterogeneous embedding/head stages + uniform
decoder stack on the 1F1B SPMD schedule.

(reference: fleet/meta_parallel/parallel_layers/pp_layers.py — GPT built as
PipelineLayer([SharedLayerDesc(embedding), LayerDesc(decoder)×L,
SharedLayerDesc(head)]) and run by pipeline_parallel.py's 1F1B. Here the
same decomposition maps onto pipeline_1f1b: embedding runs in the outer
program (its grad arrives through the pipeline's input cotangents), the L
decoder layers live as STACKED parameters [L, ...] sharded over 'pp', and
the tied head + final LN ride as post_params into the last stage's loss —
tying needs no shared-weight allreduce, the two grad paths meet in autodiff.)
"""
import math

import jax
import jax.numpy as jnp

from ... import nn
from ...ops._helpers import apply_jfn
from ...distributed.fleet.meta_parallel.pipeline_1f1b import pipeline_1f1b
from .gpt import GPTConfig

__all__ = ["PipelinedGPTForCausalLM"]


def _layernorm(x, w, b, eps=1e-5):
    mu = x.mean(-1, keepdims=True)
    var = ((x - mu) ** 2).mean(-1, keepdims=True)
    return (x - mu) * jax.lax.rsqrt(var + eps) * w + b


def _attention(q, k, v):
    """Causal attention [b, s, h, hd]; Pallas kernel when eligible, else
    the shared dense formulation from nn/functional/attention.py."""
    from ...nn.functional.attention import (_pallas_eligible,
                                            dense_attention_bshd)

    if _pallas_eligible(q, k):
        from ...ops.pallas_kernels.flash_attention import (
            flash_attention_bshd)

        return flash_attention_bshd(q, k, v, causal=True)
    return dense_attention_bshd(q, k, v, is_causal=True)


def _decoder_fwd(p, x, nh):
    """One pre-LN decoder block as a pure function of its param dict."""
    b, s, d = x.shape
    hd = d // nh
    h = _layernorm(x, p["ln1_w"], p["ln1_b"])
    qkv = h @ p["qkv_w"] + p["qkv_b"]
    qkv = qkv.reshape(b, s, 3, nh, hd)
    q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
    attn = _attention(q, k, v).reshape(b, s, d)
    x = x + attn @ p["proj_w"] + p["proj_b"]
    h = _layernorm(x, p["ln2_w"], p["ln2_b"])
    x = x + jax.nn.gelu(h @ p["fc1_w"] + p["fc1_b"]) @ p["fc2_w"] \
        + p["fc2_b"]
    return x


class PipelinedGPTForCausalLM(nn.Layer):
    """GPT whose decoder parameters are stacked [num_layers, ...] and
    sharded over the 'pp' mesh axis. `forward` runs the serial scan (eval /
    single device); `loss(ids)` runs the 1F1B pipeline schedule."""

    def __init__(self, config: GPTConfig, n_micro=4):
        super().__init__()
        self.config = config
        self.n_micro = n_micro
        d, L, ffn = config.hidden_size, config.num_layers, config.ffn_size
        mk = self.create_parameter
        normal = nn.initializer.Normal(0.0, 0.02)
        self.wte = mk([config.vocab_size, d], default_initializer=normal)
        self.wpe = mk([config.max_seq_len, d], default_initializer=normal)
        # stacked decoder params, leading dim = num_layers (sharded 'pp')
        from ...distributed.fleet.meta_parallel.mp_layers import (
            mark_sharding)

        self._stack_names = []
        ones = nn.initializer.Constant(1.0)

        def stacked(name, shape, is_bias=False, init=None):
            p = mk([L] + shape, is_bias=is_bias,
                   default_initializer=init or (
                       nn.initializer.Constant(0.0) if is_bias else normal))
            mark_sharding(p, "pp", *([None] * len(shape)))
            setattr(self, "stk_" + name, p)
            self._stack_names.append(name)
            return p

        stacked("ln1_w", [d], init=ones); stacked("ln1_b", [d], True)
        stacked("qkv_w", [d, 3 * d]); stacked("qkv_b", [3 * d], True)
        stacked("proj_w", [d, d]); stacked("proj_b", [d], True)
        stacked("ln2_w", [d], init=ones); stacked("ln2_b", [d], True)
        stacked("fc1_w", [d, ffn]); stacked("fc1_b", [ffn], True)
        stacked("fc2_w", [ffn, d]); stacked("fc2_b", [d], True)
        self.lnf_w = mk([d], default_initializer=ones)
        self.lnf_b = mk([d], is_bias=True)

    # ---- pure pieces ----
    def _embed(self, wte, wpe, ids):
        return wte[ids] + wpe[jnp.arange(ids.shape[-1])]

    def _block_fn(self, stage_params, x):
        nh = self.config.num_heads

        def body(x, p):
            return _decoder_fwd(p, x, nh), None

        out, _ = jax.lax.scan(body, x, stage_params)
        return out

    def _loss_fn(self, y_pred, labels, post):
        # fused blocked head CE (nn/functional/loss.py linear_ce_raw):
        # the last pipeline stage never materializes [micro, s, vocab]
        # logits or fp32 log-probs — the head vjp inside the 1F1B
        # head-tick cond stays memory-lean
        from ...nn.functional.loss import linear_ce_raw

        h = _layernorm(y_pred, post["lnf_w"], post["lnf_b"])
        sh = h[:, :-1].reshape(-1, h.shape[-1])
        sl = labels[:, 1:].reshape(-1)
        return jnp.mean(linear_ce_raw(sh, post["wte"].T, sl))

    def _param_tensors(self):
        stk = [getattr(self, "stk_" + n) for n in self._stack_names]
        return [self.wte, self.wpe, self.lnf_w, self.lnf_b] + stk

    # ---- API ----
    def forward(self, input_ids):
        """Serial (non-pipelined) forward to logits — eval path."""
        tensors = self._param_tensors()
        names = self._stack_names
        nh = self.config.num_heads

        def jfn(wte, wpe, lnf_w, lnf_b, *stk):
            ids = input_ids._value
            x = self._embed(wte, wpe, ids)
            p = dict(zip(names, stk))

            def body(x, pl):
                return _decoder_fwd(pl, x, nh), None

            x, _ = jax.lax.scan(body, x, p)
            h = _layernorm(x, lnf_w, lnf_b)
            return h @ wte.T

        return apply_jfn("pipelined_gpt_forward", jfn, *tensors)

    def loss(self, input_ids, labels=None):
        """Mean LM loss via the 1F1B pipeline schedule (forward-only
        fill-drain when grad is disabled — eval loops skip the backward
        machinery). The global batch is split into `n_micro` micro-batches
        on axis 0."""
        from ...autograd import engine
        from ...distributed.fleet.meta_parallel.pipeline_1f1b import (
            pipeline_forward_loss)

        labels = input_ids if labels is None else labels
        tensors = self._param_tensors()
        names = self._stack_names
        M = self.n_micro
        block_fn = self._block_fn
        loss_fn = self._loss_fn
        fwd_only = not engine.is_grad_enabled()

        def jfn(wte, wpe, lnf_w, lnf_b, *stk):
            ids = input_ids._value
            lbl = labels._value
            B = ids.shape[0]
            assert B % M == 0, f"batch {B} not divisible by n_micro {M}"
            ids_m = ids.reshape(M, B // M, ids.shape[1])
            lbl_m = lbl.reshape(M, B // M, lbl.shape[1])
            x_m = self._embed(wte, wpe, ids_m)
            stacked = dict(zip(names, stk))
            post = {"wte": wte, "lnf_w": lnf_w, "lnf_b": lnf_b}
            if fwd_only:
                return pipeline_forward_loss(block_fn, loss_fn, stacked,
                                             post, (x_m, lbl_m))
            return pipeline_1f1b(block_fn, loss_fn, stacked, post,
                                 (x_m, lbl_m))

        return apply_jfn("pipelined_gpt_loss", jfn, *tensors)
