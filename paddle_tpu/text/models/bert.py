"""BERT / ERNIE — bidirectional encoder pretraining family.

The reference trains ERNIE-3.0 (BERT-architecture encoder with
knowledge-style masking) through PaddleNLP on the fleet mpu layers;
BASELINE.md names ERNIE-3.0/BERT-base pretraining as a headline config.
Like gpt.py, ONE model definition runs serial/DP/TP/ZeRO — parallelism
comes from the GSPMD layers (fleet/layers/mpu/mp_layers.py analogs), not
the model code.

TPU-first choices mirror gpt.py: fused qkv ColumnParallelLinear,
attention via F.scaled_dot_product_attention (Pallas flash kernel when
eligible), MLM logits against the vocab-sharded embedding with
vocab-parallel softmax-CE (reference c_softmax_with_cross_entropy_op).
"""
from ... import nn
from ...distributed.fleet.meta_parallel.mp_layers import (
    ColumnParallelLinear,
    ParallelCrossEntropy,
    RowParallelLinear,
    VocabParallelEmbedding,
    shard_activation,
    split_fused_qkv,
)
from ...nn import functional as F
from ...ops import manipulation as manip

__all__ = [
    "BertConfig", "BertModel", "BertForPretraining",
    "BertPretrainingCriterion", "BertForSequenceClassification",
    "ErnieModel", "ErnieForPretraining", "bert_tiny", "bert_base",
    "ernie_3_base",
]


class BertConfig:
    def __init__(self, vocab_size=30528, hidden_size=768, num_layers=12,
                 num_heads=12, intermediate_size=None, max_position=512,
                 type_vocab_size=2, dropout=0.0, pool_act="tanh"):
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.num_heads = num_heads
        self.intermediate_size = intermediate_size or 4 * hidden_size
        self.max_position = max_position
        self.type_vocab_size = type_vocab_size
        self.dropout = dropout
        self.pool_act = pool_act


def bert_tiny(**kw):
    return BertConfig(vocab_size=1024, hidden_size=128, num_layers=2,
                      num_heads=4, max_position=128, **kw)


def bert_base(**kw):
    return BertConfig(vocab_size=30528, hidden_size=768, num_layers=12,
                      num_heads=12, max_position=512, **kw)


def ernie_3_base(**kw):
    """ERNIE-3.0-base shape (BERT-base-sized encoder, 40k vocab)."""
    return BertConfig(vocab_size=40000, hidden_size=768, num_layers=12,
                      num_heads=12, max_position=2048, **kw)


class BertEmbeddings(nn.Layer):
    """word + position + token-type embeddings → LN → dropout."""

    def __init__(self, config):
        super().__init__()
        self.word = VocabParallelEmbedding(config.vocab_size,
                                           config.hidden_size)
        self.position = nn.Embedding(config.max_position,
                                     config.hidden_size)
        self.token_type = nn.Embedding(config.type_vocab_size,
                                       config.hidden_size)
        self.ln = nn.LayerNorm(config.hidden_size)
        self.drop = nn.Dropout(config.dropout)

    def forward(self, input_ids, token_type_ids=None):
        from ...ops.creation import arange, zeros_like

        s = input_ids.shape[1]
        pos = arange(0, s, dtype="int64")
        if token_type_ids is None:
            token_type_ids = zeros_like(input_ids)
        x = (self.word(input_ids) + self.position(pos)
             + self.token_type(token_type_ids))
        return self.drop(self.ln(x))


class BertEncoderLayer(nn.Layer):
    """Post-LN encoder block (BERT convention), fused qkv, bidirectional
    attention with an additive padding mask."""

    def __init__(self, config):
        super().__init__()
        d = config.hidden_size
        self.nh = config.num_heads
        self.hd = d // config.num_heads
        self.qkv = ColumnParallelLinear(d, 3 * d, gather_output=False)
        self.proj = RowParallelLinear(d, d, input_is_parallel=True)
        self.ln1 = nn.LayerNorm(d)
        self.fc1 = ColumnParallelLinear(d, config.intermediate_size,
                                        gather_output=False)
        self.fc2 = RowParallelLinear(config.intermediate_size, d,
                                     input_is_parallel=True)
        self.ln2 = nn.LayerNorm(d)
        self.dropout = nn.Dropout(config.dropout)

    def forward(self, x, attn_mask=None, kv_lens=None):
        b, s = x.shape[0], x.shape[1]
        qkv = self.qkv(x)
        q, k, v = split_fused_qkv(qkv, b, s, self.nh, self.hd)
        attn = F.scaled_dot_product_attention(q, k, v,
                                              attn_mask=attn_mask,
                                              kv_lens=kv_lens)
        attn = manip.reshape(attn, [b, s, self.nh * self.hd])
        x = self.ln1(x + self.dropout(self.proj(attn)))
        h = self.fc2(F.gelu(self.fc1(x)))
        return self.ln2(x + self.dropout(h))


class BertModel(nn.Layer):
    """Embeddings → N encoder layers → (sequence_output, pooled)."""

    def __init__(self, config):
        super().__init__()
        self.config = config
        self.embeddings = BertEmbeddings(config)
        self.layers = nn.LayerList(
            [BertEncoderLayer(config) for _ in range(config.num_layers)])
        self.pooler = nn.Linear(config.hidden_size, config.hidden_size)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None):
        mask, kv_lens = None, None
        if attention_mask is not None and len(attention_mask.shape) == 1:
            # [b] int lengths (prefix key padding): stays eligible for
            # the Pallas flash kernel — a dense mask's values are unknown
            # at trace time, a lengths vector declares its structure
            if "int" not in str(attention_mask.dtype):
                raise ValueError(
                    "a rank-1 attention_mask is interpreted as per-example "
                    "valid LENGTHS and must be integer; got "
                    f"{attention_mask.dtype} (a squeezed [s] keep-mask is "
                    "not supported — pass the [b, s] form)")
            kv_lens = attention_mask
        elif attention_mask is not None:
            # [b, s] 1/0 keep-mask → additive [b, 1, 1, s]
            m = manip.reshape(
                attention_mask.astype("float32"),
                [attention_mask.shape[0], 1, 1, attention_mask.shape[1]])
            mask = (m - 1.0) * 1e9
        x = self.embeddings(input_ids, token_type_ids)
        x = shard_activation(x, "dp", "sp", None)
        for layer in self.layers:
            x = layer(x, attn_mask=mask, kv_lens=kv_lens)
        pooled = F.tanh(self.pooler(
            manip.squeeze(manip.slice(x, [1], [0], [1]), [1])))
        return x, pooled


class BertForPretraining(nn.Layer):
    """MLM head (transform + tied vocab-sharded decoder) + NSP head."""

    def __init__(self, config):
        super().__init__()
        self.config = config
        self.bert = BertModel(config)
        d = config.hidden_size
        self.mlm_transform = nn.Linear(d, d)
        self.mlm_ln = nn.LayerNorm(d)
        self.nsp = nn.Linear(d, 2)

    def forward(self, input_ids, token_type_ids=None,
                attention_mask=None):
        seq, pooled = self.bert(input_ids, token_type_ids, attention_mask)
        h = self.mlm_ln(F.gelu(self.mlm_transform(seq)))
        w = self.bert.embeddings.word.weight  # [vocab, d] mp-sharded
        mlm_logits = F.linear(h, manip.transpose(w, [1, 0]))
        mlm_logits = shard_activation(mlm_logits, "dp", "sp", "mp")
        return mlm_logits, self.nsp(pooled)

    def fused_mlm_loss(self, input_ids, mlm_labels, token_type_ids=None,
                       attention_mask=None, nsp_labels=None,
                       block_size=4096):
        """MLM (+optional NSP) loss with the vocab decoder and softmax-CE
        fused (F.fused_linear_cross_entropy): the [b, s, vocab] logits —
        the largest activation of the MLM step — never reach HBM.
        Single-chip / dp / sp path; vocab-sharded TP keeps forward() +
        BertPretrainingCriterion (the vocab-parallel reduction is there).
        """
        from ...distributed import mesh as mesh_mod
        from ...ops.math import mean

        if mesh_mod.has_mesh() and mesh_mod.axis_size("mp") > 1:
            raise ValueError(
                "fused_mlm_loss computes softmax over the FULL vocab; "
                "with mp>1 the tied decoder weight is vocab-sharded and "
                "the result would be silently wrong. Use forward() + "
                "BertPretrainingCriterion (ParallelCrossEntropy) under TP.")
        seq, pooled = self.bert(input_ids, token_type_ids, attention_mask)
        h = self.mlm_ln(F.gelu(self.mlm_transform(seq)))
        w = self.bert.embeddings.word.weight  # [vocab, d]
        loss = F.fused_linear_cross_entropy(
            h, w, mlm_labels, transpose_weight=True, ignore_index=-100,
            block_size=block_size)
        if nsp_labels is not None:
            loss = loss + mean(F.cross_entropy(self.nsp(pooled), nsp_labels))
        return loss


class BertPretrainingCriterion(nn.Layer):
    """Masked-LM vocab-parallel CE (ignore_index −100) + NSP CE."""

    def __init__(self, use_nsp=True):
        super().__init__()
        self.ce = ParallelCrossEntropy()
        self.use_nsp = use_nsp

    def forward(self, mlm_logits, mlm_labels, nsp_logits=None,
                nsp_labels=None):
        from ...ops.math import mean, sum as t_sum

        # ce masks ignore_index itself (per-token losses are 0 there)
        tok_loss = self.ce(mlm_logits, mlm_labels)  # [b, s]
        mask = (mlm_labels != -100).astype("float32")
        loss = t_sum(tok_loss) / (t_sum(mask) + 1e-9)
        if self.use_nsp and nsp_logits is not None:
            loss = loss + mean(
                F.cross_entropy(nsp_logits, nsp_labels))
        return loss


class BertForSequenceClassification(nn.Layer):
    def __init__(self, config, num_classes=2):
        super().__init__()
        self.bert = BertModel(config)
        self.drop = nn.Dropout(config.dropout)
        self.classifier = nn.Linear(config.hidden_size, num_classes)

    def forward(self, input_ids, token_type_ids=None,
                attention_mask=None):
        _, pooled = self.bert(input_ids, token_type_ids, attention_mask)
        return self.classifier(self.drop(pooled))


# ERNIE shares the BERT architecture in this generation; the difference
# (knowledge masking) lives in data preparation, not the network.
ErnieModel = BertModel
ErnieForPretraining = BertForPretraining
