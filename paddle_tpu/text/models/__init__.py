"""Language model zoo (reference capability: PaddleNLP model family on the
fleet mpu layers; BASELINE.json configs 3-4)."""
from .gpt import (  # noqa: F401
    GPTConfig,
    GPTDecoderLayer,
    GPTForCausalLM,
    GPTModel,
    GPTPretrainingCriterion,
    gpt_1p3b,
    gpt_medium,
    gpt_small,
    gpt_tiny,
)

from .bert import (  # noqa: F401
    BertConfig,
    BertForPretraining,
    BertForSequenceClassification,
    BertModel,
    BertPretrainingCriterion,
    ErnieForPretraining,
    ErnieModel,
    bert_base,
    bert_tiny,
    ernie_3_base,
)

__all__ = [
    "GPTConfig", "GPTDecoderLayer", "GPTModel", "GPTForCausalLM",
    "GPTPretrainingCriterion", "gpt_tiny", "gpt_small", "gpt_medium",
    "gpt_1p3b",
    "BertConfig", "BertModel", "BertForPretraining",
    "BertPretrainingCriterion", "BertForSequenceClassification",
    "ErnieModel", "ErnieForPretraining", "bert_tiny", "bert_base",
    "ernie_3_base",
]
