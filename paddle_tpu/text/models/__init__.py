"""Language model zoo (reference capability: PaddleNLP model family on the
fleet mpu layers; BASELINE.json configs 3-4)."""
from .gpt import (  # noqa: F401
    GPTConfig,
    GPTDecoderLayer,
    GPTForCausalLM,
    GPTModel,
    GPTPretrainingCriterion,
    gpt_1p3b,
    gpt_medium,
    gpt_small,
    gpt_tiny,
)

__all__ = [
    "GPTConfig", "GPTDecoderLayer", "GPTModel", "GPTForCausalLM",
    "GPTPretrainingCriterion", "gpt_tiny", "gpt_small", "gpt_medium",
    "gpt_1p3b",
]
