"""Viterbi decoding (reference: python/paddle/text/viterbi_decode.py
ViterbiDecoder:25 / viterbi_decode:105, phi kernels viterbi_decode_kernel).

TPU-first: the forward max-product recursion is a `lax.scan` over time
([B, C] carry, MXU-friendly [C, C] transition broadcast); backtraces are
stacked argmax indices walked backwards with a second scan — no python
loops, jit-safe static shapes."""
import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..ops._helpers import apply_jfn, ensure_tensor, value_of
from ..tensor_core import Tensor

__all__ = ["viterbi_decode", "ViterbiDecoder"]


def viterbi_decode(potentials, transition_params, lengths,
                   include_bos_eos_tag=True, name=None):
    """potentials [B, L, C], transition [C, C] (rows −2/−1 are BOS/EOS
    when include_bos_eos_tag), lengths [B] → (scores [B], paths [B, L])."""
    pots = ensure_tensor(potentials)
    trans = ensure_tensor(transition_params)
    lens_v = value_of(ensure_tensor(lengths))

    def jfn(pv, tv):
        B, L, C = pv.shape
        if include_bos_eos_tag:
            # reference kernel splits transition rows [..., stop, start]
            # (viterbi_decode_kernel.cc:222-236): row −1 = START scores,
            # row −2 = STOP scores, both indexed by the tag
            start = tv[-1]
            stop = tv[-2]
        else:
            start = jnp.zeros((C,), pv.dtype)
            stop = jnp.zeros((C,), pv.dtype)
        alpha0 = pv[:, 0] + start[None, :]

        def step(carry, t):
            alpha = carry  # [B, C]
            # scores[b, i, j] = alpha[b, i] + T[i, j] + emit[b, t, j]
            scores = alpha[:, :, None] + tv[None, :, :]
            best_prev = jnp.argmax(scores, axis=1)          # [B, C]
            best_score = jnp.max(scores, axis=1) + pv[:, t]  # [B, C]
            # frozen past sequence end
            active = (t < lens_v)[:, None]
            alpha_new = jnp.where(active, best_score, alpha)
            return alpha_new, best_prev

        alpha, backptrs = lax.scan(step, alpha0, jnp.arange(1, L))
        alpha_final = alpha + stop[None, :]
        scores = jnp.max(alpha_final, axis=1)
        last_tag = jnp.argmax(alpha_final, axis=1)  # [B]

        # walk backpointers; carry = tag at position t+1, emit it, and
        # step to the tag at position t (frozen past each seq's end)
        def back(carry, t):
            tag = carry  # [B] tag at position t+1
            bp = backptrs[t]  # [B, C]: chosen prev-tag for step t→t+1
            prev = jnp.take_along_axis(bp, tag[:, None], 1)[:, 0]
            tag_t = jnp.where(t + 1 < lens_v, prev, tag)
            return tag_t, tag

        tag0, tags_rev = lax.scan(back, last_tag,
                                  jnp.arange(L - 2, -1, -1))
        # tags_rev[k] is the tag at position L-1-k (k = 0..L-2)
        path = jnp.concatenate([tag0[None], jnp.flip(tags_rev, 0)],
                               axis=0)  # [L, B]
        return scores, jnp.swapaxes(path, 0, 1)

    scores, path = apply_jfn("viterbi_decode", jfn, pots, trans)
    return scores, path


class ViterbiDecoder:
    """Layer wrapper (reference viterbi_decode.py:25)."""

    def __init__(self, transitions, include_bos_eos_tag=True, name=None):
        self.transitions = ensure_tensor(transitions)
        self.include_bos_eos_tag = include_bos_eos_tag

    def __call__(self, potentials, lengths):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include_bos_eos_tag)
