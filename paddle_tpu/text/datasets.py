"""paddle_tpu.text.datasets (reference: python/paddle/text/datasets/ —
imdb.py Imdb:33, imikolov.py Imikolov, uci_housing.py UCIHousing,
movielens.py, wmt14/16.py).

The reference downloads archives; this container is zero-egress, so
every dataset takes a LOCAL `data_file` (same archive format the
reference downloads) and raises a clear error when it is absent —
parsing, vocab building, and normalization logic match the reference.
"""
import io as _io
import re
import string
import tarfile

import numpy as np

from ..io import Dataset

__all__ = ["Imdb", "Imikolov", "UCIHousing"]


def _require(data_file, name, url_hint):
    if data_file is None:
        raise ValueError(
            f"{name}: automatic download is unavailable in this "
            f"environment — pass data_file= pointing at a local copy of "
            f"the archive ({url_hint})")
    return data_file


class Imdb(Dataset):
    """IMDB sentiment (reference imdb.py:33): tar.gz of aclImdb text
    files; builds a cutoff word dict; samples = (ids ndarray, label)
    with pos=0, neg=1."""

    def __init__(self, data_file=None, mode="train", cutoff=150,
                 download=False):
        assert mode in ("train", "test")
        self.data_file = _require(data_file, "Imdb",
                                  "aclImdb_v1.tar.gz")
        self.mode = mode
        # ONE decompression pass: tokenized docs are cached per split and
        # reused for both the vocab count and the sample load
        tokenized = self._read_all()
        self.word_idx = self._build_work_dict(tokenized, cutoff)
        self._load_anno(tokenized)

    def _tokenize(self, text):
        return text.lower().translate(
            str.maketrans("", "", string.punctuation)).split()

    def _read_all(self):
        out = {("train", "pos"): [], ("train", "neg"): [],
               ("test", "pos"): [], ("test", "neg"): []}
        pat = re.compile(r"aclImdb/(train|test)/(pos|neg)/.*\.txt$")
        with tarfile.open(self.data_file) as tf:
            for m in tf.getmembers():
                mt = pat.match(m.name)
                if mt:
                    out[(mt.group(1), mt.group(2))].append(
                        self._tokenize(
                            tf.extractfile(m).read().decode("latin1")))
        return out

    def _build_work_dict(self, tokenized, cutoff):
        freq = {}
        for docs in tokenized.values():
            for toks in docs:
                for w in toks:
                    freq[w] = freq.get(w, 0) + 1
        words = [w for w, c in freq.items() if c > cutoff]
        word_idx = {w: i for i, w in enumerate(sorted(words))}
        word_idx["<unk>"] = len(word_idx)
        return word_idx

    def _load_anno(self, tokenized):
        unk = self.word_idx["<unk>"]
        self.docs, self.labels = [], []
        for label, tag in ((0, "pos"), (1, "neg")):
            for toks in tokenized[(self.mode, tag)]:
                self.docs.append(np.array(
                    [self.word_idx.get(w, unk) for w in toks], np.int64))
                self.labels.append(label)

    def __len__(self):
        return len(self.docs)

    def __getitem__(self, i):
        return self.docs[i], np.int64(self.labels[i])


class Imikolov(Dataset):
    """PTB n-gram/sequence dataset (reference imikolov.py): tar with
    ./simple-examples/data/ptb.{train,valid}.txt."""

    def __init__(self, data_file=None, data_type="NGRAM", window_size=-1,
                 mode="train", min_word_freq=50, download=False):
        assert data_type in ("NGRAM", "SEQ")
        if data_type == "NGRAM":
            assert window_size > 0
        self.data_file = _require(data_file, "Imikolov",
                                  "simple-examples.tgz")
        self.data_type = data_type
        self.window_size = window_size
        self.mode = {"train": "train", "test": "valid"}[
            "train" if mode == "train" else "test"]
        self.word_idx = self._build_dict(min_word_freq)
        self._load_anno()

    def _lines(self, split):
        pat = re.compile(rf".*/data/ptb\.{split}\.txt$")
        with tarfile.open(self.data_file) as tf:
            for m in tf.getmembers():
                if pat.match(m.name):
                    for ln in _io.TextIOWrapper(
                            tf.extractfile(m), encoding="latin1"):
                        yield ln.strip().split()

    def _build_dict(self, min_word_freq):
        freq = {}
        for words in self._lines("train"):
            for w in words:
                freq[w] = freq.get(w, 0) + 1
        freq.pop("<unk>", None)
        kept = sorted(
            [(w, c) for w, c in freq.items() if c >= min_word_freq],
            key=lambda t: (-t[1], t[0]))
        word_idx = {w: i for i, (w, _) in enumerate(kept)}
        # special tokens are REAL dict entries (reference convention) so
        # every emitted id indexes a valid embedding row
        for tok in ("<unk>", "<s>", "<e>"):
            word_idx[tok] = len(word_idx)
        return word_idx

    def _load_anno(self):
        unk = self.word_idx["<unk>"]
        s = self.word_idx["<s>"]
        e = self.word_idx["<e>"]
        self.data = []
        for words in self._lines(self.mode):
            ids = [s] + [self.word_idx.get(w, unk) for w in words] + [e]
            if self.data_type == "NGRAM":
                n = self.window_size
                for i in range(len(ids) - n + 1):
                    self.data.append(tuple(ids[i:i + n]))
            else:
                self.data.append((np.array(ids[:-1], np.int64),
                                  np.array(ids[1:], np.int64)))

    def __len__(self):
        return len(self.data)

    def __getitem__(self, i):
        d = self.data[i]
        if self.data_type == "NGRAM":
            return tuple(np.int64(v) for v in d)
        return d


class UCIHousing(Dataset):
    """Boston housing regression (reference uci_housing.py): 14
    whitespace columns, feature-wise min/max-normalized by the TRAIN
    split stats, 80/20 train/test."""

    FEATURE_DIM = 13

    def __init__(self, data_file=None, mode="train", download=False):
        assert mode in ("train", "test")
        self.data_file = _require(data_file, "UCIHousing", "housing.data")
        raw = np.loadtxt(self.data_file).astype(np.float32)
        assert raw.shape[1] == 14, "expect 14 columns (13 feat + price)"
        feats = raw[:, :13]
        n_train = int(len(raw) * 0.8)
        mx = feats[:n_train].max(axis=0)
        mn = feats[:n_train].min(axis=0)
        avg = feats[:n_train].mean(axis=0)
        feats = (feats - avg) / np.maximum(mx - mn, 1e-8)
        data = np.concatenate([feats, raw[:, 13:]], axis=1)
        self.data = data[:n_train] if mode == "train" else data[n_train:]

    def __len__(self):
        return len(self.data)

    def __getitem__(self, i):
        return self.data[i, :13], self.data[i, 13:]
