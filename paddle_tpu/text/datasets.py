"""paddle_tpu.text.datasets (reference: python/paddle/text/datasets/ —
imdb.py Imdb:33, imikolov.py Imikolov, uci_housing.py UCIHousing,
movielens.py, wmt14/16.py).

The reference downloads archives; this container is zero-egress, so
every dataset takes a LOCAL `data_file` (same archive format the
reference downloads) and raises a clear error when it is absent —
parsing, vocab building, and normalization logic match the reference.
"""
import io as _io
import re
import string
import tarfile

import numpy as np

from ..io import Dataset

__all__ = ["Imdb", "Imikolov", "UCIHousing", "WMT16", "Movielens",
           "WMT14", "Conll05st"]


def _require(data_file, name, url_hint):
    if data_file is None:
        raise ValueError(
            f"{name}: automatic download is unavailable in this "
            f"environment — pass data_file= pointing at a local copy of "
            f"the archive ({url_hint})")
    return data_file


class Imdb(Dataset):
    """IMDB sentiment (reference imdb.py:33): tar.gz of aclImdb text
    files; builds a cutoff word dict; samples = (ids ndarray, label)
    with pos=0, neg=1."""

    def __init__(self, data_file=None, mode="train", cutoff=150,
                 download=False):
        assert mode in ("train", "test")
        self.data_file = _require(data_file, "Imdb",
                                  "aclImdb_v1.tar.gz")
        self.mode = mode
        # ONE decompression pass: tokenized docs are cached per split and
        # reused for both the vocab count and the sample load
        tokenized = self._read_all()
        self.word_idx = self._build_work_dict(tokenized, cutoff)
        self._load_anno(tokenized)

    def _tokenize(self, text):
        return text.lower().translate(
            str.maketrans("", "", string.punctuation)).split()

    def _read_all(self):
        out = {("train", "pos"): [], ("train", "neg"): [],
               ("test", "pos"): [], ("test", "neg"): []}
        pat = re.compile(r"aclImdb/(train|test)/(pos|neg)/.*\.txt$")
        with tarfile.open(self.data_file) as tf:
            for m in tf.getmembers():
                mt = pat.match(m.name)
                if mt:
                    out[(mt.group(1), mt.group(2))].append(
                        self._tokenize(
                            tf.extractfile(m).read().decode("latin1")))
        return out

    def _build_work_dict(self, tokenized, cutoff):
        freq = {}
        for docs in tokenized.values():
            for toks in docs:
                for w in toks:
                    freq[w] = freq.get(w, 0) + 1
        words = [w for w, c in freq.items() if c > cutoff]
        word_idx = {w: i for i, w in enumerate(sorted(words))}
        word_idx["<unk>"] = len(word_idx)
        return word_idx

    def _load_anno(self, tokenized):
        unk = self.word_idx["<unk>"]
        self.docs, self.labels = [], []
        for label, tag in ((0, "pos"), (1, "neg")):
            for toks in tokenized[(self.mode, tag)]:
                self.docs.append(np.array(
                    [self.word_idx.get(w, unk) for w in toks], np.int64))
                self.labels.append(label)

    def __len__(self):
        return len(self.docs)

    def __getitem__(self, i):
        return self.docs[i], np.int64(self.labels[i])


class Imikolov(Dataset):
    """PTB n-gram/sequence dataset (reference imikolov.py): tar with
    ./simple-examples/data/ptb.{train,valid}.txt."""

    def __init__(self, data_file=None, data_type="NGRAM", window_size=-1,
                 mode="train", min_word_freq=50, download=False):
        assert data_type in ("NGRAM", "SEQ")
        if data_type == "NGRAM":
            assert window_size > 0
        self.data_file = _require(data_file, "Imikolov",
                                  "simple-examples.tgz")
        self.data_type = data_type
        self.window_size = window_size
        self.mode = {"train": "train", "test": "valid"}[
            "train" if mode == "train" else "test"]
        self.word_idx = self._build_dict(min_word_freq)
        self._load_anno()

    def _lines(self, split):
        pat = re.compile(rf".*/data/ptb\.{split}\.txt$")
        with tarfile.open(self.data_file) as tf:
            for m in tf.getmembers():
                if pat.match(m.name):
                    for ln in _io.TextIOWrapper(
                            tf.extractfile(m), encoding="latin1"):
                        yield ln.strip().split()

    def _build_dict(self, min_word_freq):
        freq = {}
        for words in self._lines("train"):
            for w in words:
                freq[w] = freq.get(w, 0) + 1
        freq.pop("<unk>", None)
        kept = sorted(
            [(w, c) for w, c in freq.items() if c >= min_word_freq],
            key=lambda t: (-t[1], t[0]))
        word_idx = {w: i for i, (w, _) in enumerate(kept)}
        # special tokens are REAL dict entries (reference convention) so
        # every emitted id indexes a valid embedding row
        for tok in ("<unk>", "<s>", "<e>"):
            word_idx[tok] = len(word_idx)
        return word_idx

    def _load_anno(self):
        unk = self.word_idx["<unk>"]
        s = self.word_idx["<s>"]
        e = self.word_idx["<e>"]
        self.data = []
        for words in self._lines(self.mode):
            ids = [s] + [self.word_idx.get(w, unk) for w in words] + [e]
            if self.data_type == "NGRAM":
                n = self.window_size
                for i in range(len(ids) - n + 1):
                    self.data.append(tuple(ids[i:i + n]))
            else:
                self.data.append((np.array(ids[:-1], np.int64),
                                  np.array(ids[1:], np.int64)))

    def __len__(self):
        return len(self.data)

    def __getitem__(self, i):
        d = self.data[i]
        if self.data_type == "NGRAM":
            return tuple(np.int64(v) for v in d)
        return d


class UCIHousing(Dataset):
    """Boston housing regression (reference uci_housing.py): 14
    whitespace columns, feature-wise min/max-normalized by the TRAIN
    split stats, 80/20 train/test."""

    FEATURE_DIM = 13

    def __init__(self, data_file=None, mode="train", download=False):
        assert mode in ("train", "test")
        self.data_file = _require(data_file, "UCIHousing", "housing.data")
        raw = np.loadtxt(self.data_file).astype(np.float32)
        assert raw.shape[1] == 14, "expect 14 columns (13 feat + price)"
        feats = raw[:, :13]
        n_train = int(len(raw) * 0.8)
        mx = feats[:n_train].max(axis=0)
        mn = feats[:n_train].min(axis=0)
        avg = feats[:n_train].mean(axis=0)
        feats = (feats - avg) / np.maximum(mx - mn, 1e-8)
        data = np.concatenate([feats, raw[:, 13:]], axis=1)
        self.data = data[:n_train] if mode == "train" else data[n_train:]

    def __len__(self):
        return len(self.data)

    def __getitem__(self, i):
        return self.data[i, :13], self.data[i, 13:]


class WMT16(Dataset):
    """EN↔DE translation (reference wmt16.py): tar with tab-separated
    parallel lines at wmt16/{train,val,test}. Vocab = <s>, <e>, <unk>
    then words by descending train-split frequency, truncated to
    dict_size (reference _build_dict order)."""

    START, END, UNK = "<s>", "<e>", "<unk>"

    def __init__(self, data_file=None, mode="train", src_dict_size=-1,
                 trg_dict_size=-1, lang="en", download=False):
        assert mode in ("train", "val", "test")
        # reference semantics: -1 (or any <=0) keeps the FULL vocabulary
        assert src_dict_size <= 0 or src_dict_size > 3, \
            "positive dict sizes must exceed the 3 specials (<s>/<e>/<unk>)"
        assert trg_dict_size <= 0 or trg_dict_size > 3, \
            "positive dict sizes must exceed the 3 specials (<s>/<e>/<unk>)"
        self.data_file = _require(data_file, "WMT16", "wmt16.tar.gz")
        self.mode = mode
        self.lang = lang
        # ONE decompression pass over train: counts for BOTH languages
        train_pairs = list(self._pairs("train"))
        en_dict = self._build_dict(train_pairs, 0, src_dict_size
                                   if lang == "en" else trg_dict_size)
        de_dict = self._build_dict(train_pairs, 1, trg_dict_size
                                   if lang == "en" else src_dict_size)
        self.src_dict = en_dict if lang == "en" else de_dict
        self.trg_dict = de_dict if lang == "en" else en_dict
        self._load_data(train_pairs if mode == "train"
                        else list(self._pairs(mode)))
        del train_pairs

    def _pairs(self, split):
        with tarfile.open(self.data_file) as tf:
            for ln in _io.TextIOWrapper(
                    tf.extractfile(f"wmt16/{split}"), encoding="utf-8"):
                parts = ln.strip().split("\t")
                if len(parts) == 2:
                    yield parts

    def _build_dict(self, train_pairs, col, dict_size):
        freq = {}
        for parts in train_pairs:
            for w in parts[col].split():
                freq[w] = freq.get(w, 0) + 1
        # specials are unconditional; only the WORD list is truncated
        words = [w for w, _ in sorted(freq.items(), key=lambda t: -t[1])]
        if dict_size > 0:
            words = words[:dict_size - 3]
        vocab = [self.START, self.END, self.UNK] + words
        return {w: i for i, w in enumerate(vocab)}

    def _load_data(self, pairs):
        s, e = self.src_dict[self.START], self.src_dict[self.END]
        unk_s = self.src_dict[self.UNK]
        unk_t = self.trg_dict[self.UNK]
        src_col = 0 if self.lang == "en" else 1
        self.src_ids, self.trg_ids, self.trg_ids_next = [], [], []
        for parts in pairs:
            src = [s] + [self.src_dict.get(w, unk_s)
                         for w in parts[src_col].split()] + [e]
            trg_raw = [self.trg_dict.get(w, unk_t)
                       for w in parts[1 - src_col].split()]
            self.src_ids.append(np.array(src, np.int64))
            self.trg_ids.append(np.array([s] + trg_raw, np.int64))
            self.trg_ids_next.append(np.array(trg_raw + [e], np.int64))

    def __len__(self):
        return len(self.src_ids)

    def __getitem__(self, i):
        return self.src_ids[i], self.trg_ids[i], self.trg_ids_next[i]


class Movielens(Dataset):
    """ML-1M ratings (reference movielens.py): '::'-delimited .dat files
    inside the archive; samples are (user_id, gender_id, age_id, job_id,
    movie_id, category multi-hot, title word-ids, rating)."""

    AGES = [1, 18, 25, 35, 45, 50, 56]

    def __init__(self, data_file=None, mode="train", test_ratio=0.1,
                 rand_seed=0, download=False):
        assert mode in ("train", "test")
        self.data_file = _require(data_file, "Movielens", "ml-1m.zip")
        self.mode = mode
        # ONE archive open (zip — the reference's format — or tar)
        files = self._read_archive(
            ("movies.dat", "users.dat", "ratings.dat"))
        self._load_meta(files)
        self._load_ratings(files, test_ratio, rand_seed)
        del files

    def _read_archive(self, suffixes):
        import zipfile

        out = {}
        if zipfile.is_zipfile(self.data_file):
            with zipfile.ZipFile(self.data_file) as zf:
                for name in zf.namelist():
                    for suf in suffixes:
                        if name.endswith(suf):
                            out[suf] = zf.read(name).decode(
                                "latin1").splitlines()
        else:
            with tarfile.open(self.data_file) as tf:
                for m in tf.getmembers():
                    for suf in suffixes:
                        if m.name.endswith(suf):
                            out[suf] = tf.extractfile(m).read().decode(
                                "latin1").splitlines()
        missing = [s for s in suffixes if s not in out]
        if missing:
            raise FileNotFoundError(
                f"archive is missing {missing} (expected the ml-1m "
                "layout)")
        return out

    def _load_meta(self, files):
        cats, words = {}, {}
        self.movies = {}
        self.users = {}
        for ln in files["movies.dat"]:
            mid, title, genres = ln.strip().split("::")
            for g in genres.split("|"):
                cats.setdefault(g, len(cats))
            for w in title.lower().split():
                words.setdefault(w, len(words))
            self.movies[int(mid)] = (title.lower().split(),
                                     genres.split("|"))
        for ln in files["users.dat"]:
            uid, gender, age, job, _zip = ln.strip().split("::")
            self.users[int(uid)] = (
                0 if gender == "M" else 1,
                self.AGES.index(int(age)) if int(age) in self.AGES
                else 0,
                int(job))
        self.categories_dict = cats
        self.movie_title_dict = words

    def _load_ratings(self, files, test_ratio, seed):
        rng = np.random.default_rng(seed)
        self.data = []
        for ln in files["ratings.dat"]:
            uid, mid, rating, _ts = ln.strip().split("::")
            is_test = rng.random() < test_ratio
            if (self.mode == "test") != is_test:
                continue
            uid, mid = int(uid), int(mid)
            title, genres = self.movies[mid]
            g, a, j = self.users[uid]
            cat_vec = np.zeros(len(self.categories_dict), np.int64)
            for c in genres:
                cat_vec[self.categories_dict[c]] = 1
            self.data.append((
                np.int64(uid), np.int64(g), np.int64(a), np.int64(j),
                np.int64(mid), cat_vec,
                np.array([self.movie_title_dict[w] for w in title],
                         np.int64),
                np.float32(rating)))

    def __len__(self):
        return len(self.data)

    def __getitem__(self, i):
        return self.data[i]


class WMT14(Dataset):
    """FR→EN translation (reference wmt14.py): tar containing src.dict /
    trg.dict (one token per line; rows 0-2 are <s>, <e>, <unk>) and
    tab-separated parallel files whose names end with the split name."""

    def __init__(self, data_file=None, mode="train", dict_size=-1,
                 download=False):
        assert mode in ("train", "test", "gen")
        assert dict_size > 0, "dict_size should be a positive number"
        self.data_file = _require(data_file, "WMT14",
                                  "wmt14 tarball with src/trg dicts")
        self.mode = mode
        self.dict_size = dict_size
        self._load_data()

    def _load_data(self):
        unk = 2  # reference UNK_IDX
        split_name = {"train": "train", "test": "test", "gen": "gen"}[
            self.mode]
        self.src_ids, self.trg_ids, self.trg_ids_next = [], [], []
        with tarfile.open(self.data_file) as tf:
            def to_dict(member):
                d = {}
                for i, ln in enumerate(_io.TextIOWrapper(
                        tf.extractfile(member), encoding="utf-8")):
                    if i == self.dict_size:
                        break
                    d[ln.strip()] = i
                return d

            def find(suffix):
                for m in tf.getmembers():
                    if m.isfile() and m.name.endswith(suffix):
                        return m
                raise FileNotFoundError(
                    f"archive has no file ending with {suffix!r} "
                    "(expected the wmt14 layout)")

            self.src_dict = to_dict(find("src.dict"))
            self.trg_dict = to_dict(find("trg.dict"))
            for m in tf.getmembers():
                # directories named like the split must not match
                if not m.isfile() or not m.name.endswith(split_name):
                    continue
                for ln in _io.TextIOWrapper(tf.extractfile(m),
                                            encoding="utf-8"):
                    parts = ln.strip().split("\t")
                    if len(parts) != 2:
                        continue
                    src = [self.src_dict.get(w, unk)
                           for w in parts[0].split()]
                    trg = [self.trg_dict.get(w, unk)
                           for w in parts[1].split()]
                    self.src_ids.append(np.array(src + [1], np.int64))
                    self.trg_ids.append(np.array([0] + trg, np.int64))
                    self.trg_ids_next.append(np.array(trg + [1], np.int64))

    def __len__(self):
        return len(self.src_ids)

    def __getitem__(self, i):
        return self.src_ids[i], self.trg_ids[i], self.trg_ids_next[i]


class Conll05st(Dataset):
    """CoNLL-2005 SRL test set (reference conll05.py): words.gz +
    props.gz columns inside the tarball; bracketed proposition spans are
    converted to per-predicate BIO sequences, and each sample carries
    the reference's context-window features:
    (word_ids, ctx_n2, ctx_n1, ctx_0, ctx_p1, ctx_p2, pred_id, mark,
    label_ids)."""

    def __init__(self, data_file=None, word_dict_file=None,
                 verb_dict_file=None, target_dict_file=None,
                 download=False):
        import gzip

        self.data_file = _require(data_file, "Conll05st",
                                  "conll05st-tests.tar.gz")
        with tarfile.open(self.data_file) as tf:
            wf = pf = None
            for m in tf.getmembers():
                # pin the wsj corpus (the official archive also carries
                # test.brown; mixing corpora would zip mismatched files)
                if not m.isfile():
                    continue
                if m.name.endswith("test.wsj.words.gz"):
                    wf = gzip.decompress(tf.extractfile(m).read())
                elif m.name.endswith("test.wsj.props.gz"):
                    pf = gzip.decompress(tf.extractfile(m).read())
        if wf is None or pf is None:
            raise FileNotFoundError(
                "test.wsj.words.gz / test.wsj.props.gz not in archive "
                "(expected the conll05st-release layout)")
        self._parse(wf.decode("latin1"), pf.decode("latin1"))
        self.word_dict = self._dict_from(word_dict_file, (
            w for s in self.sentences for w in s), extra=("bos", "eos"))
        self.predicate_dict = self._dict_from(verb_dict_file,
                                              self.predicates)
        self.label_dict = self._dict_from(target_dict_file, (
            l for seq in self.labels for l in seq))
        # precompute encoded samples once (pattern of the sibling
        # datasets — __getitem__ must not re-encode every epoch)
        self._samples = [self._encode(i) for i in range(len(self.sentences))]

    @staticmethod
    def _dict_from(dict_file, items, extra=()):
        if dict_file is not None:
            with open(dict_file) as f:
                d = {ln.strip(): i for i, ln in enumerate(f)}
        else:
            d = {}
            for it in items:
                d.setdefault(it, len(d))
        # __getitem__ indexes these unconditionally — guarantee them
        # even for externally supplied dict files
        for e in (*extra, "<unk>"):
            d.setdefault(e, len(d))
        return d

    def _parse(self, words_text, props_text):
        self.sentences, self.predicates, self.labels = [], [], []
        sentence, one_seg = [], []
        for wline, pline in zip(words_text.splitlines(),
                                props_text.splitlines()):
            word = wline.strip()
            cols = pline.strip().split()
            if not cols:  # end of sentence
                self._emit(sentence, one_seg)
                sentence, one_seg = [], []
            else:
                sentence.append(word)
                one_seg.append(cols)
        self._emit(sentence, one_seg)

    def _emit(self, sentence, one_seg):
        if not one_seg:
            return
        ncols = len(one_seg[0])
        columns = [[row[i] for row in one_seg] for i in range(ncols)]
        verbs = [v for v in columns[0] if v != "-"]
        for i, col in enumerate(columns[1:]):
            cur, inside, seq = "O", False, []
            for l in col:
                if l == "*":
                    seq.append("I-" + cur if inside else "O")
                elif l == "*)":
                    seq.append("I-" + cur)
                    inside = False
                elif "(" in l and ")" in l:
                    cur = l[1:l.find("*")]
                    seq.append("B-" + cur)
                    inside = False
                elif "(" in l:
                    cur = l[1:l.find("*")]
                    seq.append("B-" + cur)
                    inside = True
                else:
                    raise RuntimeError(f"unexpected label {l!r}")
            self.sentences.append(list(sentence))
            self.predicates.append(verbs[i])
            self.labels.append(seq)

    def __len__(self):
        return len(self.sentences)

    def __getitem__(self, idx):
        return self._samples[idx]

    def _encode(self, idx):
        sent = self.sentences[idx]
        labels = self.labels[idx]
        unk = self.word_dict["<unk>"]
        vi = labels.index("B-V")
        mark = np.zeros(len(labels), np.int64)
        ctx = []
        for off in (-2, -1, 0, 1, 2):
            j = vi + off
            if 0 <= j < len(sent):
                if off != 0:
                    mark[j] = 1
                ctx.append(self.word_dict.get(sent[j], unk))
            else:
                ctx.append(self.word_dict["bos" if off < 0 else "eos"])
        mark[vi] = 1
        word_idx = np.array([self.word_dict.get(w, unk) for w in sent],
                            np.int64)
        lab_idx = np.array(
            [self.label_dict.get(l, len(self.label_dict) - 1)
             for l in labels], np.int64)
        pred = np.int64(self.predicate_dict.get(
            self.predicates[idx], len(self.predicate_dict) - 1))
        return (word_idx, np.int64(ctx[0]), np.int64(ctx[1]),
                np.int64(ctx[2]), np.int64(ctx[3]), np.int64(ctx[4]),
                pred, mark, lab_idx)
