"""paddle_tpu.text — NLP models, datasets, and decoding.

(Reference: python/paddle/text/ exposes datasets + viterbi_decode; the
model zoo itself lives in PaddleNLP. Here the flagship language models
are in-tree because they are the benchmark/parallelism drivers.)
"""
from . import datasets  # noqa: F401
from . import models  # noqa: F401
from .datasets import (  # noqa: F401
    Conll05st, Imdb, Imikolov, Movielens, UCIHousing, WMT14, WMT16)
from .viterbi_decode import ViterbiDecoder, viterbi_decode  # noqa: F401

__all__ = ["models", "datasets", "Imdb", "Imikolov", "UCIHousing",
           "WMT16", "Movielens", "WMT14", "Conll05st",
           "ViterbiDecoder", "viterbi_decode"]
