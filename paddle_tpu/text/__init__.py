"""paddle_tpu.text — NLP models and (later) datasets.

(Reference: python/paddle/text/ exposes datasets + viterbi_decode; the
model zoo itself lives in PaddleNLP. Here the flagship language models are
in-tree because they are the benchmark/parallelism drivers.)
"""
from . import models  # noqa: F401

__all__ = ["models"]
