"""Automatic mixed precision.

TPU-native re-design of the reference AMP
(reference: python/paddle/amp/auto_cast.py:21, grad_scaler.py:26, op lists in
python/paddle/fluid/dygraph/amp/auto_cast.py, CUDA loss-scale ops in
paddle/fluid/operators/amp/). Differences by design:
- default low dtype is bfloat16 — the MXU-native type; fp16+loss-scaling is
  kept for parity but bf16 needs no scaler.
- the cast interposition lives in one place: the autograd tape's `apply`
  consults `amp.state()` per op name (the reference generates per-op AMP
  glue into every eager function).
"""
import contextlib
import threading

import jax.numpy as jnp
import numpy as np

__all__ = ["auto_cast", "amp_guard", "GradScaler", "decorate",
           "white_list", "black_list", "state"]

# ops that are numerically safe & fast in low precision (matmul/conv ride
# the MXU)
WHITE_LIST = {
    "matmul", "linear", "conv1d", "conv2d", "conv3d", "conv1d_transpose",
    "conv2d_transpose", "conv3d_transpose", "bmm", "mm", "mv",
    "scaled_dot_product_attention", "flash_attention", "einsum",
    # the fused head-CE does its own fp32 accumulation internally
    # (preferred_element_type on the block matmuls); its x/w inputs must
    # cast to bf16 like any other matmul or the whole point — bf16 MXU +
    # halved weight streaming — is lost (int labels skip the cast)
    "fused_linear_cross_entropy",
}
# numerically sensitive ops forced to fp32
BLACK_LIST = {
    # NB: "cross_entropy" is deliberately NOT black-listed: its fused
    # softmax-CE core does fp32 math internally (XLA fuses the upcast into
    # the reductions), so upcasting the whole [..., vocab] logits tensor
    # here would only add HBM traffic (profiled at ~5 ms/step on GPT-small).
    "exp", "log", "log2", "log10", "log1p", "softmax", "log_softmax",
    "nll_loss", "binary_cross_entropy", "bce_with_logits",
    # (keeping batch_norm black-listed measured FASTER on ResNet-50 than
    # bf16-through-BN — 47.5 vs 56 ms/step — XLA fuses the boundary casts
    # into the conv epilogues better than the in-kernel variant)
    "kl_div", "mean", "sum", "norm", "batch_norm", "batch_norm_infer",
    "layer_norm", "group_norm", "instance_norm", "softmax_with_cross_entropy",
    "sigmoid_focal_loss", "cosine_similarity", "pow", "square", "sqrt",
    "rsqrt", "cumsum", "cumprod", "var", "std", "renorm", "dist", "erfinv",
}


class _AmpState(threading.local):
    def __init__(self):
        self.enabled = False
        self.dtype = jnp.bfloat16
        self.level = "O1"
        self.custom_white = set()
        self.custom_black = set()


_state = _AmpState()


def state():
    return _state


def white_list():
    return (WHITE_LIST | _state.custom_white) - _state.custom_black


def black_list():
    return (BLACK_LIST | _state.custom_black) - _state.custom_white


def cast_inputs_for(op_name, vals):
    """Called from the tape: maybe cast op inputs per the AMP policy."""
    if not _state.enabled:
        return vals
    low = _state.dtype

    def is_float(v):
        return jnp.issubdtype(v.dtype, jnp.floating)

    if _state.level == "O2":
        # pure low precision except the black list
        if op_name in black_list():
            return tuple(
                v.astype(jnp.float32) if is_float(v) else v for v in vals
            )
        return tuple(v.astype(low) if is_float(v) else v for v in vals)
    # O1: cast only white-list ops down; black list up; others follow inputs
    if op_name in white_list():
        return tuple(v.astype(low) if is_float(v) else v for v in vals)
    if op_name in black_list():
        return tuple(
            v.astype(jnp.float32) if is_float(v) else v for v in vals
        )
    return vals


@contextlib.contextmanager
def auto_cast(enable=True, custom_white_list=None, custom_black_list=None,
              level="O1", dtype="bfloat16"):
    old = (_state.enabled, _state.dtype, _state.level, _state.custom_white,
           _state.custom_black)
    _state.enabled = enable
    _state.dtype = jnp.bfloat16 if str(dtype) in ("bfloat16", "bf16") \
        else jnp.float16
    _state.level = level
    _state.custom_white = set(custom_white_list or ())
    _state.custom_black = set(custom_black_list or ())
    try:
        yield
    finally:
        (_state.enabled, _state.dtype, _state.level, _state.custom_white,
         _state.custom_black) = old


amp_guard = auto_cast


def decorate(models, optimizers=None, level="O2", dtype="bfloat16",
             master_weight=None, save_dtype=None):
    """O2 decoration: cast model params to the low dtype
    (reference: paddle.amp.decorate)."""
    single = not isinstance(models, (list, tuple))
    model_list = [models] if single else list(models)
    for m in model_list:
        m.astype(str(dtype))
    if optimizers is None:
        return models
    return models, optimizers


class GradScaler:
    """Dynamic loss scaling (reference: python/paddle/amp/grad_scaler.py:26,
    kernels check_finite_and_unscale + update_loss_scaling)."""

    def __init__(self, enable=True, init_loss_scaling=2.0**15,
                 incr_ratio=2.0, decr_ratio=0.5, incr_every_n_steps=1000,
                 decr_every_n_nan_or_inf=2, use_dynamic_loss_scaling=True):
        self._enable = enable
        self._scale = float(init_loss_scaling)
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every = incr_every_n_steps
        self._decr_every = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = False
        # per-optimizer state since the last update(): the unscale_→clip→
        # step pattern must not divide by scale twice, and one optimizer's
        # overflow must not skip another's step (reference tracks
        # OptimizerState per optimizer the same way)
        self._unscaled = set()
        self._found_inf_per_opt = {}

    def is_enable(self):
        return self._enable

    def scale(self, var):
        if not self._enable:
            return var
        return var * self._scale

    def unscale_(self, optimizer):
        if not self._enable:
            return
        if id(optimizer) in self._unscaled:
            raise RuntimeError(
                "unscale_() has already been called on this optimizer "
                "since the last update()."
            )
        inv = 1.0 / self._scale
        found = False
        for p in optimizer._parameter_list:
            if p.grad is None:
                continue
            g = p.grad._value.astype(jnp.float32) * inv
            if not bool(jnp.isfinite(g).all()):
                found = True
            p.grad._value = g.astype(p.grad._value.dtype)
        self._found_inf = self._found_inf or found
        self._found_inf_per_opt[id(optimizer)] = found
        self._unscaled.add(id(optimizer))

    def step(self, optimizer):
        if not self._enable:
            optimizer.step()
            return
        if id(optimizer) not in self._unscaled:
            self.unscale_(optimizer)
        if not self._found_inf_per_opt.get(id(optimizer), False):
            optimizer.step()

    def update(self):
        self._unscaled.clear()
        self._found_inf_per_opt.clear()
        found_inf, self._found_inf = self._found_inf, False
        if not (self._enable and self._dynamic):
            return
        if found_inf:
            self._bad_steps += 1
            self._good_steps = 0
            if self._bad_steps >= self._decr_every:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad_steps = 0
        else:
            self._good_steps += 1
            self._bad_steps = 0
            if self._good_steps >= self._incr_every:
                self._scale *= self._incr_ratio
                self._good_steps = 0

    def minimize(self, optimizer, scaled):
        scaled.backward()
        self.step(optimizer)
        self.update()

    def get_loss_scaling(self):
        from ..tensor_core import Tensor

        return Tensor(np.float32(self._scale))

    def set_init_loss_scaling(self, v):
        self._scale = float(v)

    def state_dict(self):
        return {
            "scale": self._scale,
            "incr_ratio": self._incr_ratio,
            "decr_ratio": self._decr_ratio,
            "incr_every_n_steps": self._incr_every,
            "decr_every_n_nan_or_inf": self._decr_every,
            "good_steps": self._good_steps,
            "bad_steps": self._bad_steps,
        }

    def load_state_dict(self, d):
        self._scale = d.get("scale", self._scale)
        self._good_steps = d.get("good_steps", 0)
        self._bad_steps = d.get("bad_steps", 0)
