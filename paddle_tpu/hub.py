"""paddle.hub — load models from a hubconf.py entrypoint file.

Reference: python/paddle/hapi/hub.py (github/gitee/local sources). This
environment has no network egress, so only `source='local'` is supported;
remote sources raise with a clear message rather than hanging.
"""
import importlib.util
import os
import sys

__all__ = ["list", "help", "load"]

_HUBCONF = "hubconf.py"


def _load_hubconf(repo_dir):
    path = os.path.join(repo_dir, _HUBCONF)
    if not os.path.isfile(path):
        raise FileNotFoundError(f"no {_HUBCONF} in {repo_dir!r}")
    spec = importlib.util.spec_from_file_location("paddle_tpu_hubconf", path)
    module = importlib.util.module_from_spec(spec)
    sys.modules.pop("paddle_tpu_hubconf", None)
    spec.loader.exec_module(module)
    return module


def _check_source(source):
    if source not in ("local",):
        raise ValueError(
            f"hub source {source!r} unavailable: this build has no network "
            "egress; use source='local' with a checked-out repo directory")


def _entrypoints(module):
    deps = getattr(module, "dependencies", [])
    for dep in deps:
        if importlib.util.find_spec(dep) is None:
            raise RuntimeError(f"hubconf dependency {dep!r} not installed")
    return {
        name: fn for name, fn in vars(module).items()
        if callable(fn) and not name.startswith("_")
    }


def list(repo_dir, source="local", force_reload=False):  # noqa: A001
    """Entrypoint names exposed by the repo's hubconf.py."""
    _check_source(source)
    return sorted(_entrypoints(_load_hubconf(repo_dir)))


def help(repo_dir, model, source="local", force_reload=False):  # noqa: A001
    """Docstring of one entrypoint."""
    _check_source(source)
    eps = _entrypoints(_load_hubconf(repo_dir))
    if model not in eps:
        raise ValueError(f"unknown hub entrypoint {model!r}; "
                         f"available: {sorted(eps)}")
    return eps[model].__doc__


def load(repo_dir, model, source="local", force_reload=False, **kwargs):
    """Call the entrypoint, returning the constructed model."""
    _check_source(source)
    eps = _entrypoints(_load_hubconf(repo_dir))
    if model not in eps:
        raise ValueError(f"unknown hub entrypoint {model!r}; "
                         f"available: {sorted(eps)}")
    return eps[model](**kwargs)
