"""paddle_tpu.audio (reference: python/paddle/audio/ — features/layers.py
Spectrogram:33, MelSpectrogram:116, LogMelSpectrogram:231, MFCC:335)."""
from . import functional  # noqa: F401
from .features import (  # noqa: F401
    MFCC,
    LogMelSpectrogram,
    MelSpectrogram,
    Spectrogram,
)

__all__ = ["functional", "Spectrogram", "MelSpectrogram",
           "LogMelSpectrogram", "MFCC"]
