"""paddle_tpu.audio.functional (reference:
python/paddle/audio/functional/functional.py — hz_to_mel:24,
mel_to_hz:49, mel_frequencies:77, fft_frequencies:103,
compute_fbank_matrix:124, power_to_db:194, create_dct:246;
window.py get_window:290).

Pure jnp — differentiable, jit-safe, MXU-friendly (fbank/DCT are
matmuls)."""
import math

import jax.numpy as jnp

__all__ = ["hz_to_mel", "mel_to_hz", "mel_frequencies", "fft_frequencies",
           "compute_fbank_matrix", "power_to_db", "create_dct",
           "get_window"]


def hz_to_mel(freq, htk=False):
    freq = jnp.asarray(freq, jnp.float32)
    if htk:
        return 2595.0 * jnp.log10(1.0 + freq / 700.0)
    # slaney scale
    f_min, f_sp = 0.0, 200.0 / 3
    mels = (freq - f_min) / f_sp
    min_log_hz = 1000.0
    min_log_mel = (min_log_hz - f_min) / f_sp
    logstep = math.log(6.4) / 27.0
    return jnp.where(freq >= min_log_hz,
                     min_log_mel + jnp.log(freq / min_log_hz) / logstep,
                     mels)


def mel_to_hz(mel, htk=False):
    mel = jnp.asarray(mel, jnp.float32)
    if htk:
        return 700.0 * (10.0 ** (mel / 2595.0) - 1.0)
    f_min, f_sp = 0.0, 200.0 / 3
    freqs = f_min + f_sp * mel
    min_log_hz = 1000.0
    min_log_mel = (min_log_hz - f_min) / f_sp
    logstep = math.log(6.4) / 27.0
    return jnp.where(mel >= min_log_mel,
                     min_log_hz * jnp.exp(logstep * (mel - min_log_mel)),
                     freqs)


def mel_frequencies(n_mels=64, f_min=0.0, f_max=11025.0, htk=False):
    lo = hz_to_mel(f_min, htk)
    hi = hz_to_mel(f_max, htk)
    return mel_to_hz(jnp.linspace(lo, hi, n_mels), htk)


def fft_frequencies(sr, n_fft):
    return jnp.linspace(0, sr / 2, n_fft // 2 + 1)


def compute_fbank_matrix(sr, n_fft, n_mels=64, f_min=0.0, f_max=None,
                         htk=False, norm="slaney"):
    """(n_mels, n_fft//2+1) triangular mel filterbank."""
    f_max = f_max or sr / 2.0
    fftfreqs = fft_frequencies(sr, n_fft)
    melfreqs = mel_frequencies(n_mels + 2, f_min, f_max, htk)
    fdiff = jnp.diff(melfreqs)
    ramps = melfreqs[:, None] - fftfreqs[None, :]
    lower = -ramps[:-2] / fdiff[:-1, None]
    upper = ramps[2:] / fdiff[1:, None]
    weights = jnp.maximum(0.0, jnp.minimum(lower, upper))
    if norm == "slaney":
        enorm = 2.0 / (melfreqs[2:n_mels + 2] - melfreqs[:n_mels])
        weights = weights * enorm[:, None]
    return weights


def power_to_db(spect, ref_value=1.0, amin=1e-10, top_db=80.0):
    s = jnp.asarray(spect)
    log_spec = 10.0 * jnp.log10(jnp.maximum(amin, s))
    log_spec = log_spec - 10.0 * math.log10(max(amin, ref_value))
    if top_db is not None:
        log_spec = jnp.maximum(log_spec, log_spec.max() - top_db)
    return log_spec


def create_dct(n_mfcc, n_mels, norm="ortho"):
    """(n_mels, n_mfcc) DCT-II matrix (reference functional.py:246)."""
    n = jnp.arange(n_mels, dtype=jnp.float32)
    k = jnp.arange(n_mfcc, dtype=jnp.float32)[None, :]
    dct = jnp.cos(math.pi / n_mels * (n[:, None] + 0.5) * k)
    if norm == "ortho":
        dct = dct * jnp.where(k == 0, 1.0 / math.sqrt(n_mels),
                              math.sqrt(2.0 / n_mels))
    else:
        dct = dct * 2.0
    return dct


def get_window(window, win_length, fftbins=True):
    """hann/hamming/blackman/bartlett/kaiser/taylor subset the reference
    exposes (window.py:290)."""
    n = win_length
    m = jnp.arange(n, dtype=jnp.float32)
    denom = n if fftbins else n - 1
    if isinstance(window, tuple):
        name, arg = window
    else:
        name, arg = window, None
    if name == "hann":
        return 0.5 - 0.5 * jnp.cos(2 * math.pi * m / denom)
    if name == "hamming":
        return 0.54 - 0.46 * jnp.cos(2 * math.pi * m / denom)
    if name == "blackman":
        return (0.42 - 0.5 * jnp.cos(2 * math.pi * m / denom)
                + 0.08 * jnp.cos(4 * math.pi * m / denom))
    if name == "bartlett":
        return 1.0 - jnp.abs(2.0 * m / denom - 1.0)
    if name == "rectangular" or name == "boxcar":
        return jnp.ones((n,), jnp.float32)
    if name == "kaiser":
        import jax

        beta = 12.0 if arg is None else float(arg)
        x = 2.0 * m / denom - 1.0
        num = jax.scipy.special.i0(beta * jnp.sqrt(1 - x * x))
        return num / jax.scipy.special.i0(jnp.asarray(beta))
    raise ValueError(f"unsupported window {window!r}")
