"""Audio feature layers (reference: python/paddle/audio/features/layers.py).

STFT is framing + window + rfft: framing via gather (static shapes), the
spectrogram/mel/dct stages are matmuls — all MXU/XLA-friendly and usable
inside jitted steps."""
import jax.numpy as jnp

from ..nn.layer.layers import Layer
from ..ops._helpers import apply_jfn
from . import functional as F

__all__ = ["Spectrogram", "MelSpectrogram", "LogMelSpectrogram", "MFCC"]


def _frame(x, frame_length, hop_length, center, pad_mode):
    if center:
        pad = [(0, 0)] * (x.ndim - 1) + [(frame_length // 2,
                                          frame_length // 2)]
        x = jnp.pad(x, pad, mode=pad_mode)
    n = 1 + (x.shape[-1] - frame_length) // hop_length
    starts = jnp.arange(n) * hop_length
    idx = starts[:, None] + jnp.arange(frame_length)[None, :]
    return x[..., idx]  # (..., n_frames, frame_length)


class Spectrogram(Layer):
    """reference layers.py:33 — |STFT|^power, (..., freq, time)."""

    def __init__(self, n_fft=512, hop_length=None, win_length=None,
                 window="hann", power=2.0, center=True,
                 pad_mode="reflect", dtype=None):
        super().__init__()
        self.n_fft = n_fft
        self.hop_length = hop_length or n_fft // 4
        self.win_length = win_length or n_fft
        self.power = power
        self.center = center
        self.pad_mode = pad_mode
        w = F.get_window(window, self.win_length)
        if self.win_length < n_fft:  # center-pad window to n_fft
            lp = (n_fft - self.win_length) // 2
            w = jnp.pad(w, (lp, n_fft - self.win_length - lp))
        self._window = w

    def forward(self, x):
        def jfn(v):
            frames = _frame(v, self.n_fft, self.hop_length, self.center,
                            self.pad_mode)
            spec = jnp.fft.rfft(frames * self._window, axis=-1)
            mag = jnp.abs(spec)
            if self.power != 1.0:
                mag = mag ** self.power
            return jnp.swapaxes(mag, -1, -2)  # (..., freq, time)

        return apply_jfn("spectrogram", jfn, x)


class MelSpectrogram(Layer):
    """reference layers.py:116."""

    def __init__(self, sr=22050, n_fft=512, hop_length=None,
                 win_length=None, window="hann", power=2.0, center=True,
                 pad_mode="reflect", n_mels=64, f_min=50.0, f_max=None,
                 htk=False, norm="slaney", dtype=None):
        super().__init__()
        self._spectrogram = Spectrogram(
            n_fft=n_fft, hop_length=hop_length, win_length=win_length,
            window=window, power=power, center=center, pad_mode=pad_mode)
        self.fbank = F.compute_fbank_matrix(
            sr=sr, n_fft=n_fft, n_mels=n_mels, f_min=f_min, f_max=f_max,
            htk=htk, norm=norm)

    def forward(self, x):
        spec = self._spectrogram(x)
        return apply_jfn(
            "mel_spectrogram",
            lambda s: jnp.einsum("mf,...ft->...mt", self.fbank, s), spec)


class LogMelSpectrogram(Layer):
    """reference layers.py:231."""

    def __init__(self, sr=22050, ref_value=1.0, amin=1e-10, top_db=None,
                 **mel_kwargs):
        super().__init__()
        self._mel = MelSpectrogram(sr=sr, **mel_kwargs)
        self.ref_value = ref_value
        self.amin = amin
        self.top_db = top_db

    def forward(self, x):
        mel = self._mel(x)
        return apply_jfn(
            "log_mel",
            lambda m: F.power_to_db(m, self.ref_value, self.amin,
                                    self.top_db), mel)


class MFCC(Layer):
    """reference layers.py:335 — DCT over log-mel."""

    def __init__(self, sr=22050, n_mfcc=40, norm="ortho", **mel_kwargs):
        super().__init__()
        self._log_mel = LogMelSpectrogram(sr=sr, **mel_kwargs)
        n_mels = self._log_mel._mel.fbank.shape[0]
        self.dct = F.create_dct(n_mfcc, n_mels, norm=norm)

    def forward(self, x):
        lm = self._log_mel(x)
        return apply_jfn(
            "mfcc", lambda m: jnp.einsum("mk,...mt->...kt", self.dct, m),
            lm)
