"""The eager Tensor.

TPU-native replacement for the reference's eager Tensor
(reference: paddle/fluid/pybind/eager.cc Tensor type,
paddle/phi/api/include/tensor.h `paddle::experimental::Tensor`,
paddle/fluid/eager/autograd_meta.h:61 `AutogradMeta`).

A Tensor wraps a jax.Array (or a jax tracer, when code runs under
`to_static`/`jax.jit`). AutogradMeta collapses to three fields:
`stop_gradient`, `.grad`, and `_grad_node` (the tape creator node).
Most math methods are installed by `paddle_tpu.ops._install_tensor_methods`.
"""
import numpy as np

import jax
import jax.numpy as jnp

from .autograd import engine
from .core import dtype as dtype_mod
from .core import place as place_mod

_tensor_count = 0


class Tensor:
    __slots__ = (
        "_value",
        "stop_gradient",
        "grad",
        "_grad_node",
        "_out_index",
        "name",
        "_backward_hooks",
        "persistable",
        "trainable",
        "_pspec",  # jax PartitionSpec for distributed placement (or None)
        "_inplace_version",  # bumped on every mutation (tensor_wrapper.h)
        "__weakref__",
    )

    def __init__(self, value, stop_gradient=True, name=None):
        global _tensor_count
        if isinstance(value, Tensor):
            value = value._value
        elif not isinstance(value, (jax.Array, jax.core.Tracer)):
            value = jnp.asarray(value)
        # ownership-by-contract: Tensor WRAPS the buffer zero-copy —
        # jax arrays are immutable, so sharing is safe; donation
        # hazards are the caller's to manage (documented)
        self._value = value  # ptlint: disable=PTL501
        self.stop_gradient = stop_gradient
        self.grad = None
        self._grad_node = None
        self._out_index = 0
        if name is None:
            name = f"generated_tensor_{_tensor_count}"
            _tensor_count += 1
        self.name = name
        self._backward_hooks = None
        self.persistable = False
        self._pspec = None
        self._inplace_version = 0
        self.trainable = not stop_gradient

    # ---- metadata ----
    @property
    def shape(self):
        return list(self._value.shape)

    @property
    def dtype(self):
        return np.dtype(self._value.dtype)

    @property
    def ndim(self):
        return self._value.ndim

    @property
    def size(self):
        return int(np.prod(self._value.shape)) if self._value.shape else 1

    @property
    def place(self):
        return place_mod.get_place()

    @property
    def is_leaf(self):
        return self._grad_node is None

    def numel(self):
        return self.size

    def dim(self):
        return self.ndim

    # ---- value access ----
    def numpy(self):
        return np.asarray(self._value)

    def item(self, *args):
        if args:
            return self.numpy().item(*args)
        return self.numpy().item()

    def tolist(self):
        return self.numpy().tolist()

    def __array__(self, dtype=None):
        a = self.numpy()
        return a.astype(dtype) if dtype is not None else a

    def __deepcopy__(self, memo):
        # Fresh auto-generated name: copied layers (e.g. TransformerEncoder
        # deep-copying its prototype layer) must not alias optimizer/state
        # keys of the original parameters.
        cls = type(self)
        new = cls.__new__(cls)
        memo[id(self)] = new
        for slot_holder in type(self).__mro__:
            for s in getattr(slot_holder, "__slots__", ()):
                if s == "__weakref__" or not hasattr(self, s):
                    continue
                object.__setattr__(new, s, getattr(self, s))
        new._grad_node = None
        new._out_index = 0
        new.grad = None
        global _tensor_count
        new.name = f"generated_tensor_{_tensor_count}"
        _tensor_count += 1
        return new

    # ---- autograd ----
    def backward(self, grad_tensor=None, retain_graph=False):
        """reference: paddle/fluid/eager/backward.cc:394 Backward."""
        engine.run_backward([self], [grad_tensor], retain_graph=retain_graph)

    def clear_grad(self):
        self.grad = None

    def clear_gradient(self, set_to_zero=False):
        if set_to_zero and self.grad is not None:
            self.grad = Tensor(jnp.zeros_like(self.grad._value), True)
        else:
            self.grad = None

    def detach(self):
        t = Tensor(self._value, stop_gradient=True, name=self.name + ".detach")
        return t

    def detach_(self):
        self._grad_node = None
        self.stop_gradient = True
        return self

    def register_hook(self, hook):
        if self._backward_hooks is None:
            self._backward_hooks = []
        self._backward_hooks.append(hook)

        class _Handle:
            def remove(_self):
                try:
                    self._backward_hooks.remove(hook)
                except ValueError:
                    pass

        return _Handle()

    def retain_grads(self):
        # grads for non-leaf tensors are collected via paddle_tpu.grad();
        # eager .grad retention for intermediates not needed in practice.
        pass

    # ---- mutation ----
    def _check_mutation(self, opname):
        """Direct-assignment mutations on a NON-leaf sever the recorded
        graph — the reference detects this via inplace version counting
        (paddle/fluid/eager/tensor_wrapper.h); silently dropping the
        grad node yields wrong gradients, so raise instead. (Recorded
        vjps here capture values functionally, so mutating a LEAF never
        corrupts already-recorded gradients — only severing does.)"""
        from .autograd import engine as _engine

        if (_engine.is_grad_enabled() and not self.stop_gradient
                and self._grad_node is not None):
            raise RuntimeError(
                f"{opname} would overwrite a non-leaf Tensor that is part "
                "of a recorded gradient graph; call it under "
                "paddle.no_grad() or on a detached tensor"
            )
        self._inplace_version += 1  # only mutations that actually happen

    def set_value(self, value):
        if isinstance(value, Tensor):
            value = value._value
        value = jnp.asarray(value, dtype=self._value.dtype)
        if tuple(value.shape) != tuple(self._value.shape):
            raise ValueError(
                f"set_value shape mismatch {value.shape} vs {self._value.shape}"
            )
        self._check_mutation("set_value")
        # ownership-by-contract: immutable jax buffer, shared on purpose
        self._value = value  # ptlint: disable=PTL501
        self._grad_node = None
        return self

    def copy_(self, other, *_):
        return self.set_value(other)

    def fill_(self, v):
        self._check_mutation("fill_")
        self._value = jnp.full_like(self._value, v)
        self._grad_node = None
        return self

    def zero_(self):
        return self.fill_(0)

    def fill_diagonal_(self, value, offset=0, wrap=False, name=None):
        """In-place diagonal fill (reference: phi fill_diagonal kernel /
        Tensor.fill_diagonal_). 2-D: fill the offset diagonal (`wrap`
        restarts the diagonal past each N×N block of a tall matrix, the
        reference/torch tall-matrix semantics). N-D (all dims equal):
        fill the main hyper-diagonal."""
        self._check_mutation("fill_diagonal_")
        v = self._value
        if v.ndim < 2:
            raise ValueError("fill_diagonal_ needs at least 2 dims")
        if v.ndim == 2:
            import numpy as _np

            rows, cols = int(v.shape[0]), int(v.shape[1])
            if offset >= cols or -offset >= rows:
                return self  # diagonal entirely outside the matrix
            start = offset if offset >= 0 else -offset * cols
            flat = _np.arange(start, rows * cols, cols + 1)
            r, c = flat // cols, flat % cols
            if not wrap and len(c) > 1:
                # stop at the first wrap-around (col resets)
                brk = _np.where(_np.diff(c) < 0)[0]
                if brk.size:
                    r, c = r[: brk[0] + 1], c[: brk[0] + 1]
            new = v.at[r, c].set(value)
        else:
            if len(set(v.shape)) != 1:
                raise ValueError(
                    "N-D fill_diagonal_ needs all dims equal")
            idx = jnp.arange(v.shape[0])
            new = v.at[tuple([idx] * v.ndim)].set(value)
        self._value = new
        self._grad_node = None
        return self

    # scale_ is installed by ops._install_tensor_methods as a
    # tape-recording in-place op (no graph severing) — not defined here

    # ---- conversion ----
    def astype(self, dtype):
        from .ops.manipulation import cast

        return cast(self, dtype)

    def cast(self, dtype):
        return self.astype(dtype)

    def clone(self):
        from .ops.math import _identity

        return _identity(self)

    def cpu(self):
        return self

    def cuda(self, *a, **k):
        return self

    def to(self, *args, **kwargs):
        for a in list(args) + list(kwargs.values()):
            try:
                d = dtype_mod.convert_dtype(a)
                return self.astype(d)
            except (ValueError, TypeError):
                continue
        return self

    def pin_memory(self):
        return self

    # ---- python protocol ----
    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of a 0-D tensor")
        return self._value.shape[0]

    def __bool__(self):
        if self.size != 1:
            raise ValueError(
                "The truth value of a Tensor with more than one element is "
                "ambiguous"
            )
        return bool(self.numpy())

    def __int__(self):
        return int(self.numpy())

    def __float__(self):
        return float(self.numpy())

    def __index__(self):
        return int(self.numpy())

    def __hash__(self):
        return id(self)

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def __repr__(self):
        prefix = "Tensor(shape={}, dtype={}, stop_gradient={},\n       ".format(
            self.shape, self.dtype.name, self.stop_gradient
        )
        try:
            body = np.array2string(self.numpy(), separator=", ")
        except Exception:
            body = f"<traced {self._value}>"
        return prefix + body + ")"

    __str__ = __repr__

    # ---- indexing ----
    def __getitem__(self, idx):
        from .ops.manipulation import _getitem

        return _getitem(self, idx)

    def __setitem__(self, idx, value):
        from .ops.manipulation import _setitem

        _setitem(self, idx, value)

    @property
    def T(self):
        from .ops.linalg import t as _t

        return _t(self)


engine.register_tensor_class(Tensor)


_parameter_registry = []  # weakrefs; static.ExponentialMovingAverage reads it


class Parameter(Tensor):
    """Trainable parameter (reference: python/paddle/fluid/framework.py
    `Parameter`/`ParamBase`)."""

    __slots__ = ("optimize_attr", "regularizer", "need_clip", "is_distributed")

    def __init__(self, value, trainable=True, name=None):
        super().__init__(value, stop_gradient=not trainable, name=name)
        self.persistable = True
        self.trainable = trainable
        self.optimize_attr = {"learning_rate": 1.0}
        self.regularizer = None
        self.need_clip = True
        self.is_distributed = False
        import weakref

        _parameter_registry.append(weakref.ref(self))
        if len(_parameter_registry) % 4096 == 0:  # drop dead refs
            _parameter_registry[:] = [r for r in _parameter_registry
                                      if r() is not None]

    def __repr__(self):
        return "Parameter containing:\n" + super().__repr__()
