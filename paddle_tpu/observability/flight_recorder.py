"""Failure flight recorder — bounded ring of recent telemetry +
self-contained postmortem dumps.

When a replica dies mid-stream, a failover requeues work, a divergence
sentinel rolls training back, or a chaos injector fires, the question
is always "what was the system doing in the seconds BEFORE?" — and the
answer is gone by the time a human attaches. The recorder keeps it: a
bounded in-memory ring that continuously captures

* completed spans (full telemetry mode — fed by a `tracing` sink),
* request phase segments (`reqtrace`, metrics mode and up — so a
  killed request's timeline survives even without span tracing),
* every anomaly-journal event (`resilience.record` feeds the ring),
* periodic router/scheduler state snapshots (the router's monitor
  thread records a throttled fleet view),

and :func:`dump` writes ONE self-contained postmortem JSON — reason,
caller context (dead replica, requeued request ids + trace_ids, ...),
the ring, the live state providers' snapshots, and a compact metrics
dump — then journals a ``flight_dump`` event pointing at it. Wired
into the PR-13 failover path (`FleetRouter._handle_death`, the chaos
kill in `LocalReplica`), the PR-14 rollback path
(`run_with_fault_tolerance`), and `LLMEngine.abort_all`.

File policy: the ring and the journal/counter side effects are live in
every telemetry mode but OFF; the postmortem FILE is written when a
directory is passed, when ``PT_FLIGHT_DIR`` is set, or in full
telemetry mode (to ``PT_TELEMETRY_DIR``) — so tier-1's default metrics
mode never litters the working directory with dump files.
"""
import collections
import json
import os
import threading
import time

from . import tracing
from .metrics import _STATE, counter, registry

__all__ = ["FlightRecorder", "recorder", "record_event", "dump",
           "add_state_provider", "remove_state_provider"]

_DUMPS_TOTAL = counter(
    "pt_flight_dumps_total",
    "flight-recorder postmortem dumps, by reason (replica_death | "
    "chaos_replica_kill | failover_requeue | divergence_rollback | "
    "engine_abort | manual)", labelnames=("reason",))


def _rank():
    return int(os.environ.get("PADDLE_TRAINER_ID", "0"))


class FlightRecorder:  # ptlint: thread-shared (every runtime thread records; dump reads)
    """Bounded event ring + postmortem writer (module docstring)."""

    def __init__(self, capacity=4096):
        self._ring = collections.deque(maxlen=int(capacity))
        self._providers = {}     # name -> zero-arg snapshot fn
        self._lock = threading.Lock()   # providers dict + dump seq
        self._seq = 0

    # ---- capture ----

    def record(self, kind, **fields):
        """Append one event (cheap: a dict build + deque append, both
        GIL-atomic; gated off in telemetry mode 'off')."""
        if _STATE.mode == 0:
            return
        entry = {"t": time.time(), "kind": kind}
        entry.update(fields)
        self._ring.append(entry)

    def events(self, kind=None):
        """Snapshot of the ring (oldest first)."""
        evs = list(self._ring)
        return evs if kind is None else [e for e in evs
                                         if e.get("kind") == kind]

    def clear(self):
        self._ring.clear()

    # ---- live-state providers (dump-time snapshots) ----

    def add_state_provider(self, name, fn):
        """Register a zero-arg snapshot callable (e.g. a router's
        `metrics`) included — individually guarded — in every dump."""
        with self._lock:
            self._providers[name] = fn

    def remove_state_provider(self, name):
        with self._lock:
            self._providers.pop(name, None)

    # ---- postmortem ----

    def dump(self, reason, directory=None, **context):
        """Write the postmortem (module docstring has the file policy).
        Always journals + counts; returns the file path or None."""
        if _STATE.mode == 0:
            return None
        _DUMPS_TOTAL.labels(reason=reason).inc()
        with self._lock:
            providers = list(self._providers.items())
            seq = self._seq
            self._seq += 1
        states = {}
        for name, fn in providers:
            try:
                states[name] = fn()
            except Exception as e:   # a dying subsystem's snapshot
                states[name] = {"error": repr(e)}
        try:
            metrics_compact = registry().compact()
        except Exception as e:
            metrics_compact = {"error": repr(e)}
        payload = {"reason": reason, "t": time.time(), "rank": _rank(),
                   "context": context, "states": states,
                   "metrics": metrics_compact,
                   "events": list(self._ring)}
        d = directory or os.environ.get("PT_FLIGHT_DIR")
        if d is None and _STATE.mode >= _STATE.FULL:
            d = os.environ.get("PT_TELEMETRY_DIR") or "./telemetry"
        path = None
        if d:
            path = os.path.join(
                d, f"postmortem.rank{_rank()}.{seq}.{reason}.json")
            try:
                os.makedirs(d, exist_ok=True)
                with open(path, "w") as f:
                    # default=repr: context may carry numpy scalars /
                    # exceptions — a dump must never fail on its cargo
                    json.dump(payload, f, default=repr)
            except OSError:
                path = None
        try:
            from ..distributed.resilience import record

            record("flight_dump", reason=reason, path=path,
                   n_events=len(payload["events"]))
        except Exception:  # ptlint: disable=PTL804 (the recorder cannot record its own failure)
            pass
        return path


_RECORDER = FlightRecorder()


def recorder():
    """The process-wide default recorder."""
    return _RECORDER


def record_event(kind, **fields):
    _RECORDER.record(kind, **fields)


def dump(reason, directory=None, **context):
    return _RECORDER.dump(reason, directory=directory, **context)


def add_state_provider(name, fn):
    _RECORDER.add_state_provider(name, fn)


def remove_state_provider(name):
    _RECORDER.remove_state_provider(name)


def _span_sink(ev):
    # completed spans (full mode) flow into the ring so a postmortem
    # carries the last seconds of spans — incl. the per-request
    # phase.* events, which carry trace_id in their args
    _RECORDER.record("span", span=ev)


tracing.add_sink(_span_sink)
