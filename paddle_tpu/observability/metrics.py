"""Process-wide metrics registry: Counter / Gauge / Histogram with labels.

The measurement substrate every runtime layer shares (reference: the
monitor stats + benchmark timer scattered through the reference's
profiler/ and fluid monitors; here ONE registry instead of per-module
``stats`` dicts). Metric objects are cheap process-globals created at
import time; instrumented code calls ``.inc()`` / ``.set()`` /
``.observe()`` unconditionally and the registry decides whether anything
happens:

* mode ``off``    (``PT_TELEMETRY=0``)  — every write is a no-op behind a
  single attribute check (the overhead test pins this path).
* mode ``metrics`` (default)            — counting is live. Writes are
  LOCK-FREE: each metric child keeps per-thread cells keyed by thread id
  (a thread only ever mutates its own cell, and CPython dict get/set are
  single bytecodes), so concurrent increments never lose updates and the
  hot path takes no lock. Snapshots merge the cells.
* mode ``full``   (``PT_TELEMETRY=1``)  — same counting, plus span
  tracing and at-exit exporters (see ``tracing.py`` / package __init__).

Exporters: ``snapshot()`` (nested dict), ``to_prometheus()``
(text-format 0.0.4), ``to_jsonl()`` (one JSON object per series).
Histograms expose ``quantile(q)`` via linear interpolation over their
bucket counts.

Label cardinality is capped per metric (``max_series``): past the cap
new label combinations collapse into one ``__overflow__`` series (and a
one-time warning) instead of growing without bound or crashing a hot
path — the failure mode of label-by-request-id mistakes.
"""
import bisect
import json
import os
import threading
import warnings
from threading import get_ident

__all__ = ["Counter", "Gauge", "Histogram", "MetricsRegistry", "registry",
           "counter", "gauge", "histogram", "snapshot", "to_prometheus",
           "to_jsonl", "DEFAULT_BUCKETS"]


class _State:
    """Telemetry mode shared by metrics and tracing.

    0 = off (no-op), 1 = metrics only (default), 2 = full (+ tracing,
    + at-exit export). Resolved once from PT_TELEMETRY; tests flip it
    via observability.set_mode().
    """

    __slots__ = ("mode",)

    OFF, METRICS, FULL = 0, 1, 2

    def __init__(self):
        v = os.environ.get("PT_TELEMETRY", "").strip().lower()
        if v in ("0", "off", "false", "no"):
            self.mode = self.OFF
        elif v in ("", "metrics", "count", "counters"):
            # the mode NAMES are accepted too, so PT_TELEMETRY=metrics
            # means counting-only (not silently full)
            self.mode = self.METRICS
        else:
            self.mode = self.FULL


_STATE = _State()

# seconds-scale duration buckets: 100 µs … 5 min + overflow
DEFAULT_BUCKETS = (0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01,
                   0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
                   30.0, 60.0, 120.0, 300.0)


# ----------------------------------------------------------------- children

class _CounterCell:
    """One monotonic counter series. Lock-free: per-thread cells.

    always=True exempts the cell from off-mode gating — for counters
    that back a PRE-EXISTING accounting API (xproc.stats) whose
    consumers predate the telemetry gate and must keep counting under
    PT_TELEMETRY=0."""

    __slots__ = ("_cells", "_always")

    def __init__(self, always=False):
        self._cells = {}
        self._always = always

    def inc(self, n=1):
        if _STATE.mode == 0 and not self._always:
            return
        cells = self._cells
        tid = get_ident()
        cells[tid] = cells.get(tid, 0) + n

    @property
    def value(self):
        return sum(list(self._cells.values()))


class _GaugeCell:
    """Last-write-wins instantaneous value (a float store is atomic
    under the GIL, so no cells are needed)."""

    __slots__ = ("_v",)

    def __init__(self):
        self._v = 0.0

    def set(self, v):
        if _STATE.mode == 0:
            return
        self._v = float(v)

    def inc(self, n=1):
        if _STATE.mode == 0:
            return
        self._v += n          # convenience; not for cross-thread counting

    def dec(self, n=1):
        self.inc(-n)

    @property
    def value(self):
        return self._v


class _HistogramCell:
    """Bucketed distribution. Per-thread cells of
    [bucket_counts, sum, count]; merged at snapshot time."""

    __slots__ = ("_bounds", "_cells")

    def __init__(self, bounds):
        self._bounds = bounds
        self._cells = {}

    def observe(self, x):
        if _STATE.mode == 0:
            return
        tid = get_ident()
        cell = self._cells.get(tid)
        if cell is None:
            cell = self._cells[tid] = [[0] * (len(self._bounds) + 1),
                                       0.0, 0]
        cell[0][bisect.bisect_left(self._bounds, x)] += 1
        cell[1] += x
        cell[2] += 1

    def merged(self):
        counts = [0] * (len(self._bounds) + 1)
        total, n = 0.0, 0
        for cell in list(self._cells.values()):
            for i, c in enumerate(list(cell[0])):
                counts[i] += c
            total += cell[1]
            n += cell[2]
        return counts, total, n

    @property
    def count(self):
        return self.merged()[2]

    @property
    def sum(self):
        return self.merged()[1]

    def quantile(self, q):
        """Linear interpolation inside the bucket holding rank q·n.
        Returns 0.0 with no observations; the overflow bucket answers
        with the largest finite bound."""
        counts, _, n = self.merged()
        if n == 0:
            return 0.0
        rank = q * n
        cum = 0
        for i, c in enumerate(counts):
            prev_cum = cum
            cum += c
            if cum >= rank and c > 0:
                if i >= len(self._bounds):          # overflow bucket
                    return float(self._bounds[-1])
                lo = self._bounds[i - 1] if i > 0 else 0.0
                hi = self._bounds[i]
                frac = (rank - prev_cum) / c
                return lo + (hi - lo) * min(max(frac, 0.0), 1.0)
        return float(self._bounds[-1])


def summarize_histogram_cell(cell):
    """{count, sum, p50, p95, p99} for one histogram cell — the ONE
    percentile-view shape (`Histogram.summary()` for unlabeled
    histograms, `reqtrace.phase_summary()` per label)."""
    _counts, total, n = cell.merged()
    return {"count": n, "sum": total,
            "p50": cell.quantile(0.50),
            "p95": cell.quantile(0.95),
            "p99": cell.quantile(0.99)}


_CELL_TYPES = {"counter": _CounterCell, "gauge": _GaugeCell,
               "histogram": _HistogramCell}


# ------------------------------------------------------------------ metrics

class _Metric:
    """Shared parent machinery: an unlabeled metric proxies straight to
    its single cell; a labeled one vends children via .labels()."""

    kind = None

    def __init__(self, name, help="", labelnames=(), max_series=512,
                 **cell_kw):
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self.max_series = int(max_series)
        self._cell_kw = cell_kw
        self._children = {}
        self._lock = threading.Lock()      # child creation only
        self._overflow = None
        self._warned = False
        if not self.labelnames:
            self._default = self._make_cell()
        else:
            self._default = None

    def _make_cell(self):
        return _CELL_TYPES[self.kind](**self._cell_kw)

    def labels(self, *values, **kv):
        if kv:
            try:
                values = tuple(kv[k] for k in self.labelnames)
            except KeyError as e:
                raise ValueError(
                    f"{self.name}: unknown label {e} "
                    f"(expects {self.labelnames})") from e
        else:
            values = tuple(values)
        if len(values) != len(self.labelnames):
            raise ValueError(
                f"{self.name}: got {len(values)} label values, expects "
                f"{self.labelnames}")
        values = tuple(str(v) for v in values)
        child = self._children.get(values)
        if child is not None:
            return child
        with self._lock:
            child = self._children.get(values)
            if child is not None:
                return child
            if len(self._children) >= self.max_series:
                # cardinality blowout: collapse instead of growing or
                # raising from a hot path
                if not self._warned:
                    self._warned = True
                    warnings.warn(
                        f"metric {self.name} exceeded max_series="
                        f"{self.max_series}; new label sets collapse "
                        "into '__overflow__'", RuntimeWarning,
                        stacklevel=2)
                if self._overflow is None:
                    self._overflow = self._make_cell()
                    self._children[
                        ("__overflow__",) * len(self.labelnames)
                    ] = self._overflow
                return self._overflow
            child = self._make_cell()
            self._children[values] = child
            return child

    def _series(self):
        """[(label_values_tuple, cell)] — () key for the unlabeled cell."""
        if self._default is not None:
            return [((), self._default)]
        return list(self._children.items())

    def remove(self, *values, **kv):
        """Drop one label series (e.g. a departed rank's gauge) so it
        stops being exported as if still live. No-op if absent."""
        if kv:
            values = tuple(str(kv[k]) for k in self.labelnames)
        else:
            values = tuple(str(v) for v in values)
        with self._lock:
            self._children.pop(values, None)

    # unlabeled proxying -------------------------------------------------
    def _cell(self):
        if self._default is None:
            raise ValueError(
                f"{self.name} has labels {self.labelnames}; call "
                ".labels(...) first")
        return self._default


class Counter(_Metric):
    kind = "counter"

    def __init__(self, name, help="", labelnames=(), max_series=512,
                 always_on=False):
        super().__init__(name, help, labelnames, max_series,
                         always=always_on)

    def inc(self, n=1):
        self._cell().inc(n)

    @property
    def value(self):
        return self._cell().value


class Gauge(_Metric):
    kind = "gauge"

    def set(self, v):
        self._cell().set(v)

    def inc(self, n=1):
        self._cell().inc(n)

    def dec(self, n=1):
        self._cell().dec(n)

    @property
    def value(self):
        return self._cell().value


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, name, help="", labelnames=(), max_series=512,
                 buckets=None):
        bounds = tuple(sorted(buckets or DEFAULT_BUCKETS))
        super().__init__(name, help, labelnames, max_series,
                         bounds=bounds)
        self.buckets = bounds

    def observe(self, x):
        self._cell().observe(x)

    def quantile(self, q):
        return self._cell().quantile(q)

    def summary(self):
        """{count, sum, p50, p95, p99} for the (unlabeled) series —
        the one-call percentile view for consumers holding a histogram
        handle (labeled histograms: summarize each `_series()` cell
        via `summarize_histogram_cell`, as reqtrace.phase_summary
        does)."""
        return summarize_histogram_cell(self._cell())

    @property
    def count(self):
        return self._cell().count

    @property
    def sum(self):
        return self._cell().sum


# ----------------------------------------------------------------- registry

def _escape_label(v):
    return v.replace("\\", r"\\").replace('"', r"\"").replace("\n", r"\n")


def _fmt_labels(names, values, extra=()):
    pairs = [f'{n}="{_escape_label(v)}"' for n, v in zip(names, values)]
    pairs += [f'{n}="{_escape_label(str(v))}"' for n, v in extra]
    return "{" + ",".join(pairs) + "}" if pairs else ""


def _fmt_num(v):
    if isinstance(v, float) and v.is_integer():
        return str(int(v))
    return repr(v) if isinstance(v, float) else str(v)


class MetricsRegistry:
    """name → metric. get-or-create accessors enforce one (type,
    labelnames) per name, so two modules asking for the same counter
    share one series family."""

    def __init__(self):
        self._metrics = {}
        self._lock = threading.Lock()

    def _get_or_create(self, cls, name, help, labelnames, **kw):
        m = self._metrics.get(name)
        if m is None:
            # construct OUTSIDE the lock: the metric class arrives as
            # an argument, and caller-visible code under the registry
            # lock is the PTL803 re-entrancy shape; a losing racer
            # just discards its fresh instance
            fresh = cls(name, help=help, labelnames=labelnames, **kw)
            with self._lock:
                m = self._metrics.get(name)
                if m is None:
                    self._metrics[name] = fresh
                    return fresh
        if not isinstance(m, cls) or m.labelnames != tuple(labelnames):
            raise ValueError(
                f"metric {name} already registered as {m.kind}"
                f"{m.labelnames}; requested {cls.kind}{tuple(labelnames)}")
        return m

    def counter(self, name, help="", labelnames=(), **kw):
        return self._get_or_create(Counter, name, help, labelnames, **kw)

    def gauge(self, name, help="", labelnames=(), **kw):
        return self._get_or_create(Gauge, name, help, labelnames, **kw)

    def histogram(self, name, help="", labelnames=(), **kw):
        return self._get_or_create(Histogram, name, help, labelnames, **kw)

    def get(self, name):
        return self._metrics.get(name)

    def __iter__(self):
        return iter(list(self._metrics.values()))

    def reset(self):
        """Drop every registered metric (tests; never in production —
        module-level metric handles keep working because instrumented
        code re-fetches by name or holds the object, whose cells simply
        stop being reported)."""
        with self._lock:
            self._metrics.clear()

    # ---- exporters ----
    def snapshot(self):
        """{name: {"type", "help", "series": [{labels, ...values}]}}."""
        out = {}
        for m in self:
            series = []
            for values, cell in m._series():
                labels = dict(zip(m.labelnames, values))
                if m.kind == "histogram":
                    counts, total, n = cell.merged()
                    series.append({
                        "labels": labels, "count": n, "sum": total,
                        "buckets": dict(zip(
                            [str(b) for b in m.buckets] + ["+Inf"],
                            counts)),
                        "p50": cell.quantile(0.50),
                        "p95": cell.quantile(0.95),
                        "p99": cell.quantile(0.99)})
                else:
                    series.append({"labels": labels, "value": cell.value})
            out[m.name] = {"type": m.kind, "help": m.help,
                           "series": series}
        return out

    def to_prometheus(self):
        """Prometheus text exposition format 0.0.4. Each histogram is
        followed by a `{name}_quantile` GAUGE family (p50/p95/p99 by a
        `quantile` label), so scrapers (and the bench stamps / router
        load view) read percentiles directly instead of re-deriving
        them from bucket counts — a SEPARATE family emitted after the
        histogram's own, because foreign samples inside a
        `# TYPE ... histogram` block violate the exposition format and
        split the family in spec parsers (verified against
        prometheus_client's reference parser)."""
        lines = []
        for m in self:
            if m.help:
                lines.append(f"# HELP {m.name} {m.help}")
            ptype = m.kind
            lines.append(f"# TYPE {m.name} {ptype}")
            qlines = []
            for values, cell in m._series():
                if m.kind == "histogram":
                    counts, total, n = cell.merged()
                    cum = 0
                    for b, c in zip(list(m.buckets) + ["+Inf"], counts):
                        cum += c
                        lines.append(
                            f"{m.name}_bucket"
                            f"{_fmt_labels(m.labelnames, values, [('le', b)])}"
                            f" {cum}")
                    lab = _fmt_labels(m.labelnames, values)
                    lines.append(f"{m.name}_sum{lab} {_fmt_num(total)}")
                    lines.append(f"{m.name}_count{lab} {n}")
                    for q in (0.5, 0.95, 0.99):
                        qlab = _fmt_labels(m.labelnames, values,
                                           [("quantile", q)])
                        qlines.append(
                            f"{m.name}_quantile{qlab} "
                            f"{_fmt_num(cell.quantile(q))}")
                else:
                    lab = _fmt_labels(m.labelnames, values)
                    lines.append(f"{m.name}{lab} {_fmt_num(cell.value)}")
            if qlines:
                lines.append(f"# TYPE {m.name}_quantile gauge")
                lines += qlines
        return "\n".join(lines) + "\n"

    def to_jsonl(self):
        """One JSON object per series (the journal-friendly dump)."""
        lines = []
        for name, entry in self.snapshot().items():
            for s in entry["series"]:
                rec = {"metric": name, "type": entry["type"]}
                rec.update(s)
                lines.append(json.dumps(rec))
        return "\n".join(lines) + ("\n" if lines else "")

    def compact(self, skip_zero=True):
        """Flat {'name{k=v}': value} view of counters/gauges plus
        {count,sum,p50,p99} for histograms — the shape bench stamps and
        the anomaly journal carries."""
        out = {}
        for m in self:
            for values, cell in m._series():
                key = m.name + _fmt_labels(m.labelnames, values)
                if m.kind == "histogram":
                    counts, total, n = cell.merged()
                    if n == 0 and skip_zero:
                        continue
                    out[key] = {"count": n, "sum": round(total, 6),
                                "p50": round(cell.quantile(0.5), 6),
                                "p95": round(cell.quantile(0.95), 6),
                                "p99": round(cell.quantile(0.99), 6)}
                else:
                    v = cell.value
                    if v == 0 and skip_zero:
                        continue
                    out[key] = int(v) if float(v).is_integer() else v
        return out


_REGISTRY = MetricsRegistry()


def registry():
    """The process-wide default registry."""
    return _REGISTRY


def counter(name, help="", labelnames=(), **kw):
    return _REGISTRY.counter(name, help, labelnames, **kw)


def gauge(name, help="", labelnames=(), **kw):
    return _REGISTRY.gauge(name, help, labelnames, **kw)


def histogram(name, help="", labelnames=(), **kw):
    return _REGISTRY.histogram(name, help, labelnames, **kw)


def snapshot():
    return _REGISTRY.snapshot()


def to_prometheus():
    return _REGISTRY.to_prometheus()


def to_jsonl():
    return _REGISTRY.to_jsonl()
