"""Request-scoped distributed tracing — trace contexts + TTFT phases.

A serving request in the disaggregated fleet crosses four actors
(router → prefill replica → KV wire → decode replica), and a slow TTFT
or a failover can only be debugged if every span and timestamp the
request touches carries ONE identity. This module is that identity:

* :class:`TraceContext` — a ``trace_id`` (+ optional parent span id)
  generated at the ingress (`FleetRouter.submit` / `LLMServer.submit`)
  that rides the engine `_Request`, the `KVPagePayload` header, and —
  via `tracing.ambient_trace` — the spans of any transport call made on
  the request's behalf (the `xproc.send` frame that ships its KV pages
  carries the trace_id in its span args AND in the payload header).
  ``to_dict``/``from_dict`` are the wire form: a payload imported on
  another replica/process reconstructs the SAME trace, phase stamps
  included, so the timeline keeps accumulating across hand-offs.

* **Phase stamps** — ``ctx.stamp(phase)`` records a wall-clock,
  first-wins timestamp (``queued``, ``routed``, ``prefill_start``,
  ``prefill_end``, ``kv_export``, ``kv_transfer``, ``kv_import``,
  ``first_decode_dispatch``, ``first_token``; docs/OBSERVABILITY.md
  "TTFT decomposition" defines each). First-wins makes preemption
  replay and failover re-dispatch no-ops: the timeline stays the FIRST
  attempt's truth. Each new stamp emits the segment since the previous
  stamp three ways: a ``pt_request_phase_seconds{phase}`` histogram
  sample (phase = the segment's END stamp), a flight-recorder
  ``request_phase`` event (metrics mode and up — so a postmortem ring
  holds the killed request's recent segments), and in full mode a
  ``phase.<name>`` chrome event, which is what makes a disaggregated
  request read as one causal chain in the merged timeline.

Because the stamps form one monotone wall-clock chain from ``queued``
to ``first_token``, the per-phase durations sum EXACTLY to the
wall-clock TTFT — the decomposition accounts for the whole latency,
never a subset (pinned by tests/test_request_tracing.py; the bench's
``ttft_phase_breakdown`` stamp is built from these timelines).
"""
import os
import time

from . import tracing
from .metrics import _STATE, histogram, summarize_histogram_cell

__all__ = ["TraceContext", "new_trace", "quiet_trace", "PHASES",
           "phase_summary"]

# canonical stamp names (docs/OBSERVABILITY.md has the glossary); the
# chain is temporal, not positional — a request only ever takes the
# stamps its path crosses (no router -> no `routed`; no disaggregation
# -> no kv_* stamps) and segments pair consecutive PRESENT stamps
PHASES = ("queued", "routed", "kv_spill", "kv_prefetch",
          "prefill_start", "prefill_end",
          "kv_export", "kv_transfer", "kv_import",
          "first_decode_dispatch", "first_token")

_PHASE_SECONDS = histogram(
    "pt_request_phase_seconds",
    "per-request TTFT decomposition: seconds from the previous phase "
    "stamp to this one (phase = the segment's END stamp; the segments "
    "of one request sum to its wall-clock TTFT)",
    labelnames=("phase",))


def _new_id(nbytes=8):
    return os.urandom(nbytes).hex()


class TraceContext:
    """One request's identity + phase timeline (module docstring).
    Stamps are first-wins and idempotent, so the object is safe to
    share across a stale and a live failover attempt (both run the
    same request; the first attempt's stamps are the timeline)."""

    __slots__ = ("trace_id", "parent_id", "phases", "quiet", "_last")

    def __init__(self, trace_id=None, parent_id=None, phases=None,
                 quiet=False):
        self.trace_id = trace_id or _new_id()
        self.parent_id = parent_id
        # quiet traces stamp (ordering invariants hold) but emit
        # NOTHING — engine warm-up requests use this so the compile
        # stall inside their prefill segment never pollutes the
        # pt_request_phase_seconds distribution or recent_requests
        self.quiet = bool(quiet)
        self.phases = dict(phases or {})
        # resume from the LATEST pre-existing stamp (the wire form: an
        # imported payload's next stamp measures from the exporter's
        # last one — wall clocks, so cross-process segments align like
        # the chrome `ts` fields do)
        self._last = (max(self.phases.items(), key=lambda kv: kv[1])
                      if self.phases else None)

    def stamp(self, phase, t=None):
        """Record `phase` at wall-clock `t` (now). Returns False when
        the phase was already stamped (replay/requeue: no-op)."""
        if phase in self.phases:
            return False
        t = time.time() if t is None else float(t)
        prev = self._last
        self.phases[phase] = t
        self._last = (phase, t)
        if _STATE.mode and prev is not None and not self.quiet:
            dt = max(0.0, t - prev[1])
            _PHASE_SECONDS.labels(phase=phase).observe(dt)
            self._emit(phase, prev, dt)
        return True

    def _emit(self, phase, prev, dt):
        # ring first (metrics mode and up): the flight recorder must
        # hold a killed request's recent segments even when the span
        # buffer is off
        try:
            from .flight_recorder import record_event

            record_event("request_phase", trace_id=self.trace_id,
                         phase=phase, prev=prev[0], t=self.phases[phase],
                         dur_s=round(dt, 6),
                         replica=tracing.current_replica())
        except Exception:  # ptlint: disable=PTL804 (the guard wraps the trace event itself)
            pass
        # chrome event (full mode): ts = the segment's START stamp
        tracing.add_event(f"phase.{phase}", int(prev[1] * 1e6),
                          int(dt * 1e6),
                          args={"trace_id": self.trace_id,
                                "from": prev[0]})

    # ---- views ----

    def timeline(self):
        """Stamps in temporal order: [{"phase", "t", "dt_s"}] — dt_s
        measured from the previous stamp (0.0 for the first)."""
        items = sorted(self.phases.items(), key=lambda kv: kv[1])
        out, prev_t = [], None
        for name, t in items:
            # dt_s deliberately UNROUNDED: the exported invariant is
            # that segments sum EXACTLY to total_s — rounding each
            # segment would break the identity by up to n·5e-7
            out.append({"phase": name, "t": t,
                        "dt_s": 0.0 if prev_t is None else t - prev_t})
            prev_t = t
        return out

    def total_s(self):
        """Wall seconds first stamp -> last stamp (== the sum of the
        timeline's dt_s, by construction)."""
        if not self.phases:
            return 0.0
        ts = self.phases.values()
        return max(ts) - min(ts)

    # ---- wire form ----

    def to_dict(self):
        return {"trace_id": self.trace_id, "parent_id": self.parent_id,
                "quiet": self.quiet, "phases": dict(self.phases)}

    @classmethod
    def from_dict(cls, d):
        # `quiet` rides the wire: a warm-up payload restored on the
        # importing side must stay quiet, or its compile-stall
        # segments enter the phase telemetry over there
        return cls(trace_id=d.get("trace_id"),
                   parent_id=d.get("parent_id"),
                   phases=d.get("phases"),
                   quiet=bool(d.get("quiet", False)))


def new_trace(parent_id=None):
    return TraceContext(parent_id=parent_id)


def quiet_trace():
    """A stamp-but-emit-nothing context for WARM-UP requests: their
    prefill segment is the executable compile, and letting it into
    `pt_request_phase_seconds` / recent_requests would report the
    compile stall as serving latency."""
    return TraceContext(quiet=True)


def phase_summary():
    """{phase: {count, sum, p50, p95, p99}} over the process-global
    `pt_request_phase_seconds` histogram — the block
    `LLMServer.metrics()` / `FleetRouter.metrics()` surface."""
    out = {}
    for values, cell in _PHASE_SECONDS._series():
        s = summarize_histogram_cell(cell)
        if not s["count"]:
            continue
        out[values[0]] = {k: (round(v, 6) if isinstance(v, float)
                              else v) for k, v in s.items()}
    return out
