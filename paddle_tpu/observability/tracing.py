"""Span tracer — chrome://tracing-compatible host-side spans.

``trace_span("name", key=val)`` is a context manager AND a decorator.
In full-telemetry mode (``PT_TELEMETRY=1``) each span records one
complete ("ph": "X") chrome trace event: wall-clock ``ts`` (µs since the
unix epoch, so per-rank files from different processes align when
merged), monotonic ``dur``, ``pid`` = trainer rank, ``tid`` = thread id.
Below full mode entering a span is a single attribute check — the
overhead test pins it.

Composition with the xprof path: spans optionally ALSO enter the
existing ``profiler.RecordEvent`` (a jax TraceAnnotation), so the same
scopes show up on the device timeline when a ``jax.profiler`` capture is
active. Gated by ``PT_TRACE_ANNOTATE=1`` because TraceAnnotation has a
per-call cost even without an active capture.

Export: events buffer in memory (bounded; drops counted) and flush to
``<PT_TELEMETRY_DIR>/trace.rank<r>.jsonl`` — one JSON event per line.
``tools/trace_merge.py`` merges per-rank files into one
``trace.json`` the chrome://tracing / perfetto UI loads directly.
"""
import json
import os
import threading
import time

from .metrics import _STATE, counter

__all__ = ["trace_span", "chrome_events", "flush", "reset",
           "trace_path", "MAX_EVENTS", "set_replica", "current_replica",
           "ambient_trace", "current_trace", "add_event", "add_sink",
           "remove_sink"]

MAX_EVENTS = int(os.environ.get("PT_TRACE_BUFFER", "200000"))

_events = []
_flush_lock = threading.Lock()
_flushed_paths = set()      # paths this PROCESS already wrote (see flush)
_dropped = counter("pt_trace_events_dropped_total",
                   "span events dropped by the bounded trace buffer")

# request-identity ambience (reqtrace.py is the user-facing surface).
# Thread-locals, because the serving runtime's unit of concurrency is
# the thread: a replica's serve loop tags every span it emits with its
# replica name, and a transport call made under `ambient_trace(ctx)`
# tags its spans with the request's trace_id — that is how one
# disaggregated request reads as a single causal chain across replica
# lanes and process boundaries in the merged timeline.
_tls = threading.local()

# event sinks: each completed event (span exit or add_event) is handed
# to every registered sink — the flight recorder's feed. Full mode
# only (below full, no events exist to feed).
_sinks = []


def set_replica(name):
    """Tag every span THIS thread emits with `replica` (a replica's
    serve loop calls this at start; None clears)."""
    _tls.replica = name


def current_replica():
    return getattr(_tls, "replica", None)


def current_trace():
    """The thread's ambient TraceContext (reqtrace), or None."""
    return getattr(_tls, "trace", None)


class ambient_trace:
    """Context manager: spans emitted by this thread inside the block
    carry `ctx.trace_id` (ctx None = no-op passthrough)."""

    __slots__ = ("_ctx", "_prev")

    def __init__(self, ctx):
        self._ctx = ctx

    def __enter__(self):
        self._prev = getattr(_tls, "trace", None)
        if self._ctx is not None:
            _tls.trace = self._ctx
        return self._ctx

    def __exit__(self, *exc):
        _tls.trace = self._prev
        return False


def add_sink(fn):
    """Register an event sink: fn(event_dict) on every completed span
    event (full mode). Sinks must be cheap and never raise."""
    if fn not in _sinks:
        _sinks.append(fn)


def remove_sink(fn):
    try:
        _sinks.remove(fn)
    except ValueError:
        pass


def _finish_event(ev):
    """Stamp ambient identity, buffer (bounded), feed sinks."""
    rep = getattr(_tls, "replica", None)
    if rep is not None:
        ev["replica"] = rep
    tr = getattr(_tls, "trace", None)
    if tr is not None:
        ev.setdefault("args", {}).setdefault("trace_id", tr.trace_id)
    if len(_events) >= MAX_EVENTS:
        _dropped.inc()
    else:
        _events.append(ev)      # list.append is atomic under the GIL
    for s in list(_sinks):
        try:
            s(ev)
        except Exception:  # ptlint: disable=PTL804 (a failing sink must not take down the data path)
            pass


def _rank():
    return int(os.environ.get("PADDLE_TRAINER_ID", "0"))


def _annotate_enabled():
    return os.environ.get("PT_TRACE_ANNOTATE", "0") == "1"


class _Span:
    """One span use. Context manager (enter/exit records an event) and
    decorator (wraps fn; a fresh span per call)."""

    __slots__ = ("name", "args", "_t0", "_wall0", "_ann")

    def __init__(self, name, args):
        self.name = name
        self.args = args
        self._t0 = None
        self._ann = None

    def __enter__(self):
        if _STATE.mode < 2:
            return self
        self._wall0 = time.time()
        self._t0 = time.perf_counter()
        if _annotate_enabled():
            try:
                from ..profiler import RecordEvent

                self._ann = RecordEvent(self.name)
                self._ann.begin()
            except Exception:
                self._ann = None
        return self

    def __exit__(self, exc_type, exc, tb):
        if self._ann is not None:
            self._ann.end()
            self._ann = None
        if self._t0 is None:
            return False
        dur_us = int((time.perf_counter() - self._t0) * 1e6)
        self._t0 = None
        ev = {"name": self.name, "ph": "X",
              "ts": int(self._wall0 * 1e6), "dur": dur_us,
              "pid": _rank(), "tid": threading.get_ident()}
        if self.args:
            # COPY: decorator usage shares one args dict across calls —
            # the error annotation below must not poison other events
            ev["args"] = dict(self.args)
        if exc_type is not None:
            ev.setdefault("args", {})["error"] = exc_type.__name__
        _finish_event(ev)
        return False

    def __call__(self, fn):
        import functools

        name, args = self.name, self.args

        @functools.wraps(fn)
        def wrapped(*a, **kw):
            with _Span(name, args):
                return fn(*a, **kw)

        return wrapped


def trace_span(name, **args):
    """Span factory: ``with trace_span("x", k=v): ...`` or
    ``@trace_span("x")``. No-op (one mode check) below full telemetry."""
    return _Span(name, args)


def add_event(name, ts_us, dur_us, args=None):
    """Record one pre-timed complete event (the reqtrace phase
    segments: their start is a stamp taken earlier, not a span entry on
    this thread). Full mode only; buffered/sunk like span exits."""
    if _STATE.mode < 2:
        return
    ev = {"name": name, "ph": "X", "ts": int(ts_us), "dur": int(dur_us),
          "pid": _rank(), "tid": threading.get_ident()}
    if args:
        ev["args"] = dict(args)
    _finish_event(ev)


def chrome_events():
    """Copy of the buffered chrome trace events (oldest first)."""
    return list(_events)


def trace_path(directory=None):
    d = directory or os.environ.get("PT_TELEMETRY_DIR") or "./telemetry"
    return os.path.join(d, f"trace.rank{_rank()}.jsonl")


def flush(directory=None):
    """Append buffered events to the per-rank trace JSONL and clear the
    buffer. Best-effort (exporting must never take the run down).
    Returns the path, or None when there was nothing to write."""
    with _flush_lock:
        if not _events:
            return None
        batch = _events[:]
        del _events[:len(batch)]
        path = trace_path(directory)
        try:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            # first flush of THIS process truncates: successive runs
            # sharing PT_TELEMETRY_DIR must not concatenate into one
            # file, or trace_merge would fold distinct runs (hours
            # apart) onto a single rebased timeline
            fresh = path not in _flushed_paths
            _flushed_paths.add(path)
            with open(path, "w" if fresh else "a") as f:
                for ev in batch:
                    f.write(json.dumps(ev) + "\n")
        except OSError:
            return None
        return path


def reset():
    """Test hook: drop all buffered events."""
    del _events[:]
