"""paddle_tpu.observability — unified runtime telemetry.

ONE place to answer "what is this training/serving process doing right
now, and why is it slow": a process-wide metrics registry
(``metrics.py``) plus a chrome-trace span tracer (``tracing.py``),
wired through the hot paths (``jit.TrainStep``, ``inference.LLMEngine``,
``distributed.checkpoint``, ``distributed.xproc``, ``fleet.elastic``).
docs/OBSERVABILITY.md has the metric-name catalogue and workflows.

Modes (PT_TELEMETRY):

    PT_TELEMETRY=0   off      every metric write / span is a no-op
                              (single attribute check; overhead pinned)
    (unset)          metrics  counters/gauges/histograms live; no spans,
                              no export, compiled programs unchanged
    PT_TELEMETRY=1   full     + span tracing, TrainStep loss/grad-norm
                              observation, at-exit export of
                              metrics.rank<r>.{prom,json} and
                              trace.rank<r>.jsonl to PT_TELEMETRY_DIR
                              (default ./telemetry), and a compact
                              snapshot folded into the per-rank anomaly
                              journal (telemetry_snapshot event) so
                              chaos forensics and telemetry share one
                              event stream (docs/RESILIENCE.md)

``start_http_server(port)`` serves the registry at ``/metrics``
(Prometheus text) and ``/metrics.json`` via a stdlib ThreadingHTTPServer
— the optional pull endpoint ``inference.LLMServer`` exposes.
"""
import json
import os
import threading

from . import flight_recorder, metrics, reqtrace, steptrace, tracing  # noqa: F401,E501
from .metrics import (Counter, Gauge, Histogram, MetricsRegistry,  # noqa: F401
                      counter, gauge, histogram, registry, snapshot,
                      to_jsonl, to_prometheus, _STATE)
from .reqtrace import TraceContext, new_trace  # noqa: F401
from .tracing import chrome_events, flush, trace_span  # noqa: F401

__all__ = ["Counter", "Gauge", "Histogram", "MetricsRegistry",
           "counter", "gauge", "histogram", "registry", "snapshot",
           "to_prometheus", "to_jsonl", "trace_span", "chrome_events",
           "flush", "set_mode", "mode", "metrics_enabled", "full_enabled",
           "export_all", "export_replica", "journal_snapshot",
           "bench_snapshot", "start_http_server", "telemetry_dir",
           "TraceContext", "new_trace", "reqtrace", "steptrace",
           "flight_recorder"]

_MODES = {"off": _STATE.OFF, "metrics": _STATE.METRICS,
          "full": _STATE.FULL}
_MODE_NAMES = {v: k for k, v in _MODES.items()}


def mode():
    """Current telemetry mode name: 'off' | 'metrics' | 'full'."""
    return _MODE_NAMES[_STATE.mode]


def set_mode(name):
    """Switch telemetry mode at runtime ('off'|'metrics'|'full').
    Returns the previous mode name. Note: compiled-program choices made
    at build time (TrainStep grad-norm aux) follow the mode seen when
    the step was built, not later flips."""
    if name not in _MODES:
        raise ValueError(f"mode must be one of {sorted(_MODES)}")
    prev = mode()
    _STATE.mode = _MODES[name]
    if _STATE.mode == _STATE.FULL:
        _install_atexit()
    return prev


def metrics_enabled():
    return _STATE.mode >= _STATE.METRICS


def full_enabled():
    return _STATE.mode >= _STATE.FULL


def telemetry_dir():
    return os.environ.get("PT_TELEMETRY_DIR") or "./telemetry"


def _rank():
    return int(os.environ.get("PADDLE_TRAINER_ID", "0"))


def journal_snapshot(note=None):
    """Fold a compact registry snapshot into the per-rank anomaly
    journal (resilience's ``anomalies.rank<r>.jsonl``) as ONE
    ``telemetry_snapshot`` event — chaos runs and telemetry share that
    event stream. Returns the journal entry."""
    from ..distributed.resilience import record

    compact = registry().compact()
    fields = {"metrics": compact}
    if note:
        fields["note"] = note
    return record("telemetry_snapshot", **fields)


def bench_snapshot():
    """The compact dict bench.py stamps into every BENCH arm: registry
    dump (non-zero series only) so perf numbers come with attribution
    (recompile counts, retry storms, preemptions, ...)."""
    return registry().compact()


def export_all(directory=None, journal=True):
    """Write metrics.rank<r>.prom + metrics.rank<r>.json and flush the
    span buffer to trace.rank<r>.jsonl under `directory` (default
    PT_TELEMETRY_DIR). Best-effort; returns the directory."""
    d = directory or telemetry_dir()
    r = _rank()
    try:
        os.makedirs(d, exist_ok=True)
        with open(os.path.join(d, f"metrics.rank{r}.prom"), "w") as f:
            f.write(to_prometheus())
        with open(os.path.join(d, f"metrics.rank{r}.json"), "w") as f:
            json.dump(snapshot(), f, indent=1)
    except OSError:
        pass
    tracing.flush(d)
    if journal:
        try:
            journal_snapshot(note="export_all")
        except Exception:  # ptlint: disable=PTL804 (the guard wraps the journal snapshot itself)
            pass
    return d


def export_replica(name, view_fn=None, directory=None):
    """Per-REPLICA telemetry export: `metrics.rank<r>.<name>.json`.

    Threaded `LocalReplica`s share one process (one rank) — an at-exit
    export named by rank alone makes N replicas overwrite each other's
    files, leaving whichever replica stopped last as the only record.
    Naming by replica keeps every member's final view
    (tests/test_request_tracing.py pins two-replicas-two-files).
    `view_fn()` supplies the replica-local snapshot (the shared
    process registry rides along for context). Best-effort; returns
    the path or None."""
    import re

    d = directory or telemetry_dir()
    safe = re.sub(r"[^A-Za-z0-9_.-]", "_", str(name)) or "replica"
    path = os.path.join(d, f"metrics.rank{_rank()}.{safe}.json")
    payload = {"replica": str(name)}
    if view_fn is not None:
        try:
            payload["view"] = view_fn()
        except Exception as e:
            payload["view_error"] = repr(e)
    payload["metrics"] = registry().compact()
    try:
        os.makedirs(d, exist_ok=True)
        with open(path, "w") as f:
            json.dump(payload, f, indent=1, default=repr)
    except OSError:
        return None
    return path


_atexit_installed = False


def _install_atexit():
    global _atexit_installed
    if _atexit_installed:
        return
    _atexit_installed = True
    import atexit

    # re-check the mode AT EXIT: a supervisor process (the pod
    # launcher, bench.py's driver) drops itself to 'metrics' so it
    # never overwrites its ranked children's export files
    atexit.register(
        lambda: export_all() if _STATE.mode >= _STATE.FULL else None)


if _STATE.mode >= _STATE.FULL:
    _install_atexit()


# ----------------------------------------------------- HTTP /metrics pull

class _HTTPHandle:
    """Running /metrics endpoint. .port, .url; .stop() shuts it down."""

    def __init__(self, server, thread):
        self._server = server
        self._thread = thread
        self.host, self.port = server.server_address[:2]

    @property
    def url(self):
        return f"http://{self.host}:{self.port}/metrics"

    def stop(self):
        try:
            self._server.shutdown()
            self._server.server_close()
        except OSError:
            pass
        self._thread.join(timeout=5)


def start_http_server(port=0, host="127.0.0.1", extra_json=None):
    """Serve the global registry over stdlib HTTP:

        GET /metrics       Prometheus text format
        GET /metrics.json  registry snapshot (+ `extra_json()` merged
                           under "extra" when provided)

    port=0 picks a free port. Returns an _HTTPHandle (stop() to end).
    """
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):
            if self.path.split("?")[0] == "/metrics":
                body = to_prometheus().encode()
                ctype = "text/plain; version=0.0.4; charset=utf-8"
            elif self.path.split("?")[0] == "/metrics.json":
                payload = {"metrics": snapshot()}
                if extra_json is not None:
                    try:
                        payload["extra"] = extra_json()
                    except Exception as e:
                        payload["extra_error"] = repr(e)
                body = json.dumps(payload).encode()
                ctype = "application/json"
            else:
                self.send_response(404)
                self.end_headers()
                return
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):        # no stderr spam per scrape
            pass

    server = ThreadingHTTPServer((host, port), Handler)
    server.daemon_threads = True
    thread = threading.Thread(target=server.serve_forever,
                              name="pt-metrics-http", daemon=True)
    thread.start()
    return _HTTPHandle(server, thread)
