"""Training step tracing — the training twin of reqtrace.

A compiled train step (jit.TrainStep / distributed.DistributedTrainStep
/ jit.HybridTrainStep) spends its wall-clock in phases that only the
framework can tell apart: waiting for the input pipeline, host→device
batch conversion, python dispatch, the device step itself, publishing
updated params back to the live objects — and, between steps, the
synchronous slice of a checkpoint snapshot. This module is the one
identity those phases share, mirroring the reqtrace/TTFT contract
(docs/OBSERVABILITY.md "Training goodput"):

* :class:`StepTrace` — one step's first-wins phase timeline. The
  instrumented steps stamp ``data_wait`` / ``ckpt_snapshot`` / ``h2d``
  / ``dispatch`` / ``device_step`` (the ``block_until_ready`` delta) /
  ``opt_publish``; each new stamp emits the segment since the previous
  stamp three ways: a ``pt_train_phase_seconds{phase}`` histogram
  sample, a flight-recorder ``train_phase`` event, and in full mode a
  ``step.<phase>`` chrome event (which is what gives
  ``tools/trace_merge.py --train-report`` its per-rank train lanes).
  Stamps form one monotone wall-clock chain, so the per-phase
  durations sum EXACTLY to the step's wall time — unrounded, the same
  identity the TTFT decomposition pins.

* **Quiet warm-up** — a step whose batch signature is NEW compiles,
  and that stall must never enter the phase histograms. The step
  classes pass ``quiet=True`` for compile steps: the trace still
  stamps (ordering invariants hold, tests use it) but emits nothing.

* **Goodput gauges** — :func:`arm_goodput` with the analytic
  :func:`model_flops` turns every completed non-quiet step into
  ``pt_train_mfu`` / ``pt_train_tokens_per_second`` samples, making
  MFU a continuous first-class gauge instead of bench-only hand math.

* **Recompile sentinel** — :func:`note_recompile` counts post-warm-up
  batch-signature growth (``pt_step_recompiles_total{step}``) and
  dumps a flight-recorder postmortem, so the donation/retrace family
  is observable live, not just test-pinned.

* **Straggler attribution** — per-rank step views (ranks exchange
  ``StepTrace.to_dict()`` over xproc) feed :func:`straggler_of`, which
  names the slowest rank of a step and its slow phase; the merged
  chrome view does the same offline via trace_merge's train report.

A bounded ring of recent non-quiet step timelines backs
``recent_steps()`` (the flight-recorder state provider registered at
import), sized by ``PT_STEPTRACE_RING`` (default 256).
"""
import os
import sys
import time

from . import tracing
from .metrics import _STATE, counter, gauge, histogram, \
    summarize_histogram_cell

__all__ = ["StepTrace", "PHASES", "begin_step", "end_step", "active",
           "now", "note_ckpt_snapshot", "note_recompile", "model_flops",
           "arm_goodput", "goodput_armed", "recent_steps", "reset",
           "phase_summary", "straggler_of", "collective_bytes_per_second",
           "DEFAULT_PEAK_FLOPS"]

# segment END-stamp names in temporal order (the internal "start"
# anchor stamp opens the chain and is never a histogram label). A step
# only takes the stamps its path crosses: the first step of a process
# has no previous step to wait on (no data_wait), a run without
# checkpointing never stamps ckpt_snapshot, and device_step needs
# telemetry on (the sync is skipped when nothing would record it).
PHASES = ("ckpt_snapshot", "data_wait", "h2d", "dispatch",
          "device_step", "opt_publish")

# nominal peak used for MFU when the caller doesn't pass one:
# PT_PEAK_FLOPS env override, else the v5e bf16 chip peak bench.py
# normalizes against (bench and the live gauge must agree on the
# denominator or their MFU numbers diverge by a constant factor).
DEFAULT_PEAK_FLOPS = 197e12

_PHASE_SECONDS = histogram(
    "pt_train_phase_seconds",
    "per-step phase decomposition: seconds from the previous phase "
    "stamp to this one (phase = the segment's END stamp; one step's "
    "segments sum to its wall-clock step time; quiet warm-up/compile "
    "steps excluded)",
    labelnames=("phase",))
_RECOMPILES = counter(
    "pt_step_recompiles_total",
    "post-warm-up batch-signature growth per step family — every "
    "increment is a fresh XLA compile on the training hot path and "
    "dumps a flight-recorder postmortem (reason=step_recompile)",
    labelnames=("step",))
_MFU_GAUGE = gauge(
    "pt_train_mfu",
    "model FLOPs utilization of the last completed non-quiet step: "
    "arm_goodput()'s analytic FLOPs / step wall time / peak FLOPs")
_TOKENS_PER_S = gauge(
    "pt_train_tokens_per_second",
    "training goodput of the last completed non-quiet step: "
    "arm_goodput()'s tokens per step / step wall time")


def now():
    """Wall-clock stamp source. time.time(), not perf_counter: stamps
    from different ranks must align on one timeline, like the chrome
    `ts` fields they become."""
    return time.time()


def active():
    """True when steptrace should measure (telemetry metrics mode or
    up). The instrumented steps skip the device_step sync when nothing
    would record it — tracing must not change OFF-mode pipelining."""
    return bool(_STATE.mode)


class StepTrace:
    """One train step's phase timeline (module docstring). Stamps are
    first-wins and idempotent — a preempted/replayed step keeps the
    first attempt's truth, same discipline as reqtrace."""

    __slots__ = ("family", "step", "phases", "quiet", "_last")

    def __init__(self, family, step, phases=None, quiet=False):
        self.family = family
        self.step = int(step)
        self.quiet = bool(quiet)
        self.phases = dict(phases or {})
        self._last = (max(self.phases.items(), key=lambda kv: kv[1])
                      if self.phases else None)

    def stamp(self, phase, t=None):
        """Record `phase` at wall-clock `t` (now). Returns False when
        the phase was already stamped (replay: no-op)."""
        if phase in self.phases:
            return False
        t = now() if t is None else float(t)
        prev = self._last
        self.phases[phase] = t
        self._last = (phase, t)
        if _STATE.mode and prev is not None and not self.quiet:
            dt = max(0.0, t - prev[1])
            _PHASE_SECONDS.labels(phase=phase).observe(dt)
            self._emit(phase, prev, dt)
        return True

    def _emit(self, phase, prev, dt):
        # flight ring first (metrics mode and up): a postmortem must
        # hold the dying step's recent segments even with spans off
        try:
            from .flight_recorder import record_event

            record_event("train_phase", family=self.family,
                         step=self.step, phase=phase, prev=prev[0],
                         t=self.phases[phase], dur_s=round(dt, 6))
        except Exception:  # ptlint: disable=PTL804 (the guard wraps the trace event itself)
            pass
        # chrome event (full mode): ts = the segment's START stamp;
        # args.step is the join key trace_merge.train_report groups on
        tracing.add_event(f"step.{phase}", int(prev[1] * 1e6),
                          int(dt * 1e6),
                          args={"step": self.step, "family": self.family,
                                "from": prev[0]})

    # ---- views ----

    def timeline(self):
        """Stamps in temporal order: [{"phase", "t", "dt_s"}] — dt_s
        deliberately UNROUNDED so the segments sum EXACTLY to
        total_s() (the exported invariant; rounding would break the
        identity by up to n·5e-7)."""
        items = sorted(self.phases.items(), key=lambda kv: kv[1])
        out, prev_t = [], None
        for name, t in items:
            out.append({"phase": name, "t": t,
                        "dt_s": 0.0 if prev_t is None else t - prev_t})
            prev_t = t
        return out

    def total_s(self):
        """Wall seconds first stamp -> last stamp (== sum of the
        timeline's dt_s, by construction)."""
        if not self.phases:
            return 0.0
        ts = self.phases.values()
        return max(ts) - min(ts)

    def end_t(self):
        """Wall time of the latest stamp (the next step's data_wait
        anchor), or None before any stamp."""
        return self._last[1] if self._last else None

    def to_dict(self):
        """Wire form for the cross-rank straggler exchange."""
        return {"family": self.family, "step": self.step,
                "quiet": self.quiet, "phases": dict(self.phases)}


# ------------------------------------------------------------ step flow

# pending synchronous-snapshot interval (t0, t1): Checkpointer.save
# notes it, the NEXT step's trace consumes it as a ckpt_snapshot
# segment — the save runs between steps, so attributing it to the
# following step's pre-data_wait gap keeps the sum identity intact
_PENDING_CKPT = None


def note_ckpt_snapshot(t0, t1):
    """Record a synchronous checkpoint-snapshot interval (wall clock).
    Called by Checkpointer.save; consumed by the next begin_step."""
    global _PENDING_CKPT
    _PENDING_CKPT = (float(t0), float(t1))


def begin_step(family, step, prev_end=None, quiet=False, t_entry=None):
    """Open a step's trace. `prev_end` (the previous step's end_t())
    anchors the chain so the prev-step→this-call gap becomes the
    data_wait segment — input-pipeline stall time the step itself
    never sees. A pending checkpoint-snapshot interval inside that gap
    is carved out as ckpt_snapshot (the anchor→snapshot-start sliver
    rides with it; saves directly follow steps, so it is ≈0)."""
    global _PENDING_CKPT
    t_entry = now() if t_entry is None else float(t_entry)
    tr = StepTrace(family, step, quiet=quiet)
    ckpt, _PENDING_CKPT = _PENDING_CKPT, None
    if prev_end is not None and prev_end <= t_entry:
        tr.stamp("start", prev_end)
        if ckpt is not None and prev_end <= ckpt[1] <= t_entry:
            tr.stamp("ckpt_snapshot", ckpt[1])
        tr.stamp("data_wait", t_entry)
    else:
        # first step of the process (or a clock jump): no anchor, the
        # chain opens at entry and there is no data_wait segment
        tr.stamp("start", t_entry)
    return tr


# bounded ring of recent non-quiet step timelines (flight-recorder
# state provider + tests); PT_STEPTRACE_RING sizes it
try:
    _RING_MAX = max(1, int(os.environ.get("PT_STEPTRACE_RING", "256")))
except ValueError:
    _RING_MAX = 256
_RING = []

# goodput accounting, armed process-wide (one training job per
# process; bench arms/disarms around each arm's run)
_GOODPUT = {"flops": None, "tokens": None, "peak": None}


def arm_goodput(flops_per_step=None, tokens_per_step=None,
                peak_flops=None):
    """Arm the continuous MFU/goodput gauges: every completed
    non-quiet step sets pt_train_mfu = flops_per_step / wall /
    peak_flops and pt_train_tokens_per_second = tokens_per_step /
    wall. Call with no args to disarm. Returns the previous arming."""
    prev = dict(_GOODPUT)
    _GOODPUT["flops"] = None if flops_per_step is None \
        else float(flops_per_step)
    _GOODPUT["tokens"] = None if tokens_per_step is None \
        else float(tokens_per_step)
    if peak_flops is None:
        peak_flops = float(os.environ.get("PT_PEAK_FLOPS",
                                          DEFAULT_PEAK_FLOPS))
    _GOODPUT["peak"] = float(peak_flops)
    return prev


def goodput_armed():
    return _GOODPUT["flops"] is not None or \
        _GOODPUT["tokens"] is not None


def end_step(tr):
    """Close a step's trace: feed the timeline ring and the goodput
    gauges (non-quiet, telemetry on). Returns (total_s, end_t) — the
    step's wall time and the next step's data_wait anchor."""
    total = tr.total_s()
    if _STATE.mode and not tr.quiet and tr._last is not None:
        _RING.append({"family": tr.family, "step": tr.step,
                      "rank": tracing._rank(), "total_s": total,
                      "timeline": tr.timeline()})
        if len(_RING) > _RING_MAX:
            del _RING[:len(_RING) - _RING_MAX]
        if total > 0.0:
            if _GOODPUT["flops"] is not None:
                _MFU_GAUGE.set(
                    _GOODPUT["flops"] / total / _GOODPUT["peak"])
            if _GOODPUT["tokens"] is not None:
                _TOKENS_PER_S.set(_GOODPUT["tokens"] / total)
    return total, tr.end_t()


def recent_steps():
    """Recent non-quiet step timelines, oldest first (bounded ring)."""
    return list(_RING)


def reset():
    """Drop the ring, any pending ckpt interval, and the goodput
    arming (tests)."""
    global _PENDING_CKPT
    del _RING[:]
    _PENDING_CKPT = None
    _GOODPUT["flops"] = _GOODPUT["tokens"] = _GOODPUT["peak"] = None


# ------------------------------------------------------- recompile watch

def note_recompile(family, **context):
    """Count a post-warm-up batch-signature compile and dump a
    flight-recorder postmortem. The step classes call this only for
    signatures beyond their first — warm-up compiles are expected;
    growth after it is the retrace/donation family resurfacing."""
    _RECOMPILES.labels(step=family).inc()
    if not _STATE.mode:
        return
    try:
        from . import flight_recorder as _fr

        _fr.record_event("step_recompile", family=family, **context)
        _fr.dump("step_recompile", family=family, **context)
    except Exception:  # ptlint: disable=PTL804 (the guard wraps the trace event itself)
        pass


# --------------------------------------------------------- chaos bridge

def chaos_fire(scope):
    """Fire a chaos scope from the step hot path WITHOUT importing the
    distributed package when no plan can be active (the import is paid
    once, and only when PT_CHAOS_PLAN is set or chaos is already
    loaded). An injected delay here lands in the NEXT stamp's segment
    — the straggler chaos test keys on that."""
    if "paddle_tpu.distributed.chaos" not in sys.modules and \
            not os.environ.get("PT_CHAOS_PLAN"):
        return None
    from ..distributed import chaos

    return chaos.fire(scope)


# ------------------------------------------------------ FLOPs accountant

def _cfg_get(config, name, default=None):
    if isinstance(config, dict):
        return config.get(name, default)
    return getattr(config, name, default)


def model_flops(config, batch, seq):
    """Analytic fwd+bwd FLOPs of one decoder-transformer train step:
    6·P per token for the matmuls (fwd 2P + bwd 4P) plus the causal
    attention scores/context terms — the accountant bench.py's MFU
    math and the live pt_train_mfu gauge share. `config` is any
    object/dict with hidden_size, num_layers, vocab_size and
    (optionally) ffn_size — GPTConfig, a bench cfg, or a plain dict."""
    d = int(_cfg_get(config, "hidden_size"))
    L = int(_cfg_get(config, "num_layers"))
    v = int(_cfg_get(config, "vocab_size"))
    ffn = int(_cfg_get(config, "ffn_size", 4 * d) or 4 * d)
    per_layer = 4 * d * d + 2 * d * ffn   # qkv+proj, fc1+fc2 weights
    p_matmul = L * per_layer + v * d      # + tied lm head
    tokens = int(batch) * int(seq)
    matmul = 6 * p_matmul * tokens
    attn = L * batch * (4 * seq * seq * d) * 3 * 0.5  # fwd+2×bwd, causal
    return matmul + attn


# ------------------------------------------------- straggler attribution

def straggler_of(views):
    """Name the slowest rank of one step and its slow phase from
    per-rank step views (`StepTrace.to_dict()` / ring records — any
    dict with "rank"/"phases" or "rank"/"timeline"). The slow phase is
    the segment where the slowest rank's duration exceeds the fastest
    other rank's by the most — a uniform slowdown names the longest
    phase. Returns {"rank", "total_s", "phase", "lag_s", "per_rank"}
    or None for empty input."""
    per_rank = {}
    for i, view in enumerate(views):
        if view is None:
            continue
        rank = int(view.get("rank", i))
        phases = view.get("phases")
        if phases:
            items = sorted(phases.items(), key=lambda kv: kv[1])
            segs, prev_t = {}, None
            for name, t in items:
                if prev_t is not None:
                    segs[name] = t - prev_t
                prev_t = t
            total = items[-1][1] - items[0][1] if len(items) > 1 else 0.0
        else:
            segs = {e["phase"]: e["dt_s"]
                    for e in view.get("timeline", ()) if e["dt_s"]}
            total = view.get("total_s", sum(segs.values()))
        per_rank[rank] = {"total_s": total, "phases_s": segs}
    if not per_rank:
        return None
    slow = max(per_rank, key=lambda r: per_rank[r]["total_s"])
    segs = per_rank[slow]["phases_s"]
    others = [per_rank[r]["phases_s"] for r in per_rank if r != slow]
    best, lag = None, -1.0
    for name, dt in segs.items():
        base = min((o.get(name, 0.0) for o in others), default=0.0)
        if dt - base > lag:
            best, lag = name, dt - base
    return {"rank": slow, "total_s": per_rank[slow]["total_s"],
            "phase": best, "lag_s": max(0.0, lag),
            "per_rank": per_rank}


# ------------------------------------------- collective-time attribution

def collective_bytes_per_second(bytes_a, step_s_a, bytes_b, step_s_b):
    """Achieved bytes/s per mesh axis from a quant on/off (or any
    bytes-differing) twin pair: the per-axis byte delta over the
    measured step-time delta. `bytes_a`/`bytes_b` are per-axis byte
    dicts (analysis.extract_schedule totals); side a is the SMALLER
    one (quant on). Axes whose bytes don't differ, or whose time delta
    is non-positive (noise swamped the signal), report None — honest
    about unattributable axes rather than inventing a rate."""
    dt = float(step_s_b) - float(step_s_a)
    out = {}
    for axis in sorted(set(bytes_a) | set(bytes_b)):
        db = float(bytes_b.get(axis, 0)) - float(bytes_a.get(axis, 0))
        if db <= 0 or dt <= 0:
            out[axis] = {"delta_bytes": int(db), "delta_s": dt,
                         "bytes_per_s": None}
        else:
            out[axis] = {"delta_bytes": int(db), "delta_s": dt,
                         "bytes_per_s": db / dt}
    return out


# ---------------------------------------------------------------- views

def phase_summary():
    """{phase: {count, sum, p50, p95, p99}} over the process-global
    pt_train_phase_seconds histogram — the training twin of
    reqtrace.phase_summary()."""
    out = {}
    for values, cell in _PHASE_SECONDS._series():
        s = summarize_histogram_cell(cell)
        if not s["count"]:
            continue
        out[values[0]] = {k: (round(v, 6) if isinstance(v, float)
                              else v) for k, v in s.items()}
    return out


# postmortems carry the recent step timelines next to the event ring
try:
    from . import flight_recorder as _fr

    _fr.add_state_provider("recent_steps", recent_steps)
except Exception:  # ptlint: disable=PTL804 (optional provider hookup at import)
    pass
