"""Sequence (LoD) op family.

Reference: paddle/fluid/operators/sequence_ops/ + the LoDTensor model
(paddle/fluid/framework/lod_tensor.h): variable-length sequences stored
flat with level-of-detail offsets. TPU-native design: a `LoDTensor`
subclass carries the offsets; each op is segment math over the flat
[total_tokens, ...] array (gather/segment_sum — XLA-friendly, no ragged
shapes inside jit).
"""
import numpy as np

import jax
import jax.numpy as jnp

from ..ops._helpers import apply_jfn, ensure_tensor, value_of
from ..tensor_core import Tensor

__all__ = [
    "LoDTensor", "sequence_concat", "sequence_conv", "sequence_enumerate",
    "sequence_expand", "sequence_expand_as", "sequence_first_step",
    "sequence_last_step", "sequence_pad", "sequence_pool",
    "sequence_reshape", "sequence_reverse", "sequence_scatter",
    "sequence_slice", "sequence_softmax", "sequence_unpad",
]


class LoDTensor(Tensor):
    """Flat sequence batch + offsets (reference lod_tensor.h: one LoD
    level; offsets[i]..offsets[i+1] are sequence i's rows)."""

    __slots__ = ("lod",)

    def __init__(self, value, lod, stop_gradient=True, name=None):
        super().__init__(value, stop_gradient=stop_gradient, name=name)
        self.lod = [int(v) for v in lod]

    @property
    def seq_lengths(self):
        return [self.lod[i + 1] - self.lod[i]
                for i in range(len(self.lod) - 1)]


def _as_lod(x, lod=None):
    if isinstance(x, LoDTensor):
        return x
    if lod is None:
        raise ValueError("sequence op needs a LoDTensor (or explicit lod)")
    t = ensure_tensor(x)
    return LoDTensor(t._value, lod, stop_gradient=t.stop_gradient)


def _wrap(x, out, lod):
    o = LoDTensor(out._value, lod, stop_gradient=out.stop_gradient)
    o._grad_node = out._grad_node
    o._out_index = out._out_index
    return o


def _seg_ids(lod):
    n = len(lod) - 1
    return np.repeat(np.arange(n), np.diff(np.asarray(lod)))


def sequence_pool(input, pool_type, is_test=False, pad_value=0.0):
    """Per-sequence reduction (reference sequence_ops/sequence_pool_op.cc):
    sum/average/sqrt/max/min/first/last."""
    x = _as_lod(input)
    lod = x.lod
    seg = jnp.asarray(_seg_ids(lod))
    n = len(lod) - 1
    lens = jnp.asarray(np.maximum(np.diff(np.asarray(lod)), 1))
    pool_type = pool_type.lower()

    def jfn(v):
        if pool_type == "sum":
            return jax.ops.segment_sum(v, seg, num_segments=n)
        if pool_type == "average":
            s = jax.ops.segment_sum(v, seg, num_segments=n)
            return s / lens.reshape((-1,) + (1,) * (v.ndim - 1))
        if pool_type == "sqrt":
            s = jax.ops.segment_sum(v, seg, num_segments=n)
            return s / jnp.sqrt(lens.astype(v.dtype)).reshape(
                (-1,) + (1,) * (v.ndim - 1))
        if pool_type == "max":
            return jax.ops.segment_max(v, seg, num_segments=n)
        if pool_type == "min":
            return jax.ops.segment_min(v, seg, num_segments=n)
        if pool_type == "first":
            return v[jnp.asarray(lod[:-1])]
        if pool_type == "last":
            return v[jnp.asarray(np.maximum(np.asarray(lod[1:]) - 1, 0))]
        raise ValueError(f"unknown pool_type {pool_type}")

    out = apply_jfn("sequence_pool", jfn, x)
    # empty sequences produce pad_value (reference semantics)
    if any(l == 0 for l in x.seq_lengths):
        empt = jnp.asarray(np.asarray(x.seq_lengths) == 0)
        out = apply_jfn(
            "sequence_pool_pad",
            lambda v: jnp.where(
                empt.reshape((-1,) + (1,) * (v.ndim - 1)), pad_value, v),
            out)
    return out


def sequence_first_step(input):
    return sequence_pool(input, "first")


def sequence_last_step(input):
    return sequence_pool(input, "last")


def sequence_softmax(input, use_cudnn=False, name=None):
    """Softmax within each sequence over the flat rows
    (reference sequence_softmax_op)."""
    x = _as_lod(input)
    seg = jnp.asarray(_seg_ids(x.lod))
    n = len(x.lod) - 1

    def jfn(v):
        flat = v.reshape(-1)
        mx = jax.ops.segment_max(flat, seg, num_segments=n)
        e = jnp.exp(flat - mx[seg])
        s = jax.ops.segment_sum(e, seg, num_segments=n)
        return (e / s[seg]).reshape(v.shape)

    return _wrap(x, apply_jfn("sequence_softmax", jfn, x), x.lod)


def sequence_reverse(x, name=None):
    """Reverse rows within each sequence (reference sequence_reverse_op)."""
    t = _as_lod(x)
    idx = []
    for i in range(len(t.lod) - 1):
        idx.extend(range(t.lod[i + 1] - 1, t.lod[i] - 1, -1))
    gather = jnp.asarray(np.asarray(idx, np.int32))
    out = apply_jfn("sequence_reverse", lambda v: v[gather], t)
    return _wrap(t, out, t.lod)


def sequence_concat(input, name=None):
    """Concat same-count LoD batches sequence-wise
    (reference sequence_concat_op)."""
    xs = [_as_lod(x) for x in input]
    n = len(xs[0].lod) - 1
    order = []
    offset_base = [0]
    for x in xs:
        offset_base.append(offset_base[-1] + x.lod[-1])
    new_lod = [0]
    for i in range(n):
        total = 0
        for xi, x in enumerate(xs):
            for r in range(x.lod[i], x.lod[i + 1]):
                order.append(offset_base[xi] + r)
            total += x.lod[i + 1] - x.lod[i]
        new_lod.append(new_lod[-1] + total)
    gather = jnp.asarray(np.asarray(order, np.int32))
    from ..autograd import engine

    out = engine.apply(
        "sequence_concat",
        lambda *vs: jnp.concatenate(vs, 0)[gather], tuple(xs))
    return _wrap(xs[0], out, new_lod)


def sequence_pad(x, pad_value, maxlen=None, name=None):
    """LoD → (padded [N, L, ...], lengths) (reference sequence_pad_op)."""
    t = _as_lod(x)
    lens = np.asarray(t.seq_lengths)
    L = int(maxlen) if maxlen is not None else int(lens.max() if
                                                  len(lens) else 0)
    n = len(lens)
    # gather index per (seq, slot); padded slots read row 0 then get masked
    gidx = np.zeros((n, L), np.int32)
    mask = np.zeros((n, L), bool)
    for i in range(n):
        ln = min(int(lens[i]), L)
        gidx[i, :ln] = np.arange(t.lod[i], t.lod[i] + ln)
        mask[i, :ln] = True
    g = jnp.asarray(gidx)
    m = jnp.asarray(mask)
    pv = ensure_tensor(pad_value)

    def jfn(v, pvv):
        padded = v[g.reshape(-1)].reshape((n, L) + v.shape[1:])
        return jnp.where(m.reshape((n, L) + (1,) * (v.ndim - 1)), padded,
                         pvv.astype(v.dtype))

    from ..autograd import engine

    padded = engine.apply("sequence_pad", jfn, (t, pv))
    return padded, Tensor(jnp.asarray(lens.astype(np.int64)),
                          stop_gradient=True)


def sequence_unpad(x, length, name=None):
    """(padded, lengths) → flat LoD rows (reference sequence_unpad_op)."""
    t = ensure_tensor(x)
    lens = np.asarray(value_of(ensure_tensor(length))).astype(np.int64)
    n, L = t.shape[0], t.shape[1]
    rows = []
    lod = [0]
    for i in range(n):
        ln = int(min(lens[i], L))
        rows.extend(i * L + j for j in range(ln))
        lod.append(lod[-1] + ln)
    g = jnp.asarray(np.asarray(rows, np.int32))

    def jfn(v):
        flat = v.reshape((n * L,) + v.shape[2:])
        return flat[g]

    return _wrap(t, apply_jfn("sequence_unpad", jfn, t), lod)


def sequence_expand(x, y, ref_level=-1, name=None):
    """Repeat x's sequences per y's LoD (reference sequence_expand_op):
    sequence i of x is tiled y_len_i times."""
    if isinstance(x, LoDTensor):
        xt = x
    else:
        # non-LoD x: each ROW is one length-1 sequence (reference
        # sequence_expand_op semantics), not one big sequence
        n_rows = int(ensure_tensor(x).shape[0])
        xt = _as_lod(x, list(range(n_rows + 1)))
    yt = _as_lod(y)
    reps = yt.seq_lengths
    order = []
    new_lod = [0]
    for i in range(len(xt.lod) - 1):
        seq = list(range(xt.lod[i], xt.lod[i + 1]))
        r = reps[i] if i < len(reps) else 1
        for _ in range(max(r, 0)):
            order.extend(seq)
        new_lod.append(len(order))
    g = jnp.asarray(np.asarray(order, np.int32))
    out = apply_jfn("sequence_expand", lambda v: v[g], xt)
    return _wrap(xt, out, new_lod)


def sequence_expand_as(x, y, name=None):
    """Expand each row of x to match y's sequence lengths
    (reference sequence_expand_as_op)."""
    xt = ensure_tensor(x)
    yt = _as_lod(y)
    reps = yt.seq_lengths
    order = []
    new_lod = [0]
    for i, r in enumerate(reps):
        order.extend([i] * r)
        new_lod.append(len(order))
    g = jnp.asarray(np.asarray(order, np.int32))
    out = apply_jfn("sequence_expand_as", lambda v: v[g], xt)
    return _wrap(xt, out, new_lod)


def sequence_reshape(input, new_dim):
    """Re-chunk each sequence's flattened payload to rows of new_dim
    (reference sequence_reshape_op)."""
    t = _as_lod(input)
    d = int(t.shape[-1])
    new_lod = [0]
    for ln in t.seq_lengths:
        total = ln * d
        if total % new_dim != 0:
            raise ValueError("sequence payload not divisible by new_dim")
        new_lod.append(new_lod[-1] + total // new_dim)

    out = apply_jfn("sequence_reshape",
                    lambda v: v.reshape(-1, new_dim), t)
    return _wrap(t, out, new_lod)


def sequence_slice(input, offset, length, name=None):
    """Per-sequence slice (reference sequence_slice_op)."""
    t = _as_lod(input)
    off = np.asarray(value_of(ensure_tensor(offset))).reshape(-1)
    ln = np.asarray(value_of(ensure_tensor(length))).reshape(-1)
    order = []
    new_lod = [0]
    for i in range(len(t.lod) - 1):
        start = t.lod[i] + int(off[i])
        order.extend(range(start, start + int(ln[i])))
        new_lod.append(len(order))
    g = jnp.asarray(np.asarray(order, np.int32))
    out = apply_jfn("sequence_slice", lambda v: v[g], t)
    return _wrap(t, out, new_lod)


def sequence_scatter(input, index, updates, name=None):
    """Scatter-add updates into input at per-sequence positions
    (reference sequence_scatter_op): index is a LoD tensor of positions
    into each corresponding row of input."""
    t = ensure_tensor(input)
    idx = _as_lod(index)
    upd = ensure_tensor(updates)
    seg = _seg_ids(idx.lod)
    pos = np.asarray(value_of(idx)).reshape(-1)
    rows = jnp.asarray(seg.astype(np.int32))
    cols = jnp.asarray(pos.astype(np.int32))

    def jfn(v, u):
        return v.at[rows, cols].add(u.reshape(-1).astype(v.dtype))

    from ..autograd import engine

    return engine.apply("sequence_scatter", jfn, (t, upd))


def sequence_enumerate(input, win_size, pad_value=0, name=None):
    """Sliding-window id enumeration per sequence
    (reference sequence_enumerate_op)."""
    t = _as_lod(input)
    vals = np.asarray(value_of(t)).reshape(-1)
    out = np.full((len(vals), win_size), pad_value,
                  vals.dtype if vals.dtype.kind == "i" else np.int64)
    for i in range(len(t.lod) - 1):
        for r in range(t.lod[i], t.lod[i + 1]):
            for w in range(win_size):
                if r + w < t.lod[i + 1]:
                    out[r, w] = vals[r + w]
    o = LoDTensor(jnp.asarray(out), t.lod)
    return o


def sequence_conv(input, num_filters, filter_size=3, filter_stride=1,
                  padding=True, padding_start=None, bias_attr=None,
                  param_attr=None, act=None, name=None):
    """Sequence (context-window) convolution (reference
    sequence_conv_op): each output row contracts a window of
    filter_size rows; windows never cross sequence boundaries
    (out-of-sequence taps read zeros)."""
    from .. import nn

    t = _as_lod(input)
    d = int(t.shape[-1])
    helper = nn.Layer()
    weight = helper.create_parameter([filter_size * d, num_filters],
                                     param_attr)
    bias = (None if bias_attr is False else helper.create_parameter(
        [num_filters], bias_attr, is_bias=True))
    start = (padding_start if padding_start is not None
             else -(filter_size // 2))
    total = t.lod[-1]
    # precompute per-row, per-tap gather index (-1 = zero pad)
    gather = np.full((total, filter_size), -1, np.int32)
    for i in range(len(t.lod) - 1):
        lo, hi = t.lod[i], t.lod[i + 1]
        for r in range(lo, hi):
            for k in range(filter_size):
                srcr = r + start + k
                if lo <= srcr < hi:
                    gather[r, k] = srcr
    g = jnp.asarray(gather)
    ok = jnp.asarray(gather >= 0)

    def jfn(v, w, *rest):
        win = jnp.where(ok[..., None], v[jnp.clip(g, 0)], 0.0)
        flat = win.reshape(total, filter_size * d)
        out = flat @ w
        if rest:
            out = out + rest[0]
        return out

    from ..autograd import engine

    args = (t, weight) + ((bias,) if bias is not None else ())
    out = engine.apply("sequence_conv", jfn, args)
    if act == "relu":
        from ..ops.activation import relu as _relu

        out = _relu(out)
    elif act == "tanh":
        from ..ops.math import tanh as _tanh

        out = _tanh(out)
    return _wrap(t, out, t.lod)
