"""paddle_tpu.static — static-graph compatibility facade.

The reference's static mode (reference: python/paddle/static/, fluid
Program/Executor — SURVEY.md §2.2, §3.3) exists because graph capture there
requires building a protobuf program executed by a C++ interpreter. On TPU
the capture mechanism IS jax tracing, so this facade keeps the Program/
Executor/data API shape while delegating:
- `paddle.static.data` declares InputSpec-backed placeholders,
- a `Program` records the python callables run under `program_guard`,
- `Executor.run` traces+jit-compiles the recorded computation into one XLA
  program keyed by feed signature (the InterpreterCore instruction loop of
  the reference collapses into a single compiled module).
Differentiation/optimizers in static mode go through the same tape (the
recorded fns run eagerly inside the traced step).
"""
import contextlib

import numpy as np

import jax

from . import nn  # noqa: F401  (paddle.static.nn.cond / while_loop / ...)
from ..jit import InputSpec  # noqa: F401  (paddle.static.InputSpec)
from ..tensor_core import Tensor

__all__ = [
    "Program", "ProgramIR", "program_guard", "default_main_program",
    "default_startup_program", "data", "Executor", "InputSpec", "name_scope",
    "save", "load", "save_inference_model", "load_inference_model",
    "gradients", "append_backward", "cpu_places", "device_guard", "scope_guard",
    "global_scope", "amp",
]


class Variable:
    """Static placeholder (≈ VarDesc in framework.proto:191)."""

    def __init__(self, name, shape, dtype, program):
        self.name = name
        self.shape = list(shape)
        self.dtype = dtype
        self._program = program
        self.stop_gradient = True

    def __repr__(self):
        return f"static.Variable(name={self.name}, shape={self.shape})"


class Program:
    """Deferred computation: a list of (fn, inputs, outputs) stages
    (≈ ProgramDesc, framework.proto:236 — but stages are python closures
    traced by XLA at Executor.run, not protobuf ops)."""

    def __init__(self):
        self.placeholders = {}
        self.stages = []  # callables: feed_dict -> dict of produced tensors
        self.fetch_map = {}
        self.random_seed = None

    def clone(self, for_test=False):
        """Shallow-copy the stage list (reference Program.clone). The
        compatibility envelope, pinned by tests/test_static_extras.py:
        stages/placeholders/fetch_map are copied so later edits to
        either program don't leak into the other; `for_test=True` does
        NOT rewrite stages to strip dropout/BN-train ops the way the
        reference does — train/eval state rides the LAYER objects the
        stages close over, so switch with model.eval() before running a
        test clone."""
        p = Program()
        p.placeholders = dict(self.placeholders)
        p.stages = list(self.stages)
        p.fetch_map = dict(self.fetch_map)
        p.random_seed = self.random_seed
        return p

    def global_block(self):
        return self

    # block-like protocol used by introspection
    @property
    def ops(self):
        return self.stages

    def freeze(self, fetch_list, feed_specs=None, batch_size=1):
        """Trace the staged computation ONCE into a real, inspectable
        IR (reference: ProgramDesc, framework.proto:236 — op list,
        prunable, printable). The TPU-native IR is a JAXPR: `ops`
        lists primitive names (the OpDesc view), `prune()` is jaxpr
        dead-code elimination to a fetch subset (reference
        Program._prune), `as_text()` is the printable desc
        (Program.to_string), and `run()` executes the frozen program
        as one jitted XLA computation.

        Placeholders with None/-1 dims are traced at `batch_size`
        (override per-name via feed_specs={name: (shape, dtype)}).
        Stages must be traceable — python side effects run once at
        freeze time, and value-dependent host reads (`.numpy()` on a
        data-dependent tensor) raise jax's tracer error."""
        names = list(self.placeholders)
        specs = {}
        for n, v in self.placeholders.items():
            shape = tuple(batch_size if (s is None or s == -1) else int(s)
                          for s in v.shape)
            specs[n] = (shape, v.dtype)
        for n, sd in (feed_specs or {}).items():
            if n not in specs:
                raise KeyError(
                    f"feed_specs name {n!r} is not a declared "
                    f"placeholder (have: {sorted(specs)})")
            specs[n] = (tuple(sd[0]), sd[1])
        fetch_names = [f.name if isinstance(f, Variable) else str(f)
                       for f in fetch_list]

        def run_fn(*feed_vals):
            import jax.numpy as jnp

            env = {n: Tensor(v) for n, v in zip(names, feed_vals)}
            for stage in self.stages:
                stage(env)
            outs = []
            for f in fetch_names:
                if f not in env:
                    raise KeyError(f"fetch target {f!r} not produced")
                o = env[f]
                outs.append(o._value if isinstance(o, Tensor)
                            else jnp.asarray(o))
            return tuple(outs)

        avals = [jax.ShapeDtypeStruct(specs[n][0], np.dtype(specs[n][1]))
                 for n in names]
        closed = jax.make_jaxpr(run_fn)(*avals)
        from jax._src.interpreters import partial_eval as pe

        jaxpr_c = pe.convert_constvars_jaxpr(closed.jaxpr)
        return ProgramIR(jaxpr_c, list(closed.consts), names, fetch_names)

    def to_string(self, throw_on_error=False, with_details=False):
        """Printable program summary (reference Program.to_string):
        placeholders + stage count; freeze() gives the full op-level
        text."""
        lines = [f"Program(stages={len(self.stages)})"]
        for n, v in self.placeholders.items():
            lines.append(f"  data {n}: shape={v.shape} dtype={v.dtype}")
        return "\n".join(lines)


class ProgramIR:
    """Frozen jaxpr-backed program (the TPU-native ProgramDesc — see
    Program.freeze). Constants are held as leading args of a
    constvar-free jaxpr so pruning can drop them with ordinary DCE."""

    def __init__(self, jaxpr, consts, feed_names, fetch_names):
        self._jaxpr = jaxpr            # invars = consts ++ feeds
        self._consts = consts
        self.feed_names = list(feed_names)
        self.fetch_names = list(fetch_names)
        self._compiled = None

    # -- the OpDesc view ------------------------------------------------
    @property
    def ops(self):
        """Primitive names in execution order (reference block.ops)."""
        return [eq.primitive.name for eq in self._jaxpr.eqns]

    def op_histogram(self):
        import collections

        return collections.Counter(self.ops)

    def as_text(self):
        """The printable IR (reference Program.to_string — full desc)."""
        return str(self._jaxpr)

    # -- passes ---------------------------------------------------------
    def prune(self, fetch_list):
        """Dead-code-eliminate to a fetch subset (reference
        Program._prune): ops, constants AND feeds that the kept
        fetches don't reach are dropped from the program."""
        targets = [f.name if isinstance(f, Variable) else str(f)
                   for f in fetch_list]
        missing = [t for t in targets if t not in self.fetch_names]
        if missing:
            raise KeyError(f"prune targets not in fetch set: {missing}")
        used_out = [n in set(targets) for n in self.fetch_names]
        from jax._src.interpreters import partial_eval as pe

        new_jaxpr, used_in = pe.dce_jaxpr(self._jaxpr, used_out)
        nc = len(self._consts)
        consts = [c for c, u in zip(self._consts, used_in[:nc]) if u]
        feeds = [n for n, u in zip(self.feed_names, used_in[nc:]) if u]
        return ProgramIR(new_jaxpr, consts,
                         feeds, [n for n in self.fetch_names
                                 if n in set(targets)])

    # -- execution ------------------------------------------------------
    def run(self, feed, return_numpy=True):
        """Execute the frozen program as ONE jitted XLA computation —
        the reference Executor-over-ProgramDesc path, minus the
        interpreter (SURVEY §7: the op-by-op InterpreterCore collapses
        into a compiled jaxpr)."""
        if self._compiled is None:
            jaxpr = self._jaxpr

            def call(consts_and_feeds):
                return jax.core.eval_jaxpr(jaxpr, (), *consts_and_feeds)

            self._compiled = jax.jit(call)
        nc = len(self._consts)
        feed_vals = []
        for n, var in zip(self.feed_names, self._jaxpr.invars[nc:]):
            v = np.asarray(feed[n])
            aval = var.aval
            if tuple(v.shape) != tuple(aval.shape) or \
                    np.dtype(v.dtype) != np.dtype(aval.dtype):
                # shape-derived python scalars were BAKED IN at freeze
                # time — re-running at another shape would be silently
                # wrong, not just slow (re-freeze for a new signature)
                raise ValueError(
                    f"feed {n!r} has shape {v.shape}/{v.dtype} but the "
                    f"program was frozen at {tuple(aval.shape)}/"
                    f"{aval.dtype}; freeze() again for a new signature")
            feed_vals.append(v)
        outs = self._compiled(list(self._consts) + feed_vals)
        if return_numpy:
            outs = [np.asarray(o) for o in outs]
        return dict(zip(self.fetch_names, outs))


_default_main = Program()
_default_startup = Program()
_current_main = _default_main
_current_startup = _default_startup


def default_main_program():
    return _current_main


def default_startup_program():
    return _current_startup


@contextlib.contextmanager
def program_guard(main_program, startup_program=None):
    global _current_main, _current_startup
    old_m, old_s = _current_main, _current_startup
    _current_main = main_program
    if startup_program is not None:
        _current_startup = startup_program
    try:
        yield
    finally:
        _current_main, _current_startup = old_m, old_s


def data(name, shape, dtype="float32", lod_level=0):
    v = Variable(name, shape, dtype, _current_main)
    _current_main.placeholders[name] = v
    return v


@contextlib.contextmanager
def name_scope(prefix):
    yield


@contextlib.contextmanager
def device_guard(device=None):
    yield


@contextlib.contextmanager
def scope_guard(scope):
    yield


def global_scope():
    return None


def cpu_places(device_count=None):
    return ["cpu"]


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    from ..autograd.engine import grad as _grad

    return _grad(targets, inputs, grad_outputs=target_gradients)


def append_backward(loss, parameter_list=None, no_grad_set=None,
                    callbacks=None):
    loss.backward()
    params = parameter_list or []
    return [(p, p.grad) for p in params]


class Executor:
    """(reference: python/paddle/fluid/executor.py:1257 Executor.run →
    StandaloneExecutor/InterpreterCore). Here: run(fetch_list=...) executes
    the fetches' recorded computation; with a `program` built via
    paddle_tpu.static the feed dict maps placeholder names to numpy."""

    def __init__(self, place=None):
        self.place = place
        self._cache = {}

    def run(self, program=None, feed=None, fetch_list=None,
            return_numpy=True, **kwargs):
        feed = feed or {}
        fetch_list = fetch_list or []
        results = []
        env = {}
        for name, value in feed.items():
            env[name] = Tensor(np.asarray(value))
        prog = program or _current_main
        for stage in prog.stages:
            stage(env)
        for f in fetch_list:
            if isinstance(f, Variable):
                out = env.get(f.name)
            elif isinstance(f, str):
                out = env.get(f)
            else:
                out = f
            if out is None:
                raise KeyError(f"fetch target {f} not produced")
            results.append(out.numpy() if return_numpy and
                           isinstance(out, Tensor) else out)
        return results

    def _run_dataset(self, program, dataset, fetch_list, fetch_info,
                     print_period, debug):
        """Shared engine of train/infer_from_dataset (reference:
        executor.py train_from_dataset → MultiTrainer + hogwild_worker
        thread-per-scope loops over data_feed.cc). TPU-native: the
        dataset facade (paddle_tpu.distributed.InMemoryDataset /
        QueueDataset) streams parsed slot batches on the host; each
        batch is fed as one `run` of the program — the per-op thread
        scheduling the reference needs for CPU PS workloads is replaced
        by the compiled program (and the dataset's own parse
        parallelism)."""
        use_var = getattr(dataset, "_use_var", []) or []
        names = [v.name if isinstance(v, Variable) else str(v)
                 for v in use_var]
        fetch_list = fetch_list or []
        fetch_info = fetch_info or [
            getattr(f, "name", str(f)) for f in fetch_list]
        results = None
        for i, batch in enumerate(dataset):
            cols = list(zip(*batch))
            if names and len(cols) != len(names):
                raise ValueError(
                    f"dataset yields {len(cols)} slots but use_vars "
                    f"names {len(names)}: {names}")
            feed = {n: np.asarray(c) for n, c in zip(names, cols)}
            results = self.run(program, feed=feed, fetch_list=fetch_list)
            if print_period and (i + 1) % print_period == 0 and \
                    (fetch_list or debug):
                msg = ", ".join(
                    f"{info}: {np.asarray(r).reshape(-1)[:4]}"
                    for info, r in zip(fetch_info, results))
                print(f"[dataset] batch {i + 1}: {msg}", flush=True)
        return results

    def train_from_dataset(self, program=None, dataset=None, scope=None,
                           thread=0, debug=False, fetch_list=None,
                           fetch_info=None, print_period=100):
        """(reference executor.py:train_from_dataset). Streams the slot
        dataset through the program once (one pass == one epoch)."""
        if dataset is None:
            raise ValueError("train_from_dataset needs a dataset")
        return self._run_dataset(program, dataset, fetch_list, fetch_info,
                                 print_period, debug)

    def infer_from_dataset(self, program=None, dataset=None, scope=None,
                           thread=0, debug=False, fetch_list=None,
                           fetch_info=None, print_period=100):
        """(reference executor.py:infer_from_dataset) — identical loop;
        the program itself decides train vs infer (as in the reference,
        where the infer variant merely skips gradient ops)."""
        if dataset is None:
            raise ValueError("infer_from_dataset needs a dataset")
        return self._run_dataset(program, dataset, fetch_list, fetch_info,
                                 print_period, debug)

    def close(self):
        pass


def save(program, model_path, protocol=4):
    from ..framework.io_state import save as _save

    _save({"program": "static-facade"}, model_path + ".pdmodel")


def load(program, model_path, executor=None, var_list=None):
    pass


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor,
                         **kwargs):
    """Maps to jit.save when given a layer via kwargs['program']."""
    raise NotImplementedError(
        "use paddle_tpu.jit.save(layer, path, input_spec=...) — the "
        "TPU-native inference artifact is a StableHLO export"
    )


def load_inference_model(path_prefix, executor, **kwargs):
    raise NotImplementedError(
        "use paddle_tpu.jit.load(path) to load a StableHLO export"
    )


class _AmpFacade:
    @staticmethod
    def decorate(models=None, optimizers=None, level="O1", **kw):
        from .. import amp as _amp

        return _amp.decorate(models, optimizers, level=level, **kw)


amp = _AmpFacade()


# ---- remaining reference surface (python/paddle/static/__init__.py) ----

class Scope:
    """Variable scope (reference: paddle/fluid/framework/scope.h:60).
    The executor env dict plays the runtime role; Scope keeps the
    name→value API for save/load tooling."""

    def __init__(self):
        self.vars = {}

    def var(self, name):
        self.vars.setdefault(name, None)
        return name

    def find_var(self, name):
        return self.vars.get(name)

    def local_scope(self):
        return Scope()


_global_scope = Scope()


class BuildStrategy:
    """Graph-build knobs (reference: framework/details/build_strategy.h).
    XLA owns fusion/memory planning; fields are recorded for
    compatibility and ignored by compilation."""

    def __init__(self):
        self.enable_inplace = True
        self.fuse_all_reduce_ops = True
        self.fuse_broadcast_ops = True
        self.fuse_elewise_add_act_ops = False
        self.memory_optimize = True
        self.reduce_strategy = None
        self.gradient_scale_strategy = None


class ExecutionStrategy:
    """(reference: framework/details/execution_strategy.h)."""

    def __init__(self):
        self.num_threads = 1
        self.num_iteration_per_drop_scope = 10
        self.num_iteration_per_run = 1


class CompiledProgram:
    """(reference: python/paddle/fluid/compiler.py CompiledProgram.)
    Programs here are traced+jitted at Executor.run; this wrapper simply
    carries the strategies."""

    def __init__(self, program_or_graph, build_strategy=None):
        self.program = program_or_graph
        self.build_strategy = build_strategy or BuildStrategy()

    def with_data_parallel(self, loss_name=None, build_strategy=None,
                           exec_strategy=None, share_vars_from=None,
                           places=None):
        if build_strategy is not None:
            self.build_strategy = build_strategy
        return self

    @property
    def stages(self):
        return self.program.stages

    @property
    def placeholders(self):
        return self.program.placeholders


ParallelExecutor = CompiledProgram  # legacy alias (parallel_executor.cc)


class IpuStrategy:
    """IPU config facade (reference: python/paddle/fluid/compiler.py
    IpuStrategy). No IPU backend exists here; options are recorded."""

    def __init__(self):
        self.options = {}

    def set_options(self, options):
        self.options.update(options)

    def set_graph_config(self, **kw):
        self.options.update(kw)

    def set_pipelining_config(self, **kw):
        self.options.update(kw)

    def set_precision_config(self, **kw):
        self.options.update(kw)


class IpuCompiledProgram:
    def __init__(self, program=None, scope=None, ipu_strategy=None):
        raise RuntimeError(
            "no IPU backend in this build — TPU is the accelerator; use "
            "Executor/CompiledProgram directly")


import contextlib as _ctx


@_ctx.contextmanager
def ipu_shard_guard(index=-1, stage=-1):
    yield


def set_ipu_shard(call_func, index=-1, stage=-1):
    return call_func


def Print(input, first_n=-1, message=None, summarize=20,
          print_tensor_name=True, print_tensor_type=True,
          print_tensor_shape=True, print_tensor_layout=True,
          print_tensor_lod=True, print_phase="both"):
    """Debug print pass-through (reference:
    fluid/layers/control_flow.py Print → print_op): prints eagerly (or
    via jax.debug inside traces) and returns the input unchanged."""
    from ..ops._helpers import ensure_tensor

    t = ensure_tensor(input)
    import jax as _jax

    if isinstance(t._value, _jax.core.Tracer):
        _jax.debug.print((message or "") + " {}", t._value)
    else:
        head = f"{message or ''} "
        if print_tensor_name:
            head += f"name={t.name} "
        if print_tensor_shape:
            head += f"shape={tuple(t.shape)} "
        flat = np.asarray(t._value).reshape(-1)
        if summarize is not None and summarize >= 0:
            flat = flat[:summarize]  # -1 = print everything (reference)
        print(head + str(flat))
    return input


def accuracy(input, label, k=1, correct=None, total=None):
    """Top-k accuracy (reference: python/paddle/static/nn/metric.py
    accuracy)."""
    from ..metric import accuracy as _acc

    return _acc(input, label, k=k)


def auc(input, label, curve="ROC", num_thresholds=4095, topk=1,
        slide_steps=1, ins_tag_weight=None):
    """Batch AUC (reference: static/nn/metric.py auc). Returns
    (auc_value, batch_auc, [state]) shaped like the reference's first
    two outputs."""
    from ..metric import Auc
    from ..ops._helpers import ensure_tensor, value_of
    from ..tensor_core import Tensor
    import jax.numpy as jnp

    m = Auc(num_thresholds=num_thresholds)
    preds = np.asarray(value_of(ensure_tensor(input)))
    lbl = np.asarray(value_of(ensure_tensor(label)))
    m.update(preds, lbl)
    v = float(m.accumulate())
    t = Tensor(jnp.asarray(v, jnp.float32), stop_gradient=True)
    return t, t, []


def ctr_metric_bundle(input, label, ins_tag_weight=None):
    """CTR metrics (reference: fluid/layers/metric_op.py
    ctr_metric_bundle): returns (sqrerr, abserr, prob, q, pos, total)."""
    from ..ops._helpers import ensure_tensor, value_of
    from ..tensor_core import Tensor
    import jax.numpy as jnp

    p = np.asarray(value_of(ensure_tensor(input))).reshape(-1)
    y = np.asarray(value_of(ensure_tensor(label))).reshape(-1)

    def t(v):
        return Tensor(jnp.asarray(np.float32(v)), stop_gradient=True)

    return (t(np.sum((p - y) ** 2)), t(np.sum(np.abs(p - y))),
            t(np.sum(p)), t(np.sum(p)), t(np.sum(y)), t(len(p)))


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    from ..ops.api_misc import create_parameter as _cp

    return _cp(shape, dtype, name=name, attr=attr, is_bias=is_bias,
               default_initializer=default_initializer)


def create_global_var(shape, value, dtype, persistable=False,
                      force_cpu=False, name=None):
    from ..tensor_core import Tensor
    import jax.numpy as jnp
    from ..core import dtype as _dt

    t = Tensor(jnp.full(tuple(shape), value, _dt.convert_dtype(dtype)),
               name=name)
    t.persistable = persistable
    return t


def cuda_places(device_ids=None):
    """Accelerator places (TPU chips fill the 'cuda' role)."""
    import jax as _jax

    devs = [d for d in _jax.devices() if d.platform != "cpu"] or \
        _jax.devices()
    if device_ids is not None:
        devs = [devs[i] for i in device_ids]
    return devs


def xpu_places(device_ids=None):
    return cuda_places(device_ids)


def npu_places(device_ids=None):
    return cuda_places(device_ids)


def mlu_places(device_ids=None):
    return cuda_places(device_ids)


def exponential_decay(learning_rate, decay_steps, decay_rate,
                      staircase=False):
    """(reference: fluid/layers/learning_rate_scheduler.py): returns the
    matching LRScheduler for the trace-based runtime."""
    from ..optimizer.lr import ExponentialDecay

    return ExponentialDecay(learning_rate, gamma=decay_rate)


class WeightNormParamAttr:
    """ParamAttr requesting weight normalization (reference:
    python/paddle/fluid/param_attr.py WeightNormParamAttr). Consumed by
    nn.utils.weight_norm when layers are built from it."""

    def __init__(self, dim=None, name=None, initializer=None,
                 learning_rate=1.0, regularizer=None, trainable=True,
                 do_model_average=False, need_clip=True):
        self.dim = dim
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.need_clip = need_clip


class ExponentialMovingAverage:
    """EMA of trainable parameters (reference:
    python/paddle/static/__init__.py ExponentialMovingAverage from
    fluid/optimizer.py): update() accumulates, apply()/restore() swap."""

    def __init__(self, decay=0.999, thres_steps=None, name=None):
        self.decay = float(decay)
        self._ema = {}
        self._backup = {}
        self._step = 0

    def update(self, parameters=None):
        from ..tensor_core import Parameter

        params = parameters or [
            p for p in _collect_all_parameters() if p.trainable]
        self._step += 1
        d = min(self.decay, (1 + self._step) / (10 + self._step))
        for p in params:
            prev = self._ema.get(id(p))
            cur = p._value
            self._ema[id(p)] = (cur if prev is None
                                else d * prev + (1 - d) * cur)
            self._ema.setdefault("_ref_%d" % id(p), p)

    def apply(self, executor=None, need_restore=True):
        import contextlib

        @contextlib.contextmanager
        def guard():
            for key, val in list(self._ema.items()):
                if isinstance(key, str):
                    continue
                p = self._ema["_ref_%d" % key]
                self._backup[key] = p._value
                p._value = val
            try:
                yield
            finally:
                if need_restore:
                    self.restore()

        return guard()

    def restore(self, executor=None):
        for key, val in self._backup.items():
            self._ema["_ref_%d" % key]._value = val
        self._backup = {}


def _collect_all_parameters():
    """Every live Parameter (tensor_core keeps a weakref registry)."""
    from ..tensor_core import _parameter_registry

    return [p for p in (r() for r in _parameter_registry)
            if p is not None]


def normalize_program(program, feed_vars, fetch_vars):
    """(reference: static/io.py normalize_program) — prune to the
    feed→fetch slice. Stages are opaque closures; recorded as-is with
    the feed/fetch contract attached."""
    p = program.clone()
    p.feed_names = [getattr(v, "name", v) for v in (
        feed_vars if isinstance(feed_vars, (list, tuple)) else [feed_vars])]
    p.fetch_names = [getattr(v, "name", v) for v in (
        fetch_vars if isinstance(fetch_vars, (list, tuple))
        else [fetch_vars])]
    return p


def serialize_program(feed_vars, fetch_vars, **kwargs):
    import pickle

    prog = default_main_program()
    meta = {
        "placeholders": {k: (v.shape, str(v.dtype))
                         for k, v in prog.placeholders.items()},
        "feed": [getattr(v, "name", v) for v in (
            feed_vars if isinstance(feed_vars, (list, tuple))
            else [feed_vars])],
        "fetch": [getattr(v, "name", v) for v in (
            fetch_vars if isinstance(fetch_vars, (list, tuple))
            else [fetch_vars])],
    }
    return pickle.dumps(meta)


def deserialize_program(data):
    import pickle

    meta = pickle.loads(data)
    p = Program()
    for name, (shape, dtype) in meta["placeholders"].items():
        p.placeholders[name] = Variable(name, shape, dtype, p)
    return p


def serialize_persistables(feed_vars, fetch_vars, executor=None, **kwargs):
    import pickle

    return pickle.dumps({})


def deserialize_persistables(program, data, executor=None):
    import pickle

    return pickle.loads(data)


def save_to_file(path, content):
    with open(path, "wb") as f:
        f.write(content)


def load_from_file(path):
    with open(path, "rb") as f:
        return f.read()


def save_vars(executor, dirname, main_program=None, vars=None,
              predicate=None, filename=None):
    from ..framework.io_state import save as _save

    _save({getattr(v, "name", str(i)): v
           for i, v in enumerate(vars or [])},
          dirname if filename is None else f"{dirname}/{filename}")


def load_vars(executor, dirname, main_program=None, vars=None,
              predicate=None, filename=None):
    from ..framework.io_state import load as _load

    return _load(dirname if filename is None else f"{dirname}/{filename}")


def load_program_state(model_path, var_list=None):
    from ..framework.io_state import load as _load

    return _load(model_path)


def set_program_state(program, state_dict):
    program.state = dict(state_dict)


from ..incubate import asp as sparsity  # noqa: E402,F401
from .nn_build import py_func  # noqa: E402,F401
from . import nn  # noqa: E402,F401


def batch(reader, batch_size, drop_last=False):
    from .. import batch as _batch

    return _batch(reader, batch_size, drop_last)


__all__ += [
    "Scope", "BuildStrategy", "ExecutionStrategy", "CompiledProgram",
    "ParallelExecutor", "IpuStrategy", "IpuCompiledProgram",
    "ipu_shard_guard", "set_ipu_shard", "Print", "accuracy", "auc",
    "ctr_metric_bundle", "create_parameter", "create_global_var",
    "cuda_places", "xpu_places", "npu_places", "mlu_places",
    "exponential_decay", "WeightNormParamAttr",
    "ExponentialMovingAverage", "normalize_program", "serialize_program",
    "deserialize_program", "serialize_persistables",
    "deserialize_persistables", "save_to_file", "load_from_file",
    "save_vars", "load_vars", "load_program_state", "set_program_state",
    "sparsity", "py_func", "batch", "nn",
]
