"""paddle_tpu.static — static-graph compatibility facade.

The reference's static mode (reference: python/paddle/static/, fluid
Program/Executor — SURVEY.md §2.2, §3.3) exists because graph capture there
requires building a protobuf program executed by a C++ interpreter. On TPU
the capture mechanism IS jax tracing, so this facade keeps the Program/
Executor/data API shape while delegating:
- `paddle.static.data` declares InputSpec-backed placeholders,
- a `Program` records the python callables run under `program_guard`,
- `Executor.run` traces+jit-compiles the recorded computation into one XLA
  program keyed by feed signature (the InterpreterCore instruction loop of
  the reference collapses into a single compiled module).
Differentiation/optimizers in static mode go through the same tape (the
recorded fns run eagerly inside the traced step).
"""
import contextlib

import numpy as np

import jax

from . import nn  # noqa: F401  (paddle.static.nn.cond / while_loop / ...)
from ..jit import InputSpec  # noqa: F401  (paddle.static.InputSpec)
from ..tensor_core import Tensor

__all__ = [
    "Program", "program_guard", "default_main_program",
    "default_startup_program", "data", "Executor", "InputSpec", "name_scope",
    "save", "load", "save_inference_model", "load_inference_model",
    "gradients", "append_backward", "cpu_places", "device_guard", "scope_guard",
    "global_scope", "amp",
]


class Variable:
    """Static placeholder (≈ VarDesc in framework.proto:191)."""

    def __init__(self, name, shape, dtype, program):
        self.name = name
        self.shape = list(shape)
        self.dtype = dtype
        self._program = program
        self.stop_gradient = True

    def __repr__(self):
        return f"static.Variable(name={self.name}, shape={self.shape})"


class Program:
    """Deferred computation: a list of (fn, inputs, outputs) stages
    (≈ ProgramDesc, framework.proto:236 — but stages are python closures
    traced by XLA at Executor.run, not protobuf ops)."""

    def __init__(self):
        self.placeholders = {}
        self.stages = []  # callables: feed_dict -> dict of produced tensors
        self.fetch_map = {}
        self.random_seed = None

    def clone(self, for_test=False):
        p = Program()
        p.placeholders = dict(self.placeholders)
        p.stages = list(self.stages)
        p.fetch_map = dict(self.fetch_map)
        return p

    def global_block(self):
        return self

    # block-like protocol used by introspection
    @property
    def ops(self):
        return self.stages


_default_main = Program()
_default_startup = Program()
_current_main = _default_main
_current_startup = _default_startup


def default_main_program():
    return _current_main


def default_startup_program():
    return _current_startup


@contextlib.contextmanager
def program_guard(main_program, startup_program=None):
    global _current_main, _current_startup
    old_m, old_s = _current_main, _current_startup
    _current_main = main_program
    if startup_program is not None:
        _current_startup = startup_program
    try:
        yield
    finally:
        _current_main, _current_startup = old_m, old_s


def data(name, shape, dtype="float32", lod_level=0):
    v = Variable(name, shape, dtype, _current_main)
    _current_main.placeholders[name] = v
    return v


@contextlib.contextmanager
def name_scope(prefix):
    yield


@contextlib.contextmanager
def device_guard(device=None):
    yield


@contextlib.contextmanager
def scope_guard(scope):
    yield


def global_scope():
    return None


def cpu_places(device_count=None):
    return ["cpu"]


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    from ..autograd.engine import grad as _grad

    return _grad(targets, inputs, grad_outputs=target_gradients)


def append_backward(loss, parameter_list=None, no_grad_set=None,
                    callbacks=None):
    loss.backward()
    params = parameter_list or []
    return [(p, p.grad) for p in params]


class Executor:
    """(reference: python/paddle/fluid/executor.py:1257 Executor.run →
    StandaloneExecutor/InterpreterCore). Here: run(fetch_list=...) executes
    the fetches' recorded computation; with a `program` built via
    paddle_tpu.static the feed dict maps placeholder names to numpy."""

    def __init__(self, place=None):
        self.place = place
        self._cache = {}

    def run(self, program=None, feed=None, fetch_list=None,
            return_numpy=True, **kwargs):
        feed = feed or {}
        fetch_list = fetch_list or []
        results = []
        env = {}
        for name, value in feed.items():
            env[name] = Tensor(np.asarray(value))
        prog = program or _current_main
        for stage in prog.stages:
            stage(env)
        for f in fetch_list:
            if isinstance(f, Variable):
                out = env.get(f.name)
            elif isinstance(f, str):
                out = env.get(f)
            else:
                out = f
            if out is None:
                raise KeyError(f"fetch target {f} not produced")
            results.append(out.numpy() if return_numpy and
                           isinstance(out, Tensor) else out)
        return results

    def close(self):
        pass


def save(program, model_path, protocol=4):
    from ..framework.io_state import save as _save

    _save({"program": "static-facade"}, model_path + ".pdmodel")


def load(program, model_path, executor=None, var_list=None):
    pass


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor,
                         **kwargs):
    """Maps to jit.save when given a layer via kwargs['program']."""
    raise NotImplementedError(
        "use paddle_tpu.jit.save(layer, path, input_spec=...) — the "
        "TPU-native inference artifact is a StableHLO export"
    )


def load_inference_model(path_prefix, executor, **kwargs):
    raise NotImplementedError(
        "use paddle_tpu.jit.load(path) to load a StableHLO export"
    )


class _AmpFacade:
    @staticmethod
    def decorate(models=None, optimizers=None, level="O1", **kw):
        from .. import amp as _amp

        return _amp.decorate(models, optimizers, level=level, **kw)


amp = _AmpFacade()
