"""paddle_tpu.static.nn — control-flow + layer helpers in static style
(reference: python/paddle/static/nn/__init__.py; cond/while_loop/case/
switch_case live here in the reference's namespace)."""
from ..ops.control_flow import (  # noqa: F401
    case,
    cond,
    switch_case,
    while_loop,
)

__all__ = ["cond", "while_loop", "case", "switch_case"]

from .nn_build import (  # noqa: F401
    StaticRNN,
    batch_norm,
    bilinear_tensor_product,
    continuous_value_model,
    conv2d,
    conv2d_transpose,
    conv3d,
    conv3d_transpose,
    create_parameter,
    crf_decoding,
    data_norm,
    deform_conv2d,
    embedding,
    fc,
    group_norm,
    instance_norm,
    layer_norm,
    multi_box_head,
    nce,
    prelu,
    py_func,
    row_conv,
    sparse_embedding,
    spectral_norm,
)
from .sequence import (  # noqa: F401
    LoDTensor,
    sequence_concat,
    sequence_conv,
    sequence_enumerate,
    sequence_expand,
    sequence_expand_as,
    sequence_first_step,
    sequence_last_step,
    sequence_pad,
    sequence_pool,
    sequence_reshape,
    sequence_reverse,
    sequence_scatter,
    sequence_slice,
    sequence_softmax,
    sequence_unpad,
)

__all__ += [
    "fc", "conv2d", "conv2d_transpose", "conv3d", "conv3d_transpose",
    "batch_norm", "instance_norm", "layer_norm", "group_norm", "data_norm",
    "embedding", "sparse_embedding", "prelu", "spectral_norm",
    "deform_conv2d", "bilinear_tensor_product", "nce", "row_conv",
    "crf_decoding", "py_func", "create_parameter", "multi_box_head",
    "continuous_value_model", "StaticRNN", "LoDTensor",
    "sequence_concat", "sequence_conv", "sequence_enumerate",
    "sequence_expand", "sequence_expand_as", "sequence_first_step",
    "sequence_last_step", "sequence_pad", "sequence_pool",
    "sequence_reshape", "sequence_reverse", "sequence_scatter",
    "sequence_slice", "sequence_softmax", "sequence_unpad",
]
