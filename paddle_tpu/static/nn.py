"""paddle_tpu.static.nn — control-flow + layer helpers in static style
(reference: python/paddle/static/nn/__init__.py; cond/while_loop/case/
switch_case live here in the reference's namespace)."""
from ..ops.control_flow import (  # noqa: F401
    case,
    cond,
    switch_case,
    while_loop,
)

__all__ = ["cond", "while_loop", "case", "switch_case"]
