"""static.nn layer-builder surface (reference: python/paddle/static/nn/
— fc, conv2d, batch_norm, embedding, nce, ... over LayerHelper).

TPU-native: each builder creates the matching eager layer ONCE (owning
its parameters) and applies it — under `paddle.jit.to_static`/`Program`
tracing this is exactly the reference's build-then-run split, without a
protobuf program in between. Ops take/return Tensors.
"""
import numpy as np

import jax.numpy as jnp

from ..ops._helpers import apply_jfn, ensure_tensor, value_of
from ..tensor_core import Tensor

__all__ = [
    "fc", "conv2d", "conv2d_transpose", "conv3d", "conv3d_transpose",
    "batch_norm", "instance_norm", "layer_norm", "group_norm", "data_norm",
    "embedding", "sparse_embedding", "prelu", "spectral_norm",
    "deform_conv2d", "bilinear_tensor_product", "nce", "row_conv",
    "crf_decoding", "py_func", "create_parameter", "multi_box_head",
    "continuous_value_model", "StaticRNN",
]


def _act(out, act):
    if act is None:
        return out
    from ..nn import functional as F

    return getattr(F, act)(out)


def fc(x, size, num_flatten_dims=1, weight_attr=None, bias_attr=None,
       activation=None, name=None):
    """(reference static/nn/common.py fc)."""
    from .. import nn
    from ..ops.manipulation import reshape

    xs = x if isinstance(x, (list, tuple)) else [x]
    outs = []
    for xi in xs:
        xi = ensure_tensor(xi)
        lead = xi.shape[:num_flatten_dims]
        flat_in = int(np.prod(xi.shape[num_flatten_dims:]))
        layer = nn.Linear(flat_in, size, weight_attr=weight_attr,
                          bias_attr=bias_attr)
        flat = reshape(xi, list(lead) + [flat_in])
        outs.append(layer(flat))
    out = outs[0]
    for o in outs[1:]:
        out = out + o
    return _act(out, activation)


def conv2d(input, num_filters, filter_size, stride=1, padding=0,
           dilation=1, groups=1, param_attr=None, bias_attr=None,
           use_cudnn=True, act=None, name=None, data_format="NCHW"):
    from .. import nn

    in_c = input.shape[1 if data_format == "NCHW" else -1]
    layer = nn.Conv2D(in_c, num_filters, filter_size, stride, padding,
                      dilation, groups, weight_attr=param_attr,
                      bias_attr=bias_attr, data_format=data_format)
    return _act(layer(input), act)


def conv3d(input, num_filters, filter_size, stride=1, padding=0,
           dilation=1, groups=1, param_attr=None, bias_attr=None,
           use_cudnn=True, act=None, name=None, data_format="NCDHW"):
    from .. import nn

    in_c = input.shape[1 if data_format == "NCDHW" else -1]
    layer = nn.Conv3D(in_c, num_filters, filter_size, stride, padding,
                      dilation, groups, weight_attr=param_attr,
                      bias_attr=bias_attr, data_format=data_format)
    return _act(layer(input), act)


def _transpose_kernel_from_output(input, output_size, stride, padding,
                                  dilation, n, data_format):
    """Derive filter_size from the requested output size (reference
    conv2d_transpose supports either one): k = out - (in-1)·s + 2·p."""
    spatial = (input.shape[2:2 + n] if data_format.startswith("NC")
               else input.shape[1:1 + n])
    out = ([output_size] * n if isinstance(output_size, int)
           else list(output_size))
    s = [stride] * n if isinstance(stride, int) else list(stride)
    p = [padding] * n if isinstance(padding, int) else list(padding)
    d = [dilation] * n if isinstance(dilation, int) else list(dilation)
    k = []
    for i in range(n):
        eff = out[i] - (int(spatial[i]) - 1) * s[i] + 2 * p[i]
        if eff < 1 or (eff - 1) % d[i] != 0:
            raise ValueError(
                f"output_size {out[i]} unreachable from input "
                f"{spatial[i]} with stride {s[i]} padding {p[i]}")
        k.append((eff - 1) // d[i] + 1)
    return k


def conv2d_transpose(input, num_filters, output_size=None, filter_size=None,
                     padding=0, stride=1, dilation=1, groups=1,
                     param_attr=None, bias_attr=None, use_cudnn=True,
                     act=None, name=None, data_format="NCHW"):
    from .. import nn

    if filter_size is None:
        if output_size is None:
            raise ValueError("pass filter_size or output_size")
        filter_size = _transpose_kernel_from_output(
            input, output_size, stride, padding, dilation, 2, data_format)
    in_c = input.shape[1 if data_format == "NCHW" else -1]
    layer = nn.Conv2DTranspose(in_c, num_filters, filter_size, stride,
                               padding, weight_attr=param_attr,
                               bias_attr=bias_attr, dilation=dilation,
                               groups=groups, data_format=data_format)
    return _act(layer(input), act)


def conv3d_transpose(input, num_filters, output_size=None, filter_size=None,
                     padding=0, stride=1, dilation=1, groups=1,
                     param_attr=None, bias_attr=None, use_cudnn=True,
                     act=None, name=None, data_format="NCDHW"):
    from .. import nn

    if filter_size is None:
        if output_size is None:
            raise ValueError("pass filter_size or output_size")
        filter_size = _transpose_kernel_from_output(
            input, output_size, stride, padding, dilation, 3, data_format)
    in_c = input.shape[1 if data_format == "NCDHW" else -1]
    layer = nn.Conv3DTranspose(in_c, num_filters, filter_size, stride,
                               padding, weight_attr=param_attr,
                               bias_attr=bias_attr, dilation=dilation,
                               groups=groups, data_format=data_format)
    return _act(layer(input), act)


def batch_norm(input, act=None, is_test=False, momentum=0.9, epsilon=1e-5,
               param_attr=None, bias_attr=None, data_layout="NCHW",
               in_place=False, name=None, moving_mean_name=None,
               moving_variance_name=None, do_model_average_for_mean_and_var=True,
               use_global_stats=False):
    from .. import nn

    c = input.shape[1 if data_layout == "NCHW" else -1]
    layer = nn.BatchNorm(c, momentum=momentum, epsilon=epsilon,
                         param_attr=param_attr, bias_attr=bias_attr)
    if is_test or use_global_stats:
        layer.eval()
    return _act(layer(input), act)


def instance_norm(input, epsilon=1e-5, param_attr=None, bias_attr=None,
                  name=None):
    from .. import nn

    layer = nn.InstanceNorm2D(input.shape[1], epsilon=epsilon,
                              weight_attr=param_attr, bias_attr=bias_attr)
    return layer(input)


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1,
               epsilon=1e-5, param_attr=None, bias_attr=None, act=None,
               name=None):
    from .. import nn

    shape = list(input.shape[begin_norm_axis:])
    layer = nn.LayerNorm(shape, epsilon=epsilon,
                         weight_attr=param_attr if scale else False,
                         bias_attr=bias_attr if shift else False)
    return _act(layer(input), act)


def group_norm(input, groups, epsilon=1e-5, param_attr=None,
               bias_attr=None, act=None, data_layout="NCHW", name=None):
    from .. import nn

    layer = nn.GroupNorm(groups, input.shape[1], epsilon=epsilon,
                         weight_attr=param_attr, bias_attr=bias_attr)
    return _act(layer(input), act)


def data_norm(input, act=None, epsilon=1e-5, param_attr=None,
              data_layout="NCHW", in_place=False, name=None,
              moving_mean_name=None, moving_variance_name=None,
              do_model_average_for_mean_and_var=True, slot_dim=-1,
              sync_stats=False, summary_decay_rate=0.9999999,
              enable_scale_and_shift=False):
    """Batch-stat normalization without learned affine by default
    (reference static/nn/common.py data_norm — CTR models)."""
    x = ensure_tensor(input)

    def jfn(v):
        mean = v.mean(0, keepdims=True)
        var = v.var(0, keepdims=True)
        return (v - mean) / jnp.sqrt(var + epsilon)

    return _act(apply_jfn("data_norm", jfn, x), act)


def embedding(input, size, is_sparse=False, is_distributed=False,
              padding_idx=None, param_attr=None, dtype="float32"):
    from .. import nn

    layer = nn.Embedding(size[0], size[1], padding_idx=padding_idx,
                         weight_attr=param_attr)
    return layer(input)


def sparse_embedding(input, size, padding_idx=None, is_test=False,
                     entry=None, table_class="MemorySparseTable",
                     param_attr=None, dtype="float32", slot=None):
    """PS-backed sparse embedding (reference static/nn/common.py
    sparse_embedding → distributed_lookup_table). Dense fallback when no
    PS runtime is active; the PS path lives in distributed/ps.py."""
    return embedding(input, size, is_sparse=True, padding_idx=padding_idx,
                     param_attr=param_attr, dtype=dtype)


def prelu(x, mode, param_attr=None, data_format="NCHW", name=None):
    from .. import nn

    if mode == "all":
        num = 1
    elif mode == "channel":
        num = x.shape[1 if data_format == "NCHW" else -1]
    else:  # element
        num = int(np.prod(x.shape[1:]))
    layer = nn.PReLU(num_parameters=num, weight_attr=param_attr,
                     data_format=data_format)
    return layer(x)


def spectral_norm(weight, dim=0, power_iters=1, eps=1e-12, name=None):
    """Spectral normalization of a weight tensor (reference
    static/nn/common.py spectral_norm): returns weight / sigma_max,
    sigma estimated by power iteration."""
    w = ensure_tensor(weight)

    def jfn(wv):
        mat = jnp.moveaxis(wv, dim, 0).reshape(wv.shape[dim], -1)
        u = jnp.ones((mat.shape[0],), wv.dtype) / np.sqrt(mat.shape[0])
        for _ in range(max(power_iters, 1)):
            v = mat.T @ u
            v = v / jnp.maximum(jnp.linalg.norm(v), eps)
            u = mat @ v
            u = u / jnp.maximum(jnp.linalg.norm(u), eps)
        sigma = u @ mat @ v
        return wv / jnp.maximum(sigma, eps)

    return apply_jfn("spectral_norm", jfn, w)


def deform_conv2d(x, offset, mask, num_filters, filter_size, stride=1,
                  padding=0, dilation=1, groups=1, deformable_groups=1,
                  im2col_step=1, weight_attr=None, bias_attr=None,
                  name=None):
    from ..vision.ops import DeformConv2D

    layer = DeformConv2D(x.shape[1], num_filters, filter_size, stride,
                         padding, dilation, deformable_groups, groups,
                         weight_attr, bias_attr)
    return layer(x, offset, mask)


def bilinear_tensor_product(x, y, size, act=None, name=None,
                            param_attr=None, bias_attr=None):
    from .. import nn

    layer = nn.Bilinear(x.shape[-1], y.shape[-1], size,
                        weight_attr=param_attr, bias_attr=bias_attr)
    return _act(layer(x, y), act)


def nce(input, label, num_total_classes, sample_weight=None,
        param_attr=None, bias_attr=None, num_neg_samples=5, name=None,
        sampler="uniform", custom_dist=None, seed=0, is_sparse=False):
    """Noise-contrastive estimation loss (reference static/nn/common.py
    nce → nce_op): logistic discrimination of the true class against
    sampled noise classes."""
    from .. import nn
    from ..core import rng
    import jax

    input = ensure_tensor(input)
    label = ensure_tensor(label)
    d = input.shape[-1]
    helper = nn.Layer()
    weight = helper.create_parameter([num_total_classes, d], param_attr)
    bias = (None if bias_attr is False else helper.create_parameter(
        [num_total_classes], bias_attr, is_bias=True))
    key = rng.next_key()
    n = input.shape[0]
    neg = jax.random.randint(key, (n, num_neg_samples), 0,
                             num_total_classes)

    def jfn(x, lbl, w, *rest):
        b = rest[0] if rest else None
        lbl_i = lbl.reshape(-1).astype(jnp.int32)
        pos_w = w[lbl_i]
        pos_logit = jnp.sum(x * pos_w, -1)
        neg_w = w[neg]
        neg_logit = jnp.einsum("nd,nkd->nk", x, neg_w)
        if b is not None:
            pos_logit = pos_logit + b[lbl_i]
            neg_logit = neg_logit + b[neg]
        pos_loss = jax.nn.softplus(-pos_logit)
        neg_loss = jax.nn.softplus(neg_logit).sum(-1)
        return (pos_loss + neg_loss)[:, None]

    from ..autograd import engine

    args = (input, label, weight) + ((bias,) if bias is not None else ())
    return engine.apply("nce", jfn, args)


def row_conv(input, future_context_size, param_attr=None, act=None):
    """Lookahead row convolution (reference static/nn/common.py row_conv
    → row_conv_op, DeepSpeech2): out[t] = sum_k w[k] * x[t+k]."""
    from .. import nn

    x = ensure_tensor(input)
    d = x.shape[-1]
    k = future_context_size + 1
    helper = nn.Layer()
    weight = helper.create_parameter([k, d], param_attr)

    def jfn(v, w):
        # v: [batch, time, d] (or LoD flat [T, d] treated as one batch)
        squeeze = v.ndim == 2
        if squeeze:
            v = v[None]
        t = v.shape[1]
        out = jnp.zeros_like(v)
        for i in range(k):
            shifted = jnp.pad(v[:, i:], ((0, 0), (0, i), (0, 0)))
            out = out + shifted * w[i]
        return out[0] if squeeze else out

    from ..autograd import engine

    out = engine.apply("row_conv", jfn, (x, weight))
    return _act(out, act)


def crf_decoding(input, param_attr=None, label=None, length=None,
                 transition=None):
    """Viterbi decode with CRF transitions (reference static/nn/common.py
    crf_decoding → crf_decoding_op); delegates to text.viterbi_decode."""
    from ..text.viterbi_decode import viterbi_decode

    x = ensure_tensor(input)
    if transition is None:
        raise ValueError(
            "pass transition= (the linear_chain_crf parameter); the "
            "static-graph param_attr lookup has no scope here")
    if x.ndim == 2:
        x = Tensor(x._value[None], stop_gradient=x.stop_gradient)
    if length is None:
        length = Tensor(jnp.asarray([x.shape[1]] * x.shape[0]),
                        stop_gradient=True)
    scores, path = viterbi_decode(x, transition, length)
    return path


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    """Host-python op (reference static/nn/common.py py_func → py_func_op).
    Runs eagerly on host values; gradient support requires backward_func
    (wrapped as a custom VJP through pure_callback in utils.cpp_extension
    style); forward-only here matches the common usage."""
    xs = x if isinstance(x, (list, tuple)) else [x]
    vals = [np.asarray(value_of(ensure_tensor(t))) for t in xs]
    res = func(*vals)
    if isinstance(res, (list, tuple)):
        return [Tensor(jnp.asarray(np.asarray(r)), stop_gradient=True)
                for r in res]
    return Tensor(jnp.asarray(np.asarray(res)), stop_gradient=True)


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    from ..ops.api_misc import create_parameter as _cp

    return _cp(shape, dtype, name=name, attr=attr, is_bias=is_bias,
               default_initializer=default_initializer)


def multi_box_head(inputs, image, base_size, num_classes, aspect_ratios,
                   min_ratio=None, max_ratio=None, min_sizes=None,
                   max_sizes=None, steps=None, step_w=None, step_h=None,
                   offset=0.5, variance=(0.1, 0.1, 0.2, 0.2), flip=True,
                   clip=False, kernel_size=1, pad=0, stride=1, name=None,
                   min_max_aspect_ratios_order=False):
    """SSD detection head (reference static/nn/common.py multi_box_head):
    per-feature-map box/score convs + prior boxes."""
    from .. import nn
    from ..ops.manipulation import concat, reshape, transpose

    n_inputs = len(inputs)
    if min_sizes is None:
        min_ratio, max_ratio = int(min_ratio), int(max_ratio)
        step = int((max_ratio - min_ratio) / max(n_inputs - 2, 1))
        min_sizes, max_sizes = [], []
        for ratio in range(min_ratio, max_ratio + 1, max(step, 1)):
            min_sizes.append(base_size * ratio / 100.0)
            max_sizes.append(base_size * (ratio + step) / 100.0)
        min_sizes = [base_size * 0.10] + min_sizes[:n_inputs - 1]
        max_sizes = [base_size * 0.20] + max_sizes[:n_inputs - 1]
    locs, confs, priors, pvars = [], [], [], []
    im_h, im_w = int(image.shape[2]), int(image.shape[3])
    for i, feat in enumerate(inputs):
        ar = aspect_ratios[i]
        n_prior = (len(ar) * (2 if flip else 1) + 1 +
                   (1 if max_sizes else 0))
        c = feat.shape[1]
        loc_conv = nn.Conv2D(c, n_prior * 4, kernel_size, stride, pad)
        conf_conv = nn.Conv2D(c, n_prior * num_classes, kernel_size,
                              stride, pad)
        loc = transpose(loc_conv(feat), [0, 2, 3, 1])
        conf = transpose(conf_conv(feat), [0, 2, 3, 1])
        locs.append(reshape(loc, [feat.shape[0], -1, 4]))
        confs.append(reshape(conf, [feat.shape[0], -1, num_classes]))
        # prior boxes for this map
        fh, fw = int(feat.shape[2]), int(feat.shape[3])
        sw = steps[i] if steps else im_w / fw
        sh = steps[i] if steps else im_h / fh
        boxes = []
        for y in range(fh):
            for x_ in range(fw):
                cx = (x_ + offset) * sw
                cy = (y + offset) * sh
                sizes = [(min_sizes[i], min_sizes[i])]
                if max_sizes:
                    s = float(np.sqrt(min_sizes[i] * max_sizes[i]))
                    sizes.append((s, s))
                for a in ar:
                    if abs(a - 1.0) < 1e-6:
                        continue
                    w_a = min_sizes[i] * float(np.sqrt(a))
                    h_a = min_sizes[i] / float(np.sqrt(a))
                    sizes.append((w_a, h_a))
                    if flip:
                        sizes.append((h_a, w_a))
                for (bw, bh) in sizes:
                    box = [(cx - bw / 2) / im_w, (cy - bh / 2) / im_h,
                           (cx + bw / 2) / im_w, (cy + bh / 2) / im_h]
                    if clip:
                        box = [min(max(v, 0.0), 1.0) for v in box]
                    boxes.append(box)
        pb = np.asarray(boxes, np.float32)
        priors.append(Tensor(jnp.asarray(pb), stop_gradient=True))
        pvars.append(Tensor(jnp.asarray(
            np.tile(np.asarray(variance, np.float32), (len(pb), 1))),
            stop_gradient=True))
    mbox_locs = concat(locs, axis=1)
    mbox_confs = concat(confs, axis=1)
    box = concat(priors, axis=0)
    var = concat(pvars, axis=0)
    return mbox_locs, mbox_confs, box, var


def continuous_value_model(input, cvm, use_cvm=True):
    """CTR show/click feature handling (reference static/nn/common.py
    continuous_value_model → cvm_op): keep or strip the leading
    show/click pair of each embedding."""
    x = ensure_tensor(input)

    def jfn(v, c):
        if use_cvm:
            return jnp.concatenate([c.astype(v.dtype), v[:, 2:]], -1)
        return v[:, 2:]

    from ..autograd import engine

    return engine.apply("cvm", jfn, (x, ensure_tensor(cvm)))


class StaticRNN:
    """Static-unroll RNN builder (reference: static/nn/control_flow.py
    StaticRNN — step block recorded once, executed per time step).

    Trace-capture design: the `with rnn.step():` body runs ONCE eagerly
    on the t=0 slice; the ops it performs are recorded on the autograd
    tape (step inputs/memories are marked grad-tracked to force node
    recording). `rnn()` then REPLAYS the recorded op graph T times with
    each step's slice and the carried memories substituted — the tape is
    the sub-block program, no AST or protobuf rewriting."""

    def __init__(self, name=None):
        self._seq = []        # (full_sequence_tensor, t0_slice_tensor)
        self._memories = []   # {"pre": Tensor, "next": Tensor}
        self._outputs = []

    def step(self):
        import contextlib

        @contextlib.contextmanager
        def ctx():
            yield self

        return ctx()

    def step_input(self, x):
        x = ensure_tensor(x)
        sl = Tensor(x._value[0], stop_gradient=False)
        self._seq.append((x, sl))
        return sl

    def memory(self, init=None, shape=None, batch_ref=None,
               init_value=0.0, init_batch_dim_idx=0, ref_batch_dim_idx=1):
        if init is None:
            batch = batch_ref.shape[ref_batch_dim_idx]
            init = Tensor(jnp.full((int(batch),) + tuple(shape),
                                   np.float32(init_value)))
        init = ensure_tensor(init)
        pre = Tensor(init._value, stop_gradient=False)
        self._memories.append({"init": init, "pre": pre, "next": None})
        return pre

    def update_memory(self, mem_var, new_var):
        for mem in self._memories:
            if mem["pre"] is mem_var:
                mem["next"] = ensure_tensor(new_var)
                return
        raise ValueError("update_memory: unknown memory var")

    def step_output(self, o):
        self._outputs.append(ensure_tensor(o))

    def output(self, *outputs):
        for o in outputs:
            self.step_output(o)

    @staticmethod
    def _replay(targets, subs):
        memo = dict(subs)

        def ev(t):
            if id(t) in memo:
                return memo[id(t)]
            node = t._grad_node
            if node is None or node.jfn is None:
                return t._value
            out = node.jfn(*[ev(i) for i in node.inputs])
            res = out[t._out_index] if isinstance(out, (tuple, list)) \
                else out
            memo[id(t)] = res
            return res

        return [ev(t) for t in targets]

    def __call__(self):
        from ..autograd import engine

        if not self._seq:
            raise ValueError("StaticRNN has no step_input")
        T = int(self._seq[0][0].shape[0])
        targets = list(self._outputs) + [
            m["next"] for m in self._memories if m["next"] is not None]
        # leaves of the recorded step graph: the placeholders we substitute
        # per step, plus every OTHER tensor (parameters, constants). The
        # unroll runs as ONE tape op over those leaves, so gradients flow
        # into the step body's parameters (reference StaticRNN backward).
        placeholder_ids = ({id(sl) for _, sl in self._seq}
                           | {id(m["pre"]) for m in self._memories})
        leaves, seen = [], set()

        def collect(t):
            if id(t) in seen:
                return
            seen.add(id(t))
            node = t._grad_node
            if node is None or node.jfn is None or id(t) in placeholder_ids:
                if id(t) not in placeholder_ids:
                    leaves.append(t)
                return
            for i in node.inputs:
                collect(i)

        for t in targets:
            collect(t)
        n_seq, n_mem, n_out = (len(self._seq), len(self._memories),
                               len(self._outputs))
        seq_tensors = [full for full, _ in self._seq]
        mem_tensors = [m["init"] for m in self._memories]

        def unroll_jfn(*vals):
            seqs = vals[:n_seq]
            mems = list(vals[n_seq:n_seq + n_mem])
            base = {id(t): v for t, v in
                    zip(leaves, vals[n_seq + n_mem:])}
            acc = [[] for _ in range(n_out)]
            for step_i in range(T):
                subs = dict(base)
                for (full, sl), sv in zip(self._seq, seqs):
                    subs[id(sl)] = sv[step_i]
                for m, mv in zip(self._memories, mems):
                    subs[id(m["pre"])] = mv
                vals_t = self._replay(targets, subs)
                for i in range(n_out):
                    acc[i].append(vals_t[i])
                mems = vals_t[n_out:]
            stacked = tuple(jnp.stack(a) for a in acc)
            return stacked if n_out > 1 else stacked[0]

        out = engine.apply(
            "static_rnn", unroll_jfn,
            tuple(seq_tensors) + tuple(mem_tensors) + tuple(leaves))
        return out
