"""paddle.reader — legacy reader decorators.

Reference: python/paddle/reader/decorator.py (cache:52, map_readers:92,
shuffle:134, chain:183, compose:248, buffered:308, firstn:367,
xmap_readers:412, multiprocess_reader:505). A "reader" is a zero-arg
callable returning an iterator of samples; decorators compose them.
Kept API-faithful: these predate `paddle.io.DataLoader` but CTR/legacy
pipelines still build on them (DataLoader remains the recommended path).
"""
import itertools
import queue as queue_mod
import random
import threading
import traceback

__all__ = ["cache", "map_readers", "shuffle", "chain", "compose",
           "buffered", "firstn", "xmap_readers", "multiprocess_reader",
           "ComposeNotAligned"]


class ComposeNotAligned(ValueError):
    pass


def cache(reader):
    """Materialize once, replay from memory on every call (reference
    decorator.py:52)."""
    all_data = tuple(reader())

    def __impl__():
        return iter(all_data)

    return __impl__


def map_readers(func, *readers):
    """Yield func applied across the zip of the readers' outputs
    (reference decorator.py:92)."""

    def reader():
        rs = [r() for r in readers]
        for vals in zip(*rs):
            yield func(*vals)

    return reader


def shuffle(reader, buf_size):
    """Shuffle within a sliding buffer of `buf_size` samples (reference
    decorator.py:134)."""

    def data_reader():
        buf = []
        for e in reader():
            buf.append(e)
            if len(buf) >= buf_size:
                random.shuffle(buf)
                yield from buf
                buf = []
        if buf:
            random.shuffle(buf)
            yield from buf

    return data_reader


def chain(*readers):
    """Concatenate readers back to back (reference decorator.py:183)."""

    def reader():
        return itertools.chain(*[r() for r in readers])

    return reader


def compose(*readers, **kwargs):
    """Zip readers into flattened tuples; samples must align unless
    check_alignment=False (reference decorator.py:248)."""
    check_alignment = kwargs.pop("check_alignment", True)

    def make_tuple(x):
        return x if isinstance(x, tuple) else (x,)

    def reader():
        rs = [r() for r in readers]
        if not check_alignment:
            for outputs in zip(*rs):
                yield sum((make_tuple(o) for o in outputs), ())
        else:
            for outputs in itertools.zip_longest(*rs):
                if any(o is None for o in outputs):
                    raise ComposeNotAligned(
                        "outputs of readers are not aligned")
                yield sum((make_tuple(o) for o in outputs), ())

    return reader


def buffered(reader, size):
    """Producer thread + bounded queue: pre-reads up to `size` samples
    ahead of the consumer (reference decorator.py:308)."""

    class _End:
        def __init__(self, exc=None):
            self.exc = exc

    def data_reader():
        r = reader()
        q = queue_mod.Queue(maxsize=size)

        def read_worker():
            # the sentinel must reach the queue even on error, or the
            # consumer blocks in q.get() forever
            try:
                for d in r:
                    q.put(d)
            except BaseException as e:  # noqa: BLE001 — re-raised below
                q.put(_End(e))
            else:
                q.put(_End())

        t = threading.Thread(target=read_worker, daemon=True)
        t.start()
        e = q.get()
        while not isinstance(e, _End):
            yield e
            e = q.get()
        if e.exc is not None:
            raise e.exc

    return data_reader


def firstn(reader, n):
    """Only the first n samples (reference decorator.py:367)."""

    def firstn_reader():
        return itertools.islice(reader(), n)

    return firstn_reader


_XMAP_END = object()


def xmap_readers(mapper, reader, process_num, buffer_size, order=False):
    """Parallel `mapper` over samples with `process_num` worker THREADS
    (the reference uses threads too, decorator.py:412 — mappers are
    typically numpy/PIL which release the GIL; for pure-python mappers
    use `paddle.io.DataLoader(num_workers=...)`, real processes)."""

    class _Err:
        def __init__(self, exc):
            self.exc = exc

    def xreader():
        in_q = queue_mod.Queue(buffer_size)
        out_q = queue_mod.Queue(buffer_size)

        def feed():
            # errors surface on out_q; every worker still gets its end
            # marker so the consumer's sentinel count converges
            try:
                for i, sample in enumerate(reader()):
                    in_q.put((i, sample))
            except BaseException as e:  # noqa: BLE001
                out_q.put(_Err(e))
            finally:
                for _ in range(process_num):
                    in_q.put(_XMAP_END)

        def work():
            try:
                while True:
                    item = in_q.get()
                    if item is _XMAP_END:
                        return
                    i, sample = item
                    out_q.put((i, mapper(sample)))
            except BaseException as e:  # noqa: BLE001
                out_q.put(_Err(e))
            finally:
                out_q.put(_XMAP_END)

        threading.Thread(target=feed, daemon=True).start()
        for _ in range(process_num):
            threading.Thread(target=work, daemon=True).start()

        def results():
            finished = 0
            while finished < process_num:
                item = out_q.get()
                if isinstance(item, _Err):
                    raise item.exc
                if item is _XMAP_END:
                    finished += 1
                else:
                    yield item

        if not order:
            for _, mapped in results():
                yield mapped
        else:
            pending, next_i = {}, 0
            for i, mapped in results():
                pending[i] = mapped
                while next_i in pending:
                    yield pending.pop(next_i)
                    next_i += 1
            while next_i in pending:  # drain (a worker died mid-gap is
                yield pending.pop(next_i)  # surfaced by _Err above)
                next_i += 1

    return xreader


def multiprocess_reader(readers, use_pipe=True, queue_size=1000):
    """Run each reader in its own PROCESS, merging samples as they
    arrive (reference decorator.py:505). Uses the fork context (readers
    are usually closures over open files — unpicklable); samples cross
    via an mp.Queue either way (`use_pipe` kept for API compat)."""
    import multiprocessing as mp

    assert len(readers) > 0, "readers must not be empty"

    _END, _FAIL = "__mp_reader_end__", "__mp_reader_fail__"

    def queue_reader():
        ctx = mp.get_context("fork")
        q = ctx.Queue(queue_size)

        def _read(r):
            # tagged sentinels: a None SAMPLE must not end the stream,
            # and a child exception must fail the parent, not truncate
            try:
                for s in r():
                    q.put(("s", s))
            except BaseException:  # noqa: BLE001 — marshalled to parent
                q.put((_FAIL, traceback.format_exc()))
            else:
                q.put((_END, None))

        procs = [ctx.Process(target=_read, args=(r,), daemon=True)
                 for r in readers]
        for p in procs:
            p.start()
        finished = 0
        while finished < len(readers):
            tag, payload = q.get()
            if tag == _END:
                finished += 1
            elif tag == _FAIL:
                for p in procs:
                    p.terminate()
                raise RuntimeError(
                    f"multiprocess_reader child failed:\n{payload}")
            else:
                yield payload
        for p in procs:
            p.join()

    return queue_reader
