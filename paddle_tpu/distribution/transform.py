"""paddle.distribution.transform namespace (reference:
python/paddle/distribution/transform.py). The Transform classes live in
the package __init__; this module pins the reference import path."""
from . import (  # noqa: F401
    AffineTransform,
    ExpTransform,
    SigmoidTransform,
    Transform,
)

__all__ = ["Transform", "AffineTransform", "ExpTransform",
           "SigmoidTransform"]
