"""paddle_tpu.distribution — probability distributions.

TPU-native re-design of the reference distribution package
(reference: python/paddle/distribution/ — distribution.py Distribution
base, normal.py, uniform.py, categorical.py, beta.py, dirichlet.py,
multinomial.py, independent.py, transformed_distribution.py, kl.py
kl_divergence + register_kl).

Sampling draws PRNG keys from the framework RNG (core.rng), so samples
are reproducible under paddle.seed and per-step keys thread correctly
inside compiled train steps. Densities are pure jnp — differentiable
and jit-safe; `rsample` is reparameterized where the reference's is.
"""
import math

import numpy as np

import jax
import jax.numpy as jnp

from ..core import rng
from ..ops._helpers import ensure_tensor, value_of
from ..tensor_core import Tensor

__all__ = [
    "Distribution", "Normal", "Uniform", "Categorical", "Beta",
    "Dirichlet", "Multinomial", "Independent", "TransformedDistribution",
    "Transform", "AffineTransform", "ExpTransform", "SigmoidTransform",
    "kl_divergence", "register_kl",
]


def _val(x):
    return value_of(ensure_tensor(x)) if not isinstance(x, (int, float)) \
        else jnp.asarray(x, jnp.float32)


def _t(v):
    return Tensor(v, stop_gradient=True)


class Distribution:
    """Base (reference distribution.py:40)."""

    def __init__(self, batch_shape=(), event_shape=()):
        self._batch_shape = tuple(batch_shape)
        self._event_shape = tuple(event_shape)

    @property
    def batch_shape(self):
        return list(self._batch_shape)

    @property
    def event_shape(self):
        return list(self._event_shape)

    def sample(self, shape=()):
        raise NotImplementedError

    def rsample(self, shape=()):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def prob(self, value):
        from ..ops._helpers import apply_jfn

        return apply_jfn("dist_prob", jnp.exp, self.log_prob(value))

    def entropy(self):
        raise NotImplementedError

    def kl_divergence(self, other):
        return kl_divergence(self, other)

    def _ext(self, shape):
        return tuple(int(s) for s in shape)


class Normal(Distribution):
    """reference normal.py:35."""

    def __init__(self, loc, scale, name=None):
        self.loc = _val(loc)
        self.scale = _val(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape,
                                              self.scale.shape))

    @property
    def mean(self):
        return _t(jnp.broadcast_to(self.loc, self._batch_shape))

    @property
    def variance(self):
        return _t(jnp.broadcast_to(self.scale ** 2, self._batch_shape))

    def sample(self, shape=()):
        return self.rsample(shape)

    def rsample(self, shape=()):
        shp = self._ext(shape) + self._batch_shape
        eps = jax.random.normal(rng.next_key(), shp)
        return _t(self.loc + self.scale * eps)

    def log_prob(self, value):
        v = _val(value)
        var = self.scale ** 2
        return _t(-((v - self.loc) ** 2) / (2 * var)
                  - jnp.log(self.scale) - 0.5 * math.log(2 * math.pi))

    def entropy(self):
        return _t(jnp.broadcast_to(
            0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(self.scale),
            self._batch_shape))


class Uniform(Distribution):
    """reference uniform.py:34."""

    def __init__(self, low, high, name=None):
        self.low = _val(low)
        self.high = _val(high)
        super().__init__(jnp.broadcast_shapes(self.low.shape,
                                              self.high.shape))

    def sample(self, shape=()):
        return self.rsample(shape)

    def rsample(self, shape=()):
        shp = self._ext(shape) + self._batch_shape
        u = jax.random.uniform(rng.next_key(), shp)
        return _t(self.low + (self.high - self.low) * u)

    def log_prob(self, value):
        v = _val(value)
        inside = (v >= self.low) & (v < self.high)
        lp = -jnp.log(self.high - self.low)
        return _t(jnp.where(inside, lp, -jnp.inf))

    def entropy(self):
        return _t(jnp.broadcast_to(jnp.log(self.high - self.low),
                                   self._batch_shape))


class Categorical(Distribution):
    """reference categorical.py:34 (constructed from logits)."""

    def __init__(self, logits=None, probs=None, name=None):
        if logits is None:
            p = _val(probs)
            logits = jnp.log(p / p.sum(-1, keepdims=True))
        self.logits = jax.nn.log_softmax(_val(logits), axis=-1)
        super().__init__(self.logits.shape[:-1])

    @property
    def probs(self):
        return _t(jnp.exp(self.logits))

    def sample(self, shape=()):
        shp = self._ext(shape)
        draw = jax.random.categorical(
            rng.next_key(), self.logits,
            shape=shp + self.logits.shape[:-1])
        return _t(draw)

    def log_prob(self, value):
        idx = _val(value).astype(jnp.int32)
        return _t(jnp.take_along_axis(
            self.logits, idx[..., None], axis=-1)[..., 0])

    def entropy(self):
        p = jnp.exp(self.logits)
        return _t(-(p * self.logits).sum(-1))


class Beta(Distribution):
    """reference beta.py:20."""

    def __init__(self, alpha, beta, name=None):
        self.alpha = _val(alpha)
        self.beta = _val(beta)
        super().__init__(jnp.broadcast_shapes(self.alpha.shape,
                                              self.beta.shape))

    @property
    def mean(self):
        return _t(self.alpha / (self.alpha + self.beta))

    def sample(self, shape=()):
        shp = self._ext(shape) + self._batch_shape
        return _t(jax.random.beta(rng.next_key(), self.alpha, self.beta,
                                  shape=shp))

    def log_prob(self, value):
        from jax.scipy.special import betaln

        v = _val(value)
        return _t((self.alpha - 1) * jnp.log(v)
                  + (self.beta - 1) * jnp.log1p(-v)
                  - betaln(self.alpha, self.beta))

    def entropy(self):
        from jax.scipy.special import betaln, digamma

        a, b = self.alpha, self.beta
        return _t(betaln(a, b) - (a - 1) * digamma(a)
                  - (b - 1) * digamma(b)
                  + (a + b - 2) * digamma(a + b))


class Dirichlet(Distribution):
    """reference dirichlet.py:20."""

    def __init__(self, concentration, name=None):
        self.concentration = _val(concentration)
        super().__init__(self.concentration.shape[:-1],
                         self.concentration.shape[-1:])

    @property
    def mean(self):
        c = self.concentration
        return _t(c / c.sum(-1, keepdims=True))

    def sample(self, shape=()):
        shp = self._ext(shape) + self._batch_shape
        return _t(jax.random.dirichlet(rng.next_key(), self.concentration,
                                       shape=shp))

    def log_prob(self, value):
        from jax.scipy.special import gammaln

        c = self.concentration
        v = _val(value)
        norm = gammaln(c.sum(-1)) - gammaln(c).sum(-1)
        return _t(((c - 1) * jnp.log(v)).sum(-1) + norm)

    def entropy(self):
        from jax.scipy.special import digamma, gammaln

        c = self.concentration
        c0 = c.sum(-1)
        k = c.shape[-1]
        lnB = gammaln(c).sum(-1) - gammaln(c0)
        return _t(lnB + (c0 - k) * digamma(c0)
                  - ((c - 1) * digamma(c)).sum(-1))


class Multinomial(Distribution):
    """reference multinomial.py:20."""

    def __init__(self, total_count, probs, name=None):
        self.total_count = int(total_count)
        p = _val(probs)
        self.probs_ = p / p.sum(-1, keepdims=True)
        super().__init__(p.shape[:-1], p.shape[-1:])

    def sample(self, shape=()):
        shp = self._ext(shape)
        logits = jnp.log(self.probs_)
        draws = jax.random.categorical(
            rng.next_key(), logits,
            shape=(self.total_count,) + shp + logits.shape[:-1])
        k = self.probs_.shape[-1]
        counts = jax.nn.one_hot(draws, k).sum(axis=0)
        return _t(counts)

    def log_prob(self, value):
        from jax.scipy.special import gammaln

        v = _val(value)
        coef = gammaln(jnp.asarray(self.total_count + 1.0)) \
            - gammaln(v + 1.0).sum(-1)
        return _t(coef + (v * jnp.log(self.probs_)).sum(-1))


class Independent(Distribution):
    """Reinterpret batch dims as event dims (reference independent.py)."""

    def __init__(self, base, reinterpreted_batch_rank):
        self.base = base
        self.rank = int(reinterpreted_batch_rank)
        bs = base._batch_shape
        super().__init__(bs[: len(bs) - self.rank],
                         bs[len(bs) - self.rank:] + base._event_shape)

    def sample(self, shape=()):
        return self.base.sample(shape)

    def rsample(self, shape=()):
        return self.base.rsample(shape)

    def log_prob(self, value):
        lp = value_of(self.base.log_prob(value))
        return _t(lp.sum(axis=tuple(range(lp.ndim - self.rank, lp.ndim))))

    def entropy(self):
        e = value_of(self.base.entropy())
        return _t(e.sum(axis=tuple(range(e.ndim - self.rank, e.ndim))))


# ------------------------------------------------------------ transforms

class Transform:
    """reference transform.py:60."""

    def forward(self, x):
        raise NotImplementedError

    def inverse(self, y):
        raise NotImplementedError

    def forward_log_det_jacobian(self, x):
        raise NotImplementedError


class AffineTransform(Transform):
    def __init__(self, loc, scale):
        self.loc = _val(loc)
        self.scale = _val(scale)

    def forward(self, x):
        return self.loc + self.scale * x

    def inverse(self, y):
        return (y - self.loc) / self.scale

    def forward_log_det_jacobian(self, x):
        return jnp.broadcast_to(jnp.log(jnp.abs(self.scale)), x.shape)


class ExpTransform(Transform):
    def forward(self, x):
        return jnp.exp(x)

    def inverse(self, y):
        return jnp.log(y)

    def forward_log_det_jacobian(self, x):
        return x


class SigmoidTransform(Transform):
    def forward(self, x):
        return jax.nn.sigmoid(x)

    def inverse(self, y):
        return jnp.log(y) - jnp.log1p(-y)

    def forward_log_det_jacobian(self, x):
        return -jax.nn.softplus(-x) - jax.nn.softplus(x)


class TransformedDistribution(Distribution):
    """reference transformed_distribution.py:20."""

    def __init__(self, base, transforms):
        self.base = base
        self.transforms = list(transforms)
        super().__init__(base._batch_shape, base._event_shape)

    def sample(self, shape=()):
        x = value_of(self.base.sample(shape))
        for t in self.transforms:
            x = t.forward(x)
        return _t(x)

    def log_prob(self, value):
        y = _val(value)
        lp = jnp.zeros(())
        for t in reversed(self.transforms):
            x = t.inverse(y)
            lp = lp - t.forward_log_det_jacobian(x)
            y = x
        return _t(lp + value_of(self.base.log_prob(_t(y))))


# -------------------------------------------------------------------- kl

_KL_REGISTRY = {}


def register_kl(type_p, type_q):
    """reference kl.py register_kl decorator."""

    def deco(fn):
        _KL_REGISTRY[(type_p, type_q)] = fn
        return fn

    return deco


def kl_divergence(p, q):
    for (tp, tq), fn in _KL_REGISTRY.items():
        if isinstance(p, tp) and isinstance(q, tq):
            return fn(p, q)
    raise NotImplementedError(
        f"no KL registered for ({type(p).__name__}, {type(q).__name__})")


@register_kl(Normal, Normal)
def _kl_normal(p, q):
    vr = (p.scale / q.scale) ** 2
    t1 = ((p.loc - q.loc) / q.scale) ** 2
    return _t(0.5 * (vr + t1 - 1 - jnp.log(vr)))


@register_kl(Categorical, Categorical)
def _kl_cat(p, q):
    pp = jnp.exp(p.logits)
    return _t((pp * (p.logits - q.logits)).sum(-1))


@register_kl(Uniform, Uniform)
def _kl_uniform(p, q):
    res = jnp.log((q.high - q.low) / (p.high - p.low))
    oob = (p.low < q.low) | (p.high > q.high)
    return _t(jnp.where(oob, jnp.inf, res))


@register_kl(Beta, Beta)
def _kl_beta(p, q):
    from jax.scipy.special import betaln, digamma

    a1, b1, a2, b2 = p.alpha, p.beta, q.alpha, q.beta
    t = betaln(a2, b2) - betaln(a1, b1)
    t += (a1 - a2) * digamma(a1) + (b1 - b2) * digamma(b1)
    t += (a2 - a1 + b2 - b1) * digamma(a1 + b1)
    return _t(t)


@register_kl(Dirichlet, Dirichlet)
def _kl_dirichlet(p, q):
    from jax.scipy.special import digamma, gammaln

    c1, c2 = p.concentration, q.concentration
    s1 = c1.sum(-1)
    t = gammaln(s1) - gammaln(c2.sum(-1))
    t += (gammaln(c2) - gammaln(c1)).sum(-1)
    t += ((c1 - c2) * (digamma(c1) - digamma(s1)[..., None])).sum(-1)
    return _t(t)


class ExponentialFamily(Distribution):
    """Base for exponential-family distributions (reference:
    python/paddle/distribution/exponential_family.py): entropy via the
    Bregman identity over natural parameters when not overridden."""

    @property
    def _natural_parameters(self):
        raise NotImplementedError

    def _log_normalizer(self, *natural_params):
        raise NotImplementedError

    @property
    def _mean_carrier_measure(self):
        raise NotImplementedError

    def entropy(self):
        import jax

        nat = [jnp.asarray(_val(p)) for p in self._natural_parameters]
        lg_normal, grads = jax.value_and_grad(
            lambda ps: jnp.sum(self._log_normalizer(*ps)), argnums=0)(
                tuple(nat))
        ent = -self._mean_carrier_measure + lg_normal
        for p, g in zip(nat, grads):
            ent = ent - p * g
        return _t(ent)


from . import transform  # noqa: E402,F401
