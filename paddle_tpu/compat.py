"""paddle.compat (reference: python/paddle/compat.py) — py2/py3 text
helpers still imported by legacy user code."""
import math

__all__ = ["to_text", "to_bytes", "round", "floor_division",
           "get_exception_message"]


def _map_structure(obj, leaf, inplace):
    if isinstance(obj, list):
        if inplace:
            obj[:] = [_map_structure(o, leaf, inplace) for o in obj]
            return obj
        return [_map_structure(o, leaf, inplace) for o in obj]
    if isinstance(obj, tuple):  # immutable: never in place
        return tuple(_map_structure(o, leaf, False) for o in obj)
    if isinstance(obj, set):
        vals = {_map_structure(o, leaf, False) for o in obj}
        if inplace:
            obj.clear()
            obj.update(vals)
            return obj
        return vals
    if isinstance(obj, dict):
        items = {_map_structure(k, leaf, False): _map_structure(
            v, leaf, False) for k, v in obj.items()}
        if inplace:
            obj.clear()
            obj.update(items)
            return obj
        return items
    return leaf(obj)


def to_text(obj, encoding="utf-8", inplace=False):
    """bytes → str recursively through lists/sets/dicts (reference
    compat.py:25)."""
    if obj is None:
        return obj
    return _map_structure(
        obj, lambda o: o.decode(encoding) if isinstance(o, bytes) else o,
        inplace)


def to_bytes(obj, encoding="utf-8", inplace=False):
    """str → bytes recursively (reference compat.py:121)."""
    if obj is None:
        return obj
    return _map_structure(
        obj, lambda o: o.encode(encoding) if isinstance(o, str) else o,
        inplace)


def round(x, d=0):
    """Python-2-style half-away-from-zero rounding (reference
    compat.py:206 — py3 builtin round is banker's)."""
    if x == 0.0 or math.isinf(x) or math.isnan(x):
        return x
    p = 10 ** d
    shifted = (x * p) + math.copysign(0.5, x)
    # floor toward -inf only works for positives; negatives need ceil or
    # every non-half value rounds an extra step away from zero
    toward_zero = math.floor(shifted) if x > 0 else math.ceil(shifted)
    return float(toward_zero) / p


def floor_division(x, y):
    return x // y


def get_exception_message(exc):
    return str(exc)
