"""paddle.sysconfig (reference: python/paddle/sysconfig.py:20,37) —
include/lib dirs for building extensions against the framework. Here the
native pieces are the ctypes-built C++ cores in `paddle_tpu/native/`."""
import os

__all__ = ["get_include", "get_lib"]

_ROOT = os.path.dirname(os.path.abspath(__file__))


def get_include():
    """Directory of C headers/sources for custom native extensions."""
    return os.path.join(_ROOT, "native")


def get_lib():
    """Directory containing the compiled native shared library."""
    return os.path.join(_ROOT, "native")
