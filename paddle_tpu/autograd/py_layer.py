"""Custom autograd op (paddle.autograd.PyLayer).

reference: python/paddle/autograd/py_layer.py + paddle/fluid/eager/pylayer/.
Implemented directly on the tape: forward runs under no_grad, a GradNode is
created whose backward calls the user's static backward().
"""
import jax.numpy as jnp

from . import engine


class PyLayerContext:
    def __init__(self):
        self.saved_tensor_list = []
        self._materialize_grads = True
        self.not_inplace_tensors = ()

    def save_for_backward(self, *tensors):
        self.saved_tensor_list = list(tensors)

    def saved_tensor(self):
        return self.saved_tensor_list

    def mark_not_inplace(self, *tensors):
        self.not_inplace_tensors = tensors

    def set_materialize_grads(self, value):
        self._materialize_grads = bool(value)


class _PyLayerNode(engine.GradNode):
    __slots__ = ("ctx", "backward_fn")

    def __init__(self, ctx, backward_fn, inputs, out_meta):
        super().__init__("PyLayer", None, None, inputs, out_meta)
        self.ctx = ctx
        self.backward_fn = backward_fn


class PyLayerMeta(type):
    pass


class PyLayer(metaclass=PyLayerMeta):
    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *grads):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        from ..tensor_core import Tensor

        ctx = PyLayerContext()
        tensor_inputs = [a for a in args if isinstance(a, Tensor)]
        need = engine.is_grad_enabled() and any(
            not t.stop_gradient for t in tensor_inputs
        )
        with engine.no_grad_guard():
            outs = cls.forward(ctx, *args, **kwargs)
        multi = isinstance(outs, (tuple, list))
        outs_t = tuple(outs) if multi else (outs,)
        if not need:
            return outs
        out_meta = [(tuple(o.shape), o.dtype) for o in outs_t]
        node = _PyLayerNode(ctx, cls.backward, tuple(tensor_inputs), out_meta)
        result = []
        for i, o in enumerate(outs_t):
            t = Tensor(o._value, stop_gradient=False)
            t._grad_node = node
            t._out_index = i
            result.append(t)
        # Custom execution in engine: monkey-free — engine calls vjp_fn; we
        # instead give the node a vjp_fn shim that calls user backward.
        def _vjp(cts):
            from ..tensor_core import Tensor as T

            if node.n_outputs == 1:
                cts = (cts,)
            ct_tensors = [T(c, True) for c in cts]
            with engine.no_grad_guard():
                gin = cls.backward(ctx, *ct_tensors)
            if not isinstance(gin, (tuple, list)):
                gin = (gin,)
            vals = []
            for g in gin:
                if g is None:
                    vals.append(None)
                else:
                    vals.append(g._value if isinstance(g, T) else jnp.asarray(g))
            return tuple(vals)

        node.vjp_fn = _vjp
        return tuple(result) if multi else result[0]
