from .engine import grad, is_grad_enabled  # noqa: F401
from .engine import no_grad_guard as _no_grad_guard
from .engine import enable_grad_guard as _enable_grad_guard


class no_grad:
    """Context manager + decorator (paddle.no_grad,
    reference: python/paddle/fluid/dygraph/base.py no_grad_)."""

    def __enter__(self):
        self._ctx = _no_grad_guard()
        return self._ctx.__enter__()

    def __exit__(self, *exc):
        return self._ctx.__exit__(*exc)

    def __call__(self, fn):
        import functools

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with no_grad():
                return fn(*args, **kwargs)

        return wrapper


class enable_grad:
    def __enter__(self):
        self._ctx = _enable_grad_guard()
        return self._ctx.__enter__()

    def __exit__(self, *exc):
        return self._ctx.__exit__(*exc)


def backward(tensors, grad_tensors=None, retain_graph=False):
    """paddle.autograd.backward."""
    from .engine import run_backward

    tensors = tensors if isinstance(tensors, (list, tuple)) else [tensors]
    if grad_tensors is None:
        grad_tensors = [None] * len(tensors)
    elif not isinstance(grad_tensors, (list, tuple)):
        grad_tensors = [grad_tensors]
    run_backward(list(tensors), list(grad_tensors), retain_graph=retain_graph)


from .py_layer import PyLayer, PyLayerContext  # noqa: E402,F401


def set_grad_enabled(mode):
    """Context manager (reference: python/paddle/autograd/__init__.py)."""
    return _enable_grad_guard() if mode else _no_grad_guard()


class saved_tensors_hooks:
    """Pack/unpack hooks for tensors saved by the tape
    (reference: eager/saved_tensors_hooks.cc). The functional tape saves
    jax values inside closures, so hooks observe/replace Tensor snapshots
    at record time via the engine's hook points."""

    def __init__(self, pack_hook, unpack_hook):
        self.pack_hook = pack_hook
        self.unpack_hook = unpack_hook

    def __enter__(self):
        from . import engine

        self._prev = getattr(engine, "_saved_tensor_hooks", None)
        engine._saved_tensor_hooks = (self.pack_hook, self.unpack_hook)
        return self

    def __exit__(self, *exc):
        from . import engine

        engine._saved_tensor_hooks = self._prev
        return False


backward_mode = "reverse"  # informational: the tape is reverse-mode
