"""Tape-based eager autograd over jax.vjp.

TPU-native replacement for the reference's eager autograd engine
(reference: paddle/fluid/eager/grad_node_info.h:168 `GradNodeBase`,
paddle/fluid/eager/backward.cc:105 `RunBackward`, :394 `Backward`).

Design: every differentiable op funnels through `apply(name, jfn, tensors)`.
When grad is required we call `jax.vjp(jfn, *values)` — forward executes
eagerly (or traces, under jax.jit) and we keep the vjp closure on a GradNode.
`run_backward` does the same queue + pending-count traversal as the
reference's RunBackward. Higher-order grad (create_graph=True) re-linearizes
the forward (node stores `jfn` and input tensors) so grads of grads flow
through the original inputs, not just cotangents.
"""
import contextlib
import threading
from collections import defaultdict, deque

import jax
import jax.numpy as jnp
import numpy as np

_amp_mod = None


def _amp():
    """amp module accessor (lazy once: amp imports tensor machinery)."""
    global _amp_mod
    if _amp_mod is None:
        from .. import amp as _amp_mod_

        _amp_mod = _amp_mod_
    return _amp_mod

__all__ = [
    "apply",
    "no_grad_guard",
    "enable_grad_guard",
    "is_grad_enabled",
    "run_backward",
    "grad",
    "GradNode",
    "register_tensor_class",
    "wrap",
]


class _State(threading.local):
    def __init__(self):
        self.grad_enabled = True


_state = _State()


def is_grad_enabled():
    return _state.grad_enabled


@contextlib.contextmanager
def no_grad_guard():
    old = _state.grad_enabled
    _state.grad_enabled = False
    try:
        yield
    finally:
        _state.grad_enabled = old


@contextlib.contextmanager
def enable_grad_guard():
    old = _state.grad_enabled
    _state.grad_enabled = True
    try:
        yield
    finally:
        _state.grad_enabled = old


_tensor_cls = None


def register_tensor_class(cls):
    global _tensor_cls
    _tensor_cls = cls


def wrap(value, stop_gradient=True):
    return _tensor_cls(value, stop_gradient=stop_gradient)


class GradNode:
    """One recorded op on the tape (≈ egr::GradNodeBase)."""

    __slots__ = (
        "name",
        "vjp_fn",
        "jfn",
        "inputs",
        "n_outputs",
        "out_meta",
        "deferred_vals",
    )

    def __init__(self, name, vjp_fn, jfn, inputs, out_meta,
                 deferred_vals=None):
        self.name = name
        self.vjp_fn = vjp_fn
        self.jfn = jfn  # kept for create_graph re-linearization
        self.inputs = inputs  # tuple[Tensor]
        self.n_outputs = len(out_meta)
        self.out_meta = out_meta  # [(shape, dtype)]
        # trace-time ops defer linearization (see apply); the forward vals
        # are kept so a late tape backward can still jax.vjp them
        self.deferred_vals = deferred_vals

    def __repr__(self):
        return f"GradNode({self.name})"


def _nan_guard(name, outs):
    """FLAGS_check_nan_inf watchdog (reference: paddle/fluid/framework/
    operator.cc:1460 CheckOpHasNanOrInf + details/nan_inf_utils). Eager
    per-op scan attributing the first non-finite output to its op; under
    an outer trace the values are Tracers and the jit-level check
    (jax_debug_nans, toggled by the same flag) takes over."""
    from ..core import flags as flags_mod

    if not flags_mod.get_flag("check_nan_inf"):
        return
    seq = outs if isinstance(outs, (tuple, list)) else (outs,)
    for i, o in enumerate(seq):
        if isinstance(o, jax.core.Tracer):
            return
        if hasattr(o, "dtype") and jnp.issubdtype(o.dtype, jnp.inexact):
            if not bool(jnp.isfinite(o).all()):
                raise FloatingPointError(
                    f"NaN or Inf detected in output {i} of op '{name}' "
                    f"(shape {tuple(o.shape)}, dtype {o.dtype}) — "
                    "FLAGS_check_nan_inf is enabled"
                )


def apply(name, jfn, tensors, n_outputs=None):
    """Run `jfn(*[t.value])`, recording a GradNode if grad is needed.

    `tensors` must all be Tensor instances; non-tensor attrs belong inside the
    jfn closure. Multi-output jfns must return a tuple. Integer/bool outputs
    are treated as non-differentiable (stop_gradient=True on the result;
    float0 cotangents fed to vjp).
    """
    vals = tuple(t._value for t in tensors)
    # AMP interposition: one central cast point for every op (the reference
    # generates per-op AMP glue; see paddle_tpu/amp/__init__.py)
    if _amp()._state.enabled:
        vals = _amp().cast_inputs_for(name, vals)
    need = _state.grad_enabled and any(not t.stop_gradient for t in tensors)
    if not need:
        out = jfn(*vals)
        _nan_guard(name, out)
        if isinstance(out, (tuple, list)):
            return tuple(wrap(o, True) for o in out)
        return wrap(out, True)

    if any(isinstance(v, jax.core.Tracer) for v in vals):
        # Under an outer trace (jit / value_and_grad / checkpoint) the
        # outer AD differentiates the staged ops directly — eagerly
        # vjp-ing here would (a) trace every op twice and (b) decompose
        # custom_vjp ops (e.g. Pallas kernels) into primitives the outer
        # AD cannot transpose. Linearize lazily only if the tape backward
        # is actually invoked on these tracers.
        outs = jfn(*vals)
        vjp_fn, deferred = None, vals
    else:
        outs, vjp_fn = jax.vjp(jfn, *vals)
        deferred = None
    _nan_guard(name, outs)
    multi = isinstance(outs, (tuple, list))
    outs_t = tuple(outs) if multi else (outs,)
    out_meta = [(o.shape, o.dtype) for o in outs_t]
    node = GradNode(name, vjp_fn, jfn, tuple(tensors), out_meta,
                    deferred_vals=deferred)
    result = []
    for i, o in enumerate(outs_t):
        nondiff = not jnp.issubdtype(o.dtype, jnp.inexact)
        t = wrap(o, stop_gradient=nondiff)
        if not nondiff:
            t._grad_node = node
            t._out_index = i
        result.append(t)
    return tuple(result) if multi else result[0]


def _ones_like_meta(shape, dtype):
    import jax.numpy as jnp

    return jnp.ones(shape, dtype)


def _discover(root_nodes, seeds_per_node):
    """BFS the node graph; return expected contribution count per node."""
    expected = defaultdict(int)
    for n, c in seeds_per_node.items():
        expected[n] += c
    seen = set(root_nodes)
    stack = list(root_nodes)
    while stack:
        n = stack.pop()
        for it in n.inputs:
            cn = it._grad_node
            if cn is not None:
                expected[cn] += 1
                if cn not in seen:
                    seen.add(cn)
                    stack.append(cn)
    return expected


def run_backward(roots, root_grads, retain_graph=False, create_graph=False,
                 grad_sinks=None, accumulate_leaf=True):
    """Reverse traversal (≈ backward.cc:105 RunBackward).

    roots: list[Tensor]; root_grads: list[Tensor|None].
    grad_sinks: optional dict  id(tensor) -> [tensor, accumulated-grad] used by
    `grad()` to collect gradients for arbitrary (possibly non-leaf) tensors.
    """
    import jax.numpy as jnp

    seeds = defaultdict(int)
    root_nodes = []
    for t in roots:
        n = t._grad_node
        if n is not None:
            seeds[n] += 1
            if n not in root_nodes:
                root_nodes.append(n)
    expected = _discover(root_nodes, seeds)

    contrib = defaultdict(int)
    outgrads = {}
    ready = deque()

    def _sink(tensor, g):
        if grad_sinks is not None and id(tensor) in grad_sinks:
            slot = grad_sinks[id(tensor)]
            slot[1] = g if slot[1] is None else _add_grads(slot[1], g)

    def _add_grads(a, b):
        if create_graph:
            from ..ops.math import add as t_add

            return t_add(a, b)
        return wrap(a._value + b._value, a.stop_gradient and b.stop_gradient)

    def _accum_leaf(tensor, g):
        _sink(tensor, g)
        if accumulate_leaf and not tensor.stop_gradient:
            if tensor.grad is None:
                tensor.grad = g
            else:
                tensor.grad = _add_grads(tensor.grad, g)

    def _add_outgrad(node, idx, g):
        slots = outgrads.setdefault(node, [None] * node.n_outputs)
        slots[idx] = g if slots[idx] is None else _add_grads(slots[idx], g)
        contrib[node] += 1
        if contrib[node] == expected[node]:
            ready.append(node)

    # Seed root grads.
    for t, g in zip(roots, root_grads):
        if g is None:
            if not jnp.issubdtype(t._value.dtype, jnp.inexact):
                raise ValueError("backward() root must be floating point")
            g = wrap(_ones_like_meta(t._value.shape, t._value.dtype), True)
        n = t._grad_node
        if n is None:
            _accum_leaf(t, g)
        else:
            _sink(t, g)
            _add_outgrad(n, t._out_index, g)

    # Drain queue.
    while ready:
        node = ready.popleft()
        slots = outgrads.pop(node, [None] * node.n_outputs)
        if node.vjp_fn is None and node.deferred_vals is not None \
                and not create_graph:  # create_graph re-linearizes anyway
            _, node.vjp_fn = jax.vjp(node.jfn, *node.deferred_vals)
            node.deferred_vals = None
        if node.vjp_fn is None and not create_graph:
            raise RuntimeError(
                f"grad graph for {node.name} already freed; "
                "pass retain_graph=True to backward() to reuse it"
            )
        if create_graph:
            in_grads = _node_grad_recorded(node, slots)
        else:
            cts = []
            for (shape, dtype), g in zip(node.out_meta, slots):
                if g is None:
                    cts.append(jnp.zeros(shape, dtype))
                else:
                    cts.append(g._value)
            arg = tuple(cts) if node.n_outputs > 1 else cts[0]
            raw = node.vjp_fn(arg)
            in_grads = [
                None
                if g is None
                or (isinstance(g, np.ndarray) and g.dtype == jax.dtypes.float0)
                else wrap(g, True)
                for g in raw
            ]
        if not retain_graph and not create_graph:
            node.vjp_fn = None
        for it, ig in zip(node.inputs, in_grads):
            if ig is None:
                continue
            if getattr(ig, "_value", None) is not None and isinstance(
                ig._value, np.ndarray
            ) and ig._value.dtype == jax.dtypes.float0:
                continue
            hooks = getattr(it, "_backward_hooks", None)
            if hooks:
                for hook in hooks:
                    out = hook(ig)
                    if out is not None:
                        ig = out
            cn = it._grad_node
            if cn is None:
                _accum_leaf(it, ig)
            else:
                _sink(it, ig)
                _add_outgrad(cn, it._out_index, ig)


def _node_grad_recorded(node, slots):
    """create_graph path: recompute forward+vjp through `apply` so the grads
    themselves land on the tape (second-order autodiff)."""
    import jax.numpy as jnp

    k = len(node.inputs)
    if node.jfn is None:
        raise NotImplementedError(
            f"create_graph=True through {node.name} is not supported "
            "(custom PyLayer backward is opaque to re-linearization); "
            "implement the op functionally or without create_graph"
        )
    ct_tensors = []
    for (shape, dtype), g in zip(node.out_meta, slots):
        if g is None:
            g = wrap(jnp.zeros(shape, dtype), True)
        ct_tensors.append(g)
    jfn = node.jfn
    multi = node.n_outputs > 1

    def gradfn(*args):
        xs, cts = args[:k], args[k:]
        _, vjp = jax.vjp(jfn, *xs)
        raw = vjp(tuple(cts) if multi else cts[0])
        return tuple(raw)

    outs = apply("grad:" + node.name, gradfn, tuple(node.inputs) + tuple(ct_tensors))
    if not isinstance(outs, tuple):
        outs = (outs,)
    return list(outs)


def grad(outputs, inputs, grad_outputs=None, retain_graph=None,
         create_graph=False, allow_unused=False):
    """paddle.grad equivalent (reference: eager/general_grad.h GeneralGrad)."""
    outputs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    if grad_outputs is None:
        grad_outputs = [None] * len(outputs)
    elif not isinstance(grad_outputs, (list, tuple)):
        grad_outputs = [grad_outputs]
    if retain_graph is None:
        retain_graph = create_graph
    sinks = {id(t): [t, None] for t in inputs}
    run_backward(
        list(outputs),
        list(grad_outputs),
        retain_graph=retain_graph,
        create_graph=create_graph,
        grad_sinks=sinks,
        accumulate_leaf=False,
    )
    results = []
    for t in inputs:
        g = sinks[id(t)][1]
        if g is None and not allow_unused:
            raise ValueError(
                "one of the inputs receives no gradient; "
                "pass allow_unused=True to permit this"
            )
        results.append(g)
    return results
