"""dataset.image (reference: python/paddle/dataset/image.py) — numpy
image helpers used by the legacy pipelines. The reference uses cv2;
PIL + numpy serve here (same outputs for these ops)."""
import numpy as np

__all__ = ["load_image", "resize_short", "center_crop", "random_crop",
           "left_right_flip", "to_chw", "simple_transform",
           "load_and_transform"]


def load_image(path, is_color=True):
    from PIL import Image

    img = Image.open(path)
    img = img.convert("RGB" if is_color else "L")
    arr = np.asarray(img)
    return arr if is_color else arr[..., None]


def resize_short(im, size):
    from PIL import Image

    h, w = im.shape[:2]
    scale = size / min(h, w)
    nh, nw = int(round(h * scale)), int(round(w * scale))
    pim = Image.fromarray(im.squeeze() if im.shape[-1] == 1 else im)
    out = np.asarray(pim.resize((nw, nh), Image.BILINEAR))
    return out if out.ndim == 3 else out[..., None]


def center_crop(im, size, is_color=True):
    h, w = im.shape[:2]
    hs, ws = (h - size) // 2, (w - size) // 2
    return im[hs:hs + size, ws:ws + size]


def random_crop(im, size, is_color=True):
    h, w = im.shape[:2]
    hs = np.random.randint(0, h - size + 1)
    ws = np.random.randint(0, w - size + 1)
    return im[hs:hs + size, ws:ws + size]


def left_right_flip(im, is_color=True):
    return im[:, ::-1]


def to_chw(im, order=(2, 0, 1)):
    return im.transpose(order)


def simple_transform(im, resize_size, crop_size, is_train, is_color=True,
                     mean=None):
    im = resize_short(im, resize_size)
    if is_train:
        im = random_crop(im, crop_size)
        if np.random.randint(2):
            im = left_right_flip(im, is_color)
    else:
        im = center_crop(im, crop_size, is_color)
    im = to_chw(im).astype(np.float32)
    if mean is not None:
        im -= np.asarray(mean, np.float32).reshape(-1, 1, 1)
    return im


def load_and_transform(filename, resize_size, crop_size, is_train,
                       is_color=True, mean=None):
    return simple_transform(load_image(filename, is_color), resize_size,
                            crop_size, is_train, is_color, mean)
