"""dataset.imikolov (reference: python/paddle/dataset/imikolov.py) —
PTB language-model readers: NGRAM yields n-tuples of word ids, SEQ
yields id sequences."""
from .common import reader_from_dataset

__all__ = ["build_dict", "train", "test"]


def build_dict(data_file=None, min_word_freq=50):
    from ..text.datasets import Imikolov

    return Imikolov(data_file=data_file, data_type="SEQ", mode="train",
                    min_word_freq=min_word_freq).word_idx


def _make(mode, n, data_type, data_file, min_word_freq):
    from ..text.datasets import Imikolov

    ds = Imikolov(data_file=data_file, data_type=data_type,
                  window_size=n, mode=mode, min_word_freq=min_word_freq)
    return reader_from_dataset(ds, lambda s: tuple(
        v.tolist() if hasattr(v, "tolist") else v for v in s)
        if isinstance(s, tuple) else s)


def train(word_idx=None, n=5, data_type="NGRAM", data_file=None,
          min_word_freq=50):
    return _make("train", n, data_type, data_file, min_word_freq)


def test(word_idx=None, n=5, data_type="NGRAM", data_file=None,
         min_word_freq=50):
    return _make("test", n, data_type, data_file, min_word_freq)
