"""dataset.conll05 (reference: python/paddle/dataset/conll05.py) — SRL
test reader + dicts."""
from .common import reader_from_dataset

__all__ = ["test", "get_dict"]


def _ds(data_file, **kw):
    from ..text.datasets import Conll05st

    return Conll05st(data_file=data_file, **kw)


def get_dict(data_file=None, **kw):
    ds = _ds(data_file, **kw)
    return ds.word_dict, ds.predicate_dict, ds.label_dict


def test(data_file=None, **kw):
    return reader_from_dataset(_ds(data_file, **kw))
