"""dataset.voc2012 (reference: python/paddle/dataset/voc2012.py) —
readers yield (image CHW float32, segmentation mask HW int64)."""
import numpy as np

from .common import reader_from_dataset

__all__ = ["train", "test", "valid"]


def _map(sample):
    img, mask = sample
    img = np.asarray(img, np.float32)
    if img.ndim == 3 and img.shape[-1] in (1, 3):
        img = img.transpose(2, 0, 1)
    return img, np.asarray(mask, np.int64)


def _make(mode, kw):
    from ..vision.datasets import VOC2012

    return reader_from_dataset(VOC2012(mode=mode, **kw), _map)


def train(**kw):
    return _make("train", kw)


def test(**kw):
    return _make("test", kw)


def valid(**kw):
    return _make("valid", kw)
