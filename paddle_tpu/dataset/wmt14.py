"""dataset.wmt14 (reference: python/paddle/dataset/wmt14.py) —
translation readers yielding (src ids, trg ids, trg-next ids)."""
from .common import reader_from_dataset

__all__ = ["train", "test"]


def _make(mode, dict_size, data_file):
    from ..text.datasets import WMT14

    ds = WMT14(data_file=data_file, mode=mode, dict_size=dict_size)
    return reader_from_dataset(ds, lambda s: tuple(
        v.tolist() if hasattr(v, "tolist") else v for v in s))


def train(dict_size=30000, data_file=None):
    return _make("train", dict_size, data_file)


def test(dict_size=30000, data_file=None):
    return _make("test", dict_size, data_file)
