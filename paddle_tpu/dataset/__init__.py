"""paddle.dataset — legacy reader-style dataset loaders.

Reference: python/paddle/dataset/ (mnist.py, cifar.py, uci_housing.py,
imdb.py, imikolov.py, movielens.py, conll05.py, wmt14.py, wmt16.py,
flowers.py, voc2012.py, image.py, common.py). Each module exposes
reader CREATORS (`train()`, `test()`, ...) returning zero-arg callables
that yield reference-shaped sample tuples — the composition layer
`paddle.reader` consumes them.

TPU-native design: these are thin adapters over the map-style Dataset
classes in `paddle_tpu.vision.datasets` / `paddle_tpu.text.datasets`
(single source of truth for parsing + normalization). Vision loaders run
hermetically (synthetic fallback when no archive is given); text loaders
need a local archive via `data_file=` — automatic download is
unavailable in this environment.
"""
from . import (cifar, common, conll05, flowers, image, imdb, imikolov,  # noqa: F401
               mnist, movielens, uci_housing, voc2012, wmt14, wmt16)

__all__ = ["mnist", "cifar", "uci_housing", "imdb", "imikolov",
           "movielens", "conll05", "wmt14", "wmt16", "flowers",
           "voc2012", "image", "common"]
