"""dataset.wmt16 (reference: python/paddle/dataset/wmt16.py) —
translation readers yielding (src ids, trg ids, trg-next ids)."""
from .common import reader_from_dataset

__all__ = ["train", "test", "validation"]


def _make(mode, src_dict_size, trg_dict_size, data_file, lang):
    from ..text.datasets import WMT16

    ds = WMT16(data_file=data_file, mode=mode,
               src_dict_size=src_dict_size, trg_dict_size=trg_dict_size,
               lang=lang)
    return reader_from_dataset(ds, lambda s: tuple(
        v.tolist() if hasattr(v, "tolist") else v for v in s))


def train(src_dict_size=-1, trg_dict_size=-1, data_file=None, lang="en"):
    return _make("train", src_dict_size, trg_dict_size, data_file, lang)


def test(src_dict_size=-1, trg_dict_size=-1, data_file=None, lang="en"):
    return _make("test", src_dict_size, trg_dict_size, data_file, lang)


def validation(src_dict_size=-1, trg_dict_size=-1, data_file=None,
               lang="en"):
    return _make("val", src_dict_size, trg_dict_size, data_file, lang)
