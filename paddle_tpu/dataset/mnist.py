"""dataset.mnist (reference: python/paddle/dataset/mnist.py) — readers
yield (flat 784 float32 in [-1, 1], int label), the reference's wire
shape. Backed by vision.datasets.MNIST (synthetic fallback without
archives)."""
import numpy as np

from .common import reader_from_dataset

__all__ = ["train", "test"]


def _map(sample):
    img, label = sample
    flat = np.asarray(img, np.float32).reshape(-1)
    return flat * 2.0 - 1.0, int(label)  # dataset gives [0,1]; ref [-1,1]


def _make(mode, image_path, label_path):
    from ..vision.datasets import MNIST

    return reader_from_dataset(
        MNIST(image_path=image_path, label_path=label_path, mode=mode),
        _map)


def train(image_path=None, label_path=None):
    return _make("train", image_path, label_path)


def test(image_path=None, label_path=None):
    return _make("test", image_path, label_path)
