"""dataset.flowers (reference: python/paddle/dataset/flowers.py) —
readers yield (image CHW float32 [0,1], int label)."""
import numpy as np

from .common import reader_from_dataset

__all__ = ["train", "test", "valid"]


def _map(sample):
    img, label = sample
    img = np.asarray(img, np.float32)
    if img.ndim == 3 and img.shape[-1] in (1, 3):  # HWC -> CHW
        img = img.transpose(2, 0, 1)
    return img / 255.0, int(label)


def _make(mode, kw):
    from ..vision.datasets import Flowers

    return reader_from_dataset(Flowers(mode=mode, **kw), _map)


def train(**kw):
    return _make("train", kw)


def test(**kw):
    return _make("test", kw)


def valid(**kw):
    return _make("valid", kw)
