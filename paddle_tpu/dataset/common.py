"""dataset.common (reference: python/paddle/dataset/common.py) — shared
helpers for the legacy loaders. `download` is a local-file check here
(zero-egress environment): it validates the given path (and md5 when
provided) instead of fetching."""
import hashlib
import os
import pickle

DATA_HOME = os.path.expanduser(
    os.environ.get("PADDLE_TPU_DATA_HOME", "~/.cache/paddle_tpu/dataset"))

__all__ = ["DATA_HOME", "md5file", "download", "split",
           "cluster_files_reader", "reader_from_dataset"]


def md5file(fname):
    h = hashlib.md5()
    with open(fname, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def download(url, module_name, md5sum=None, save_name=None):
    """Reference common.py `download` fetches over HTTP. No egress here:
    `url` must be a LOCAL path (or the file must already sit under
    DATA_HOME/module_name); md5 is verified when given."""
    candidates = [url] if url and os.path.exists(url) else []
    if save_name:
        candidates.append(os.path.join(DATA_HOME, module_name, save_name))
    for path in candidates:
        if os.path.exists(path):
            if md5sum and md5file(path) != md5sum:
                raise IOError(f"{path}: md5 mismatch")
            return path
    raise IOError(
        f"dataset file for {module_name} not found — downloads are "
        f"unavailable; place the archive locally and pass its path")


def split(reader, line_count, suffix="%05d.pickle", dumper=None):
    """Split a reader's samples into pickled chunk files of `line_count`
    samples (reference common.py split)."""
    dumper = dumper or (lambda obj, f: pickle.dump(obj, f))
    buf, idx, files = [], 0, []

    def _flush():
        nonlocal buf, idx
        if not buf:
            return
        name = suffix % idx
        with open(name, "wb") as f:
            dumper(buf, f)
        files.append(name)
        buf, idx = [], idx + 1

    for sample in reader():
        buf.append(sample)
        if len(buf) == line_count:
            _flush()
    _flush()
    return files


def cluster_files_reader(files_pattern, trainer_count, trainer_id,
                         loader=None):
    """Read this trainer's round-robin share of pickled chunk files
    (reference common.py cluster_files_reader)."""
    import glob

    loader = loader or (lambda f: pickle.load(f))

    def reader():
        files = sorted(glob.glob(files_pattern))
        for i, name in enumerate(files):
            if i % trainer_count == trainer_id:
                with open(name, "rb") as f:
                    yield from loader(f)

    return reader


def reader_from_dataset(ds, map_fn=None):
    """Adapter: map-style Dataset -> legacy reader creator."""

    def reader():
        for i in range(len(ds)):
            s = ds[i]
            yield map_fn(s) if map_fn else s

    return reader
