"""dataset.cifar (reference: python/paddle/dataset/cifar.py) — readers
yield (flat 3072 float32 in [0, 1], int label)."""
import numpy as np

from .common import reader_from_dataset

__all__ = ["train10", "test10", "train100", "test100"]


def _map(sample):
    img, label = sample
    return np.asarray(img, np.float32).reshape(-1), int(label)


def _make(cls_name, mode, data_file):
    from ..vision import datasets as vd

    ds = getattr(vd, cls_name)(data_file=data_file, mode=mode)
    return reader_from_dataset(ds, _map)


def train10(data_file=None):
    return _make("Cifar10", "train", data_file)


def test10(data_file=None):
    return _make("Cifar10", "test", data_file)


def train100(data_file=None):
    return _make("Cifar100", "train", data_file)


def test100(data_file=None):
    return _make("Cifar100", "test", data_file)
