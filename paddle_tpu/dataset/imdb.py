"""dataset.imdb (reference: python/paddle/dataset/imdb.py) — readers
yield (word-id list, 0/1 label). The vocabulary is built by the backing
`text.datasets.Imdb` (cutoff-frequency dict, same rule as the
reference); pass its `word_dict()` result around for embedding sizes."""
from .common import reader_from_dataset

__all__ = ["word_dict", "train", "test"]


def word_dict(data_file=None, cutoff=150):
    from ..text.datasets import Imdb

    return Imdb(data_file=data_file, mode="train", cutoff=cutoff).word_idx


def _make(mode, data_file, cutoff):
    from ..text.datasets import Imdb

    ds = Imdb(data_file=data_file, mode=mode, cutoff=cutoff)
    return reader_from_dataset(
        ds, lambda s: (s[0].tolist(), int(s[1])))


def train(word_idx=None, data_file=None, cutoff=150):
    return _make("train", data_file, cutoff)


def test(word_idx=None, data_file=None, cutoff=150):
    return _make("test", data_file, cutoff)
