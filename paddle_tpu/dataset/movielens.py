"""dataset.movielens (reference: python/paddle/dataset/movielens.py) —
rating tuples for recommender baselines."""
from .common import reader_from_dataset

__all__ = ["train", "test", "get_movie_title_dict", "max_movie_id",
           "max_user_id", "max_job_id", "age_table"]

age_table = [1, 18, 25, 35, 45, 50, 56]


def _ds(mode, data_file):
    from ..text.datasets import Movielens

    return Movielens(data_file=data_file, mode=mode)


def train(data_file=None):
    return reader_from_dataset(_ds("train", data_file))


def test(data_file=None):
    return reader_from_dataset(_ds("test", data_file))


def get_movie_title_dict(data_file=None):
    ds = _ds("train", data_file)
    return getattr(ds, "movie_title_dict", {})


def max_movie_id(data_file=None):
    ds = _ds("train", data_file)
    return int(getattr(ds, "max_movie_id", 0))


def max_user_id(data_file=None):
    ds = _ds("train", data_file)
    return int(getattr(ds, "max_user_id", 0))


def max_job_id(data_file=None):
    ds = _ds("train", data_file)
    return int(getattr(ds, "max_job_id", 0))
