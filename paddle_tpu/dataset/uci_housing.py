"""dataset.uci_housing (reference: python/paddle/dataset/uci_housing.py)
— readers yield (13 float32 features, [price])."""
from .common import reader_from_dataset

__all__ = ["train", "test", "feature_names"]

feature_names = ["CRIM", "ZN", "INDUS", "CHAS", "NOX", "RM", "AGE",
                 "DIS", "RAD", "TAX", "PTRATIO", "B", "LSTAT"]


def _make(mode, data_file):
    from ..text.datasets import UCIHousing

    return reader_from_dataset(UCIHousing(data_file=data_file, mode=mode))


def train(data_file=None):
    return _make("train", data_file)


def test(data_file=None):
    return _make("test", data_file)
