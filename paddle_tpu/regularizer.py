"""Weight-decay regularizers (reference: python/paddle/fluid/regularizer.py).

In the reference these append scale/sum ops into the backward program; here
they are coefficient carriers the optimizer folds into its update (coupled
L2 or decoupled, per optimizer).
"""

__all__ = ["L1Decay", "L2Decay"]


class WeightDecayRegularizer:
    def __init__(self, coeff=0.0):
        self._coeff = float(coeff)

    def __repr__(self):
        return f"{self.__class__.__name__}(coeff={self._coeff})"


class L1Decay(WeightDecayRegularizer):
    """L1 penalty: grad += coeff * sign(param). Applied by Optimizer.step
    when set as a param's regularizer."""


class L2Decay(WeightDecayRegularizer):
    """L2 penalty: grad += coeff * param."""
