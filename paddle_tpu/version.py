"""paddle.version (reference: generated python/paddle/version.py) —
version metadata + `show()`."""
full_version = "0.1.0"
major = "0"
minor = "1"
patch = "0"
rc = "0"
istaged = True
commit = "unknown"
with_gpu = "OFF"   # TPU build: XLA/PJRT owns the device
cuda_version = "False"
cudnn_version = "False"

__all__ = ["full_version", "major", "minor", "patch", "rc", "show",
           "istaged", "commit"]


def show():
    """Print the version breakdown (reference version.py show())."""
    if istaged:
        print("full_version:", full_version)
        print("major:", major)
        print("minor:", minor)
        print("patch:", patch)
        print("rc:", rc)
    else:
        print("commit:", commit)


def cuda():
    return cuda_version


def cudnn():
    return cudnn_version
