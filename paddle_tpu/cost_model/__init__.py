"""paddle.cost_model (reference: python/paddle/cost_model/cost_model.py:23
`CostModel` — profiles a static program through the C++ profiler and
serves per-op times from a shipped benchmark JSON).

TPU-native redesign: "profile a program" = time the compiled XLA
executable of a traced function (whole-program measurement is the
meaningful unit under fusion — per-op wall times only exist for ops big
enough to not fuse away); the static per-op table is MEASURED on the
current backend on first use and cached (the reference ships a
GPU-measured static_op_benchmark.json; shipping one would bake in the
wrong hardware). The analytic *communication* cost model for parallel
placement planning lives in `distributed.auto_parallel.CostModel`.
"""
import json
import time

import numpy as np

__all__ = ["CostModel"]

_STANDARD_OPS = {
    # op -> (builder returning (fn, args)); sizes chosen MXU/VPU-typical
    "matmul": lambda jnp: (lambda a, b: a @ b,
                           (np.zeros((1024, 1024), np.float32),) * 2),
    "relu": lambda jnp: (lambda a: jnp.maximum(a, 0),
                         (np.zeros((4096, 1024), np.float32),)),
    "softmax": lambda jnp: (None, (np.zeros((4096, 1024), np.float32),)),
    "layer_norm": lambda jnp: (None, (np.zeros((4096, 1024), np.float32),)),
    "elementwise_add": lambda jnp: (lambda a, b: a + b,
                                    (np.zeros((4096, 1024),
                                              np.float32),) * 2),
}


class CostModel:
    """Measure compiled-program and per-op times (reference
    cost_model.py:23)."""

    def __init__(self):
        self._static_cost_data = None

    # -- reference demo surface -------------------------------------------
    def build_program(self):
        """A tiny fc+mean static program pair, as the reference's demo
        builds (cost_model.py:28)."""
        from paddle_tpu import static

        main_program = static.Program()
        startup_program = static.Program()
        with static.program_guard(main_program=main_program,
                                  startup_program=startup_program):
            static.data(name="X", shape=[None, 1], dtype="float32")

            def stage(env):
                hidden = static.nn.fc(env["X"], 10)
                env["loss"] = hidden.mean()

            main_program.stages.append(stage)
        return startup_program, main_program

    def profile_measure(self, startup_program=None, main_program=None,
                        device=None, fetch_cost_list=("time",),
                        fn=None, args=None, iters=10):
        """Time one compiled step. Either the reference-shaped
        (startup_program, main_program) pair — executed through the
        static Executor — or a direct `fn(*args)` jitted whole. Returns
        {"time": ms_per_iter, "device": ...}."""
        import jax

        import paddle_tpu as paddle

        if fn is not None:
            jfn = jax.jit(fn)
            jax.block_until_ready(jfn(*args))  # compile
            t0 = time.perf_counter()
            for _ in range(iters):
                out = jfn(*args)
            jax.block_until_ready(out)
        else:
            from paddle_tpu import static

            exe = static.Executor()
            exe.run(startup_program)
            feed = {"X": np.random.random((10, 1)).astype(np.float32)}
            exe.run(main_program, feed=feed)  # compile
            t0 = time.perf_counter()
            for _ in range(iters):
                exe.run(main_program, feed=feed)
        dt_ms = (time.perf_counter() - t0) / iters * 1e3
        dev = device or paddle.device.get_device()
        return {"time": dt_ms, "device": dev}

    # -- per-op static table ----------------------------------------------
    def static_cost_data(self, path=None):
        """Per-op time table. With `path`, loads that JSON (the
        reference's static_op_benchmark.json shape) — always, replacing
        any cache, and raising if the file is missing rather than
        silently re-measuring. Without it, measures the standard op set
        on the CURRENT backend once and caches."""
        if path is not None:
            with open(path) as f:  # FileNotFoundError on a typo'd path
                self._static_cost_data = json.load(f)
            return self._static_cost_data
        if self._static_cost_data is None:
            self._static_cost_data = self._measure_standard_ops()
        return self._static_cost_data

    def _measure_standard_ops(self):
        import jax
        import jax.numpy as jnp

        table = {}
        for name, build in _STANDARD_OPS.items():
            fn, args = build(jnp)
            if fn is None:
                fn = {"softmax": lambda a: jax.nn.softmax(a, axis=-1),
                      "layer_norm": lambda a: (
                          (a - a.mean(-1, keepdims=True))
                          / jnp.sqrt(a.var(-1, keepdims=True) + 1e-5)),
                      }[name]
            res = self.profile_measure(fn=fn, args=args, iters=20)
            table[name] = {"op_time": str(res["time"]),
                           "forward": True, "dtype": "float32"}
        return table

    def get_static_op_time(self, op_name, forward=True, dtype="float32"):
        """Reference cost_model.py:72 — one op's measured time."""
        data = self.static_cost_data()
        if op_name not in data:
            raise KeyError(
                f"no cost entry for op {op_name!r}; known: {sorted(data)}")
        return data[op_name]
