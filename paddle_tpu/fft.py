"""paddle_tpu.fft (reference: python/paddle/fft.py — fft/ifft/rfft/
irfft/hfft/ihfft + 2d/n variants + helpers; phi kernels fft_c2c/r2c/c2r).

Thin tape-funneled wrappers over jnp.fft — differentiable where jax
defines VJPs, jit-safe, and norm semantics matching the reference
("backward" default, "forward", "ortho")."""
import jax.numpy as jnp

from .ops._helpers import apply_jfn, ensure_tensor
from .tensor_core import Tensor

__all__ = [
    "fft", "ifft", "rfft", "irfft", "hfft", "ihfft",
    "fft2", "ifft2", "rfft2", "irfft2",
    "fftn", "ifftn", "rfftn", "irfftn",
    "fftfreq", "rfftfreq", "fftshift", "ifftshift",
]


def _norm(norm):
    if norm is None:
        return "backward"
    assert norm in ("backward", "forward", "ortho"), norm
    return norm


def _wrap1(op_name, jfn_name):
    def op(x, n=None, axis=-1, norm="backward", name=None):
        f = getattr(jnp.fft, jfn_name)
        return apply_jfn(
            op_name, lambda v: f(v, n=n, axis=axis, norm=_norm(norm)),
            ensure_tensor(x))

    op.__name__ = op_name
    return op


fft = _wrap1("fft", "fft")
ifft = _wrap1("ifft", "ifft")
rfft = _wrap1("rfft", "rfft")
irfft = _wrap1("irfft", "irfft")
hfft = _wrap1("hfft", "hfft")
ihfft = _wrap1("ihfft", "ihfft")


def _wrap2(op_name, jfn_name):
    def op(x, s=None, axes=(-2, -1), norm="backward", name=None):
        f = getattr(jnp.fft, jfn_name)
        return apply_jfn(
            op_name, lambda v: f(v, s=s, axes=axes, norm=_norm(norm)),
            ensure_tensor(x))

    op.__name__ = op_name
    return op


fft2 = _wrap2("fft2", "fft2")
ifft2 = _wrap2("ifft2", "ifft2")
rfft2 = _wrap2("rfft2", "rfft2")
irfft2 = _wrap2("irfft2", "irfft2")


def _wrapn(op_name, jfn_name):
    def op(x, s=None, axes=None, norm="backward", name=None):
        f = getattr(jnp.fft, jfn_name)
        return apply_jfn(
            op_name, lambda v: f(v, s=s, axes=axes, norm=_norm(norm)),
            ensure_tensor(x))

    op.__name__ = op_name
    return op


fftn = _wrapn("fftn", "fftn")
ifftn = _wrapn("ifftn", "ifftn")
rfftn = _wrapn("rfftn", "rfftn")
irfftn = _wrapn("irfftn", "irfftn")


def fftfreq(n, d=1.0, dtype=None, name=None):
    return Tensor(jnp.fft.fftfreq(n, d=d), stop_gradient=True)


def rfftfreq(n, d=1.0, dtype=None, name=None):
    return Tensor(jnp.fft.rfftfreq(n, d=d), stop_gradient=True)


def fftshift(x, axes=None, name=None):
    return apply_jfn("fftshift", lambda v: jnp.fft.fftshift(v, axes=axes),
                     ensure_tensor(x))


def ifftshift(x, axes=None, name=None):
    return apply_jfn("ifftshift",
                     lambda v: jnp.fft.ifftshift(v, axes=axes),
                     ensure_tensor(x))


def _split_axes(x, s, axes):
    nd = (x.numpy().ndim if hasattr(x, "numpy") else jnp.asarray(x).ndim)
    if axes is None:
        axes = tuple(range(nd)) if s is None else tuple(
            range(nd - len(s), nd))
    axes = tuple(a if a >= 0 else nd + a for a in axes)
    if s is None:
        s = [None] * len(axes)
    return list(s), list(axes)


def hfftn(x, s=None, axes=None, norm="backward", name=None):
    """Hermitian-input nD FFT (reference: python/paddle/fft.py hfftn):
    c2c transforms over the leading axes, Hermitian c2r over the last."""
    s_, axes_ = _split_axes(ensure_tensor(x), s, axes)
    out = ensure_tensor(x)
    if len(axes_) > 1:
        lead_s = [v for v in s_[:-1]]
        out = fftn(out, s=None if all(v is None for v in lead_s)
                   else [o or out.shape[a] for o, a in
                         zip(lead_s, axes_[:-1])],
                   axes=axes_[:-1], norm=norm)
    return hfft(out, n=s_[-1], axis=axes_[-1], norm=norm)


def hfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return hfftn(x, s=s, axes=axes, norm=norm)


def ihfftn(x, s=None, axes=None, norm="backward", name=None):
    """Inverse Hermitian nD FFT: r2c over the last axis, c2c inverses over
    the rest (reference: python/paddle/fft.py ihfftn)."""
    s_, axes_ = _split_axes(ensure_tensor(x), s, axes)
    out = ihfft(ensure_tensor(x), n=s_[-1], axis=axes_[-1], norm=norm)
    if len(axes_) > 1:
        lead_s = [v for v in s_[:-1]]
        out = ifftn(out, s=None if all(v is None for v in lead_s)
                    else [o or out.shape[a] for o, a in
                          zip(lead_s, axes_[:-1])],
                    axes=axes_[:-1], norm=norm)
    return out


def ihfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return ihfftn(x, s=s, axes=axes, norm=norm)


__all__ += ["hfft2", "hfftn", "ihfft2", "ihfftn"]


# predicate re-exports (the reference's fft module namespace carries them)
from .ops.api_misc import (  # noqa: E402,F401
    is_complex,
    is_floating_point,
    is_integer,
)
