"""nn.utils (reference: python/paddle/nn/utils/)."""
import jax.numpy as jnp

from ...tensor_core import Tensor
from ..clip import clip_grad_norm_, clip_grad_value_  # noqa: F401

__all__ = ["parameters_to_vector", "vector_to_parameters", "weight_norm",
           "remove_weight_norm", "spectral_norm", "clip_grad_norm_",
           "clip_grad_value_"]


def parameters_to_vector(parameters, name=None):
    vals = [p._value.reshape(-1) for p in parameters]
    return Tensor(jnp.concatenate(vals))


def vector_to_parameters(vec, parameters, name=None):
    offset = 0
    v = vec._value if isinstance(vec, Tensor) else jnp.asarray(vec)
    for p in parameters:
        n = int(jnp.prod(jnp.asarray(p._value.shape))) if p._value.shape else 1
        p._value = v[offset: offset + n].reshape(p._value.shape).astype(
            p._value.dtype)
        offset += n


def weight_norm(layer, name="weight", dim=0):
    """Reparameterize weight = g * v/||v||.

    Implemented as a forward-pre-hook recomputing the weight each call
    (reference: python/paddle/nn/utils/weight_norm_hook.py).
    """
    import numpy as np

    from ...tensor_core import Parameter

    w = getattr(layer, name)
    wv = w._value
    axes = tuple(i for i in range(wv.ndim) if i != dim)
    g0 = jnp.sqrt(jnp.sum(wv * wv, axis=axes, keepdims=True))
    v = Parameter(wv, trainable=True)
    g = Parameter(g0, trainable=True)
    del layer._parameters[name]
    layer.add_parameter(name + "_v", v)
    layer.add_parameter(name + "_g", g)

    def _compute(lyr, inputs):
        vv = lyr._parameters[name + "_v"]
        gg = lyr._parameters[name + "_g"]
        from ...ops._helpers import apply_jfn

        def jfn(vval, gval):
            nrm = jnp.sqrt(jnp.sum(vval * vval, axis=axes, keepdims=True))
            return gval * vval / jnp.maximum(nrm, 1e-12)

        wt = apply_jfn("weight_norm", jfn, vv, gg)
        object.__setattr__(lyr, "_wn_weight", wt)
        lyr._parameters.pop(name, None)
        # stash computed weight where forward looks it up
        lyr.__dict__[name] = wt
        return None

    h = layer.register_forward_pre_hook(_compute)
    layer.__dict__["_weight_norm_hook"] = h
    layer.__dict__["_weight_norm_name"] = name
    _compute(layer, ())
    return layer


def remove_weight_norm(layer, name="weight"):
    h = layer.__dict__.pop("_weight_norm_hook", None)
    if h is not None:
        h.remove()
    from ...tensor_core import Parameter

    w = layer.__dict__.pop(name, None)
    v = layer._parameters.pop(name + "_v", None)
    g = layer._parameters.pop(name + "_g", None)
    if v is not None and g is not None:
        axes = tuple(
            i for i in range(v._value.ndim)
            if v._value.shape[i] != g._value.shape[i] or g._value.shape[i] == 1
        )
        nrm = jnp.sqrt(jnp.sum(v._value ** 2, axis=axes, keepdims=True))
        layer.add_parameter(name, Parameter(g._value * v._value / nrm))
    return layer


def spectral_norm(layer, name="weight", n_power_iterations=1, eps=1e-12,
                  dim=None):
    """Power-iteration spectral normalization as a forward-pre-hook."""
    import jax

    from ...core import rng

    w = getattr(layer, name)
    wv = w._value
    if dim is None:
        dim = 0
    mat = jnp.moveaxis(wv, dim, 0).reshape(wv.shape[dim], -1)
    u = jax.random.normal(rng.next_key(), (mat.shape[0],))
    u = u / jnp.maximum(jnp.linalg.norm(u), eps)
    state = {"u": u}

    def _compute(lyr, inputs):
        wt = lyr._parameters[name]
        # power iteration runs off-tape on current values; the normalization
        # itself goes through the tape so grads flow into the parameter
        m = jnp.moveaxis(wt._value, dim, 0).reshape(wt._value.shape[dim], -1)
        u = state["u"]
        for _ in range(n_power_iterations):
            v = m.T @ u
            v = v / jnp.maximum(jnp.linalg.norm(v), eps)
            u = m @ v
            u = u / jnp.maximum(jnp.linalg.norm(u), eps)
        state["u"] = u
        from ...ops._helpers import apply_jfn

        def jfn(wval):
            mm = jnp.moveaxis(wval, dim, 0).reshape(wval.shape[dim], -1)
            sigma = u @ mm @ v
            return wval / sigma

        lyr.__dict__[name] = apply_jfn("spectral_norm", jfn, wt)
        return None

    layer.register_forward_pre_hook(_compute)
    return layer
