"""paddle_tpu.nn — layer zoo + functional (reference: python/paddle/nn/)."""
from . import functional  # noqa: F401
from . import initializer  # noqa: F401
from . import utils  # noqa: F401
from .clip import (  # noqa: F401
    ClipGradByGlobalNorm,
    ClipGradByNorm,
    ClipGradByValue,
)
from .layer.activation import *  # noqa: F401,F403
from .layer.common import *  # noqa: F401,F403
from .layer.container import *  # noqa: F401,F403
from .layer.conv import *  # noqa: F401,F403
from .layer.layers import Layer, ParamAttr  # noqa: F401
from .layer.loss import *  # noqa: F401,F403
from .layer.norm import *  # noqa: F401,F403
from .layer.pooling import *  # noqa: F401,F403
from .layer.rnn import *  # noqa: F401,F403
from .layer.transformer import *  # noqa: F401,F403

from . import layer  # noqa: F401
from .decode import BeamSearchDecoder, Decoder, dynamic_decode  # noqa: F401
from .utils import spectral_norm  # noqa: F401
from .layer import activation as _act_mod
from .layer import loss  # noqa: F401  (paddle.nn.loss legacy namespace)
from . import quant  # noqa: F401
