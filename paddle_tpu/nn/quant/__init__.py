"""paddle.nn.quant namespace (reference: python/paddle/nn/quant/) — the
quantization machinery lives in paddle_tpu/quantization; this re-exports
the layer-facing pieces under the reference's path."""
from ...quantization import (  # noqa: F401
    ImperativeQuantAware,
    PostTrainingQuantization,
    QuantizedLinear,
)

__all__ = ["ImperativeQuantAware", "PostTrainingQuantization",
           "QuantizedLinear"]
