"""paddle.nn.quant namespace (reference: python/paddle/nn/quant/) — the
quantization machinery lives in paddle_tpu/quantization; this re-exports
the layer-facing pieces under the reference's path."""
from ...quantization import (  # noqa: F401
    ImperativeQuantAware,
    PostTrainingQuantization,
    QuantizedLinear,
)
from ...quantization.runtime import (  # noqa: F401
    Int4WeightOnlyLinear,
    Int8WeightOnlyLinear,
    quantize_model_int4,
    quantize_model_int8,
)

__all__ = ["ImperativeQuantAware", "PostTrainingQuantization",
           "QuantizedLinear", "Int8WeightOnlyLinear",
           "Int4WeightOnlyLinear", "quantize_model_int8",
           "quantize_model_int4"]
