"""Weight initializers.

TPU-native replacement for the reference's initializer ops
(reference: python/paddle/fluid/initializer.py — ConstantInitializer,
NormalInitializer, XavierInitializer, MSRAInitializer, …). Those append
fill/gaussian ops to a startup program; here an initializer is a pure
function (shape, dtype, PRNG key) → jax array, evaluated at Layer
construction time.
"""
import math

import jax
import jax.numpy as jnp
import numpy as np

from ...core import dtype as dtype_mod
from ...core import rng

__all__ = [
    "Initializer",
    "Constant",
    "Normal",
    "TruncatedNormal",
    "Uniform",
    "XavierNormal",
    "XavierUniform",
    "KaimingNormal",
    "KaimingUniform",
    "Assign",
    "Orthogonal",
    "Dirac",
    "calculate_gain",
    "set_global_initializer",
]

_global_weight_init = None
_global_bias_init = None


def set_global_initializer(weight_init, bias_init=None):
    """Reference parity: fluid.set_global_initializer."""
    global _global_weight_init, _global_bias_init
    _global_weight_init = weight_init
    _global_bias_init = bias_init


def calculate_gain(nonlinearity, param=None):
    recipes = {
        "sigmoid": 1.0,
        "linear": 1.0,
        "conv1d": 1.0,
        "conv2d": 1.0,
        "conv3d": 1.0,
        "conv1d_transpose": 1.0,
        "conv2d_transpose": 1.0,
        "conv3d_transpose": 1.0,
        "tanh": 5.0 / 3,
        "relu": math.sqrt(2.0),
        "leaky_relu": math.sqrt(2.0 / (1 + (param if param is not None else 0.01) ** 2)),
        "selu": 3.0 / 4,
    }
    if nonlinearity not in recipes:
        raise ValueError(f"unsupported nonlinearity: {nonlinearity}")
    return recipes[nonlinearity]


def _fans(shape):
    shape = tuple(shape)
    if len(shape) == 0:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    # conv kernels: paddle layout [out_c, in_c, *spatial]
    receptive = int(np.prod(shape[2:]))
    return shape[1] * receptive, shape[0] * receptive


class Initializer:
    def _init(self, shape, dtype):
        raise NotImplementedError

    def __call__(self, param, block=None):
        # reference-compat path: re-initialize an existing parameter
        param._value = self._init(tuple(param._value.shape), param._value.dtype)
        return param


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def _init(self, shape, dtype):
        return jnp.full(shape, self.value, dtype_mod.convert_dtype(dtype))


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0, name=None):
        self.mean, self.std = mean, std

    def _init(self, shape, dtype):
        dtype = dtype_mod.convert_dtype(dtype)
        k = rng.next_key()
        return self.mean + self.std * jax.random.normal(k, shape, dtype)


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0, name=None):
        self.mean, self.std = mean, std

    def _init(self, shape, dtype):
        dtype = dtype_mod.convert_dtype(dtype)
        k = rng.next_key()
        return self.mean + self.std * jax.random.truncated_normal(
            k, -2.0, 2.0, shape, dtype
        )


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0, name=None):
        self.low, self.high = low, high

    def _init(self, shape, dtype):
        dtype = dtype_mod.convert_dtype(dtype)
        k = rng.next_key()
        return jax.random.uniform(k, shape, dtype, self.low, self.high)


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0, name=None):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def _init(self, shape, dtype):
        fi, fo = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        k = rng.next_key()
        return std * jax.random.normal(k, shape, dtype_mod.convert_dtype(dtype))


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0, name=None):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def _init(self, shape, dtype):
        fi, fo = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        k = rng.next_key()
        return jax.random.uniform(
            k, shape, dtype_mod.convert_dtype(dtype), -limit, limit
        )


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def _init(self, shape, dtype):
        fi, _ = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        std = gain / math.sqrt(fi)
        k = rng.next_key()
        return std * jax.random.normal(k, shape, dtype_mod.convert_dtype(dtype))


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def _init(self, shape, dtype):
        fi, _ = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        limit = gain * math.sqrt(3.0 / fi)
        k = rng.next_key()
        return jax.random.uniform(
            k, shape, dtype_mod.convert_dtype(dtype), -limit, limit
        )


class Assign(Initializer):
    def __init__(self, value, name=None):
        self.value = value

    def _init(self, shape, dtype):
        from ...tensor_core import Tensor

        v = self.value
        if isinstance(v, Tensor):
            v = v._value
        arr = jnp.asarray(v, dtype_mod.convert_dtype(dtype))
        if tuple(arr.shape) != tuple(shape):
            arr = arr.reshape(shape)
        return arr


class Orthogonal(Initializer):
    def __init__(self, gain=1.0, name=None):
        self.gain = gain

    def _init(self, shape, dtype):
        if len(shape) < 2:
            raise ValueError("Orthogonal init needs >=2 dims")
        rows = shape[0]
        cols = int(np.prod(shape[1:]))
        k = rng.next_key()
        flat = jax.random.normal(k, (max(rows, cols), min(rows, cols)), jnp.float32)
        q, r = jnp.linalg.qr(flat)
        q = q * jnp.sign(jnp.diagonal(r))
        if rows < cols:
            q = q.T
        return (self.gain * q[:rows, :cols]).reshape(shape).astype(
            dtype_mod.convert_dtype(dtype)
        )


class Dirac(Initializer):
    """Identity-preserving conv kernel init (reference: nn/initializer/dirac.py)."""

    def __init__(self, groups=1, name=None):
        self.groups = groups

    def _init(self, shape, dtype):
        if len(shape) not in (3, 4, 5):
            raise ValueError("Dirac init supports 3/4/5-D conv kernels")
        out_c, in_c = shape[0], shape[1]
        arr = np.zeros(shape, dtype=np.float32)
        per_group = out_c // self.groups
        centers = tuple(s // 2 for s in shape[2:])
        for g in range(self.groups):
            for i in range(min(per_group, in_c)):
                arr[(g * per_group + i, i) + centers] = 1.0
        return jnp.asarray(arr, dtype_mod.convert_dtype(dtype))


class Bilinear(Initializer):
    """Bilinear-upsampling kernel init for transposed convs
    (reference: python/paddle/nn/initializer/Bilinear ←
    fluid/initializer.py BilinearInitializer): each output channel gets
    the separable triangle filter that linearly interpolates."""

    def _init(self, shape, dtype):
        if len(shape) != 4:
            raise ValueError("Bilinear init expects a 4-D conv kernel")
        kh, kw = int(shape[2]), int(shape[3])
        fh, fw = (kh + 1) // 2, (kw + 1) // 2
        ch = (2 * fh - 1 - fh % 2) / (2.0 * fh)
        cw = (2 * fw - 1 - fw % 2) / (2.0 * fw)
        yy = 1 - np.abs(np.arange(kh) / fh - ch)
        xx = 1 - np.abs(np.arange(kw) / fw - cw)
        filt = np.outer(yy, xx).astype("float32")
        weight = np.zeros(shape, "float32")
        for o in range(shape[0]):
            for i in range(shape[1]):
                weight[o, i] = filt
        return jnp.asarray(weight, dtype_mod.convert_dtype(dtype))


__all__.append("Bilinear")
