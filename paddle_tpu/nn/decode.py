"""Beam-search decoding (reference: python/paddle/nn/decode.py
BeamSearchDecoder + dynamic_decode).

Design: the decode loop is host-driven (eager) — each step's cell call runs
as the usual tape ops, the beam bookkeeping is jnp on the side. This is the
idiomatic TPU split for autoregressive search: dynamic stopping lives on the
host, per-step math is compiled. (The KV-cache greedy path in
text/models/gpt.py is the fully-compiled alternative for generation.)
"""
import jax
import jax.numpy as jnp
import numpy as np

from ..tensor_core import Tensor
from ..ops._helpers import ensure_tensor, value_of
from . import functional as F

__all__ = ["Decoder", "BeamSearchDecoder", "dynamic_decode"]


class Decoder:
    """Abstract decode contract: initialize() -> (inputs, states, finished);
    step() -> (outputs, states, inputs, finished); finalize() optional."""

    def initialize(self, inits):
        raise NotImplementedError

    def step(self, time, inputs, states, **kwargs):
        raise NotImplementedError

    def finalize(self, outputs, final_states, sequence_lengths):
        raise NotImplementedError

    @property
    def tracks_own_finished(self):
        return False


class BeamSearchDecoder(Decoder):
    """Beam search over a step cell (reference decode.py:BeamSearchDecoder).

    cell: callable (inputs, states) -> (outputs, next_states); logits come
    from output_fn(outputs) (or outputs directly). Tokens are embedded with
    embedding_fn (or passed through).
    """

    def __init__(self, cell, start_token, end_token, beam_size,
                 embedding_fn=None, output_fn=None):
        self.cell = cell
        self.start_token = int(start_token)
        self.end_token = int(end_token)
        self.beam_size = int(beam_size)
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn

    # ---- layout helpers (reference: _expand_to_beam_size etc.) ----

    @staticmethod
    def tile_beam_merge_with_batch(x, beam_size):
        """[batch, ...] -> [batch*beam, ...] by tiling each sample."""
        x = ensure_tensor(x)
        v = value_of(x)
        tiled = jnp.repeat(v[:, None], beam_size, axis=1)
        return Tensor(tiled.reshape((-1,) + v.shape[1:]),
                      stop_gradient=x.stop_gradient)

    def _merge(self, v):
        return v.reshape((-1,) + v.shape[2:])

    def _split(self, v):
        return v.reshape((-1, self.beam_size) + v.shape[1:])

    def initialize(self, initial_cell_states):
        states = jax.tree.map(
            lambda t: self._merge(jnp.repeat(
                value_of(ensure_tensor(t))[:, None], self.beam_size, 1)),
            initial_cell_states)
        batch = jax.tree.leaves(states)[0].shape[0] // self.beam_size
        # beam 0 live, the rest dead at start so step 0 picks distinct tokens
        log_probs = jnp.tile(
            jnp.asarray([0.0] + [-1e9] * (self.beam_size - 1),
                        jnp.float32)[None], (batch, 1))
        finished = jnp.zeros((batch, self.beam_size), bool)
        tokens = jnp.full((batch * self.beam_size,), self.start_token,
                          jnp.int64)
        inputs = Tensor(tokens, stop_gradient=True)
        if self.embedding_fn is not None:
            inputs = self.embedding_fn(inputs)
        return inputs, (states, log_probs, finished), \
            Tensor(finished, stop_gradient=True)

    def step(self, time, inputs, states, **kwargs):
        cell_states, log_probs, finished = states
        wrapped = jax.tree.map(
            lambda v: Tensor(v, stop_gradient=True), cell_states)
        outputs, next_cell = self.cell(inputs, wrapped)
        logits = self.output_fn(outputs) if self.output_fn else outputs
        lv = value_of(ensure_tensor(logits)).astype(jnp.float32)
        vocab = lv.shape[-1]
        batch = lv.shape[0] // self.beam_size
        step_lp = jax.nn.log_softmax(lv, -1).reshape(
            (batch, self.beam_size, vocab))
        # finished beams emit only end_token, at no extra cost
        noend = jnp.full((vocab,), -1e9, jnp.float32).at[
            self.end_token].set(0.0)
        step_lp = jnp.where(finished[..., None], noend, step_lp)
        total = log_probs[..., None] + step_lp           # [b, beam, V]
        flat = total.reshape((batch, self.beam_size * vocab))
        top_scores, top_idx = jax.lax.top_k(flat, self.beam_size)
        parent = (top_idx // vocab).astype(jnp.int64)    # [b, beam]
        token = (top_idx % vocab).astype(jnp.int64)
        binc = jnp.arange(batch)[:, None]
        next_finished = finished[binc, parent] | (token == self.end_token)
        next_cell_v = jax.tree.map(
            lambda t: self._merge(self._split(
                value_of(ensure_tensor(t)))[binc, parent]), next_cell)
        next_inputs = Tensor(token.reshape(-1), stop_gradient=True)
        if self.embedding_fn is not None:
            next_inputs = self.embedding_fn(next_inputs)
        step_outputs = (top_scores, token, parent)
        return (step_outputs, (next_cell_v, top_scores, next_finished),
                next_inputs, Tensor(next_finished, stop_gradient=True))

    def finalize(self, outputs, final_states, sequence_lengths):
        scores, predicted_ids, parent_ids = outputs
        # [T, batch, beam] backtrace (reference calls gather_tree too)
        seqs = F.gather_tree(Tensor(predicted_ids, stop_gradient=True),
                             Tensor(parent_ids, stop_gradient=True))
        return seqs, final_states


def dynamic_decode(decoder, inits=None, max_step_num=None,
                   output_time_major=False, impute_finished=False,
                   is_test=False, return_length=False, **kwargs):
    """Run decoder.step until every sequence finishes or max_step_num
    (reference: decode.py dynamic_decode)."""
    inputs, states, finished = decoder.initialize(inits)
    fin = value_of(ensure_tensor(finished))
    seq_len = jnp.zeros(fin.shape, jnp.int64)
    scores_acc, ids_acc, parents_acc = [], [], []
    time = 0
    while True:
        if max_step_num is not None and time >= max_step_num:
            break
        step_out, states, inputs, finished = decoder.step(
            time, inputs, states, **kwargs)
        scores, token, parent = step_out
        scores_acc.append(scores)
        ids_acc.append(token)
        parents_acc.append(parent)
        prev_fin = fin
        fin = value_of(ensure_tensor(finished))
        seq_len = seq_len + (~prev_fin).astype(jnp.int64)
        time += 1
        if bool(np.asarray(fin).all()):
            break
    outputs = (jnp.stack(scores_acc), jnp.stack(ids_acc),
               jnp.stack(parents_acc))
    try:
        final, final_states = decoder.finalize(outputs, states, seq_len)
    except NotImplementedError:
        final, final_states = (
            Tensor(outputs[1], stop_gradient=True), states)
    if not output_time_major and isinstance(final, Tensor):
        final = Tensor(jnp.moveaxis(value_of(final), 0, 1),
                       stop_gradient=True)
    rets = (final, final_states)
    if return_length:
        rets = rets + (Tensor(seq_len, stop_gradient=True),)
    return rets
