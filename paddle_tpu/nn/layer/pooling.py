"""Pooling layers (reference: python/paddle/nn/layer/pooling.py)."""
from .. import functional as F
from .layers import Layer

__all__ = [
    "MaxPool1D", "MaxPool2D", "MaxPool3D",
    "MaxUnPool1D", "MaxUnPool2D", "MaxUnPool3D",
    "AvgPool1D", "AvgPool2D", "AvgPool3D",
    "AdaptiveAvgPool1D", "AdaptiveAvgPool2D", "AdaptiveAvgPool3D",
    "AdaptiveMaxPool1D", "AdaptiveMaxPool2D", "AdaptiveMaxPool3D",
]


class _Pool(Layer):
    def __init__(self, kernel_size, stride, padding, ceil_mode, data_format,
                 **kw):
        super().__init__()
        self.ksize = kernel_size
        self.stride = stride
        self.padding = padding
        self.ceil_mode = ceil_mode
        self.data_format = data_format
        self.kw = kw

    def extra_repr(self):
        return f"kernel_size={self.ksize}, stride={self.stride}, padding={self.padding}"


class MaxPool1D(_Pool):
    def __init__(self, kernel_size, stride=None, padding=0, return_mask=False,
                 ceil_mode=False, name=None):
        super().__init__(kernel_size, stride, padding, ceil_mode, "NCL",
                         return_mask=return_mask)

    def forward(self, x):
        return F.max_pool1d(x, self.ksize, self.stride, self.padding,
                            return_mask=self.kw["return_mask"],
                            ceil_mode=self.ceil_mode)


class MaxPool2D(_Pool):
    def __init__(self, kernel_size, stride=None, padding=0, return_mask=False,
                 ceil_mode=False, data_format="NCHW", name=None):
        super().__init__(kernel_size, stride, padding, ceil_mode, data_format,
                         return_mask=return_mask)

    def forward(self, x):
        return F.max_pool2d(x, self.ksize, self.stride, self.padding,
                            return_mask=self.kw["return_mask"],
                            ceil_mode=self.ceil_mode,
                            data_format=self.data_format)


class MaxPool3D(_Pool):
    def __init__(self, kernel_size, stride=None, padding=0, return_mask=False,
                 ceil_mode=False, data_format="NCDHW", name=None):
        super().__init__(kernel_size, stride, padding, ceil_mode, data_format,
                         return_mask=return_mask)

    def forward(self, x):
        return F.max_pool3d(x, self.ksize, self.stride, self.padding,
                            return_mask=self.kw["return_mask"],
                            ceil_mode=self.ceil_mode,
                            data_format=self.data_format)


class AvgPool1D(_Pool):
    def __init__(self, kernel_size, stride=None, padding=0, exclusive=True,
                 ceil_mode=False, name=None):
        super().__init__(kernel_size, stride, padding, ceil_mode, "NCL",
                         exclusive=exclusive)

    def forward(self, x):
        return F.avg_pool1d(x, self.ksize, self.stride, self.padding,
                            exclusive=self.kw["exclusive"],
                            ceil_mode=self.ceil_mode)


class AvgPool2D(_Pool):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 exclusive=True, divisor_override=None, data_format="NCHW",
                 name=None):
        super().__init__(kernel_size, stride, padding, ceil_mode, data_format,
                         exclusive=exclusive)

    def forward(self, x):
        return F.avg_pool2d(x, self.ksize, self.stride, self.padding,
                            ceil_mode=self.ceil_mode,
                            exclusive=self.kw["exclusive"],
                            data_format=self.data_format)


class AvgPool3D(_Pool):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 exclusive=True, divisor_override=None, data_format="NCDHW",
                 name=None):
        super().__init__(kernel_size, stride, padding, ceil_mode, data_format,
                         exclusive=exclusive)

    def forward(self, x):
        return F.avg_pool3d(x, self.ksize, self.stride, self.padding,
                            ceil_mode=self.ceil_mode,
                            exclusive=self.kw["exclusive"],
                            data_format=self.data_format)


class _AdaptivePool(Layer):
    def __init__(self, output_size, data_format=None):
        super().__init__()
        self._output_size = output_size
        self._data_format = data_format


class AdaptiveAvgPool1D(_AdaptivePool):
    def forward(self, x):
        return F.adaptive_avg_pool1d(x, self._output_size)


class AdaptiveAvgPool2D(_AdaptivePool):
    def __init__(self, output_size, data_format="NCHW", name=None):
        super().__init__(output_size, data_format)

    def forward(self, x):
        return F.adaptive_avg_pool2d(x, self._output_size, self._data_format)


class AdaptiveAvgPool3D(_AdaptivePool):
    def __init__(self, output_size, data_format="NCDHW", name=None):
        super().__init__(output_size, data_format)

    def forward(self, x):
        return F.adaptive_avg_pool3d(x, self._output_size, self._data_format)


class AdaptiveMaxPool1D(_AdaptivePool):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__(output_size)

    def forward(self, x):
        return F.adaptive_max_pool1d(x, self._output_size)


class AdaptiveMaxPool2D(_AdaptivePool):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__(output_size)

    def forward(self, x):
        return F.adaptive_max_pool2d(x, self._output_size)


class AdaptiveMaxPool3D(_AdaptivePool):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__(output_size)

    def forward(self, x):
        return F.adaptive_max_pool3d(x, self._output_size)


class MaxUnPool1D(Layer):
    """Inverse of MaxPool1D given the pooling mask
    (reference: python/paddle/nn/layer/pooling.py MaxUnPool1D)."""

    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NCL", output_size=None, name=None):
        super().__init__()
        self.ksize = kernel_size
        self.stride = stride
        self.padding = padding
        self.data_format = data_format
        self.output_size = output_size

    def forward(self, x, indices):
        return F.max_unpool1d(x, indices, self.ksize, self.stride,
                              self.padding, self.data_format,
                              self.output_size)


class MaxUnPool2D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NCHW", output_size=None, name=None):
        super().__init__()
        self.ksize = kernel_size
        self.stride = stride
        self.padding = padding
        self.data_format = data_format
        self.output_size = output_size

    def forward(self, x, indices):
        return F.max_unpool2d(x, indices, self.ksize, self.stride,
                              self.padding, self.data_format,
                              self.output_size)


class MaxUnPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NCDHW", output_size=None, name=None):
        super().__init__()
        self.ksize = kernel_size
        self.stride = stride
        self.padding = padding
        self.data_format = data_format
        self.output_size = output_size

    def forward(self, x, indices):
        return F.max_unpool3d(x, indices, self.ksize, self.stride,
                              self.padding, self.data_format,
                              self.output_size)
