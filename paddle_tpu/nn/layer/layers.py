"""Layer: the module base class.

TPU-native re-design of the reference's dygraph Layer
(reference: python/paddle/fluid/dygraph/layers.py `Layer`). Parameters are
jax arrays held in `Parameter` tensors; there is no LayerHelper/program —
construction allocates arrays eagerly via initializers and forward runs on
the autograd tape. The pytree of parameters is what jitted train steps and
pjit shardings consume (`Layer.raw_state_dict`).
"""
import collections

import numpy as np

from ...core import dtype as dtype_mod
from ...tensor_core import Parameter, Tensor
from .. import initializer as init_mod

__all__ = ["Layer"]


class HookRemoveHelper:
    next_id = 0

    def __init__(self, hooks):
        self._hooks = hooks
        self._id = HookRemoveHelper.next_id
        HookRemoveHelper.next_id += 1

    def remove(self):
        self._hooks.pop(self._id, None)


class Layer:
    """Base class for all neural network layers (paddle.nn.Layer parity)."""

    def __init__(self, name_scope=None, dtype="float32"):
        self.training = True
        if name_scope is None:
            name_scope = _camel_to_snake(self.__class__.__name__)
        self._full_name = _unique_name(name_scope)
        self._dtype = dtype_mod.convert_dtype(dtype) if dtype is not None else None
        self._parameters = collections.OrderedDict()
        self._sub_layers = collections.OrderedDict()
        self._buffers = collections.OrderedDict()
        self._non_persistable_buffer_names_set = set()
        self._forward_pre_hooks = collections.OrderedDict()
        self._forward_post_hooks = collections.OrderedDict()
        self._casted_by_pure_fp16 = False

    # ---- mode ----
    def train(self):
        self.training = True
        for l in self.sublayers():
            l.training = True
        return self

    def eval(self):
        self.training = False
        for l in self.sublayers():
            l.training = False
        return self

    def full_name(self):
        return self._full_name

    # ---- parameter/buffer creation ----
    def create_parameter(
        self,
        shape,
        attr=None,
        dtype=None,
        is_bias=False,
        default_initializer=None,
    ):
        """Create and register an initialized Parameter.

        `attr` is a ParamAttr (or False to skip: returns None, used for
        optional biases — mirroring reference bias_attr=False).
        """
        if attr is False:
            return None
        attr = ParamAttr._to_attr(attr)
        dtype = dtype_mod.convert_dtype(dtype or self._dtype or "float32")
        initializer = attr.initializer or default_initializer
        if initializer is None:
            initializer = (
                init_mod.Constant(0.0) if is_bias else init_mod.XavierUniform()
            )
        value = initializer._init(tuple(int(s) for s in shape), dtype)
        p = Parameter(value, trainable=attr.trainable, name=attr.name)
        p.optimize_attr["learning_rate"] = attr.learning_rate
        p.regularizer = attr.regularizer
        p.need_clip = attr.need_clip
        return p

    def create_tensor(self, name=None, persistable=None, dtype=None):
        import jax.numpy as jnp

        t = Tensor(jnp.zeros([], dtype_mod.convert_dtype(dtype or "float32")), name=name)
        t.persistable = bool(persistable)
        return t

    def register_buffer(self, name, tensor, persistable=True):
        if tensor is not None and not isinstance(tensor, Tensor):
            tensor = Tensor(tensor)
        self._buffers[name] = tensor
        if persistable:
            self._non_persistable_buffer_names_set.discard(name)
        else:
            self._non_persistable_buffer_names_set.add(name)
        return tensor

    def add_parameter(self, name, parameter):
        if parameter is not None and not isinstance(parameter, Parameter):
            raise TypeError(
                f"parameter {name} must be a Parameter, got {type(parameter)}"
            )
        self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name, sublayer):
        if sublayer is not None and not isinstance(sublayer, Layer):
            raise TypeError(f"sublayer {name} must be a Layer")
        self._sub_layers[str(name)] = sublayer
        return sublayer

    # ---- traversal ----
    def parameters(self, include_sublayers=True):
        return [p for _, p in self.named_parameters(include_sublayers=include_sublayers)]

    def named_parameters(self, prefix="", include_sublayers=True):
        seen = set()
        for name, layer in self.named_sublayers(
            prefix=prefix, include_self=True
        ) if include_sublayers else [(prefix, self)]:
            for pname, p in layer._parameters.items():
                if p is None or id(p) in seen:
                    continue
                seen.add(id(p))
                yield (name + "." + pname if name else pname), p

    def buffers(self, include_sublayers=True):
        return [b for _, b in self.named_buffers(include_sublayers=include_sublayers)]

    def named_buffers(self, prefix="", include_sublayers=True):
        seen = set()
        for name, layer in self.named_sublayers(
            prefix=prefix, include_self=True
        ) if include_sublayers else [(prefix, self)]:
            for bname, b in layer._buffers.items():
                if b is None or id(b) in seen:
                    continue
                seen.add(id(b))
                yield (name + "." + bname if name else bname), b

    def children(self):
        return [l for _, l in self.named_children()]

    def named_children(self):
        for name, layer in self._sub_layers.items():
            if layer is not None:
                yield name, layer

    def sublayers(self, include_self=False):
        return [l for _, l in self.named_sublayers(include_self=include_self)]

    def named_sublayers(self, prefix="", include_self=False, layers_set=None):
        if layers_set is None:
            layers_set = set()
        if include_self and id(self) not in layers_set:
            layers_set.add(id(self))
            yield prefix, self
        for name, layer in self.named_children():
            if id(layer) in layers_set:
                continue
            layers_set.add(id(layer))
            sub_prefix = prefix + "." + name if prefix else name
            yield sub_prefix, layer
            yield from layer.named_sublayers(
                prefix=sub_prefix, include_self=False, layers_set=layers_set
            )

    def apply(self, fn):
        for l in self.children():
            l.apply(fn)
        fn(self)
        return self

    # ---- hooks ----
    def register_forward_pre_hook(self, hook):
        helper = HookRemoveHelper(self._forward_pre_hooks)
        self._forward_pre_hooks[helper._id] = hook
        return helper

    def register_forward_post_hook(self, hook):
        helper = HookRemoveHelper(self._forward_post_hooks)
        self._forward_post_hooks[helper._id] = hook
        return helper

    # ---- call ----
    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def __call__(self, *inputs, **kwargs):
        for hook in list(self._forward_pre_hooks.values()):
            out = hook(self, inputs)
            if out is not None:
                inputs = out if isinstance(out, tuple) else (out,)
        outputs = self.forward(*inputs, **kwargs)
        for hook in list(self._forward_post_hooks.values()):
            res = hook(self, inputs, outputs)
            if res is not None:
                outputs = res
        return outputs

    # ---- state dict ----
    def state_dict(self, destination=None, include_sublayers=True, use_hook=True):
        if destination is None:
            destination = collections.OrderedDict()
        for name, p in self.named_parameters(include_sublayers=include_sublayers):
            destination[name] = p
        for name, b in self.named_buffers(include_sublayers=include_sublayers):
            if _buffer_persistable(self, name):
                destination[name] = b
        return destination

    def _all_entries(self):
        """name → holder mapping for both parameters and persistable buffers."""
        entries = {}
        for prefix, layer in self.named_sublayers(include_self=True):
            for pname, p in layer._parameters.items():
                if p is not None:
                    entries[prefix + "." + pname if prefix else pname] = (
                        layer,
                        "_parameters",
                        pname,
                    )
            for bname, b in layer._buffers.items():
                if b is not None:
                    entries[prefix + "." + bname if prefix else bname] = (
                        layer,
                        "_buffers",
                        bname,
                    )
        return entries

    def set_state_dict(self, state_dict, use_structured_name=True):
        """Load values into existing parameters/buffers (shape-checked)."""
        import jax.numpy as jnp

        entries = self._all_entries()
        missing, unexpected = [], []
        for name, value in state_dict.items():
            if name not in entries:
                unexpected.append(name)
                continue
            layer, store, key = entries[name]
            target = getattr(layer, store)[key]
            # COPY on ingest, both branches: loaded state must own its
            # buffers. np.asarray(jax_cpu_array) and jnp.asarray(
            # np_view) are both zero-copy on CPU, so a state_dict() ->
            # .numpy() -> set_state_dict round-trip would hand two
            # models ONE buffer — and a DONATING compiled step then
            # updates the "independent" copy's params in place
            # (root-caused as the dp-equivalence/zero2 divergence;
            # docs/RESILIENCE.md "Buffer aliasing"). The direct
            # Tensor->Tensor route shares the same hazard through the
            # Array OBJECT. jnp.array(copy=True) preserves sharding
            # AND commitment (verified), so jit signatures don't flip;
            # numpy input copies at the host level as before.
            if isinstance(value, Tensor):
                arr = jnp.array(value._value, copy=True)
            elif hasattr(value, "sharding"):
                # raw jax.Array (the load_raw_state_dict route): the
                # host-level np.array round-trip would collapse a
                # sharded array to one device (the PTL602 drift class)
                # — copy on-device instead, sharding/commitment kept
                arr = jnp.array(value, copy=True)
            else:
                arr = jnp.asarray(np.array(value))
            if tuple(arr.shape) != tuple(target._value.shape):
                raise ValueError(
                    f"shape mismatch for {name}: loaded {tuple(arr.shape)} vs "
                    f"expected {tuple(target._value.shape)}"
                )
            target._value = jnp.asarray(arr, target._value.dtype)
        for name in entries:
            if name not in state_dict:
                missing.append(name)
        return missing, unexpected

    load_dict = set_state_dict

    def raw_state_dict(self):
        """Pure-pytree view: name → jax.Array. Feed this to jit/pjit."""
        return {k: v._value for k, v in self.state_dict().items()}

    def load_raw_state_dict(self, tree):
        self.set_state_dict(tree)

    # ---- dtype / device movement ----
    def to(self, device=None, dtype=None, blocking=None):
        if dtype is not None:
            self._transform_dtype(dtype_mod.convert_dtype(dtype))
        return self

    def astype(self, dtype):
        self._transform_dtype(dtype_mod.convert_dtype(dtype))
        return self

    def _transform_dtype(self, dtype):
        import jax.numpy as jnp

        for _, p in self.named_parameters():
            if np.issubdtype(np.dtype(p._value.dtype), np.floating):
                p._value = jnp.asarray(p._value, dtype)
        for _, b in self.named_buffers():
            if np.issubdtype(np.dtype(b._value.dtype), np.floating):
                b._value = jnp.asarray(b._value, dtype)
        self._dtype = dtype

    def float(self):
        return self.astype("float32")

    def bfloat16(self):
        return self.astype("bfloat16")

    # ---- attribute magic ----
    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        layers = self.__dict__.get("_sub_layers")
        buffers = self.__dict__.get("_buffers")
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError("call Layer.__init__ before assigning parameters")
            _strip(self, name, layers, buffers)
            params[name] = value
        elif isinstance(value, Layer):
            if layers is None:
                raise RuntimeError("call Layer.__init__ before assigning sublayers")
            _strip(self, name, params, buffers)
            layers[name] = value
        elif params is not None and name in params:
            if value is None:
                params[name] = None
            elif isinstance(value, Tensor):
                params[name]._value = value._value
            else:
                raise TypeError(f"cannot assign {type(value)} to parameter {name}")
        elif buffers is not None and name in buffers:
            if value is None or isinstance(value, Tensor):
                buffers[name] = value
            else:
                import jax.numpy as jnp

                buffers[name] = Tensor(jnp.asarray(value))
        else:
            object.__setattr__(self, name, value)

    def __getattr__(self, name):
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(
            f"'{self.__class__.__name__}' object has no attribute '{name}'"
        )

    def __delattr__(self, name):
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                del d[name]
                return
        object.__delattr__(self, name)

    def __dir__(self):
        return (
            list(super().__dir__())
            + list(self._parameters)
            + list(self._sub_layers)
            + list(self._buffers)
        )

    def extra_repr(self):
        return ""

    def __repr__(self):
        extra = self.extra_repr()
        lines = []
        for name, layer in self._sub_layers.items():
            mod_str = repr(layer)
            mod_str = _addindent(mod_str, 2)
            lines.append(f"({name}): {mod_str}")
        main = self.__class__.__name__ + "("
        if extra:
            main += extra
        if lines:
            main += "\n  " + "\n  ".join(lines) + "\n"
        return main + ")"


def _strip(layer, name, *stores):
    layer.__dict__.pop(name, None)
    for s in stores:
        if s is not None:
            s.pop(name, None)


def _buffer_persistable(root, qualified_name):
    parts = qualified_name.split(".")
    layer = root
    for p in parts[:-1]:
        layer = layer._sub_layers.get(p)
        if layer is None:
            return True
    return parts[-1] not in layer._non_persistable_buffer_names_set


def _addindent(s, n):
    lines = s.split("\n")
    if len(lines) == 1:
        return s
    return lines[0] + "\n" + "\n".join(" " * n + l for l in lines[1:])


def _unique_name(base):
    # ONE counter owns naming: utils.unique_name.guard()/switch() must
    # scope layer names too (reference fluid/unique_name.py)
    from ...utils import unique_name as _un

    return _un.generate(base)


def _camel_to_snake(name):
    out = []
    for i, ch in enumerate(name):
        if ch.isupper() and i > 0:
            out.append("_")
        out.append(ch.lower())
    return "".join(out)


class ParamAttr:
    """Parameter attribute bundle (reference: python/paddle/fluid/param_attr.py)."""

    def __init__(
        self,
        name=None,
        initializer=None,
        learning_rate=1.0,
        regularizer=None,
        trainable=True,
        need_clip=True,
    ):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.need_clip = need_clip

    @staticmethod
    def _to_attr(attr):
        if attr is None:
            return ParamAttr()
        if isinstance(attr, ParamAttr):
            return attr
        if isinstance(attr, str):
            return ParamAttr(name=attr)
        if isinstance(attr, init_mod.Initializer):
            return ParamAttr(initializer=attr)
        raise TypeError(f"cannot convert {type(attr)} to ParamAttr")
