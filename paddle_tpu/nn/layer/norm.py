"""Normalization layers (reference: python/paddle/nn/layer/norm.py).

BatchNorm running stats are buffers updated eagerly; under a jitted train
step use `paddle_tpu.jit`'s functional train-step capture which threads
buffer state explicitly.
"""
import numpy as np

import jax.numpy as jnp

from .. import functional as F
from .. import initializer as I
from .layers import Layer

__all__ = [
    "SpectralNorm",
    "BatchNorm", "BatchNorm1D", "BatchNorm2D", "BatchNorm3D",
    "SyncBatchNorm", "LayerNorm", "GroupNorm",
    "InstanceNorm1D", "InstanceNorm2D", "InstanceNorm3D",
    "LocalResponseNorm",
]


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 use_global_stats=None, name=None):
        super().__init__()
        self._num_features = num_features
        self._momentum = momentum
        self._epsilon = epsilon
        self._data_format = data_format
        self._use_global_stats = use_global_stats
        if weight_attr is False:
            self.weight = None
        else:
            self.weight = self.create_parameter(
                shape=[num_features], attr=weight_attr,
                default_initializer=I.Constant(1.0),
            )
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter(
                shape=[num_features], attr=bias_attr, is_bias=True,
            )
        import jax.numpy as jnp

        self.register_buffer("_mean", jnp.zeros([num_features], "float32"))
        self.register_buffer("_variance", jnp.ones([num_features], "float32"))

    def forward(self, input):
        return F.batch_norm(
            input, self._mean, self._variance, self.weight, self.bias,
            training=self.training, momentum=self._momentum,
            epsilon=self._epsilon, data_format=self._data_format,
            use_global_stats=self._use_global_stats,
        )

    def extra_repr(self):
        return f"num_features={self._num_features}, momentum={self._momentum}"


class BatchNorm(_BatchNormBase):
    """Legacy fluid.dygraph.BatchNorm-compatible signature."""

    def __init__(self, num_channels, act=None, momentum=0.9, epsilon=1e-5,
                 param_attr=None, bias_attr=None, dtype="float32",
                 data_layout="NCHW", in_place=False, moving_mean_name=None,
                 moving_variance_name=None, do_model_average_for_mean_and_var=True,
                 use_global_stats=False, trainable_statistics=False):
        super().__init__(num_channels, momentum, epsilon, param_attr,
                         bias_attr, data_layout,
                         use_global_stats if use_global_stats else None)
        self._act = act

    def forward(self, input):
        out = super().forward(input)
        if self._act:
            out = getattr(F, self._act)(out)
        return out


class BatchNorm1D(_BatchNormBase):
    pass


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCDHW",
                 use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr,
                         bias_attr, data_format, use_global_stats)


class SyncBatchNorm(_BatchNormBase):
    """Cross-replica BN. Under pjit/SPMD the batch axis is sharded and XLA
    turns the mean/var reductions into cross-replica collectives
    automatically, so the implementation is the plain batch_norm — this class
    exists for API parity (reference: python/paddle/nn/layer/norm.py
    SyncBatchNorm, which needs explicit NCCL allreduce of stats)."""

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        out = layer
        if isinstance(layer, _BatchNormBase) and not isinstance(layer, cls):
            out = cls(layer._num_features, layer._momentum, layer._epsilon,
                      None, None, layer._data_format)
            out.weight = layer.weight
            out.bias = layer.bias
            out._buffers.update(layer._buffers)
        for name, sub in list(layer._sub_layers.items()):
            out._sub_layers[name] = cls.convert_sync_batchnorm(sub)
        return out


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self._normalized_shape = list(normalized_shape)
        self._epsilon = epsilon
        if weight_attr is False:
            self.weight = None
        else:
            self.weight = self.create_parameter(
                shape=self._normalized_shape, attr=weight_attr,
                default_initializer=I.Constant(1.0),
            )
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter(
                shape=self._normalized_shape, attr=bias_attr, is_bias=True,
            )

    def forward(self, input):
        return F.layer_norm(input, self._normalized_shape, self.weight,
                            self.bias, self._epsilon)

    def extra_repr(self):
        return f"normalized_shape={self._normalized_shape}"


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self._num_groups = num_groups
        self._num_channels = num_channels
        self._epsilon = epsilon
        self._data_format = data_format
        self.weight = (
            None if weight_attr is False else self.create_parameter(
                shape=[num_channels], attr=weight_attr,
                default_initializer=I.Constant(1.0),
            )
        )
        self.bias = (
            None if bias_attr is False else self.create_parameter(
                shape=[num_channels], attr=bias_attr, is_bias=True,
            )
        )

    def forward(self, input):
        return F.group_norm(input, self._num_groups, self._epsilon,
                            self.weight, self.bias, self._data_format)


class _InstanceNormBase(Layer):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self._epsilon = epsilon
        if weight_attr is False:
            self.scale = None
            self.bias = None
        else:
            self.scale = self.create_parameter(
                shape=[num_features], attr=weight_attr,
                default_initializer=I.Constant(1.0),
            )
            self.bias = self.create_parameter(
                shape=[num_features], attr=bias_attr, is_bias=True,
            )

    def forward(self, input):
        return F.instance_norm(input, weight=self.scale, bias=self.bias,
                               eps=self._epsilon)


class InstanceNorm1D(_InstanceNormBase):
    pass


class InstanceNorm2D(_InstanceNormBase):
    pass


class InstanceNorm3D(_InstanceNormBase):
    pass


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=1e-4, beta=0.75, k=1.0,
                 data_format="NCHW", name=None):
        super().__init__()
        self.size = size
        self.alpha = alpha
        self.beta = beta
        self.k = k
        self.data_format = data_format

    def forward(self, input):
        return F.local_response_norm(input, self.size, self.alpha, self.beta,
                                     self.k, self.data_format)


class SpectralNorm(Layer):
    """Spectral normalization of a WEIGHT tensor via power iteration
    (reference: python/paddle/nn/layer/norm.py SpectralNorm; phi kernel
    spectral_norm_kernel). forward(weight) -> weight / sigma, with
    persistent u/v direction buffers updated per call."""

    def __init__(self, weight_shape, dim=0, power_iters=1, eps=1e-12,
                 dtype="float32"):
        super().__init__()
        import numpy as _np

        self._dim = dim
        self._power_iters = power_iters
        self._eps = eps
        h = int(weight_shape[dim])
        w = int(_np.prod(weight_shape)) // h
        self._h, self._w = h, w
        from ...core import dtype as _dtype_mod
        from ...tensor_core import Tensor as _T

        dt = _dtype_mod.convert_dtype(dtype)
        rng = _np.random.default_rng(0)
        u = rng.standard_normal(h).astype(_np.float32)
        v = rng.standard_normal(w).astype(_np.float32)
        self.register_buffer(
            "weight_u",
            _T(jnp.asarray(u / (_np.linalg.norm(u) + eps), dt)))
        self.register_buffer(
            "weight_v",
            _T(jnp.asarray(v / (_np.linalg.norm(v) + eps), dt)))

    def forward(self, weight):
        from ...ops._helpers import apply_jfn, ensure_tensor, value_of

        weight = ensure_tensor(weight)
        dim, h, w, eps = self._dim, self._h, self._w, self._eps
        u0 = value_of(self.weight_u)
        v0 = value_of(self.weight_v)
        iters = self._power_iters

        def jfn(wt):
            perm = (dim,) + tuple(i for i in range(wt.ndim) if i != dim)
            m = jnp.transpose(wt, perm).reshape(h, w)
            u, v = u0, v0
            for _ in range(iters):
                v = m.T @ u
                v = v / (jnp.linalg.norm(v) + eps)
                u = m @ v
                u = u / (jnp.linalg.norm(u) + eps)
            sigma = u @ m @ v
            return wt / sigma, u, v

        out, u_new, v_new = apply_jfn("spectral_norm", jfn, weight)
        self.weight_u._value = value_of(u_new)
        self.weight_v._value = value_of(v_new)
        return out
