"""Recurrent layers (reference: python/paddle/nn/layer/rnn.py).

TPU-native design: the time loop is `lax.scan` — a single compiled loop XLA
can pipeline — rather than the reference's per-step cuDNN calls or python
loops. The whole (layers × directions) stack runs as one tape op so
backward is one vjp through the scans.
"""
import math

import jax
import jax.numpy as jnp
from jax import lax

from ...ops._helpers import apply_jfn, ensure_tensor
from .. import initializer as I
from .layers import Layer, ParamAttr

__all__ = [
    "RNNCellBase", "SimpleRNNCell", "LSTMCell", "GRUCell", "RNN", "BiRNN",
    "SimpleRNN", "LSTM", "GRU",
]


def _std_uniform(hidden_size):
    k = 1.0 / math.sqrt(hidden_size)
    return I.Uniform(-k, k)


class RNNCellBase(Layer):
    def get_initial_states(self, batch_ref, shape=None, dtype="float32",
                           init_value=0.0, batch_dim_idx=0):
        from ...ops import creation

        batch = batch_ref.shape[batch_dim_idx]
        shape = shape or self.state_shape
        if isinstance(shape[0], (list, tuple)):
            return tuple(
                creation.full([batch] + list(s), init_value, dtype)
                for s in shape
            )
        return creation.full([batch] + list(shape), init_value, dtype)


class SimpleRNNCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, activation="tanh",
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__()
        init = _std_uniform(hidden_size)
        self.weight_ih = self.create_parameter(
            [hidden_size, input_size], weight_ih_attr, default_initializer=init)
        self.weight_hh = self.create_parameter(
            [hidden_size, hidden_size], weight_hh_attr, default_initializer=init)
        self.bias_ih = self.create_parameter(
            [hidden_size], bias_ih_attr, is_bias=True, default_initializer=init)
        self.bias_hh = self.create_parameter(
            [hidden_size], bias_hh_attr, is_bias=True, default_initializer=init)
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.activation = activation
        self._act = jnp.tanh if activation == "tanh" else jax.nn.relu

    @property
    def state_shape(self):
        return (self.hidden_size,)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        act = self._act

        def jfn(x, h, wi, wh, bi, bh):
            out = act(x @ wi.T + bi + h @ wh.T + bh)
            return out, out

        out, h = apply_jfn("simple_rnn_cell", jfn, ensure_tensor(inputs),
                           ensure_tensor(states), self.weight_ih,
                           self.weight_hh, self.bias_ih, self.bias_hh)
        return out, h


class LSTMCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        init = _std_uniform(hidden_size)
        self.weight_ih = self.create_parameter(
            [4 * hidden_size, input_size], weight_ih_attr,
            default_initializer=init)
        self.weight_hh = self.create_parameter(
            [4 * hidden_size, hidden_size], weight_hh_attr,
            default_initializer=init)
        self.bias_ih = self.create_parameter(
            [4 * hidden_size], bias_ih_attr, is_bias=True,
            default_initializer=init)
        self.bias_hh = self.create_parameter(
            [4 * hidden_size], bias_hh_attr, is_bias=True,
            default_initializer=init)
        self.input_size = input_size
        self.hidden_size = hidden_size

    @property
    def state_shape(self):
        return ((self.hidden_size,), (self.hidden_size,))

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs, self.state_shape)
        h, c = states

        def jfn(x, hv, cv, wi, wh, bi, bh):
            gates = x @ wi.T + bi + hv @ wh.T + bh
            i, f, g, o = jnp.split(gates, 4, axis=-1)
            i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
            g = jnp.tanh(g)
            new_c = f * cv + i * g
            new_h = o * jnp.tanh(new_c)
            return new_h, new_h, new_c

        out, new_h, new_c = apply_jfn(
            "lstm_cell", jfn, ensure_tensor(inputs), ensure_tensor(h),
            ensure_tensor(c), self.weight_ih, self.weight_hh, self.bias_ih,
            self.bias_hh)
        return out, (new_h, new_c)


class GRUCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        init = _std_uniform(hidden_size)
        self.weight_ih = self.create_parameter(
            [3 * hidden_size, input_size], weight_ih_attr,
            default_initializer=init)
        self.weight_hh = self.create_parameter(
            [3 * hidden_size, hidden_size], weight_hh_attr,
            default_initializer=init)
        self.bias_ih = self.create_parameter(
            [3 * hidden_size], bias_ih_attr, is_bias=True,
            default_initializer=init)
        self.bias_hh = self.create_parameter(
            [3 * hidden_size], bias_hh_attr, is_bias=True,
            default_initializer=init)
        self.input_size = input_size
        self.hidden_size = hidden_size

    @property
    def state_shape(self):
        return (self.hidden_size,)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)

        def jfn(x, h, wi, wh, bi, bh):
            xg = x @ wi.T + bi
            hg = h @ wh.T + bh
            xr, xz, xn = jnp.split(xg, 3, axis=-1)
            hr, hz, hn = jnp.split(hg, 3, axis=-1)
            r = jax.nn.sigmoid(xr + hr)
            z = jax.nn.sigmoid(xz + hz)
            n = jnp.tanh(xn + r * hn)
            out = (1 - z) * n + z * h
            return out, out

        out, h = apply_jfn("gru_cell", jfn, ensure_tensor(inputs),
                           ensure_tensor(states), self.weight_ih,
                           self.weight_hh, self.bias_ih, self.bias_hh)
        return out, h


class RNN(Layer):
    """Scan a cell over the time axis."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from ...ops import manipulation as manip

        time_axis = 0 if self.time_major else 1
        steps = inputs.shape[time_axis]
        xs = manip.unbind(inputs, axis=time_axis)
        if self.is_reverse:
            xs = xs[::-1]
        states = initial_states
        outs = []
        for x in xs:
            out, states = self.cell(x, states)
            outs.append(out)
        if self.is_reverse:
            outs = outs[::-1]
        outputs = manip.stack(outs, axis=time_axis)
        return outputs, states


class BiRNN(Layer):
    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.rnn_fw = RNN(cell_fw, False, time_major)
        self.rnn_bw = RNN(cell_bw, True, time_major)
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from ...ops import manipulation as manip

        sf, sb = (initial_states if initial_states is not None else (None, None))
        of, stf = self.rnn_fw(inputs, sf)
        ob, stb = self.rnn_bw(inputs, sb)
        return manip.concat([of, ob], axis=-1), (stf, stb)


class _RNNBase(Layer):
    """Multi-layer (bi)directional recurrent net, one lax.scan per layer."""

    MODE = None

    def __init__(self, mode, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation="tanh", weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None):
        super().__init__()
        self.mode = mode
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.time_major = time_major
        self.dropout = dropout
        self.activation = activation
        self.bidirect = 2 if direction in ("bidirect", "bidirectional") else 1
        gate_mult = {"RNN": 1, "LSTM": 4, "GRU": 3}[mode]
        init = _std_uniform(hidden_size)
        self._param_names = []
        for layer in range(num_layers):
            for d in range(self.bidirect):
                in_sz = input_size if layer == 0 else hidden_size * self.bidirect
                sfx = f"_{layer}" + ("_reverse" if d else "")
                names = [f"weight_ih{sfx}", f"weight_hh{sfx}",
                         f"bias_ih{sfx}", f"bias_hh{sfx}"]
                shapes = [[gate_mult * hidden_size, in_sz],
                          [gate_mult * hidden_size, hidden_size],
                          [gate_mult * hidden_size],
                          [gate_mult * hidden_size]]
                attrs = [weight_ih_attr, weight_hh_attr, bias_ih_attr,
                         bias_hh_attr]
                for n, s, a in zip(names, shapes, attrs):
                    p = self.create_parameter(s, a, is_bias="bias" in n,
                                              default_initializer=init)
                    self.add_parameter(n, p)
                self._param_names.append(names)

    def _step(self, mode):
        act = jnp.tanh if self.activation == "tanh" else jax.nn.relu

        if mode == "RNN":
            def step(carry, x, wi, wh, bi, bh):
                h = carry[0]
                nh = act(x @ wi.T + bi + h @ wh.T + bh)
                return (nh,), nh
        elif mode == "GRU":
            def step(carry, x, wi, wh, bi, bh):
                h = carry[0]
                xg = x @ wi.T + bi
                hg = h @ wh.T + bh
                xr, xz, xn = jnp.split(xg, 3, axis=-1)
                hr, hz, hn = jnp.split(hg, 3, axis=-1)
                r = jax.nn.sigmoid(xr + hr)
                z = jax.nn.sigmoid(xz + hz)
                n = jnp.tanh(xn + r * hn)
                nh = (1 - z) * n + z * h
                return (nh,), nh
        else:
            def step(carry, x, wi, wh, bi, bh):
                h, c = carry
                gates = x @ wi.T + bi + h @ wh.T + bh
                i, f, g, o = jnp.split(gates, 4, axis=-1)
                i, f, o = (jax.nn.sigmoid(i), jax.nn.sigmoid(f),
                           jax.nn.sigmoid(o))
                g = jnp.tanh(g)
                nc = f * c + i * g
                nh = o * jnp.tanh(nc)
                return (nh, nc), nh
        return step

    def forward(self, inputs, initial_states=None, sequence_length=None):
        inputs = ensure_tensor(inputs)
        mode = self.mode
        n_states = 2 if mode == "LSTM" else 1
        nl, nd, hs = self.num_layers, self.bidirect, self.hidden_size
        time_major = self.time_major
        step = self._step(mode)
        params = [self._parameters[n] for names in self._param_names
                  for n in names]

        if initial_states is not None:
            if mode == "LSTM":
                init_h, init_c = initial_states
                init_list = [ensure_tensor(init_h), ensure_tensor(init_c)]
            else:
                init_list = [ensure_tensor(initial_states)]
        else:
            init_list = []

        # inter-layer dropout keys (applied to every layer output except the
        # last, paddle/torch semantics), drawn eagerly from the generator
        drop_keys = None
        if self.dropout > 0.0 and self.training and nl > 1:
            from ...core import rng as _rng

            drop_keys = [_rng.next_key() for _ in range(nl - 1)]
        drop_p = self.dropout

        def jfn(xv, *flat):
            ps = flat[: len(params)]
            inits = flat[len(params):]
            if time_major:
                xv = jnp.swapaxes(xv, 0, 1)  # -> batch, time, feat
            batch = xv.shape[0]
            if inits:
                h0_all = inits[0]
                c0_all = inits[1] if mode == "LSTM" else None
            else:
                h0_all = jnp.zeros((nl * nd, batch, hs), xv.dtype)
                c0_all = jnp.zeros((nl * nd, batch, hs), xv.dtype) if mode == "LSTM" else None
            layer_in = xv
            last_h, last_c = [], []
            idx = 0
            for layer in range(nl):
                dir_outs = []
                for d in range(nd):
                    wi, wh, bi, bh = ps[4 * idx: 4 * idx + 4]
                    h0 = h0_all[idx]
                    carry = (h0, c0_all[idx]) if mode == "LSTM" else (h0,)
                    seq = jnp.swapaxes(layer_in, 0, 1)  # time-major for scan
                    if d == 1:
                        seq = jnp.flip(seq, 0)

                    def body(c, x, wi=wi, wh=wh, bi=bi, bh=bh):
                        return step(c, x, wi, wh, bi, bh)

                    carry, ys = lax.scan(body, carry, seq)
                    if d == 1:
                        ys = jnp.flip(ys, 0)
                    dir_outs.append(jnp.swapaxes(ys, 0, 1))
                    last_h.append(carry[0])
                    if mode == "LSTM":
                        last_c.append(carry[1])
                    idx += 1
                layer_in = (jnp.concatenate(dir_outs, -1) if nd == 2
                            else dir_outs[0])
                if drop_keys is not None and layer < nl - 1:
                    keep = jax.random.bernoulli(
                        drop_keys[layer], 1.0 - drop_p, layer_in.shape)
                    layer_in = jnp.where(keep, layer_in / (1.0 - drop_p), 0.0)
            out = layer_in
            if time_major:
                out = jnp.swapaxes(out, 0, 1)
            hN = jnp.stack(last_h, 0)
            if mode == "LSTM":
                return out, hN, jnp.stack(last_c, 0)
            return out, hN

        res = apply_jfn(f"{mode.lower()}_net", jfn, inputs, *params,
                        *init_list)
        if mode == "LSTM":
            out, h, c = res
            return out, (h, c)
        out, h = res
        return out, h


class SimpleRNN(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation="tanh", weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__("RNN", input_size, hidden_size, num_layers,
                         direction, time_major, dropout, activation,
                         weight_ih_attr, weight_hh_attr, bias_ih_attr,
                         bias_hh_attr)


class LSTM(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__("LSTM", input_size, hidden_size, num_layers,
                         direction, time_major, dropout, "tanh",
                         weight_ih_attr, weight_hh_attr, bias_ih_attr,
                         bias_hh_attr)


class GRU(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__("GRU", input_size, hidden_size, num_layers,
                         direction, time_major, dropout, "tanh",
                         weight_ih_attr, weight_hh_attr, bias_ih_attr,
                         bias_hh_attr)
