"""Common layers (reference: python/paddle/nn/layer/common.py)."""
from .. import functional as F
from .. import initializer as I
from .layers import Layer

__all__ = [
    "PairwiseDistance",
    "Fold",
    "Linear",
    "Identity",
    "Dropout",
    "Dropout2D",
    "Dropout3D",
    "AlphaDropout",
    "Embedding",
    "Flatten",
    "Pad1D",
    "Pad2D",
    "Pad3D",
    "ZeroPad2D",
    "Upsample",
    "UpsamplingNearest2D",
    "UpsamplingBilinear2D",
    "Bilinear",
    "CosineSimilarity",
    "Unfold",
    "PixelShuffle",
    "PixelUnshuffle",
    "ChannelShuffle",
]


class Identity(Layer):
    def __init__(self, *args, **kwargs):
        super().__init__()

    def forward(self, input):
        return input


class Linear(Layer):
    def __init__(self, in_features, out_features, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        self._in_features = in_features
        self._out_features = out_features
        self.weight = self.create_parameter(
            shape=[in_features, out_features], attr=weight_attr,
            dtype=self._dtype, is_bias=False,
        )
        self.bias = self.create_parameter(
            shape=[out_features], attr=bias_attr, dtype=self._dtype, is_bias=True,
        )

    def forward(self, input):
        return F.linear(input, self.weight, self.bias)

    def extra_repr(self):
        return f"in_features={self._in_features}, out_features={self._out_features}"


class Dropout(Layer):
    def __init__(self, p=0.5, axis=None, mode="upscale_in_train", name=None):
        super().__init__()
        self.p = p
        self.axis = axis
        self.mode = mode

    def forward(self, input):
        return F.dropout(input, p=self.p, axis=self.axis,
                         training=self.training, mode=self.mode)

    def extra_repr(self):
        return f"p={self.p}, axis={self.axis}, mode={self.mode}"


class Dropout2D(Layer):
    def __init__(self, p=0.5, data_format="NCHW", name=None):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, input):
        return F.dropout2d(input, p=self.p, training=self.training,
                           data_format=self.data_format)


class Dropout3D(Layer):
    def __init__(self, p=0.5, data_format="NCDHW", name=None):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, input):
        return F.dropout3d(input, p=self.p, training=self.training,
                           data_format=self.data_format)


class AlphaDropout(Layer):
    def __init__(self, p=0.5, name=None):
        super().__init__()
        self.p = p

    def forward(self, input):
        return F.alpha_dropout(input, p=self.p, training=self.training)


class Embedding(Layer):
    def __init__(self, num_embeddings, embedding_dim, padding_idx=None,
                 sparse=False, weight_attr=None, name=None):
        super().__init__()
        self._num_embeddings = num_embeddings
        self._embedding_dim = embedding_dim
        self._padding_idx = padding_idx
        self.weight = self.create_parameter(
            shape=[num_embeddings, embedding_dim], attr=weight_attr,
            dtype=self._dtype, default_initializer=I.Normal(0.0, 1.0),
        )
        if padding_idx is not None:
            import jax.numpy as jnp

            pid = padding_idx if padding_idx >= 0 else num_embeddings + padding_idx
            self.weight._value = self.weight._value.at[pid].set(0.0)

    def forward(self, x):
        return F.embedding(x, self.weight, padding_idx=self._padding_idx)

    def extra_repr(self):
        return f"{self._num_embeddings}, {self._embedding_dim}"


class Flatten(Layer):
    def __init__(self, start_axis=1, stop_axis=-1):
        super().__init__()
        self.start_axis = start_axis
        self.stop_axis = stop_axis

    def forward(self, input):
        from ...ops.manipulation import flatten

        return flatten(input, start_axis=self.start_axis,
                       stop_axis=self.stop_axis)


class _PadNd(Layer):
    def __init__(self, padding, mode, value, data_format):
        super().__init__()
        self._pad = padding
        self._mode = mode
        self._value = value
        self._data_format = data_format

    def forward(self, x):
        return F.pad(x, self._pad, mode=self._mode, value=self._value,
                     data_format=self._data_format)


class Pad1D(_PadNd):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCL",
                 name=None):
        if isinstance(padding, int):
            padding = [padding, padding]
        super().__init__(padding, mode, value, data_format)


class Pad2D(_PadNd):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCHW",
                 name=None):
        if isinstance(padding, int):
            padding = [padding] * 4
        super().__init__(padding, mode, value, data_format)


class ZeroPad2D(Pad2D):
    def __init__(self, padding, data_format="NCHW", name=None):
        super().__init__(padding, "constant", 0.0, data_format)


class Pad3D(_PadNd):
    def __init__(self, padding, mode="constant", value=0.0,
                 data_format="NCDHW", name=None):
        if isinstance(padding, int):
            padding = [padding] * 6
        super().__init__(padding, mode, value, data_format)


class Upsample(Layer):
    def __init__(self, size=None, scale_factor=None, mode="nearest",
                 align_corners=False, align_mode=0, data_format="NCHW",
                 name=None):
        super().__init__()
        self.size = size
        self.scale_factor = scale_factor
        self.mode = mode
        self.align_corners = align_corners
        self.align_mode = align_mode
        self.data_format = data_format

    def forward(self, x):
        return F.interpolate(x, self.size, self.scale_factor, self.mode,
                             self.align_corners, self.align_mode,
                             self.data_format)


class UpsamplingNearest2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW",
                 name=None):
        super().__init__(size, scale_factor, "nearest", False, 0, data_format)


class UpsamplingBilinear2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW",
                 name=None):
        super().__init__(size, scale_factor, "bilinear", True, 0, data_format)


class Bilinear(Layer):
    def __init__(self, in1_features, in2_features, out_features,
                 weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        self.weight = self.create_parameter(
            shape=[out_features, in1_features, in2_features], attr=weight_attr,
            dtype=self._dtype,
        )
        self.bias = self.create_parameter(
            shape=[1, out_features], attr=bias_attr, dtype=self._dtype,
            is_bias=True,
        )

    def forward(self, x1, x2):
        return F.bilinear(x1, x2, self.weight, self.bias)


class CosineSimilarity(Layer):
    def __init__(self, axis=1, eps=1e-8):
        super().__init__()
        self._axis = axis
        self._eps = eps

    def forward(self, x1, x2):
        return F.cosine_similarity(x1, x2, axis=self._axis, eps=self._eps)


class Unfold(Layer):
    def __init__(self, kernel_sizes, strides=1, paddings=0, dilations=1,
                 name=None):
        super().__init__()
        self.kernel_sizes = kernel_sizes
        self.strides = strides
        self.paddings = paddings
        self.dilations = dilations

    def forward(self, input):
        return F.unfold(input, self.kernel_sizes, self.strides, self.paddings,
                        self.dilations)


class PixelShuffle(Layer):
    def __init__(self, upscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self._factor = upscale_factor
        self._data_format = data_format

    def forward(self, x):
        return F.pixel_shuffle(x, self._factor, self._data_format)


class PixelUnshuffle(Layer):
    def __init__(self, downscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self._factor = downscale_factor
        self._data_format = data_format

    def forward(self, x):
        return F.pixel_unshuffle(x, self._factor, self._data_format)


class ChannelShuffle(Layer):
    def __init__(self, groups, data_format="NCHW", name=None):
        super().__init__()
        self._groups = groups
        self._data_format = data_format

    def forward(self, x):
        return F.channel_shuffle(x, self._groups, self._data_format)


class PairwiseDistance(Layer):
    """(reference: python/paddle/nn/layer/distance.py)."""

    def __init__(self, p=2.0, epsilon=1e-6, keepdim=False, name=None):
        super().__init__()
        self.p = p
        self.epsilon = epsilon
        self.keepdim = keepdim

    def forward(self, x, y):
        return F.pairwise_distance(x, y, p=self.p, epsilon=self.epsilon,
                                   keepdim=self.keepdim)


class Fold(Layer):
    """(reference: python/paddle/nn/layer/common.py Fold)."""

    def __init__(self, output_sizes, kernel_sizes, strides=1, paddings=0,
                 dilations=1, name=None):
        super().__init__()
        self.output_sizes = output_sizes
        self.kernel_sizes = kernel_sizes
        self.strides = strides
        self.paddings = paddings
        self.dilations = dilations

    def forward(self, x):
        return F.fold(x, self.output_sizes, self.kernel_sizes,
                      self.strides, self.paddings, self.dilations)
