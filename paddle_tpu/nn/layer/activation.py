"""Activation layers (reference: python/paddle/nn/layer/activation.py)."""
from .. import functional as F
from .layers import Layer

__all__ = [
    "CELU", "ELU", "GELU", "GLU", "Hardshrink", "Hardsigmoid", "Hardswish",
    "Hardtanh", "LeakyReLU", "LogSigmoid", "LogSoftmax", "Maxout", "Mish",
    "PReLU", "ReLU", "ReLU6", "RReLU", "SELU", "Sigmoid", "Silu", "Softmax",
    "Softmax2D",
    "Softplus", "Softshrink", "Softsign", "Swish", "Tanh", "Tanhshrink",
    "ThresholdedReLU",
]


def _simple(name, fname, **defaults):
    def __init__(self, name=None, **kw):
        Layer.__init__(self)
        self._kw = {**defaults, **kw}

    def forward(self, x):
        return getattr(F, fname)(x, **self._kw)

    return type(name, (Layer,), {"__init__": __init__, "forward": forward})


ReLU = _simple("ReLU", "relu")
ReLU6 = _simple("ReLU6", "relu6")
Sigmoid = _simple("Sigmoid", "sigmoid")
Silu = _simple("Silu", "silu")
Tanh = _simple("Tanh", "tanh")
Tanhshrink = _simple("Tanhshrink", "tanhshrink")
Softsign = _simple("Softsign", "softsign")
LogSigmoid = _simple("LogSigmoid", "log_sigmoid")
Mish = _simple("Mish", "mish")
Hardswish = _simple("Hardswish", "hardswish")
Hardsigmoid = _simple("Hardsigmoid", "hardsigmoid")


class ELU(Layer):
    def __init__(self, alpha=1.0, name=None):
        super().__init__()
        self._alpha = alpha

    def forward(self, x):
        return F.elu(x, self._alpha)


class CELU(Layer):
    def __init__(self, alpha=1.0, name=None):
        super().__init__()
        self._alpha = alpha

    def forward(self, x):
        return F.celu(x, self._alpha)


class GELU(Layer):
    def __init__(self, approximate=False, name=None):
        super().__init__()
        self._approximate = approximate

    def forward(self, x):
        return F.gelu(x, self._approximate)


class GLU(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self._axis = axis

    def forward(self, x):
        return F.glu(x, self._axis)


class Hardshrink(Layer):
    def __init__(self, threshold=0.5, name=None):
        super().__init__()
        self._threshold = threshold

    def forward(self, x):
        return F.hardshrink(x, self._threshold)


class Hardtanh(Layer):
    def __init__(self, min=-1.0, max=1.0, name=None):
        super().__init__()
        self._min, self._max = min, max

    def forward(self, x):
        return F.hardtanh(x, self._min, self._max)


class LeakyReLU(Layer):
    def __init__(self, negative_slope=0.01, name=None):
        super().__init__()
        self._negative_slope = negative_slope

    def forward(self, x):
        return F.leaky_relu(x, self._negative_slope)


class LogSoftmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self._axis = axis

    def forward(self, x):
        return F.log_softmax(x, self._axis)


class Maxout(Layer):
    def __init__(self, groups, axis=1, name=None):
        super().__init__()
        self._groups, self._axis = groups, axis

    def forward(self, x):
        return F.maxout(x, self._groups, self._axis)


class PReLU(Layer):
    def __init__(self, num_parameters=1, init=0.25, weight_attr=None,
                 data_format="NCHW", name=None):
        super().__init__()
        from .. import initializer as I

        self._data_format = data_format
        self.weight = self.create_parameter(
            shape=[num_parameters], attr=weight_attr,
            default_initializer=I.Constant(init),
        )

    def forward(self, x):
        return F.prelu(x, self.weight, self._data_format)


class RReLU(Layer):
    def __init__(self, lower=1.0 / 8.0, upper=1.0 / 3.0, name=None):
        super().__init__()
        self._lower, self._upper = lower, upper

    def forward(self, x):
        return F.rrelu(x, self._lower, self._upper, self.training)


class SELU(Layer):
    def __init__(self, scale=1.0507009873554805, alpha=1.6732632423543772,
                 name=None):
        super().__init__()
        self._scale, self._alpha = scale, alpha

    def forward(self, x):
        return F.selu(x, self._scale, self._alpha)


class Softmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self._axis = axis

    def forward(self, x):
        return F.softmax(x, self._axis)


class Softplus(Layer):
    def __init__(self, beta=1.0, threshold=20.0, name=None):
        super().__init__()
        self._beta, self._threshold = beta, threshold

    def forward(self, x):
        return F.softplus(x, self._beta, self._threshold)


class Softshrink(Layer):
    def __init__(self, threshold=0.5, name=None):
        super().__init__()
        self._threshold = threshold

    def forward(self, x):
        return F.softshrink(x, self._threshold)


class Swish(Layer):
    def __init__(self, name=None):
        super().__init__()

    def forward(self, x):
        return F.swish(x)


class ThresholdedReLU(Layer):
    def __init__(self, threshold=1.0, name=None):
        super().__init__()
        self._threshold = threshold

    def forward(self, x):
        return F.thresholded_relu(x, self._threshold)


class Softmax2D(Layer):
    """Softmax over the channel axis of NCHW inputs
    (reference: python/paddle/nn/layer/activation.py Softmax2D)."""

    def __init__(self, name=None):
        super().__init__()

    def forward(self, x):
        if x.ndim not in (3, 4):
            raise ValueError(
                f"Softmax2D expects 3D/4D input, got {x.ndim}D")
        return F.softmax(x, axis=-3)
