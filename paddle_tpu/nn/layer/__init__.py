from . import (  # noqa: F401
    activation,
    common,
    container,
    conv,
    layers,
    loss,
    norm,
    pooling,
    rnn,
    transformer,
)
from .layers import Layer  # noqa: F401
