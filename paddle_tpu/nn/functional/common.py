"""Common functionals: linear, dropout, embedding, pad, interpolate, unfold.

(Reference: python/paddle/nn/functional/common.py + input.py; kernels in
paddle/phi/kernels/. Dropout draws a fresh PRNG subkey per eager call from
the framework generator — under jit the train step threads keys explicitly.)
"""
import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ...core import rng
from ...ops._helpers import apply_jfn, ensure_tensor

__all__ = [
    "linear",
    "pairwise_distance",
    "fold",
    "dropout",
    "dropout2d",
    "dropout3d",
    "alpha_dropout",
    "embedding",
    "one_hot",
    "pad",
    "interpolate",
    "upsample",
    "unfold",
    "pixel_shuffle",
    "pixel_unshuffle",
    "channel_shuffle",
    "label_smooth",
    "cosine_similarity",
    "bilinear",
    "affine_grid",
    "grid_sample",
]


def linear(x, weight, bias=None, name=None):
    """y = x @ W + b with paddle's [in, out] weight layout.

    The single densest op in the framework — maps straight onto the MXU.
    """
    x = ensure_tensor(x)
    weight = ensure_tensor(weight)
    if bias is not None:
        return apply_jfn(
            "linear", lambda xv, wv, bv: jnp.matmul(xv, wv) + bv, x, weight,
            ensure_tensor(bias)
        )
    return apply_jfn("linear", jnp.matmul, x, weight)


def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train",
            name=None):
    x = ensure_tensor(x)
    if not training or p == 0:
        if mode == "downscale_in_infer" and not training:
            return apply_jfn("dropout_scale", lambda xv: xv * (1 - p), x)
        return x
    if p == 1:
        return apply_jfn("dropout_all", jnp.zeros_like, x)
    key = rng.next_key()

    def jfn(xv):
        shape = xv.shape
        if axis is not None:
            axes = (axis,) if isinstance(axis, int) else tuple(axis)
            shape = tuple(s if i in axes else 1 for i, s in enumerate(xv.shape))
        keep = jax.random.bernoulli(key, 1.0 - p, shape)
        if mode == "upscale_in_train":
            return jnp.where(keep, xv / (1.0 - p), 0.0).astype(xv.dtype)
        return jnp.where(keep, xv, 0.0).astype(xv.dtype)

    return apply_jfn("dropout", jfn, x)


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    axis = (0, 1) if data_format == "NCHW" else (0, 3)
    return dropout(x, p=p, axis=axis, training=training)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    axis = (0, 1) if data_format == "NCDHW" else (0, 4)
    return dropout(x, p=p, axis=axis, training=training)


def alpha_dropout(x, p=0.5, training=True, name=None):
    x = ensure_tensor(x)
    if not training or p == 0:
        return x
    key = rng.next_key()
    alpha = 1.6732632423543772
    scale = 1.0507009873554805
    alpha_p = -alpha * scale

    def jfn(xv):
        keep = jax.random.bernoulli(key, 1.0 - p, xv.shape)
        a = (1.0 / np.sqrt((1 - p) * (1 + p * alpha_p**2))) if p < 1 else 0.0
        b = -a * alpha_p * p
        out = jnp.where(keep, xv, alpha_p)
        return (a * out + b).astype(xv.dtype)

    return apply_jfn("alpha_dropout", jfn, x)


def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    """Row gather; vocab-parallel variant lives in distributed mpu.

    (Reference: phi/kernels/embedding_kernel; padding_idx rows get zero grad
    — implemented by zeroing the row in fwd via where, vjp then drops it.)
    """
    x = ensure_tensor(x)
    weight = ensure_tensor(weight)

    def jfn(ids, w):
        out = jnp.take(w, ids.astype(jnp.int32), axis=0)
        if padding_idx is not None:
            pid = padding_idx if padding_idx >= 0 else w.shape[0] + padding_idx
            mask = (ids.astype(jnp.int32) != pid)[..., None]
            out = jnp.where(mask, out, 0.0)
        return out

    return apply_jfn("embedding", jfn, x, weight)


def one_hot(x, num_classes, name=None):
    x = ensure_tensor(x)
    return apply_jfn(
        "one_hot",
        lambda ids: jax.nn.one_hot(ids.astype(jnp.int32), num_classes),
        x,
    )


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    x = ensure_tensor(x)
    from ...ops.manipulation import pad as _oppad
    return _oppad(x, pad, mode=mode, value=value, data_format=data_format)


def interpolate(x, size=None, scale_factor=None, mode="nearest",
                align_corners=False, align_mode=0, data_format="NCHW",
                name=None):
    x = ensure_tensor(x)
    channel_last = data_format in ("NHWC", "NWC", "NDHWC")
    nd = x.ndim - 2

    def out_sizes(spatial):
        if size is not None:
            s = size if isinstance(size, (list, tuple)) else [size] * nd
            return tuple(int(v) for v in s)
        sf = scale_factor if isinstance(scale_factor, (list, tuple)) else [scale_factor] * nd
        return tuple(int(np.floor(sp * f)) for sp, f in zip(spatial, sf))

    def jfn(xv):
        if channel_last:
            xv = jnp.moveaxis(xv, -1, 1)
        spatial = xv.shape[2:]
        outs = out_sizes(spatial)
        if mode == "nearest":
            out = xv
            for i, (in_s, out_s) in enumerate(zip(spatial, outs)):
                idx = jnp.floor(jnp.arange(out_s) * (in_s / out_s)).astype(jnp.int32)
                out = jnp.take(out, idx, axis=2 + i)
        elif mode in ("bilinear", "linear", "trilinear", "bicubic"):
            method = "cubic" if mode == "bicubic" else "linear"
            if align_corners:
                # jax.image has no align_corners; do coordinate gather
                out = _resize_align_corners(xv, outs, method)
            else:
                out = jax.image.resize(
                    xv, xv.shape[:2] + outs, method=method
                ).astype(xv.dtype)
        elif mode == "area":
            out = xv
            for i, (in_s, out_s) in enumerate(zip(spatial, outs)):
                if in_s % out_s == 0:
                    k = in_s // out_s
                    shp = out.shape[: 2 + i] + (out_s, k) + out.shape[3 + i:]
                    out = out.reshape(shp).mean(axis=3 + i)
                else:
                    out = jax.image.resize(out, out.shape[:2 + i] + (out_s,) + out.shape[3 + i:], "linear")
        else:
            raise ValueError(f"unsupported interpolate mode {mode}")
        if channel_last:
            out = jnp.moveaxis(out, 1, -1)
        return out

    return apply_jfn("interpolate", jfn, x)


def _resize_align_corners(xv, outs, method):
    out = xv
    for i, out_s in enumerate(outs):
        ax = 2 + i
        in_s = out.shape[ax]
        if out_s == 1 or in_s == 1:
            coords = jnp.zeros(out_s)
        else:
            coords = jnp.arange(out_s) * ((in_s - 1) / (out_s - 1))
        lo = jnp.floor(coords).astype(jnp.int32)
        hi = jnp.clip(lo + 1, 0, in_s - 1)
        w = (coords - lo).astype(out.dtype)
        a = jnp.take(out, lo, axis=ax)
        b = jnp.take(out, hi, axis=ax)
        shape = [1] * out.ndim
        shape[ax] = out_s
        w = w.reshape(shape)
        out = a * (1 - w) + b * w
    return out


def upsample(x, size=None, scale_factor=None, mode="nearest",
             align_corners=False, align_mode=0, data_format="NCHW", name=None):
    return interpolate(x, size, scale_factor, mode, align_corners, align_mode,
                       data_format)


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    """im2col (reference: phi/kernels/funcs/im2col.h)."""
    x = ensure_tensor(x)
    k = (kernel_sizes,) * 2 if isinstance(kernel_sizes, int) else tuple(kernel_sizes)
    s = (strides,) * 2 if isinstance(strides, int) else tuple(strides)
    p = (paddings,) * 2 if isinstance(paddings, int) else tuple(paddings)
    d = (dilations,) * 2 if isinstance(dilations, int) else tuple(dilations)
    if len(p) == 2:
        p = (p[0], p[1], p[0], p[1])

    def jfn(xv):
        N, C, H, W = xv.shape
        xv = jnp.pad(xv, ((0, 0), (0, 0), (p[0], p[2]), (p[1], p[3])))
        oh = (xv.shape[2] - (d[0] * (k[0] - 1) + 1)) // s[0] + 1
        ow = (xv.shape[3] - (d[1] * (k[1] - 1) + 1)) // s[1] + 1
        patches = []
        for i in range(k[0]):
            for j in range(k[1]):
                sl = xv[:, :, i * d[0]: i * d[0] + oh * s[0]: s[0],
                        j * d[1]: j * d[1] + ow * s[1]: s[1]]
                patches.append(sl)
        out = jnp.stack(patches, axis=2)  # N,C,k*k,oh,ow
        return out.reshape(N, C * k[0] * k[1], oh * ow)

    return apply_jfn("unfold", jfn, x)


def pixel_shuffle(x, upscale_factor, data_format="NCHW", name=None):
    x = ensure_tensor(x)
    r = upscale_factor

    def jfn(xv):
        if data_format == "NHWC":
            xv = jnp.moveaxis(xv, -1, 1)
        N, C, H, W = xv.shape
        out = xv.reshape(N, C // (r * r), r, r, H, W)
        out = out.transpose(0, 1, 4, 2, 5, 3).reshape(N, C // (r * r), H * r, W * r)
        if data_format == "NHWC":
            out = jnp.moveaxis(out, 1, -1)
        return out

    return apply_jfn("pixel_shuffle", jfn, x)


def pixel_unshuffle(x, downscale_factor, data_format="NCHW", name=None):
    x = ensure_tensor(x)
    r = downscale_factor

    def jfn(xv):
        if data_format == "NHWC":
            xv = jnp.moveaxis(xv, -1, 1)
        N, C, H, W = xv.shape
        out = xv.reshape(N, C, H // r, r, W // r, r)
        out = out.transpose(0, 1, 3, 5, 2, 4).reshape(N, C * r * r, H // r, W // r)
        if data_format == "NHWC":
            out = jnp.moveaxis(out, 1, -1)
        return out

    return apply_jfn("pixel_unshuffle", jfn, x)


def channel_shuffle(x, groups, data_format="NCHW", name=None):
    x = ensure_tensor(x)

    def jfn(xv):
        if data_format == "NHWC":
            xv = jnp.moveaxis(xv, -1, 1)
        N, C = xv.shape[:2]
        out = xv.reshape((N, groups, C // groups) + xv.shape[2:])
        out = jnp.swapaxes(out, 1, 2).reshape(xv.shape)
        if data_format == "NHWC":
            out = jnp.moveaxis(out, 1, -1)
        return out

    return apply_jfn("channel_shuffle", jfn, x)


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    label = ensure_tensor(label)
    if prior_dist is not None:
        pd = ensure_tensor(prior_dist)
        return apply_jfn(
            "label_smooth",
            lambda y, p: (1 - epsilon) * y + epsilon * p,
            label, pd,
        )
    return apply_jfn(
        "label_smooth",
        lambda y: (1 - epsilon) * y + epsilon / y.shape[-1],
        label,
    )


def cosine_similarity(x1, x2, axis=1, eps=1e-8):
    def jfn(a, b):
        num = (a * b).sum(axis=axis)
        den = jnp.linalg.norm(a, axis=axis) * jnp.linalg.norm(b, axis=axis)
        return num / jnp.maximum(den, eps)

    return apply_jfn("cosine_similarity", jfn, ensure_tensor(x1),
                     ensure_tensor(x2))


def bilinear(x1, x2, weight, bias=None, name=None):
    tensors = [ensure_tensor(x1), ensure_tensor(x2), ensure_tensor(weight)]
    if bias is not None:
        tensors.append(ensure_tensor(bias))

    def jfn(a, b, w, *rest):
        out = jnp.einsum("bi,oij,bj->bo", a, w, b)
        if rest:
            out = out + rest[0]
        return out

    return apply_jfn("bilinear", jfn, *tensors)


def affine_grid(theta, out_shape, align_corners=True, name=None):
    theta = ensure_tensor(theta)

    def jfn(th):
        N, H, W = out_shape[0], out_shape[2], out_shape[3]
        if align_corners:
            xs = jnp.linspace(-1, 1, W)
            ys = jnp.linspace(-1, 1, H)
        else:
            xs = (jnp.arange(W) * 2 + 1) / W - 1
            ys = (jnp.arange(H) * 2 + 1) / H - 1
        gx, gy = jnp.meshgrid(xs, ys)
        ones = jnp.ones_like(gx)
        base = jnp.stack([gx, gy, ones], axis=-1)  # H,W,3
        return jnp.einsum("hwk,nck->nhwc", base, th)

    return apply_jfn("affine_grid", jfn, theta)


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True, name=None):
    x = ensure_tensor(x)
    grid = ensure_tensor(grid)

    def jfn(xv, g):
        N, C, H, W = xv.shape
        gx, gy = g[..., 0], g[..., 1]
        if align_corners:
            fx = (gx + 1) * (W - 1) / 2
            fy = (gy + 1) * (H - 1) / 2
        else:
            fx = ((gx + 1) * W - 1) / 2
            fy = ((gy + 1) * H - 1) / 2
        if mode == "nearest":
            ix = jnp.clip(jnp.round(fx), 0, W - 1).astype(jnp.int32)
            iy = jnp.clip(jnp.round(fy), 0, H - 1).astype(jnp.int32)
            out = xv[jnp.arange(N)[:, None, None], :, iy, ix]
            return jnp.moveaxis(out, -1, 1)
        x0 = jnp.floor(fx)
        y0 = jnp.floor(fy)
        wx = (fx - x0).astype(xv.dtype)
        wy = (fy - y0).astype(xv.dtype)

        def gather(ix, iy):
            inb = ((ix >= 0) & (ix <= W - 1) & (iy >= 0) & (iy <= H - 1))
            ix_c = jnp.clip(ix, 0, W - 1).astype(jnp.int32)
            iy_c = jnp.clip(iy, 0, H - 1).astype(jnp.int32)
            v = xv[jnp.arange(N)[:, None, None], :, iy_c, ix_c]  # N,Hg,Wg,C
            if padding_mode == "zeros":
                v = v * inb[..., None]
            return v

        v00 = gather(x0, y0)
        v01 = gather(x0 + 1, y0)
        v10 = gather(x0, y0 + 1)
        v11 = gather(x0 + 1, y0 + 1)
        out = (
            v00 * ((1 - wx) * (1 - wy))[..., None]
            + v01 * (wx * (1 - wy))[..., None]
            + v10 * ((1 - wx) * wy)[..., None]
            + v11 * (wx * wy)[..., None]
        )
        return jnp.moveaxis(out, -1, 1)

    return apply_jfn("grid_sample", jfn, x, grid)


def pairwise_distance(x, y, p=2.0, epsilon=1e-6, keepdim=False, name=None):
    """p-norm distance over the last dim (reference:
    python/paddle/nn/functional/distance.py; p=inf → Chebyshev,
    p=0 → nonzero count, matching p_norm's ord rules)."""

    def jfn(a, b):
        d = jnp.abs(a - b) + epsilon
        if p == float("inf"):
            out = d.max(axis=-1)
        elif p == float("-inf"):
            out = d.min(axis=-1)
        elif p == 0:
            out = (d != 0).astype(d.dtype).sum(axis=-1)
        else:
            out = (d ** p).sum(axis=-1) ** (1.0 / p)
        return out[..., None] if keepdim else out

    return apply_jfn("pairwise_distance", jfn, ensure_tensor(x),
                     ensure_tensor(y))


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0,
         dilations=1, name=None):
    """col2im: combine sliding blocks [N, C·kh·kw, L] → [N, C, H, W],
    summing overlaps (reference: python/paddle/nn/functional/common.py
    fold; inverse of unfold). Static kernel loops → XLA scatter-adds."""

    def _pair(v):
        return (v, v) if isinstance(v, int) else tuple(v)

    H, W = _pair(output_sizes)
    kh, kw = _pair(kernel_sizes)
    sh, sw = _pair(strides)
    ph, pw = _pair(paddings)
    dh, dw = _pair(dilations)
    oh = (H + 2 * ph - (dh * (kh - 1) + 1)) // sh + 1
    ow = (W + 2 * pw - (dw * (kw - 1) + 1)) // sw + 1

    def jfn(v):
        N = v.shape[0]
        C = v.shape[1] // (kh * kw)
        blocks = v.reshape(N, C, kh, kw, oh, ow)
        out = jnp.zeros((N, C, H + 2 * ph, W + 2 * pw), v.dtype)
        for i in range(kh):
            for j in range(kw):
                hi = i * dh
                wj = j * dw
                out = out.at[
                    :, :, hi:hi + sh * oh:sh, wj:wj + sw * ow:sw
                ].add(blocks[:, :, i, j])
        return out[:, :, ph:ph + H, pw:pw + W]

    return apply_jfn("fold", jfn, ensure_tensor(x))
