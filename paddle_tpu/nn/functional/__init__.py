"""paddle.nn.functional parity namespace.

(Reference: python/paddle/nn/functional/__init__.py.) Activations live in
ops/activation (single tape-op implementations); structural functionals in
the sibling modules here.
"""
from ...ops.activation import (  # noqa: F401
    celu,
    elu,
    gelu,
    glu,
    gumbel_softmax,
    hardshrink,
    hardsigmoid,
    hardswish,
    hardtanh,
    leaky_relu,
    log_sigmoid,
    log_softmax,
    maxout,
    mish,
    prelu,
    relu,
    relu6,
    rrelu,
    selu,
    sigmoid,
    silu,
    softmax,
    softplus,
    softshrink,
    softsign,
    swish,
    tanhshrink,
    thresholded_relu,
)
from ...ops.math import tanh  # noqa: F401
from .attention import paged_attention  # noqa: F401
from .attention import scaled_dot_product_attention  # noqa: F401
from .common import *  # noqa: F401,F403
from .conv import *  # noqa: F401,F403
from .extras import *  # noqa: F401,F403
from .loss import *  # noqa: F401,F403
from .norm import *  # noqa: F401,F403
from .pooling import *  # noqa: F401,F403

from ...ops.manipulation import squeeze, unsqueeze  # noqa: F401
from ...ops.creation import diag_embed  # noqa: F401
