"""Loss functionals.

(Reference: paddle/phi/kernels/gpu/cross_entropy_kernel.cu,
python/paddle/nn/functional/loss.py. The softmax-CE here is the
log-sum-exp formulation XLA fuses into one kernel; the Pallas fused
vocab-parallel variant lives in ops/pallas_kernels.)
"""
import functools

import jax
import jax.numpy as jnp

from ...ops._helpers import apply_jfn, ensure_tensor, value_of

__all__ = [
    "cross_entropy",
    "ctc_loss",
    "huber_loss",
    "poisson_nll_loss",
    "multi_label_soft_margin_loss",
    "softmax_with_cross_entropy",
    "nll_loss",
    "mse_loss",
    "l1_loss",
    "smooth_l1_loss",
    "binary_cross_entropy",
    "binary_cross_entropy_with_logits",
    "kl_div",
    "margin_ranking_loss",
    "hinge_embedding_loss",
    "cosine_embedding_loss",
    "triplet_margin_loss",
    "log_loss",
    "square_error_cost",
    "sigmoid_focal_loss",
]


def _reduce(out, reduction):
    if reduction == "mean":
        return jnp.mean(out)
    if reduction == "sum":
        return jnp.sum(out)
    return out


@functools.partial(jax.custom_vjp, nondiff_argnums=())
def _softmax_ce_core(logits, labels):
    """Per-position softmax CE over the last axis: lse(logits) - logits[label].

    Memory-lean custom VJP: the fp32 upcast is consumed only by reduces and a
    gather, so XLA fuses the convert into the reduction loops — no fp32
    [..., vocab] array is ever written to HBM, and the backward recomputes
    softmax from the (bf16) logits instead of saving fp32 log-probs. This is
    the fused-CE capability of the reference's
    c_softmax_with_cross_entropy / cross_entropy_kernel.cu, TPU-style.
    """
    out, _ = _softmax_ce_fwd(logits, labels)
    return out


def _softmax_ce_fwd(logits, labels):
    lf = logits.astype(jnp.float32)
    m = jnp.max(lf, axis=-1)
    lse = m + jnp.log(jnp.sum(jnp.exp(lf - m[..., None]), axis=-1))
    picked = jnp.take_along_axis(
        lf, labels[..., None].astype(jnp.int32), axis=-1)[..., 0]
    return lse - picked, (logits, labels, lse)


def _softmax_ce_bwd(res, g):
    logits, labels, lse = res
    lf = logits.astype(jnp.float32)
    p = jnp.exp(lf - lse[..., None])
    # one-hot as a fused iota compare (a jax.nn.one_hot array would be a
    # full [..., vocab] fp32 materialization — the thing we're avoiding)
    hit = jax.lax.broadcasted_iota(
        jnp.int32, lf.shape, lf.ndim - 1) == labels[..., None].astype(
            jnp.int32)
    d = (p - hit.astype(jnp.float32)) * g[..., None]
    return d.astype(logits.dtype), None


_softmax_ce_core.defvjp(_softmax_ce_fwd, _softmax_ce_bwd)


def cross_entropy(input, label, weight=None, ignore_index=-100, reduction="mean",
                  soft_label=False, axis=-1, use_softmax=True, name=None):
    input = ensure_tensor(input)
    label = ensure_tensor(label)
    tensors = [input, label] + ([ensure_tensor(weight)] if weight is not None else [])

    def jfn(logits, lbl, *rest):
        fused = (not soft_label) and use_softmax and (
            axis == -1 or axis == logits.ndim - 1)
        if fused:
            logp = logits  # placeholder for ndim only
        elif use_softmax:
            # fp32 here regardless of AMP: cross_entropy is off the AMP
            # black list (the fused path handles its own precision)
            logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=axis)
        else:
            logp = jnp.log(jnp.clip(logits.astype(jnp.float32), 1e-15, 1.0))
        if soft_label:
            if rest:
                # class weights apply to soft labels too (reference
                # python/paddle/nn/functional/loss.py weighted soft-label
                # branch): weight each class term, normalize the mean by
                # the effective per-sample weight sum
                w = rest[0]
                wshape = [1] * logp.ndim
                wshape[axis if axis >= 0 else logp.ndim + axis] = -1
                wb = w.reshape(wshape).astype(logp.dtype)
                loss = -(lbl * wb * logp).sum(axis=axis)
                if reduction == "mean":
                    denom = (lbl * wb).sum(axis=axis)
                    return loss.sum() / jnp.maximum(denom.sum(), 1e-12)
            else:
                loss = -(lbl * logp).sum(axis=axis)
            if reduction == "none":
                loss = jnp.expand_dims(loss, axis)
            return _reduce(loss, reduction)
        lbl_i = lbl.astype(jnp.int32)
        squeeze_axis = axis if axis >= 0 else logp.ndim + axis
        if lbl_i.ndim == logp.ndim:
            lbl_i = jnp.squeeze(lbl_i, axis=squeeze_axis)
        valid = lbl_i != ignore_index
        safe = jnp.where(valid, lbl_i, 0)
        if fused:
            # fused memory-lean path: no fp32 [..., vocab] materialization
            loss = jnp.where(valid, _softmax_ce_core(logits, safe), 0.0)
        else:
            picked = jnp.take_along_axis(
                logp, jnp.expand_dims(safe, squeeze_axis), axis=squeeze_axis
            ).squeeze(squeeze_axis)
            loss = jnp.where(valid, -picked, 0.0)
        if rest:
            # accumulate the weight-sum denominator in the loss dtype
            # (f32 on both paths), never in the bf16 logits dtype
            w = rest[0][safe] * valid.astype(loss.dtype)
            loss = loss * rest[0][safe]
            if reduction == "mean":
                return loss.sum() / jnp.maximum(w.sum(), 1e-12)
        elif reduction == "mean":
            denom = jnp.maximum(valid.sum(), 1)
            return loss.sum() / denom.astype(loss.dtype)
        return _reduce(loss, reduction)

    return apply_jfn("cross_entropy", jfn, *tensors)


def softmax_with_cross_entropy(logits, label, soft_label=False,
                               ignore_index=-100, numeric_stable_mode=True,
                               return_softmax=False, axis=-1):
    loss = cross_entropy(logits, label, soft_label=soft_label,
                         ignore_index=ignore_index, reduction="none", axis=axis)
    # reference returns loss with the class axis kept as size-1
    from ...ops import manipulation as manip
    if not soft_label:
        loss = manip.unsqueeze(loss, axis)
    if return_softmax:
        from ...ops.activation import softmax as _softmax
        return loss, _softmax(logits, axis=axis)
    return loss


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean",
             name=None):
    input = ensure_tensor(input)
    label = ensure_tensor(label)
    tensors = [input, label] + ([ensure_tensor(weight)] if weight is not None else [])

    def jfn(logp, lbl, *rest):
        lbl_i = lbl.astype(jnp.int32)
        valid = lbl_i != ignore_index
        safe = jnp.where(valid, lbl_i, 0)
        picked = jnp.take_along_axis(
            logp, jnp.expand_dims(safe, 1), axis=1
        ).squeeze(1)
        loss = jnp.where(valid, -picked, 0.0)
        if rest:
            w = rest[0][safe] * valid.astype(logp.dtype)
            loss = loss * rest[0][safe]
            if reduction == "mean":
                return loss.sum() / jnp.maximum(w.sum(), 1e-12)
        elif reduction == "mean":
            return loss.sum() / jnp.maximum(valid.sum(), 1).astype(loss.dtype)
        return _reduce(loss, reduction)

    return apply_jfn("nll_loss", jfn, *tensors)


def mse_loss(input, label, reduction="mean", name=None):
    return apply_jfn(
        "mse_loss",
        lambda a, b: _reduce((a - b) ** 2, reduction),
        ensure_tensor(input), ensure_tensor(label),
    )


def l1_loss(input, label, reduction="mean", name=None):
    return apply_jfn(
        "l1_loss",
        lambda a, b: _reduce(jnp.abs(a - b), reduction),
        ensure_tensor(input), ensure_tensor(label),
    )


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    def jfn(a, b):
        d = a - b
        ad = jnp.abs(d)
        out = jnp.where(ad < delta, 0.5 * d * d / delta, ad - 0.5 * delta)
        return _reduce(out, reduction)

    return apply_jfn("smooth_l1_loss", jfn, ensure_tensor(input),
                     ensure_tensor(label))


def binary_cross_entropy(input, label, weight=None, reduction="mean", name=None):
    tensors = [ensure_tensor(input), ensure_tensor(label)] + (
        [ensure_tensor(weight)] if weight is not None else []
    )

    def jfn(p, y, *rest):
        p = jnp.clip(p, 1e-12, 1.0 - 1e-12)
        out = -(y * jnp.log(p) + (1 - y) * jnp.log(1 - p))
        if rest:
            out = out * rest[0]
        return _reduce(out, reduction)

    return apply_jfn("binary_cross_entropy", jfn, *tensors)


def binary_cross_entropy_with_logits(logit, label, weight=None, reduction="mean",
                                     pos_weight=None, name=None):
    tensors = [ensure_tensor(logit), ensure_tensor(label)]
    if weight is not None:
        tensors.append(ensure_tensor(weight))
    if pos_weight is not None:
        tensors.append(ensure_tensor(pos_weight))

    def jfn(x, y, *rest):
        # stable: max(x,0) - x*y + log(1+exp(-|x|)), with pos_weight folding
        i = 0
        w = rest[i] if weight is not None else None
        if weight is not None:
            i += 1
        pw = rest[i] if pos_weight is not None else None
        if pw is not None:
            log_w = (pw - 1) * y + 1
            out = (1 - y) * x + log_w * (
                jnp.logaddexp(0.0, -jnp.abs(x)) + jnp.maximum(-x, 0.0)
            )
        else:
            out = jnp.maximum(x, 0) - x * y + jnp.logaddexp(0.0, -jnp.abs(x))
        if w is not None:
            out = out * w
        return _reduce(out, reduction)

    return apply_jfn("bce_with_logits", jfn, *tensors)


def kl_div(input, label, reduction="mean", name=None):
    def jfn(logp, y):
        out = jnp.where(y > 0, y * (jnp.log(jnp.clip(y, 1e-12)) - logp), 0.0)
        if reduction == "batchmean":
            return out.sum() / logp.shape[0]
        return _reduce(out, reduction)

    return apply_jfn("kl_div", jfn, ensure_tensor(input), ensure_tensor(label))


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean",
                        name=None):
    def jfn(a, b, y):
        return _reduce(jnp.maximum(0.0, -y * (a - b) + margin), reduction)

    return apply_jfn("margin_ranking_loss", jfn, ensure_tensor(input),
                     ensure_tensor(other), ensure_tensor(label))


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean", name=None):
    def jfn(x, y):
        out = jnp.where(y == 1, x, jnp.maximum(0.0, margin - x))
        return _reduce(out, reduction)

    return apply_jfn("hinge_embedding_loss", jfn, ensure_tensor(input),
                     ensure_tensor(label))


def cosine_embedding_loss(input1, input2, label, margin=0, reduction="mean",
                          name=None):
    def jfn(a, b, y):
        cos = (a * b).sum(-1) / jnp.maximum(
            jnp.linalg.norm(a, axis=-1) * jnp.linalg.norm(b, axis=-1), 1e-12
        )
        out = jnp.where(y == 1, 1 - cos, jnp.maximum(0.0, cos - margin))
        return _reduce(out, reduction)

    return apply_jfn("cosine_embedding_loss", jfn, ensure_tensor(input1),
                     ensure_tensor(input2), ensure_tensor(label))


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2,
                        epsilon=1e-6, swap=False, reduction="mean", name=None):
    def jfn(a, pos, neg):
        dp = jnp.linalg.norm(a - pos + epsilon, ord=p, axis=-1)
        dn = jnp.linalg.norm(a - neg + epsilon, ord=p, axis=-1)
        if swap:
            dn2 = jnp.linalg.norm(pos - neg + epsilon, ord=p, axis=-1)
            dn = jnp.minimum(dn, dn2)
        return _reduce(jnp.maximum(0.0, dp - dn + margin), reduction)

    return apply_jfn("triplet_margin_loss", jfn, ensure_tensor(input),
                     ensure_tensor(positive), ensure_tensor(negative))


def log_loss(input, label, epsilon=1e-4, name=None):
    def jfn(p, y):
        return -y * jnp.log(p + epsilon) - (1 - y) * jnp.log(1 - p + epsilon)

    return apply_jfn("log_loss", jfn, ensure_tensor(input), ensure_tensor(label))


def square_error_cost(input, label):
    return apply_jfn("square_error_cost", lambda a, b: (a - b) ** 2,
                     ensure_tensor(input), ensure_tensor(label))


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                       reduction="sum", name=None):
    tensors = [ensure_tensor(logit), ensure_tensor(label)]
    if normalizer is not None:
        tensors.append(ensure_tensor(normalizer))

    def jfn(x, y, *rest):
        p = jax.nn.sigmoid(x)
        ce = jnp.maximum(x, 0) - x * y + jnp.logaddexp(0.0, -jnp.abs(x))
        p_t = p * y + (1 - p) * (1 - y)
        a_t = alpha * y + (1 - alpha) * (1 - y)
        out = a_t * ((1 - p_t) ** gamma) * ce
        if rest:
            out = out / rest[0]
        return _reduce(out, reduction)

    return apply_jfn("sigmoid_focal_loss", jfn, *tensors)


def huber_loss(input, label, delta=1.0, reduction="mean", name=None):
    """(reference: python/paddle/nn/functional/loss.py huber_loss)."""
    input = ensure_tensor(input)
    label = ensure_tensor(label)

    def jfn(x, y):
        r = x - y
        a = jnp.abs(r)
        return _reduce(jnp.where(a <= delta, 0.5 * r * r,
                                 delta * (a - 0.5 * delta)), reduction)

    return apply_jfn("huber_loss", jfn, input, label)


def poisson_nll_loss(input, label, log_input=True, full=False,
                     epsilon=1e-8, reduction="mean", name=None):
    """(reference loss.py poisson_nll_loss; optional Stirling term)."""
    input = ensure_tensor(input)
    label = ensure_tensor(label)

    def jfn(x, y):
        if log_input:
            out = jnp.exp(x) - y * x
        else:
            out = x - y * jnp.log(x + epsilon)
        if full:
            stir = (y * jnp.log(y + epsilon) - y
                    + 0.5 * jnp.log(2 * jnp.pi * (y + epsilon)))
            out = out + jnp.where(y > 1, stir, 0.0)
        return _reduce(out, reduction)

    return apply_jfn("poisson_nll_loss", jfn, input, label)


def multi_label_soft_margin_loss(input, label, weight=None,
                                 reduction="mean", name=None):
    """(reference loss.py multi_label_soft_margin_loss): mean over
    classes of BCE-with-logits against ±1-style multi-hot labels."""
    input = ensure_tensor(input)
    label = ensure_tensor(label)
    tensors = [input, label]
    if weight is not None:
        tensors.append(ensure_tensor(weight))

    def jfn(x, y, *w):
        term = (y * jax.nn.log_sigmoid(x)
                + (1 - y) * jax.nn.log_sigmoid(-x))
        if w:
            term = term * w[0]
        return _reduce(-term.mean(axis=-1), reduction)

    return apply_jfn("multi_label_soft_margin", jfn, *tensors)


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False):
    """CTC loss (reference: python/paddle/nn/functional/loss.py ctc_loss
    → warpctc op paddle/fluid/operators/warpctc_op.cc).

    log_probs: [T, B, C] UNNORMALIZED logits (log_softmax applied here,
    as warpctc does); labels [B, L]; lengths per batch. TPU-first: the
    alpha recursion is one lax.scan over time in the log semiring,
    vectorized over batch and extended-label position — no per-sample
    loops, static shapes."""
    lp_t = ensure_tensor(log_probs)
    lab_t = ensure_tensor(labels)
    il = jnp.asarray(value_of(ensure_tensor(input_lengths)))
    ll = jnp.asarray(value_of(ensure_tensor(label_lengths)))

    def jfn(logits, lab):
        T, B, C = logits.shape
        L = lab.shape[1]
        lp = jax.nn.log_softmax(logits, axis=-1)
        S = 2 * L + 1
        # extended labels: blank, l1, blank, l2, ..., blank
        ext = jnp.full((B, S), blank, lab.dtype)
        ext = ext.at[:, 1::2].set(lab)
        NEG = jnp.float32(-1e30)
        pos = jnp.arange(S)[None, :]

        # allowed skip (s-2 → s): only onto a label that differs from
        # the label two back
        ext_m2 = jnp.pad(ext, ((0, 0), (2, 0)), constant_values=-1)[:, :S]
        can_skip = (ext != blank) & (ext != ext_m2)

        emit0 = jnp.take_along_axis(lp[0], ext, axis=1)  # [B, S]
        alpha0 = jnp.where(pos < 2, emit0, NEG)

        def step(alpha, t):
            prev1 = jnp.pad(alpha, ((0, 0), (1, 0)),
                            constant_values=NEG)[:, :S]
            prev2 = jnp.pad(alpha, ((0, 0), (2, 0)),
                            constant_values=NEG)[:, :S]
            prev2 = jnp.where(can_skip, prev2, NEG)
            merged = jnp.logaddexp(jnp.logaddexp(alpha, prev1), prev2)
            emit = jnp.take_along_axis(lp[t], ext, axis=1)
            new = merged + emit
            # sequences already past their input length keep alpha
            active = (t < il)[:, None]
            return jnp.where(active, new, alpha), None

        alpha, _ = jax.lax.scan(step, alpha0, jnp.arange(1, T))
        # ends: positions 2*ll and 2*ll-1 of the extended sequence
        end_blank = jnp.take_along_axis(alpha, (2 * ll)[:, None], 1)[:, 0]
        end_label = jnp.take_along_axis(
            alpha, jnp.maximum(2 * ll - 1, 0)[:, None], 1)[:, 0]
        # empty target: the only end state is the blank at position 0
        # (the clamped 2·ll−1 read would double-count it)
        nll = jnp.where(ll > 0, -jnp.logaddexp(end_blank, end_label),
                        -end_blank)
        if norm_by_times:
            # warpctc's norm_by_times: scale by the input length
            nll = nll / jnp.maximum(il, 1).astype(nll.dtype)
        return nll

    loss = apply_jfn("ctc_loss", jfn, lp_t, lab_t)
    if reduction == "mean":
        from ...ops.math import mean as t_mean

        return t_mean(loss / ensure_tensor(
            jnp.maximum(ll, 1).astype(jnp.float32)))
    if reduction == "sum":
        from ...ops.math import sum as t_sum

        return t_sum(loss)
    return loss
