"""Loss functionals.

(Reference: paddle/phi/kernels/gpu/cross_entropy_kernel.cu,
python/paddle/nn/functional/loss.py. The softmax-CE here is the
log-sum-exp formulation XLA fuses into one kernel; the Pallas fused
vocab-parallel variant lives in ops/pallas_kernels.)
"""
import functools

import jax
import jax.numpy as jnp

from ...autograd import engine
from ...ops._helpers import apply_jfn, ensure_tensor, value_of

__all__ = [
    "cross_entropy",
    "ctc_loss",
    "huber_loss",
    "poisson_nll_loss",
    "multi_label_soft_margin_loss",
    "softmax_with_cross_entropy",
    "nll_loss",
    "mse_loss",
    "l1_loss",
    "smooth_l1_loss",
    "binary_cross_entropy",
    "binary_cross_entropy_with_logits",
    "kl_div",
    "margin_ranking_loss",
    "hinge_embedding_loss",
    "cosine_embedding_loss",
    "triplet_margin_loss",
    "log_loss",
    "square_error_cost",
    "sigmoid_focal_loss",
    "dice_loss",
    "npair_loss",
    "soft_margin_loss",
    "triplet_margin_with_distance_loss",
    "hsigmoid_loss",
    "margin_cross_entropy",
    "fused_linear_cross_entropy",
]


def _reduce(out, reduction):
    if reduction == "mean":
        return jnp.mean(out)
    if reduction == "sum":
        return jnp.sum(out)
    return out


@functools.partial(jax.custom_vjp, nondiff_argnums=())
def _softmax_ce_core(logits, labels):
    """Per-position softmax CE over the last axis: lse(logits) - logits[label].

    Memory-lean custom VJP: the fp32 upcast is consumed only by reduces and a
    gather, so XLA fuses the convert into the reduction loops — no fp32
    [..., vocab] array is ever written to HBM, and the backward recomputes
    softmax from the (bf16) logits instead of saving fp32 log-probs. This is
    the fused-CE capability of the reference's
    c_softmax_with_cross_entropy / cross_entropy_kernel.cu, TPU-style.
    """
    out, _ = _softmax_ce_fwd(logits, labels)
    return out


def _softmax_ce_fwd(logits, labels):
    lf = logits.astype(jnp.float32)
    m = jnp.max(lf, axis=-1)
    lse = m + jnp.log(jnp.sum(jnp.exp(lf - m[..., None]), axis=-1))
    picked = jnp.take_along_axis(
        lf, labels[..., None].astype(jnp.int32), axis=-1)[..., 0]
    return lse - picked, (logits, labels, lse)


def _softmax_ce_bwd(res, g):
    logits, labels, lse = res
    lf = logits.astype(jnp.float32)
    p = jnp.exp(lf - lse[..., None])
    # one-hot as a fused iota compare (a jax.nn.one_hot array would be a
    # full [..., vocab] fp32 materialization — the thing we're avoiding)
    hit = jax.lax.broadcasted_iota(
        jnp.int32, lf.shape, lf.ndim - 1) == labels[..., None].astype(
            jnp.int32)
    d = (p - hit.astype(jnp.float32)) * g[..., None]
    return d.astype(logits.dtype), None


_softmax_ce_core.defvjp(_softmax_ce_fwd, _softmax_ce_bwd)


# ---------------------------------------------------------------------------
# Fused LM-head projection + softmax CE, logits never materialized.
#
# The vocab head of a GPT-style model turns a [N, d] hidden block into
# [N, V] logits (V ~ 50k) only to immediately reduce them to N scalars.
# At b16·s1024 that intermediate is ~1.6 GB of bf16 HBM traffic in the
# forward and again in the backward — the single largest slab of the
# step (docs/PERF_NOTES.md hypothesis 1). This kernel scans over token
# blocks: each block's logits live only inside one scan iteration
# (XLA keeps them in registers/VMEM-sized tiles), the forward saves just
# the per-token LSE [N], and the backward recomputes each block's logits
# from (x, w) instead of loading them. FLOPs go up by the head fwd
# matmul (~+50% of head cost); HBM traffic for the [N, V] slab goes to
# zero. Same trade the reference's fused kernels make
# (paddle/fluid/operators/fused/fused_attention_op.cu recomputes rather
# than stores), applied to the head.
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def _linear_ce_core(x, w, bias, labels, block):
    out, _ = _linear_ce_fwd(x, w, bias, labels, block)
    return out


def _block_logits(xi, w, bias):
    # bf16 MXU matmul, fp32 accumulation/output: LSE stays accurate
    # without an fp32 [block, V] weight copy.
    logits = jnp.dot(xi, w, preferred_element_type=jnp.float32)
    return logits + bias.astype(jnp.float32)


def _linear_ce_fwd(x, w, bias, labels, block):
    n = x.shape[0]
    nb = n // block
    xb = x.reshape(nb, block, x.shape[1])
    lb = labels.reshape(nb, block)

    def body(_, xl):
        xi, li = xl
        logits = _block_logits(xi, w, bias)
        m = jnp.max(logits, axis=-1)
        lse = m + jnp.log(jnp.sum(jnp.exp(logits - m[:, None]), axis=-1))
        picked = jnp.take_along_axis(logits, li[:, None], axis=-1)[:, 0]
        return None, (lse - picked, lse)

    _, (loss, lse) = jax.lax.scan(body, None, (xb, lb))
    return loss.reshape(n), (x, w, bias, labels, lse.reshape(n))


def _linear_ce_bwd(block, res, g):
    x, w, bias, labels, lse = res
    n = x.shape[0]
    nb = n // block
    xb = x.reshape(nb, block, x.shape[1])
    lb = labels.reshape(nb, block)
    lseb = lse.reshape(nb, block)
    gb = g.reshape(nb, block)

    def body(carry, inp):
        dw, db = carry
        xi, li, lsei, gi = inp
        logits = _block_logits(xi, w, bias)
        p = jnp.exp(logits - lsei[:, None])
        hit = jax.lax.broadcasted_iota(jnp.int32, p.shape, 1) == li[:, None]
        d = (p - hit.astype(jnp.float32)) * gi[:, None]
        dl = d.astype(w.dtype)  # bf16 operand for both MXU matmuls
        dxi = jnp.dot(dl, w.T)
        dw = dw + jnp.dot(xi.T, dl, preferred_element_type=jnp.float32)
        db = db + jnp.sum(d, axis=0)
        return (dw, db), dxi

    # The dw carry stays fp32 regardless of w's dtype: a bf16 carry
    # rounds the running sum to an 8-bit mantissa every block, losing
    # small per-block contributions as the block count grows (long
    # sequences / small block_size) — a silent gradient-quality
    # regression under AMP. The HBM cost is one fp32 [h, V] carry
    # round-trip per block; keep block_size large (default 4096 → ~4
    # round-trips) rather than narrowing the accumulator.
    dw0 = jnp.zeros(w.shape, jnp.float32)
    db0 = jnp.zeros(bias.shape, jnp.float32)
    (dw, db), dx = jax.lax.scan(body, (dw0, db0), (xb, lb, lseb, gb))
    return (dx.reshape(x.shape).astype(x.dtype), dw.astype(w.dtype),
            db.astype(bias.dtype), None)


_linear_ce_core.defvjp(_linear_ce_fwd, _linear_ce_bwd)


def linear_ce_raw(x2d, w, labels, block_size=4096, bias=None):
    """Raw-array (jnp in / jnp out) form of the fused linear+CE: per-row
    losses for ``x2d @ w (+ bias)`` vs int ``labels``, logits never
    materialized. Handles pad-to-block internally; vjp-compatible, so it
    drops into shard_map'd pipeline loss fns (gpt_pipeline._loss_fn)."""
    n = x2d.shape[0]
    vocab = w.shape[1]
    if bias is None:
        bias = jnp.zeros((vocab,), x2d.dtype)
    labels = labels.astype(jnp.int32)
    block = min(block_size, max(n, 1))
    npad = (-n) % block
    if npad:
        x2d = jnp.pad(x2d, ((0, npad), (0, 0)))
        labels = jnp.pad(labels, (0, npad))
    return _linear_ce_core(x2d, w, bias, labels, block)[:n]


def fused_linear_cross_entropy(x, weight, label, bias=None,
                               transpose_weight=False, ignore_index=-100,
                               reduction="mean", block_size=4096, name=None):
    """Softmax CE of ``x @ weight (+ bias)`` without materializing logits.

    ``x``: [..., d] hidden states; ``weight``: [d, V] (or [V, d] with
    ``transpose_weight=True`` — the tied-embedding layout); ``label``:
    [...] int class ids. Scans over ``block_size``-token blocks so the
    [tokens, V] logits exist only tile-at-a-time; backward recomputes
    them per block. See the design note above `_linear_ce_core`.
    """
    x = ensure_tensor(x)
    weight = ensure_tensor(weight)
    label = ensure_tensor(label)
    tensors = [x, weight, label]
    if bias is not None:
        tensors.append(ensure_tensor(bias))

    def jfn(xv, wv, lblv, *rest):
        d = xv.shape[-1]
        xf = xv.reshape(-1, d)
        wf = wv.T if transpose_weight else wv
        bv = rest[0] if rest else None  # linear_ce_raw owns the default
        lf = lblv.reshape(-1).astype(jnp.int32)
        valid = lf != ignore_index
        safe = jnp.where(valid, lf, 0)
        # linear_ce_raw pads to a block multiple internally (shifted
        # sequences make n = b*(s-1), rarely divisible); grad-of-slice
        # zeros the pad rows' cotangent
        loss = linear_ce_raw(xf, wf, safe, block_size=block_size, bias=bv)
        loss = jnp.where(valid, loss, 0.0)
        if reduction == "mean":
            denom = jnp.maximum(valid.sum(), 1).astype(loss.dtype)
            return loss.sum() / denom
        if reduction == "sum":
            return loss.sum()
        return loss.reshape(lblv.shape)

    return apply_jfn("fused_linear_cross_entropy", jfn, *tensors)


def cross_entropy(input, label, weight=None, ignore_index=-100, reduction="mean",
                  soft_label=False, axis=-1, use_softmax=True, name=None):
    input = ensure_tensor(input)
    label = ensure_tensor(label)
    tensors = [input, label] + ([ensure_tensor(weight)] if weight is not None else [])

    def jfn(logits, lbl, *rest):
        fused = (not soft_label) and use_softmax and (
            axis == -1 or axis == logits.ndim - 1)
        if fused:
            logp = logits  # placeholder for ndim only
        elif use_softmax:
            # fp32 here regardless of AMP: cross_entropy is off the AMP
            # black list (the fused path handles its own precision)
            logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=axis)
        else:
            logp = jnp.log(jnp.clip(logits.astype(jnp.float32), 1e-15, 1.0))
        if soft_label:
            if rest:
                # class weights apply to soft labels too (reference
                # python/paddle/nn/functional/loss.py weighted soft-label
                # branch): weight each class term, normalize the mean by
                # the effective per-sample weight sum
                w = rest[0]
                wshape = [1] * logp.ndim
                wshape[axis if axis >= 0 else logp.ndim + axis] = -1
                wb = w.reshape(wshape).astype(logp.dtype)
                loss = -(lbl * wb * logp).sum(axis=axis)
                if reduction == "mean":
                    denom = (lbl * wb).sum(axis=axis)
                    return loss.sum() / jnp.maximum(denom.sum(), 1e-12)
            else:
                loss = -(lbl * logp).sum(axis=axis)
            if reduction == "none":
                loss = jnp.expand_dims(loss, axis)
            return _reduce(loss, reduction)
        lbl_i = lbl.astype(jnp.int32)
        squeeze_axis = axis if axis >= 0 else logp.ndim + axis
        if lbl_i.ndim == logp.ndim:
            lbl_i = jnp.squeeze(lbl_i, axis=squeeze_axis)
        valid = lbl_i != ignore_index
        safe = jnp.where(valid, lbl_i, 0)
        if fused:
            # fused memory-lean path: no fp32 [..., vocab] materialization
            loss = jnp.where(valid, _softmax_ce_core(logits, safe), 0.0)
        else:
            picked = jnp.take_along_axis(
                logp, jnp.expand_dims(safe, squeeze_axis), axis=squeeze_axis
            ).squeeze(squeeze_axis)
            loss = jnp.where(valid, -picked, 0.0)
        if rest:
            # accumulate the weight-sum denominator in the loss dtype
            # (f32 on both paths), never in the bf16 logits dtype
            w = rest[0][safe] * valid.astype(loss.dtype)
            loss = loss * rest[0][safe]
            if reduction == "mean":
                return loss.sum() / jnp.maximum(w.sum(), 1e-12)
        elif reduction == "mean":
            denom = jnp.maximum(valid.sum(), 1)
            return loss.sum() / denom.astype(loss.dtype)
        return _reduce(loss, reduction)

    return apply_jfn("cross_entropy", jfn, *tensors)


def softmax_with_cross_entropy(logits, label, soft_label=False,
                               ignore_index=-100, numeric_stable_mode=True,
                               return_softmax=False, axis=-1):
    loss = cross_entropy(logits, label, soft_label=soft_label,
                         ignore_index=ignore_index, reduction="none", axis=axis)
    # reference returns loss with the class axis kept as size-1
    from ...ops import manipulation as manip
    if not soft_label:
        loss = manip.unsqueeze(loss, axis)
    if return_softmax:
        from ...ops.activation import softmax as _softmax
        return loss, _softmax(logits, axis=axis)
    return loss


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean",
             name=None):
    input = ensure_tensor(input)
    label = ensure_tensor(label)
    tensors = [input, label] + ([ensure_tensor(weight)] if weight is not None else [])

    def jfn(logp, lbl, *rest):
        lbl_i = lbl.astype(jnp.int32)
        valid = lbl_i != ignore_index
        safe = jnp.where(valid, lbl_i, 0)
        picked = jnp.take_along_axis(
            logp, jnp.expand_dims(safe, 1), axis=1
        ).squeeze(1)
        loss = jnp.where(valid, -picked, 0.0)
        if rest:
            w = rest[0][safe] * valid.astype(logp.dtype)
            loss = loss * rest[0][safe]
            if reduction == "mean":
                return loss.sum() / jnp.maximum(w.sum(), 1e-12)
        elif reduction == "mean":
            return loss.sum() / jnp.maximum(valid.sum(), 1).astype(loss.dtype)
        return _reduce(loss, reduction)

    return apply_jfn("nll_loss", jfn, *tensors)


def mse_loss(input, label, reduction="mean", name=None):
    return apply_jfn(
        "mse_loss",
        lambda a, b: _reduce((a - b) ** 2, reduction),
        ensure_tensor(input), ensure_tensor(label),
    )


def l1_loss(input, label, reduction="mean", name=None):
    return apply_jfn(
        "l1_loss",
        lambda a, b: _reduce(jnp.abs(a - b), reduction),
        ensure_tensor(input), ensure_tensor(label),
    )


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    def jfn(a, b):
        d = a - b
        ad = jnp.abs(d)
        out = jnp.where(ad < delta, 0.5 * d * d / delta, ad - 0.5 * delta)
        return _reduce(out, reduction)

    return apply_jfn("smooth_l1_loss", jfn, ensure_tensor(input),
                     ensure_tensor(label))


def binary_cross_entropy(input, label, weight=None, reduction="mean", name=None):
    tensors = [ensure_tensor(input), ensure_tensor(label)] + (
        [ensure_tensor(weight)] if weight is not None else []
    )

    def jfn(p, y, *rest):
        p = jnp.clip(p, 1e-12, 1.0 - 1e-12)
        out = -(y * jnp.log(p) + (1 - y) * jnp.log(1 - p))
        if rest:
            out = out * rest[0]
        return _reduce(out, reduction)

    return apply_jfn("binary_cross_entropy", jfn, *tensors)


def binary_cross_entropy_with_logits(logit, label, weight=None, reduction="mean",
                                     pos_weight=None, name=None):
    tensors = [ensure_tensor(logit), ensure_tensor(label)]
    if weight is not None:
        tensors.append(ensure_tensor(weight))
    if pos_weight is not None:
        tensors.append(ensure_tensor(pos_weight))

    def jfn(x, y, *rest):
        # stable: max(x,0) - x*y + log(1+exp(-|x|)), with pos_weight folding
        i = 0
        w = rest[i] if weight is not None else None
        if weight is not None:
            i += 1
        pw = rest[i] if pos_weight is not None else None
        if pw is not None:
            log_w = (pw - 1) * y + 1
            out = (1 - y) * x + log_w * (
                jnp.logaddexp(0.0, -jnp.abs(x)) + jnp.maximum(-x, 0.0)
            )
        else:
            out = jnp.maximum(x, 0) - x * y + jnp.logaddexp(0.0, -jnp.abs(x))
        if w is not None:
            out = out * w
        return _reduce(out, reduction)

    return apply_jfn("bce_with_logits", jfn, *tensors)


def kl_div(input, label, reduction="mean", name=None):
    def jfn(logp, y):
        out = jnp.where(y > 0, y * (jnp.log(jnp.clip(y, 1e-12)) - logp), 0.0)
        if reduction == "batchmean":
            return out.sum() / logp.shape[0]
        return _reduce(out, reduction)

    return apply_jfn("kl_div", jfn, ensure_tensor(input), ensure_tensor(label))


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean",
                        name=None):
    def jfn(a, b, y):
        return _reduce(jnp.maximum(0.0, -y * (a - b) + margin), reduction)

    return apply_jfn("margin_ranking_loss", jfn, ensure_tensor(input),
                     ensure_tensor(other), ensure_tensor(label))


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean", name=None):
    def jfn(x, y):
        out = jnp.where(y == 1, x, jnp.maximum(0.0, margin - x))
        return _reduce(out, reduction)

    return apply_jfn("hinge_embedding_loss", jfn, ensure_tensor(input),
                     ensure_tensor(label))


def cosine_embedding_loss(input1, input2, label, margin=0, reduction="mean",
                          name=None):
    def jfn(a, b, y):
        cos = (a * b).sum(-1) / jnp.maximum(
            jnp.linalg.norm(a, axis=-1) * jnp.linalg.norm(b, axis=-1), 1e-12
        )
        out = jnp.where(y == 1, 1 - cos, jnp.maximum(0.0, cos - margin))
        return _reduce(out, reduction)

    return apply_jfn("cosine_embedding_loss", jfn, ensure_tensor(input1),
                     ensure_tensor(input2), ensure_tensor(label))


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2,
                        epsilon=1e-6, swap=False, reduction="mean", name=None):
    def jfn(a, pos, neg):
        dp = jnp.linalg.norm(a - pos + epsilon, ord=p, axis=-1)
        dn = jnp.linalg.norm(a - neg + epsilon, ord=p, axis=-1)
        if swap:
            dn2 = jnp.linalg.norm(pos - neg + epsilon, ord=p, axis=-1)
            dn = jnp.minimum(dn, dn2)
        return _reduce(jnp.maximum(0.0, dp - dn + margin), reduction)

    return apply_jfn("triplet_margin_loss", jfn, ensure_tensor(input),
                     ensure_tensor(positive), ensure_tensor(negative))


def log_loss(input, label, epsilon=1e-4, name=None):
    def jfn(p, y):
        return -y * jnp.log(p + epsilon) - (1 - y) * jnp.log(1 - p + epsilon)

    return apply_jfn("log_loss", jfn, ensure_tensor(input), ensure_tensor(label))


def square_error_cost(input, label):
    return apply_jfn("square_error_cost", lambda a, b: (a - b) ** 2,
                     ensure_tensor(input), ensure_tensor(label))


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                       reduction="sum", name=None):
    tensors = [ensure_tensor(logit), ensure_tensor(label)]
    if normalizer is not None:
        tensors.append(ensure_tensor(normalizer))

    def jfn(x, y, *rest):
        p = jax.nn.sigmoid(x)
        ce = jnp.maximum(x, 0) - x * y + jnp.logaddexp(0.0, -jnp.abs(x))
        p_t = p * y + (1 - p) * (1 - y)
        a_t = alpha * y + (1 - alpha) * (1 - y)
        out = a_t * ((1 - p_t) ** gamma) * ce
        if rest:
            out = out / rest[0]
        return _reduce(out, reduction)

    return apply_jfn("sigmoid_focal_loss", jfn, *tensors)


def huber_loss(input, label, delta=1.0, reduction="mean", name=None):
    """(reference: python/paddle/nn/functional/loss.py huber_loss)."""
    input = ensure_tensor(input)
    label = ensure_tensor(label)

    def jfn(x, y):
        r = x - y
        a = jnp.abs(r)
        return _reduce(jnp.where(a <= delta, 0.5 * r * r,
                                 delta * (a - 0.5 * delta)), reduction)

    return apply_jfn("huber_loss", jfn, input, label)


def poisson_nll_loss(input, label, log_input=True, full=False,
                     epsilon=1e-8, reduction="mean", name=None):
    """(reference loss.py poisson_nll_loss; optional Stirling term)."""
    input = ensure_tensor(input)
    label = ensure_tensor(label)

    def jfn(x, y):
        if log_input:
            out = jnp.exp(x) - y * x
        else:
            out = x - y * jnp.log(x + epsilon)
        if full:
            stir = (y * jnp.log(y + epsilon) - y
                    + 0.5 * jnp.log(2 * jnp.pi * (y + epsilon)))
            out = out + jnp.where(y > 1, stir, 0.0)
        return _reduce(out, reduction)

    return apply_jfn("poisson_nll_loss", jfn, input, label)


def multi_label_soft_margin_loss(input, label, weight=None,
                                 reduction="mean", name=None):
    """(reference loss.py multi_label_soft_margin_loss): mean over
    classes of BCE-with-logits against ±1-style multi-hot labels."""
    input = ensure_tensor(input)
    label = ensure_tensor(label)
    tensors = [input, label]
    if weight is not None:
        tensors.append(ensure_tensor(weight))

    def jfn(x, y, *w):
        term = (y * jax.nn.log_sigmoid(x)
                + (1 - y) * jax.nn.log_sigmoid(-x))
        if w:
            term = term * w[0]
        return _reduce(-term.mean(axis=-1), reduction)

    return apply_jfn("multi_label_soft_margin", jfn, *tensors)


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False):
    """CTC loss (reference: python/paddle/nn/functional/loss.py ctc_loss
    → warpctc op paddle/fluid/operators/warpctc_op.cc).

    log_probs: [T, B, C] UNNORMALIZED logits (log_softmax applied here,
    as warpctc does); labels [B, L]; lengths per batch. TPU-first: the
    alpha recursion is one lax.scan over time in the log semiring,
    vectorized over batch and extended-label position — no per-sample
    loops, static shapes."""
    lp_t = ensure_tensor(log_probs)
    lab_t = ensure_tensor(labels)
    il = jnp.asarray(value_of(ensure_tensor(input_lengths)))
    ll = jnp.asarray(value_of(ensure_tensor(label_lengths)))

    def jfn(logits, lab):
        T, B, C = logits.shape
        L = lab.shape[1]
        lp = jax.nn.log_softmax(logits, axis=-1)
        S = 2 * L + 1
        # extended labels: blank, l1, blank, l2, ..., blank
        ext = jnp.full((B, S), blank, lab.dtype)
        ext = ext.at[:, 1::2].set(lab)
        NEG = jnp.float32(-1e30)
        pos = jnp.arange(S)[None, :]

        # allowed skip (s-2 → s): only onto a label that differs from
        # the label two back
        ext_m2 = jnp.pad(ext, ((0, 0), (2, 0)), constant_values=-1)[:, :S]
        can_skip = (ext != blank) & (ext != ext_m2)

        emit0 = jnp.take_along_axis(lp[0], ext, axis=1)  # [B, S]
        alpha0 = jnp.where(pos < 2, emit0, NEG)

        def step(alpha, t):
            prev1 = jnp.pad(alpha, ((0, 0), (1, 0)),
                            constant_values=NEG)[:, :S]
            prev2 = jnp.pad(alpha, ((0, 0), (2, 0)),
                            constant_values=NEG)[:, :S]
            prev2 = jnp.where(can_skip, prev2, NEG)
            merged = jnp.logaddexp(jnp.logaddexp(alpha, prev1), prev2)
            emit = jnp.take_along_axis(lp[t], ext, axis=1)
            new = merged + emit
            # sequences already past their input length keep alpha
            active = (t < il)[:, None]
            return jnp.where(active, new, alpha), None

        alpha, _ = jax.lax.scan(step, alpha0, jnp.arange(1, T))
        # ends: positions 2*ll and 2*ll-1 of the extended sequence
        end_blank = jnp.take_along_axis(alpha, (2 * ll)[:, None], 1)[:, 0]
        end_label = jnp.take_along_axis(
            alpha, jnp.maximum(2 * ll - 1, 0)[:, None], 1)[:, 0]
        # empty target: the only end state is the blank at position 0
        # (the clamped 2·ll−1 read would double-count it)
        nll = jnp.where(ll > 0, -jnp.logaddexp(end_blank, end_label),
                        -end_blank)
        if norm_by_times:
            # warpctc's norm_by_times: scale by the input length
            nll = nll / jnp.maximum(il, 1).astype(nll.dtype)
        return nll

    loss = apply_jfn("ctc_loss", jfn, lp_t, lab_t)
    if reduction == "mean":
        from ...ops.math import mean as t_mean

        return t_mean(loss / ensure_tensor(
            jnp.maximum(ll, 1).astype(jnp.float32)))
    if reduction == "sum":
        from ...ops.math import sum as t_sum

        return t_sum(loss)
    return loss


def dice_loss(input, label, epsilon=1e-5, name=None):
    """Dice coefficient loss over the last (class) axis
    (reference: python/paddle/nn/functional/loss.py dice_loss)."""
    input = ensure_tensor(input)
    label = ensure_tensor(label)

    def jfn(x, lbl):
        lbl_i = lbl.astype(jnp.int32)
        if lbl_i.ndim == x.ndim:
            lbl_i = jnp.squeeze(lbl_i, -1)
        onehot = jax.nn.one_hot(lbl_i, x.shape[-1], dtype=x.dtype)
        reduce_axes = tuple(range(1, x.ndim))
        inse = jnp.sum(x * onehot, axis=reduce_axes)
        denom = jnp.sum(x, axis=reduce_axes) + jnp.sum(onehot,
                                                       axis=reduce_axes)
        return jnp.mean(1.0 - 2.0 * inse / (denom + epsilon))

    return apply_jfn("dice_loss", jfn, input, label)


def npair_loss(anchor, positive, labels, l2_reg=0.002):
    """N-pair loss (reference: loss.py npair_loss): row-softmax CE over the
    anchor·positiveᵀ similarity with label-equality soft targets, plus an
    L2 pull on the embeddings."""
    anchor = ensure_tensor(anchor)
    positive = ensure_tensor(positive)
    labels = ensure_tensor(labels)

    def jfn(a, p, lbl):
        lbl = lbl.reshape(-1).astype(jnp.float32)
        batch = a.shape[0]
        eq = (lbl[:, None] == lbl[None, :]).astype(a.dtype)
        targets = eq / jnp.maximum(eq.sum(-1, keepdims=True), 1e-12)
        sim = a @ p.T
        logp = jax.nn.log_softmax(sim.astype(jnp.float32), -1)
        ce = jnp.mean(jnp.sum(-targets * logp, axis=-1))
        l2 = (jnp.sum(a * a) + jnp.sum(p * p)) / batch * l2_reg * 0.25
        return ce + l2

    return apply_jfn("npair_loss", jfn, anchor, positive, labels)


def soft_margin_loss(input, label, reduction="mean", name=None):
    """log(1 + exp(-label·input)) with labels in {-1, 1}
    (reference: loss.py soft_margin_loss)."""
    input = ensure_tensor(input)
    label = ensure_tensor(label)

    def jfn(x, y):
        out = jnp.log1p(jnp.exp(-y.astype(x.dtype) * x))
        return _reduce(out, reduction)

    return apply_jfn("soft_margin_loss", jfn, input, label)


def triplet_margin_with_distance_loss(input, positive, negative,
                                      distance_function=None, margin=1.0,
                                      swap=False, reduction="mean",
                                      name=None):
    """max(0, d(a,p) - d(a,n) + margin) with a pluggable distance
    (reference: loss.py triplet_margin_with_distance_loss)."""
    from .common import pairwise_distance

    input = ensure_tensor(input)
    positive = ensure_tensor(positive)
    negative = ensure_tensor(negative)
    dist = distance_function or pairwise_distance
    dp = ensure_tensor(dist(input, positive))
    dn = ensure_tensor(dist(input, negative))
    if swap:
        dpn = ensure_tensor(dist(positive, negative))
        tensors = (dp, dn, dpn)
    else:
        tensors = (dp, dn)

    def jfn(dpv, dnv, *rest):
        if rest:
            dnv = jnp.minimum(dnv, rest[0])
        out = jnp.maximum(dpv - dnv + margin, 0.0)
        return _reduce(out, reduction)

    return engine.apply("triplet_margin_with_distance_loss", jfn, tensors)


def _hsigmoid_default_paths(num_classes):
    """Per-class (node_index, bit) tables for the complete-binary-tree code
    (reference: paddle/fluid/operators/math/matrix_bit_code.h SimpleCode:
    c = label + num_classes, index(bit) = (c >> (bit+1)) - 1,
    bit(bit) = (c >> bit) & 1, length = findLastSet(c) - 1)."""
    import numpy as np

    max_len = int(np.floor(np.log2(2 * num_classes - 1)))
    table = np.full((num_classes, max_len), -1, np.int32)
    code = np.zeros((num_classes, max_len), np.float32)
    for cls in range(num_classes):
        c = cls + num_classes
        length = int(np.floor(np.log2(c)))
        for bit in range(length):
            table[cls, bit] = (c >> (bit + 1)) - 1
            code[cls, bit] = float((c >> bit) & 1)
    return table, code


def hsigmoid_loss(input, label, num_classes, weight, bias=None,
                  path_table=None, path_code=None, is_sparse=False,
                  name=None):
    """Hierarchical sigmoid loss (reference: loss.py hsigmoid_loss →
    phi hsigmoid_loss kernel). Default path uses the complete-binary-tree
    code; custom trees pass path_table/path_code ([N, L], -1-padded)."""
    input = ensure_tensor(input)
    label = ensure_tensor(label)
    weight = ensure_tensor(weight)
    tensors = [input, label, weight]
    if bias is not None:
        tensors.append(ensure_tensor(bias))
    custom = path_table is not None
    if custom:
        tensors.append(ensure_tensor(path_table))
        tensors.append(ensure_tensor(path_code))
    else:
        import numpy as np

        table_np, code_np = _hsigmoid_default_paths(int(num_classes))
        table_c, code_c = jnp.asarray(table_np), jnp.asarray(code_np)

    def jfn(x, lbl, w, *rest):
        rest = list(rest)
        b = rest.pop(0) if bias is not None else None
        if custom:
            tbl = rest.pop(0).astype(jnp.int32)      # [N, L]
            bits = rest.pop(0).astype(jnp.float32)   # [N, L]
        else:
            lbl_i = lbl.reshape(-1).astype(jnp.int32)
            tbl = table_c[lbl_i]
            bits = code_c[lbl_i]
        valid = (tbl >= 0)
        safe = jnp.where(valid, tbl, 0)
        w_path = w[safe]                      # [N, L, D]
        ct = jnp.promote_types(x.dtype, jnp.float32)
        z = jnp.einsum("nd,nld->nl", x.astype(ct), w_path.astype(ct))
        if b is not None:
            z = z + b.reshape(-1)[safe]
        # softplus(z) - bit*z == -log sigmoid BCE on the path decision
        per_node = jnp.where(valid, jax.nn.softplus(z) - bits * z, 0.0)
        return jnp.mean(jnp.sum(per_node, axis=-1, keepdims=True))

    return apply_jfn("hsigmoid_loss", jfn, *tensors)


def margin_cross_entropy(logits, label, margin1=1.0, margin2=0.5,
                         margin3=0.0, scale=64.0, group=None,
                         return_softmax=False, reduction="mean"):
    """ArcFace/CosFace-style margin softmax CE: the target-class cosine
    becomes cos(m1·θ + m2) - m3 before scaling (reference:
    python/paddle/nn/functional/loss.py margin_cross_entropy →
    margin_cross_entropy op). Logits must be cosine similarities."""
    logits = ensure_tensor(logits)
    label = ensure_tensor(label)

    def jfn(cos, lbl):
        lbl_i = lbl.reshape(-1).astype(jnp.int32)
        cf = cos.astype(jnp.promote_types(cos.dtype, jnp.float32))
        hit = jax.lax.broadcasted_iota(
            jnp.int32, cf.shape, cf.ndim - 1) == lbl_i[:, None]
        theta = jnp.arccos(jnp.clip(cf, -1.0 + 1e-7, 1.0 - 1e-7))
        modified = jnp.cos(margin1 * theta + margin2) - margin3
        z = jnp.where(hit, modified, cf) * scale
        logp = jax.nn.log_softmax(z, -1)
        loss = -jnp.take_along_axis(logp, lbl_i[:, None], -1)
        out = _reduce(loss, reduction)
        if return_softmax:
            return out, jnp.exp(logp)
        return out

    return apply_jfn("margin_cross_entropy", jfn, logits, label)
