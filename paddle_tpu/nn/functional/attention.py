"""Attention functional.

(Reference: the fused attention CUDA ops
paddle/fluid/operators/fused/fused_attention_op.cu and fmha_ref.h. On TPU
the default path is the jnp softmax formulation — XLA fuses it well — and
when shapes warrant, the Pallas flash-attention kernel
(ops/pallas_kernels/flash_attention.py) is used instead.)
"""
import math

import jax.numpy as jnp

from ...ops._helpers import apply_jfn, ensure_tensor

__all__ = ["scaled_dot_product_attention", "dense_attention_bshd",
           "paged_attention"]


def dense_attention_bshd(q, k, v, is_causal=False, attn_mask=None,
                         drop_key=None, dropout_p=0.0):
    """Pure-jnp softmax attention on [batch, seq, heads, head_dim] — the
    XLA-fused fallback used when the Pallas kernel is not eligible. Shared
    by scaled_dot_product_attention and the pipelined GPT block."""
    scale = 1.0 / math.sqrt(q.shape[-1])
    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    scores = jnp.einsum("bhqd,bhkd->bhqk", qt, kt) * scale
    if is_causal:
        sq, sk = scores.shape[-2], scores.shape[-1]
        causal = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        scores = jnp.where(causal, scores, jnp.asarray(-jnp.inf,
                                                       scores.dtype))
    if attn_mask is not None:
        if attn_mask.dtype == jnp.bool_:
            scores = jnp.where(attn_mask, scores,
                               jnp.asarray(-jnp.inf, scores.dtype))
        else:
            scores = scores + attn_mask
    w = jnp.exp(scores - scores.max(axis=-1, keepdims=True))
    w = w / w.sum(axis=-1, keepdims=True)
    if drop_key is not None and dropout_p > 0.0:
        import jax

        keep = jax.random.bernoulli(drop_key, 1.0 - dropout_p, w.shape)
        w = jnp.where(keep, w / (1.0 - dropout_p), 0.0)
    out = jnp.einsum("bhqk,bhkd->bhqd", w.astype(vt.dtype), vt)
    return jnp.swapaxes(out, 1, 2)


def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False,
                                 training=True, kv_lens=None, name=None):
    """Inputs [batch, seq, heads, head_dim] (paddle convention).

    kv_lens: optional [batch] int per-example valid key length — the
    prefix key-padding mask (padded BERT/ERNIE batches). Unlike a dense
    `attn_mask` (whose values are unknown at trace time, forcing the jnp
    path), a lengths vector states its structure up front, so it rides
    the Pallas flash kernel. Mutually exclusive with attn_mask.
    """
    query = ensure_tensor(query)
    key = ensure_tensor(key)
    value = ensure_tensor(value)
    if kv_lens is not None and attn_mask is not None:
        raise ValueError("pass either attn_mask or kv_lens, not both")
    tensors = [query, key, value]
    if attn_mask is not None:
        tensors.append(ensure_tensor(attn_mask))

    use_pallas = _pallas_eligible(query, key)
    if use_pallas and attn_mask is None and dropout_p == 0.0:
        from ...ops.pallas_kernels import flash_attention

        if kv_lens is not None:
            lens_t = ensure_tensor(kv_lens)

            def jfn_lens(q, k, v, lens):
                return flash_attention.flash_attention_bshd(
                    q, k, v, causal=is_causal, kv_lens=lens)

            return apply_jfn("flash_attention", jfn_lens, query, key,
                             value, lens_t)

        def jfn(q, k, v):
            return flash_attention.flash_attention_bshd(q, k, v, causal=is_causal)

        return apply_jfn("flash_attention", jfn, query, key, value)

    drop_key = None
    if dropout_p > 0.0 and training:
        from ...core import rng

        drop_key = rng.next_key()

    if kv_lens is not None:
        lens_t = ensure_tensor(kv_lens)

        def jfn_lens(q, k, v, lens):
            lens = lens.astype(jnp.int32)
            # zero-length rows: mask against max(len, 1) (a fully-masked
            # softmax row is NaN and the NaN survives where-grads), then
            # zero those rows — matching the Pallas kernel's safe_l
            # zeros so CPU and TPU agree
            keep = (jnp.arange(k.shape[1])[None, :]
                    < jnp.maximum(lens, 1)[:, None])[:, None, None, :]
            out = dense_attention_bshd(
                q, k, v, is_causal=is_causal, attn_mask=keep,
                drop_key=drop_key, dropout_p=dropout_p)
            return jnp.where((lens > 0)[:, None, None, None], out, 0.0)

        return apply_jfn("scaled_dot_product_attention", jfn_lens, query,
                         key, value, lens_t)

    def jfn(q, k, v, *rest):
        return dense_attention_bshd(
            q, k, v, is_causal=is_causal,
            attn_mask=rest[0] if rest else None,
            drop_key=drop_key, dropout_p=dropout_p)

    return apply_jfn("scaled_dot_product_attention", jfn, *tensors)


def paged_attention(query, k_pool, v_pool, page_tables, slot_ids, kv_lens,
                    k_scales=None, v_scales=None, frontier_offset=None,
                    max_tokens_per_slot=None, name=None):
    """Ragged paged attention over a paged KV-cache pool — the serving
    decode path (inference/llm_engine.py; PAPERS.md "Ragged Paged
    Attention"). One query per FLAT scheduled token, so a single call
    serves a continuous batch mixing decode tokens (1 per sequence) and
    chunked-prefill tokens (many per sequence) with zero padding between
    sequences.

    query        [T, heads, head_dim] — flat token batch
    k_pool/v_pool [num_pages, page_size, heads, head_dim] — the pool;
                 page 0 is by convention the engine's trash page
    page_tables  [num_slots, pages_per_seq] int — physical page id per
                 (slot, logical page); unallocated entries may hold any
                 valid id (they are masked by kv_lens)
    slot_ids     [T] int — owning decode slot per token
    kv_lens      [T] int — valid kv length for each token (its position
                 + 1, i.e. the token attends to its own k/v and every
                 earlier one); 0 marks a padding token → zero output
    k_scales/v_scales  [num_pages, page_size, heads] fp32 — the
                 per-row dequant scales of an INT8 or packed-INT4 pool
                 (quantization runtime, kv_dtype="int8"/"int4"):
                 gathered rows are dequantized `codes * scale` before
                 attention (dequant-on-gather). A pool whose head_dim
                 is HALF the query's holds packed int4 nibbles and is
                 unpacked after the gather. None for float pools.
    frontier_offset  optional scalar int added to every NONZERO
                 kv_lens row (zero rows stay padding). The fused
                 decode window (gpt.py `_paged_decode_fused`) passes
                 its scan iteration here, so the kv_lens VECTOR stays
                 window-invariant and only one scalar advances the
                 frontier per iteration.
    max_tokens_per_slot  optional STATIC int: the caller's guarantee
                 that no slot owns more than this many of the T query
                 tokens. Sizes the jnp slot grid [S, C] at
                 C = max_tokens_per_slot instead of the worst-case
                 C = T — the speculative verify step packs exactly
                 k+1 tokens per slot, so its score tensor shrinks from
                 [S, h, T, L] to [S, h, k+1, L]. When the T tokens are
                 additionally slot-major contiguous in blocks of this
                 size (the verify layout), the Pallas path amortizes
                 each slot's page DMAs across the whole query block.
                 A caller that VIOLATES the bound gets silently
                 dropped queries (out-of-bounds scatter) — it is a
                 contract, not a clamp.

    jnp reference semantics everywhere (mirrors the dense decode path in
    text/models/gpt.py `_cached_attention` op for op, so engine greedy
    decode stays token-identical to `generate()`); the Pallas kernel
    (ops/pallas_kernels/paged_attention.py) takes over behind the same
    TPU gate as flash attention.
    """
    q = ensure_tensor(query)
    kp = ensure_tensor(k_pool)
    vp = ensure_tensor(v_pool)
    pt = ensure_tensor(page_tables)
    sid = ensure_tensor(slot_ids)
    lens = ensure_tensor(kv_lens)
    if (k_scales is None) != (v_scales is None):
        raise ValueError("pass both k_scales and v_scales or neither")
    scales = () if k_scales is None else (
        ensure_tensor(k_scales), ensure_tensor(v_scales))
    has_off = frontier_offset is not None
    off = (ensure_tensor(frontier_offset),) if has_off else ()

    if _paged_pallas_eligible(q, kp):
        from ...ops.pallas_kernels import paged_attention as pa_kernel

        # the blocked-query kernel variant needs the slot-major
        # contract: q rows arrive in contiguous blocks of
        # max_tokens_per_slot, one slot per block (the verify layout)
        qps = (max_tokens_per_slot
               if max_tokens_per_slot is not None
               and q.shape[0] % max_tokens_per_slot == 0 else None)

        def jfn_pallas(qv, kpool, vpool, tables, sids, ls, *rest):
            off_v, sc = ((rest[0], rest[1:]) if has_off
                         else (None, rest))
            return pa_kernel.ragged_paged_attention(
                qv, kpool, vpool, tables, sids, ls,
                k_scales=sc[0] if sc else None,
                v_scales=sc[1] if sc else None,
                frontier_offset=off_v, q_per_slot=qps)

        return apply_jfn("paged_attention", jfn_pallas, q, kp, vp, pt,
                         sid, lens, *off, *scales)

    def jfn(qv, kpool, vpool, tables, sids, ls, *rest):
        import jax

        n_pages, page_size, h, d = kpool.shape
        # a quantized pool whose rows are HALF the query head_dim holds
        # PACKED int4 (kv_dtype="int4"): unpack after the gather, then
        # dequant by the same per-row scale planes. The shape mismatch
        # is the discriminator — an unpacked pool always matches q.
        packed4 = bool(scales) and d * 2 == qv.shape[-1]
        n_slots, pages_per_seq = tables.shape
        tokens = qv.shape[0]
        L = pages_per_seq * page_size
        ls = ls.astype(jnp.int32)
        off_v, sc = (rest[0], rest[1:]) if has_off else (None, rest)
        if has_off:
            # advance every live token's frontier; padding rows stay 0
            ls = jnp.where(ls > 0, ls + off_v.astype(jnp.int32), 0)
        sids = sids.astype(jnp.int32)
        # gather each SLOT's kv once ([S, L, h, d]) and scatter the
        # queries onto a [S, C] slot grid, so the per-TOKEN [T, L, h, d]
        # materialization never forms — 2× fewer bytes moved than the
        # naive per-token gather at serving shapes, and the slot-level
        # einsum is a clean batched matmul. (The Pallas kernel avoids
        # even the [S, L] gather by DMA-ing pages from the table.)
        l_idx = jnp.arange(L, dtype=jnp.int32)
        phys = (tables.astype(jnp.int32)[:, l_idx // page_size]
                * page_size + (l_idx % page_size)[None, :])   # [S, L]
        k_all = kpool.reshape(n_pages * page_size, h, d)
        v_all = vpool.reshape(n_pages * page_size, h, d)
        ks = k_all[phys]                            # [S, L, h, d]
        vs = v_all[phys]
        if sc:  # int8/int4 pool: dequant-on-gather by per-row scales
            if packed4:
                from ...quantization.runtime import unpack_int4

                ks = unpack_int4(ks, axis=-1)   # [S, L, h, 2d] int8
                vs = unpack_int4(vs, axis=-1)
                d = d * 2
            ksc = sc[0].reshape(n_pages * page_size, h)[phys]  # [S,L,h]
            vsc = sc[1].reshape(n_pages * page_size, h)[phys]
            ks = ks.astype(jnp.float32) * ksc[..., None]
            vs = vs.astype(jnp.float32) * vsc[..., None]
        # chunk position of each token within its slot (order-stable):
        # cpos[t] = #earlier tokens with the same slot — collision-free
        # grid coordinates whatever order the scheduler packed
        eq = sids[:, None] == sids[None, :]
        cpos = jnp.sum(jnp.tril(eq, -1), axis=1)    # [T]
        # worst case one slot owns every token; a caller-provided
        # per-slot bound (the verify step: exactly k+1) shrinks the
        # grid — and the [S, h, C, L] score tensor — accordingly
        C = (tokens if max_tokens_per_slot is None
             else min(tokens, int(max_tokens_per_slot)))
        qs = jnp.zeros((n_slots, C, h, d), qv.dtype).at[
            (sids, cpos)].set(qv)
        lgrid = jnp.zeros((n_slots, C), jnp.int32).at[
            (sids, cpos)].set(ls)
        sc = jnp.einsum("schd,slhd->shcl", qs, ks) / math.sqrt(d)
        allowed = (l_idx[None, None, None, :]
                   < lgrid[:, None, :, None])
        sc = jnp.where(allowed, sc, jnp.float32(-1e30))
        # softmax statistics in f32 even for bf16 pools (same contract
        # as _cached_attention); empty grid cells softmax to uniform
        # garbage but are never gathered back
        w = jax.nn.softmax(sc.astype(jnp.float32), axis=-1).astype(
            vs.dtype)
        o = jnp.einsum("shcl,slhd->schd", w, vs).astype(qv.dtype)
        out = o[(sids, cpos)]                       # [T, h, d]
        # padding tokens (kv_len 0): the fully-masked softmax row is
        # uniform garbage — zero it explicitly
        return jnp.where((ls > 0)[:, None, None], out,
                         jnp.zeros_like(out))

    return apply_jfn("paged_attention", jfn, q, kp, vp, pt, sid, lens,
                     *off, *scales)


def _pallas_backend_ok():
    """The shared Pallas gate policy: kernels flag on AND a real TPU
    backend (ONE place — both the flash and the paged gates call it)."""
    from ...core import flags

    if not flags.get_flag("use_pallas_kernels"):
        return False
    try:
        import jax

        return jax.default_backend() == "tpu"
    except Exception:
        return False


def _paged_pallas_eligible(q, k_pool):
    """Pallas ragged-paged-attention gate: `_pallas_backend_ok` +
    MXU-friendly head_dim + lane-tileable page size (the grid is
    per-token so seq alignment is moot)."""
    return (
        _pallas_backend_ok()
        and len(q.shape) == 3
        and q.shape[2] in (64, 128, 256)
        and k_pool.shape[1] % 8 == 0
    )


def _pallas_eligible(q, k):
    """Use the Pallas kernel only on real TPU backends with tileable shapes
    (both q and kv sequence lengths; the kernel assumes self-attention
    geometry for the causal diagonal)."""
    shape = q.shape
    return (
        _pallas_backend_ok()
        and len(shape) == 4
        and shape[1] % 128 == 0
        and k.shape[1] == shape[1]
        and shape[3] in (64, 128, 256)
    )
