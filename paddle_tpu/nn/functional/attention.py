"""Attention functional.

(Reference: the fused attention CUDA ops
paddle/fluid/operators/fused/fused_attention_op.cu and fmha_ref.h. On TPU
the default path is the jnp softmax formulation — XLA fuses it well — and
when shapes warrant, the Pallas flash-attention kernel
(ops/pallas_kernels/flash_attention.py) is used instead.)
"""
import math

import jax.numpy as jnp

from ...ops._helpers import apply_jfn, ensure_tensor

__all__ = ["scaled_dot_product_attention", "dense_attention_bshd"]


def dense_attention_bshd(q, k, v, is_causal=False, attn_mask=None,
                         drop_key=None, dropout_p=0.0):
    """Pure-jnp softmax attention on [batch, seq, heads, head_dim] — the
    XLA-fused fallback used when the Pallas kernel is not eligible. Shared
    by scaled_dot_product_attention and the pipelined GPT block."""
    scale = 1.0 / math.sqrt(q.shape[-1])
    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    scores = jnp.einsum("bhqd,bhkd->bhqk", qt, kt) * scale
    if is_causal:
        sq, sk = scores.shape[-2], scores.shape[-1]
        causal = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        scores = jnp.where(causal, scores, jnp.asarray(-jnp.inf,
                                                       scores.dtype))
    if attn_mask is not None:
        if attn_mask.dtype == jnp.bool_:
            scores = jnp.where(attn_mask, scores,
                               jnp.asarray(-jnp.inf, scores.dtype))
        else:
            scores = scores + attn_mask
    w = jnp.exp(scores - scores.max(axis=-1, keepdims=True))
    w = w / w.sum(axis=-1, keepdims=True)
    if drop_key is not None and dropout_p > 0.0:
        import jax

        keep = jax.random.bernoulli(drop_key, 1.0 - dropout_p, w.shape)
        w = jnp.where(keep, w / (1.0 - dropout_p), 0.0)
    out = jnp.einsum("bhqk,bhkd->bhqd", w.astype(vt.dtype), vt)
    return jnp.swapaxes(out, 1, 2)


def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False,
                                 training=True, kv_lens=None, name=None):
    """Inputs [batch, seq, heads, head_dim] (paddle convention).

    kv_lens: optional [batch] int per-example valid key length — the
    prefix key-padding mask (padded BERT/ERNIE batches). Unlike a dense
    `attn_mask` (whose values are unknown at trace time, forcing the jnp
    path), a lengths vector states its structure up front, so it rides
    the Pallas flash kernel. Mutually exclusive with attn_mask.
    """
    query = ensure_tensor(query)
    key = ensure_tensor(key)
    value = ensure_tensor(value)
    if kv_lens is not None and attn_mask is not None:
        raise ValueError("pass either attn_mask or kv_lens, not both")
    tensors = [query, key, value]
    if attn_mask is not None:
        tensors.append(ensure_tensor(attn_mask))

    use_pallas = _pallas_eligible(query, key)
    if use_pallas and attn_mask is None and dropout_p == 0.0:
        from ...ops.pallas_kernels import flash_attention

        if kv_lens is not None:
            lens_t = ensure_tensor(kv_lens)

            def jfn_lens(q, k, v, lens):
                return flash_attention.flash_attention_bshd(
                    q, k, v, causal=is_causal, kv_lens=lens)

            return apply_jfn("flash_attention", jfn_lens, query, key,
                             value, lens_t)

        def jfn(q, k, v):
            return flash_attention.flash_attention_bshd(q, k, v, causal=is_causal)

        return apply_jfn("flash_attention", jfn, query, key, value)

    drop_key = None
    if dropout_p > 0.0 and training:
        from ...core import rng

        drop_key = rng.next_key()

    if kv_lens is not None:
        lens_t = ensure_tensor(kv_lens)

        def jfn_lens(q, k, v, lens):
            lens = lens.astype(jnp.int32)
            # zero-length rows: mask against max(len, 1) (a fully-masked
            # softmax row is NaN and the NaN survives where-grads), then
            # zero those rows — matching the Pallas kernel's safe_l
            # zeros so CPU and TPU agree
            keep = (jnp.arange(k.shape[1])[None, :]
                    < jnp.maximum(lens, 1)[:, None])[:, None, None, :]
            out = dense_attention_bshd(
                q, k, v, is_causal=is_causal, attn_mask=keep,
                drop_key=drop_key, dropout_p=dropout_p)
            return jnp.where((lens > 0)[:, None, None, None], out, 0.0)

        return apply_jfn("scaled_dot_product_attention", jfn_lens, query,
                         key, value, lens_t)

    def jfn(q, k, v, *rest):
        return dense_attention_bshd(
            q, k, v, is_causal=is_causal,
            attn_mask=rest[0] if rest else None,
            drop_key=drop_key, dropout_p=dropout_p)

    return apply_jfn("scaled_dot_product_attention", jfn, *tensors)


def _pallas_eligible(q, k):
    """Use the Pallas kernel only on real TPU backends with tileable shapes
    (both q and kv sequence lengths; the kernel assumes self-attention
    geometry for the causal diagonal)."""
    from ...core import flags

    if not flags.get_flag("use_pallas_kernels"):
        return False
    try:
        import jax

        if jax.default_backend() != "tpu":
            return False
    except Exception:
        return False
    shape = q.shape
    return (
        len(shape) == 4
        and shape[1] % 128 == 0
        and k.shape[1] == shape[1]
        and shape[3] in (64, 128, 256)
    )
