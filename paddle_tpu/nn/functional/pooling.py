"""Pooling functionals via lax.reduce_window.

(Reference: paddle/phi/kernels/funcs/pooling.h + gpu pool kernels; on TPU
reduce_window is the native windowed-reduction primitive and XLA fuses the
divide for avg pool.)
"""
import numpy as np

import jax.numpy as jnp
from jax import lax

from ...ops._helpers import apply_jfn, ensure_tensor

__all__ = [
    "max_pool1d",
    "max_pool2d",
    "max_pool3d",
    "max_unpool1d",
    "max_unpool2d",
    "max_unpool3d",
    "avg_pool1d",
    "avg_pool2d",
    "avg_pool3d",
    "adaptive_avg_pool1d",
    "adaptive_avg_pool2d",
    "adaptive_avg_pool3d",
    "adaptive_max_pool1d",
    "adaptive_max_pool2d",
    "adaptive_max_pool3d",
]


def _norm(v, n):
    if v is None:
        return None
    if isinstance(v, int):
        return (v,) * n
    v = tuple(int(x) for x in v)
    return v * n if len(v) == 1 else v


def _concrete_init(init, dtype):
    """reduce_window init must be a CONCRETE scalar: jax's monoid matcher
    (reduce_window -> the differentiable reduce_window_max/add primitives)
    compares it by value, which fails on traced/device arrays under jit."""
    return np.asarray(init, dtype)[()]


def _pool(x, n, kernel, stride, padding, mode, ceil_mode, exclusive,
          channel_last, return_mask=False):
    kernel = _norm(kernel, n)
    stride = _norm(stride, n) or kernel
    if isinstance(padding, str):
        pad = padding.upper()
    else:
        p = _norm(padding, n)
        pad = [(pi, pi) for pi in p]
    x = ensure_tensor(x)

    if channel_last:
        dims = (1,) + kernel + (1,)
        strides = (1,) + stride + (1,)
        pads = [(0, 0)] + (pad if not isinstance(pad, str) else []) + [(0, 0)]
    else:
        dims = (1, 1) + kernel
        strides = (1, 1) + stride
        pads = [(0, 0), (0, 0)] + (pad if not isinstance(pad, str) else [])

    if ceil_mode and not isinstance(pad, str):
        # grow the high-side padding so the last partial window is included
        def ceil_extra(size, k, s, lo, hi):
            out = -(-(size + lo + hi - k) // s) + 1
            needed = (out - 1) * s + k - (size + lo + hi)
            return max(0, needed)
        pads = list(pads)

    if mode == "max":
        init, op = -jnp.inf, lax.max

        if return_mask:
            spatial_axes = (
                tuple(range(x._value.ndim - n - 1, x._value.ndim - 1))
                if channel_last else
                tuple(range(x._value.ndim - n, x._value.ndim))
            )

            def jfn_mask(xv):
                p = pads
                if isinstance(pad, str):
                    raise ValueError(
                        "return_mask with string padding is unsupported")
                if ceil_mode:
                    p = _grow_for_ceil(xv.shape, dims, strides, pads)
                # flat spatial index per element (paddle mask semantics:
                # position within the per-channel spatial plane)
                idx = jnp.zeros(xv.shape, jnp.int32)
                mult = 1
                for ax in reversed(spatial_axes):
                    idx = idx + lax.broadcasted_iota(
                        jnp.int32, xv.shape, ax) * mult
                    mult *= xv.shape[ax]

                def red(a, b):
                    av, ai = a
                    bv, bi = b
                    # lowest index wins ties (paddle keeps the first max)
                    take_b = (bv > av) | ((bv == av) & (bi < ai))
                    return (jnp.where(take_b, bv, av),
                            jnp.where(take_b, bi, ai))

                # the value output goes through the DIFFERENTIABLE monoid
                # reduce; the index comes from a stop-gradient variadic
                # reduce (its transpose rule doesn't exist, and ints don't
                # need one)
                out = lax.reduce_window(
                    xv, _concrete_init(init, xv.dtype), lax.max, dims,
                    strides, p)
                _, ind = lax.reduce_window(
                    (lax.stop_gradient(xv), idx),
                    (_concrete_init(init, xv.dtype),
                     _concrete_init(jnp.iinfo(jnp.int32).max, jnp.int32)),
                    red, dims, strides, p)
                return out, ind

            return apply_jfn(f"max_pool{n}d_with_mask", jfn_mask, x)

        def jfn(xv):
            p = pads
            if isinstance(pad, str):
                return _reduce_window_str(xv, init, op, dims, strides, pad)
            if ceil_mode:
                p = _grow_for_ceil(xv.shape, dims, strides, pads)
            return lax.reduce_window(xv, _concrete_init(init, xv.dtype), op,
                                     dims, strides, p)

        return apply_jfn(f"max_pool{n}d", jfn, x)

    # avg
    def jfn(xv):
        p = pads
        if isinstance(pad, str):
            s = _reduce_window_str(xv, 0.0, lax.add, dims, strides, pad)
            cnt = _reduce_window_str(jnp.ones_like(xv), 0.0, lax.add, dims,
                                     strides, pad)
            return s / cnt
        if ceil_mode:
            p = _grow_for_ceil(xv.shape, dims, strides, pads)
        s = lax.reduce_window(xv, _concrete_init(0.0, xv.dtype), lax.add, dims,
                              strides, p)
        if exclusive:
            cnt = lax.reduce_window(jnp.ones_like(xv), _concrete_init(0.0, xv.dtype),
                                    lax.add, dims, strides, p)
            return s / cnt
        return s / float(np.prod(kernel))

    return apply_jfn(f"avg_pool{n}d", jfn, x)


def _grow_for_ceil(shape, dims, strides, pads):
    out = []
    for size, k, s, (lo, hi) in zip(shape, dims, strides, pads):
        eff = size + lo + hi
        n_out = -(-(eff - k) // s) + 1 if eff >= k else 1
        needed = (n_out - 1) * s + k - eff
        out.append((lo, hi + max(0, needed)))
    return out


def _reduce_window_str(xv, init, op, dims, strides, pad_str):
    return lax.reduce_window(xv, _concrete_init(init, xv.dtype), op, dims,
                             strides, pad_str)


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCL", name=None):
    return _pool(x, 1, kernel_size, stride, padding, "max", ceil_mode, True,
                 data_format in ("NLC",), return_mask)


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCHW", name=None):
    return _pool(x, 2, kernel_size, stride, padding, "max", ceil_mode, True,
                 data_format == "NHWC", return_mask)


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCDHW", name=None):
    return _pool(x, 3, kernel_size, stride, padding, "max", ceil_mode, True,
                 data_format == "NDHWC", return_mask)


def _max_unpool(x, indices, n, kernel, stride, padding, output_size,
                channel_last):
    """Scatter pooled values back to their argmax positions
    (reference: phi/kernels/cpu/unpool_kernel.cc; indices are flat
    positions within the per-channel spatial plane, as produced by
    max_pool(return_mask=True))."""
    kernel = _norm(kernel, n)
    stride = _norm(stride, n) or kernel
    p = _norm(padding, n)
    x = ensure_tensor(x)
    indices = ensure_tensor(indices)

    in_spatial = (tuple(x.shape[-n - 1:-1]) if channel_last
                  else tuple(x.shape[-n:]))
    if output_size is None:
        out_spatial = tuple(
            (in_spatial[i] - 1) * stride[i] - 2 * p[i] + kernel[i]
            for i in range(n))
    else:
        out_spatial = tuple(int(s) for s in output_size)[-n:]

    def jfn(xv, iv):
        if channel_last:
            xv = jnp.moveaxis(xv, -1, 1)
            iv = jnp.moveaxis(iv, -1, 1)
        nb, c = xv.shape[0], xv.shape[1]
        lin = int(np.prod(xv.shape[2:]))
        lout = int(np.prod(out_spatial))
        xf = xv.reshape(nb, c, lin)
        idx = iv.reshape(nb, c, lin).astype(jnp.int32)
        out = jnp.zeros((nb, c, lout), xv.dtype)
        out = out.at[
            jnp.arange(nb)[:, None, None],
            jnp.arange(c)[None, :, None],
            idx,
        ].set(xf, mode="drop")
        out = out.reshape((nb, c) + out_spatial)
        if channel_last:
            out = jnp.moveaxis(out, 1, -1)
        return out

    return apply_jfn(f"max_unpool{n}d", jfn, x, indices)


def max_unpool1d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCL", output_size=None, name=None):
    return _max_unpool(x, indices, 1, kernel_size, stride, padding,
                       output_size, data_format == "NLC")


def max_unpool2d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCHW", output_size=None, name=None):
    return _max_unpool(x, indices, 2, kernel_size, stride, padding,
                       output_size, data_format == "NHWC")


def max_unpool3d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCDHW", output_size=None, name=None):
    return _max_unpool(x, indices, 3, kernel_size, stride, padding,
                       output_size, data_format == "NDHWC")


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, data_format="NCL", name=None):
    return _pool(x, 1, kernel_size, stride, padding, "avg", ceil_mode,
                 exclusive, data_format in ("NLC",))


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCHW",
               name=None):
    return _pool(x, 2, kernel_size, stride, padding, "avg", ceil_mode,
                 exclusive, data_format == "NHWC")


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCDHW",
               name=None):
    return _pool(x, 3, kernel_size, stride, padding, "avg", ceil_mode,
                 exclusive, data_format == "NDHWC")


def _adaptive(x, n, output_size, mode, channel_last):
    output_size = _norm(output_size, n)
    x = ensure_tensor(x)

    def jfn(xv):
        spatial = xv.shape[-n - 1:-1] if channel_last else xv.shape[-n:]
        axes = (
            tuple(range(xv.ndim - n - 1, xv.ndim - 1))
            if channel_last
            else tuple(range(xv.ndim - n, xv.ndim))
        )
        out = xv
        # adaptive pooling with uniform bins when divisible, else gather-based
        for ax, in_s, out_s in zip(axes, spatial, output_size):
            if in_s % out_s == 0:
                k = in_s // out_s
                new_shape = out.shape[:ax] + (out_s, k) + out.shape[ax + 1:]
                r = out.reshape(new_shape)
                out = r.max(axis=ax + 1) if mode == "max" else r.mean(axis=ax + 1)
            else:
                starts = (np.arange(out_s) * in_s) // out_s
                ends = -(-((np.arange(out_s) + 1) * in_s) // out_s)
                slices = []
                for s0, e0 in zip(starts, ends):
                    seg = lax.slice_in_dim(out, int(s0), int(e0), axis=ax)
                    red = seg.max(axis=ax) if mode == "max" else seg.mean(axis=ax)
                    slices.append(red)
                out = jnp.stack(slices, axis=ax)
        return out

    return apply_jfn(f"adaptive_{mode}_pool{n}d", jfn, x)


def adaptive_avg_pool1d(x, output_size, name=None):
    return _adaptive(x, 1, output_size, "avg", False)


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    return _adaptive(x, 2, output_size, "avg", data_format == "NHWC")


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    return _adaptive(x, 3, output_size, "avg", data_format == "NDHWC")


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    return _adaptive(x, 1, output_size, "max", False)


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    return _adaptive(x, 2, output_size, "max", False)


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    return _adaptive(x, 3, output_size, "max", False)
