"""Pooling functionals via lax.reduce_window.

(Reference: paddle/phi/kernels/funcs/pooling.h + gpu pool kernels; on TPU
reduce_window is the native windowed-reduction primitive and XLA fuses the
divide for avg pool.)
"""
import numpy as np

import jax.numpy as jnp
from jax import lax

from ...ops._helpers import apply_jfn, ensure_tensor

__all__ = [
    "max_pool1d",
    "max_pool2d",
    "max_pool3d",
    "avg_pool1d",
    "avg_pool2d",
    "avg_pool3d",
    "adaptive_avg_pool1d",
    "adaptive_avg_pool2d",
    "adaptive_avg_pool3d",
    "adaptive_max_pool1d",
    "adaptive_max_pool2d",
    "adaptive_max_pool3d",
]


def _norm(v, n):
    if v is None:
        return None
    if isinstance(v, int):
        return (v,) * n
    v = tuple(int(x) for x in v)
    return v * n if len(v) == 1 else v


def _concrete_init(init, dtype):
    """reduce_window init must be a CONCRETE scalar: jax's monoid matcher
    (reduce_window -> the differentiable reduce_window_max/add primitives)
    compares it by value, which fails on traced/device arrays under jit."""
    return np.asarray(init, dtype)[()]


def _pool(x, n, kernel, stride, padding, mode, ceil_mode, exclusive,
          channel_last):
    kernel = _norm(kernel, n)
    stride = _norm(stride, n) or kernel
    if isinstance(padding, str):
        pad = padding.upper()
    else:
        p = _norm(padding, n)
        pad = [(pi, pi) for pi in p]
    x = ensure_tensor(x)

    if channel_last:
        dims = (1,) + kernel + (1,)
        strides = (1,) + stride + (1,)
        pads = [(0, 0)] + (pad if not isinstance(pad, str) else []) + [(0, 0)]
    else:
        dims = (1, 1) + kernel
        strides = (1, 1) + stride
        pads = [(0, 0), (0, 0)] + (pad if not isinstance(pad, str) else [])

    if ceil_mode and not isinstance(pad, str):
        # grow the high-side padding so the last partial window is included
        def ceil_extra(size, k, s, lo, hi):
            out = -(-(size + lo + hi - k) // s) + 1
            needed = (out - 1) * s + k - (size + lo + hi)
            return max(0, needed)
        pads = list(pads)

    if mode == "max":
        init, op = -jnp.inf, lax.max

        def jfn(xv):
            p = pads
            if isinstance(pad, str):
                return _reduce_window_str(xv, init, op, dims, strides, pad)
            if ceil_mode:
                p = _grow_for_ceil(xv.shape, dims, strides, pads)
            return lax.reduce_window(xv, _concrete_init(init, xv.dtype), op,
                                     dims, strides, p)

        return apply_jfn(f"max_pool{n}d", jfn, x)

    # avg
    def jfn(xv):
        p = pads
        if isinstance(pad, str):
            s = _reduce_window_str(xv, 0.0, lax.add, dims, strides, pad)
            cnt = _reduce_window_str(jnp.ones_like(xv), 0.0, lax.add, dims,
                                     strides, pad)
            return s / cnt
        if ceil_mode:
            p = _grow_for_ceil(xv.shape, dims, strides, pads)
        s = lax.reduce_window(xv, _concrete_init(0.0, xv.dtype), lax.add, dims,
                              strides, p)
        if exclusive:
            cnt = lax.reduce_window(jnp.ones_like(xv), _concrete_init(0.0, xv.dtype),
                                    lax.add, dims, strides, p)
            return s / cnt
        return s / float(np.prod(kernel))

    return apply_jfn(f"avg_pool{n}d", jfn, x)


def _grow_for_ceil(shape, dims, strides, pads):
    out = []
    for size, k, s, (lo, hi) in zip(shape, dims, strides, pads):
        eff = size + lo + hi
        n_out = -(-(eff - k) // s) + 1 if eff >= k else 1
        needed = (n_out - 1) * s + k - eff
        out.append((lo, hi + max(0, needed)))
    return out


def _reduce_window_str(xv, init, op, dims, strides, pad_str):
    return lax.reduce_window(xv, _concrete_init(init, xv.dtype), op, dims,
                             strides, pad_str)


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCL", name=None):
    return _pool(x, 1, kernel_size, stride, padding, "max", ceil_mode, True,
                 data_format in ("NLC",))


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCHW", name=None):
    return _pool(x, 2, kernel_size, stride, padding, "max", ceil_mode, True,
                 data_format == "NHWC")


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCDHW", name=None):
    return _pool(x, 3, kernel_size, stride, padding, "max", ceil_mode, True,
                 data_format == "NDHWC")


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, data_format="NCL", name=None):
    return _pool(x, 1, kernel_size, stride, padding, "avg", ceil_mode,
                 exclusive, data_format in ("NLC",))


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCHW",
               name=None):
    return _pool(x, 2, kernel_size, stride, padding, "avg", ceil_mode,
                 exclusive, data_format == "NHWC")


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCDHW",
               name=None):
    return _pool(x, 3, kernel_size, stride, padding, "avg", ceil_mode,
                 exclusive, data_format == "NDHWC")


def _adaptive(x, n, output_size, mode, channel_last):
    output_size = _norm(output_size, n)
    x = ensure_tensor(x)

    def jfn(xv):
        spatial = xv.shape[-n - 1:-1] if channel_last else xv.shape[-n:]
        axes = (
            tuple(range(xv.ndim - n - 1, xv.ndim - 1))
            if channel_last
            else tuple(range(xv.ndim - n, xv.ndim))
        )
        out = xv
        # adaptive pooling with uniform bins when divisible, else gather-based
        for ax, in_s, out_s in zip(axes, spatial, output_size):
            if in_s % out_s == 0:
                k = in_s // out_s
                new_shape = out.shape[:ax] + (out_s, k) + out.shape[ax + 1:]
                r = out.reshape(new_shape)
                out = r.max(axis=ax + 1) if mode == "max" else r.mean(axis=ax + 1)
            else:
                starts = (np.arange(out_s) * in_s) // out_s
                ends = -(-((np.arange(out_s) + 1) * in_s) // out_s)
                slices = []
                for s0, e0 in zip(starts, ends):
                    seg = lax.slice_in_dim(out, int(s0), int(e0), axis=ax)
                    red = seg.max(axis=ax) if mode == "max" else seg.mean(axis=ax)
                    slices.append(red)
                out = jnp.stack(slices, axis=ax)
        return out

    return apply_jfn(f"adaptive_{mode}_pool{n}d", jfn, x)


def adaptive_avg_pool1d(x, output_size, name=None):
    return _adaptive(x, 1, output_size, "avg", False)


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    return _adaptive(x, 2, output_size, "avg", data_format == "NHWC")


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    return _adaptive(x, 3, output_size, "avg", data_format == "NDHWC")


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    return _adaptive(x, 1, output_size, "max", False)


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    return _adaptive(x, 2, output_size, "max", False)


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    return _adaptive(x, 3, output_size, "max", False)
