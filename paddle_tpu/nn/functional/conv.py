"""Convolution functionals.

TPU-native design: all convs lower to `lax.conv_general_dilated`, which XLA
maps onto the MXU (reference implements these as cuDNN calls in
paddle/phi/kernels/gpu/conv_kernel.cu — here the systolic array replaces
cuDNN and XLA picks the tiling).
"""
import jax.numpy as jnp
from jax import lax

from ...ops._helpers import apply_jfn, ensure_tensor

__all__ = [
    "conv1d",
    "conv2d",
    "conv3d",
    "conv1d_transpose",
    "conv2d_transpose",
    "conv3d_transpose",
]


def _norm_tuple(v, n, name):
    if isinstance(v, int):
        return (v,) * n
    v = tuple(int(x) for x in v)
    if len(v) == 1:
        return v * n
    if len(v) != n:
        raise ValueError(f"{name} must have {n} elements, got {v}")
    return v


def _norm_padding(padding, n, channel_last=False):
    """Paddle padding: int, list[int], list[pair], or 'SAME'/'VALID'."""
    if isinstance(padding, str):
        return padding.upper()
    if isinstance(padding, int):
        return [(padding, padding)] * n
    padding = list(padding)
    if len(padding) == n and all(isinstance(p, int) for p in padding):
        return [(p, p) for p in padding]
    if len(padding) == 2 * n and all(isinstance(p, int) for p in padding):
        return [(padding[2 * i], padding[2 * i + 1]) for i in range(n)]
    if all(isinstance(p, (list, tuple)) for p in padding):
        pairs = [tuple(p) for p in padding]
        if len(pairs) == n + 2:
            # full per-dim spec; strip batch+channel at their layout positions
            pairs = pairs[1:-1] if channel_last else pairs[2:]
        return pairs
    raise ValueError(f"bad padding spec: {padding}")


def _dim_numbers(n, channel_last):
    if n == 1:
        return ("NWC", "WIO", "NWC") if channel_last else ("NCW", "OIW", "NCW")
    if n == 2:
        return ("NHWC", "HWIO", "NHWC") if channel_last else ("NCHW", "OIHW", "NCHW")
    return ("NDHWC", "DHWIO", "NDHWC") if channel_last else ("NCDHW", "OIDHW", "NCDHW")


def _conv(x, weight, bias, stride, padding, dilation, groups, n, data_format):
    channel_last = data_format in ("NHWC", "NLC", "NWC", "NDHWC")
    stride = _norm_tuple(stride, n, "stride")
    dilation = _norm_tuple(dilation, n, "dilation")
    pad = _norm_padding(padding, n, channel_last)
    dn = _dim_numbers(n, channel_last)
    x = ensure_tensor(x)
    weight = ensure_tensor(weight)

    # paddle weight layout is [out_c, in_c/groups, *k] == OI* — transpose for
    # channel_last dim numbers inside the jfn so autograd sees one op.
    def jfn(xv, wv):
        if channel_last:
            perm = tuple(range(2, 2 + n)) + (1, 0)  # OI* -> *IO
            wv = jnp.transpose(wv, perm)
        return lax.conv_general_dilated(
            xv,
            wv,
            window_strides=stride,
            padding=pad,
            rhs_dilation=dilation,
            dimension_numbers=dn,
            feature_group_count=groups,
            preferred_element_type=None,
        )

    out = apply_jfn(f"conv{n}d", jfn, x, weight)
    if bias is not None:
        bias = ensure_tensor(bias)
        shape = (1, -1) + (1,) * n if not channel_last else (1,) * (n + 1) + (-1,)
        out = apply_jfn(
            f"conv{n}d_bias", lambda o, b: o + b.reshape(shape), out, bias
        )
    return out


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL", name=None):
    df = "NWC" if data_format in ("NLC", "NWC") else "NCW"
    return _conv(x, weight, bias, stride, padding, dilation, groups, 1, df)


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 2, data_format)


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 3, data_format)


def _conv_transpose(x, weight, bias, stride, padding, output_padding, dilation,
                    groups, n, data_format, output_size=None):
    channel_last = data_format in ("NHWC", "NLC", "NWC", "NDHWC")
    stride = _norm_tuple(stride, n, "stride")
    dilation = _norm_tuple(dilation, n, "dilation")
    opad = _norm_tuple(output_padding, n, "output_padding")
    pad = _norm_padding(padding, n, channel_last)
    x = ensure_tensor(x)
    weight = ensure_tensor(weight)
    dn = _dim_numbers(n, channel_last)

    # Gradient-of-conv formulation (paddle conv_transpose == input-grad of
    # conv): use lhs_dilation (fractional stride). Padding arithmetic:
    # lo = k_eff-1-p_lo, hi = k_eff-1-p_hi+opad with k_eff = (k-1)*d+1.
    def jfn(xv, wv):
        ks = wv.shape[2:]
        if isinstance(pad, str):
            raise ValueError("SAME/VALID strings unsupported for conv_transpose")
        tpad = []
        for i in range(n):
            k_eff = (ks[i] - 1) * dilation[i] + 1
            lo, hi = pad[i]
            tpad.append((k_eff - 1 - lo, k_eff - 1 - hi + opad[i]))
        # weight layout [in_c, out_c/groups, *k]: IO* — flip spatial, swap IO
        wv = jnp.flip(wv, axis=tuple(range(2, 2 + n)))
        if groups > 1:
            ic, ocg = wv.shape[0], wv.shape[1]
            wv = wv.reshape((groups, ic // groups, ocg) + wv.shape[2:])
            wv = jnp.swapaxes(wv, 1, 2)
            wv = wv.reshape((groups * ocg, ic // groups) + wv.shape[3:])
        else:
            wv = jnp.swapaxes(wv, 0, 1)
        if channel_last:
            perm = tuple(range(2, 2 + n)) + (1, 0)
            wv = jnp.transpose(wv, perm)
        return lax.conv_general_dilated(
            xv,
            wv,
            window_strides=(1,) * n,
            padding=tpad,
            lhs_dilation=stride,
            rhs_dilation=dilation,
            dimension_numbers=dn,
            feature_group_count=groups,
        )

    out = apply_jfn(f"conv{n}d_transpose", jfn, x, weight)
    if bias is not None:
        bias = ensure_tensor(bias)
        shape = (1, -1) + (1,) * n if not channel_last else (1,) * (n + 1) + (-1,)
        out = apply_jfn(
            f"conv{n}d_transpose_bias", lambda o, b: o + b.reshape(shape), out, bias
        )
    return out


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     groups=1, dilation=1, output_size=None, data_format="NCL",
                     name=None):
    df = "NWC" if data_format in ("NLC", "NWC") else "NCW"
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           dilation, groups, 1, df, output_size)


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     groups=1, dilation=1, output_size=None, data_format="NCHW",
                     name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           dilation, groups, 2, data_format, output_size)


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     groups=1, dilation=1, output_size=None, data_format="NCDHW",
                     name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           dilation, groups, 3, data_format, output_size)
