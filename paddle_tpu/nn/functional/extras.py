"""Remaining nn.functional surface: masks, video shift, beam backtrace,
padding, PartialFC sampling, block-sparse attention.

Reference: python/paddle/nn/functional/{common,extension,input}.py and the
matching phi kernels (sequence_mask, temporal_shift_op, gather_tree_op,
class_center_sample_op, sparse_attention_op).
"""
import numpy as np

import jax
import jax.numpy as jnp

from ...autograd import engine
from ...ops._helpers import apply_jfn, ensure_tensor, value_of
from ...tensor_core import Tensor

__all__ = [
    "sequence_mask", "temporal_shift", "gather_tree", "zeropad2d",
    "class_center_sample", "sparse_attention", "relu_", "elu_", "tanh_",
    "softmax_",
]


def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    """[..., maxlen] mask with 1 where position < length
    (reference: nn/functional/extension.py sequence_mask)."""
    from ...core import dtype as dtype_mod

    x = ensure_tensor(x)
    d = dtype_mod.convert_dtype(dtype)
    if maxlen is None:
        maxlen = int(np.asarray(value_of(x)).max())

    def jfn(lengths):
        pos = jnp.arange(int(maxlen))
        return (pos < lengths[..., None]).astype(d)

    return apply_jfn("sequence_mask", jfn, x)


def temporal_shift(x, seg_num, shift_ratio=0.25, data_format="NCHW",
                   name=None):
    """TSM channel shift across the time axis (reference:
    nn/functional/extension.py temporal_shift → temporal_shift_op): the
    first shift_ratio channels move one step back in time, the next
    shift_ratio one step forward, the rest stay."""
    x = ensure_tensor(x)

    def jfn(xv):
        v = jnp.moveaxis(xv, -1, 1) if data_format == "NHWC" else xv
        nt, c = v.shape[0], v.shape[1]
        n = nt // seg_num
        v5 = v.reshape((n, seg_num) + v.shape[1:])
        c1 = int(c * shift_ratio)
        c2 = int(c * 2 * shift_ratio)
        # slide the time axis with zero fill at the boundary
        back = jnp.concatenate(
            [v5[:, 1:, :c1], jnp.zeros_like(v5[:, :1, :c1])], axis=1)
        fwd = jnp.concatenate(
            [jnp.zeros_like(v5[:, :1, c1:c2]), v5[:, :-1, c1:c2]], axis=1)
        out = jnp.concatenate([back, fwd, v5[:, :, c2:]], axis=2)
        out = out.reshape(v.shape)
        return jnp.moveaxis(out, 1, -1) if data_format == "NHWC" else out

    return apply_jfn("temporal_shift", jfn, x)


def gather_tree(ids, parents):
    """Beam-search backtrace (reference: nn/functional/extension.py
    gather_tree → gather_tree_op): walk parent pointers from the last
    step so each beam holds its full ancestry path.

    ids/parents: [max_time, batch, beam]."""
    ids = ensure_tensor(ids)
    parents = ensure_tensor(parents)

    def jfn(idv, parv):
        t, batch, beam = idv.shape
        binc = jnp.arange(batch)[:, None]

        def step(beam_sel, xs):
            id_t, par_t = xs  # [batch, beam]
            # current selection points into this step's beams
            out = jnp.take_along_axis(id_t, beam_sel, axis=1)
            nxt = jnp.take_along_axis(par_t, beam_sel, axis=1)
            return nxt, out

        init = jnp.tile(jnp.arange(beam)[None, :], (batch, 1))
        _, outs = jax.lax.scan(step, init, (idv[::-1], parv[::-1]))
        del binc
        return outs[::-1]

    return apply_jfn("gather_tree", jfn, ids, parents)


def zeropad2d(x, padding, data_format="NCHW", name=None):
    """Zero-pad H/W (reference: nn/functional/common.py zeropad2d);
    padding = [left, right, top, bottom]."""
    x = ensure_tensor(x)
    left, right, top, bottom = (int(p) for p in padding)

    def jfn(xv):
        if data_format == "NHWC":
            cfg = [(0, 0), (top, bottom), (left, right), (0, 0)]
        else:
            cfg = [(0, 0), (0, 0), (top, bottom), (left, right)]
        return jnp.pad(xv, cfg)

    return apply_jfn("zeropad2d", jfn, x)


def class_center_sample(label, num_classes, num_samples, group=None):
    """PartialFC class-center sampling (reference:
    nn/functional/common.py class_center_sample → class_center_sample_op):
    keep every positive class, pad with uniformly sampled negatives up to
    num_samples, and remap labels into the sampled index space. Host-side
    (eager-only) — sampling is data-dependent by design."""
    from ...core import rng

    label = ensure_tensor(label)
    lbl = np.asarray(value_of(label)).reshape(-1).astype(np.int64)
    pos = np.unique(lbl)
    if len(pos) >= num_samples:
        sampled = pos
    else:
        seed = int(
            jax.random.randint(rng.next_key(), (), 0, 2**31 - 1))
        gen = np.random.default_rng(seed)
        neg_pool = np.setdiff1d(np.arange(num_classes, dtype=np.int64), pos,
                                assume_unique=True)
        extra = gen.choice(neg_pool, size=num_samples - len(pos),
                           replace=False)
        sampled = np.sort(np.concatenate([pos, extra]))
    remap = np.full(num_classes, -1, np.int64)
    remap[sampled] = np.arange(len(sampled))
    remapped = remap[lbl].reshape(np.asarray(value_of(label)).shape)
    return (Tensor(jnp.asarray(remapped), stop_gradient=True),
            Tensor(jnp.asarray(sampled), stop_gradient=True))


def sparse_attention(query, key, value, sparse_csr_offset,
                     sparse_csr_columns, key_padding_mask=None,
                     attn_mask=None, name=None):
    """Block-sparse attention with a CSR-described pattern (reference:
    nn/functional/common.py sparse_attention → sparse_attention CUDA op).
    TPU lowering: the CSR pattern becomes a dense additive mask and XLA
    fuses the masked softmax — numerically identical, O(M·N) transient.

    q/k/v: [batch, heads, seq, head_dim]; offset: [batch, heads, seq+1];
    columns: [batch, heads, nnz]."""
    query = ensure_tensor(query)
    key = ensure_tensor(key)
    value = ensure_tensor(value)
    offset = ensure_tensor(sparse_csr_offset)
    columns = ensure_tensor(sparse_csr_columns)

    def jfn(q, k, v, off, cols):
        b, h, m, d = q.shape
        nnz = cols.shape[-1]
        # row id of each nnz entry: #offsets <= j, minus the leading 0
        ar = jnp.arange(nnz)
        rows = (jax.vmap(jax.vmap(
            lambda o: jnp.searchsorted(o, ar, side="right") - 1))(
                off.astype(jnp.int32)))
        # scatter allowed (row, col) pairs into a dense mask
        mask = jnp.zeros((b, h, m, m), bool)
        bidx = jnp.arange(b)[:, None, None]
        hidx = jnp.arange(h)[None, :, None]
        mask = mask.at[bidx, hidx, rows, cols.astype(jnp.int32)].set(True)
        scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) / jnp.sqrt(
            jnp.asarray(d, q.dtype))
        scores = jnp.where(mask, scores, jnp.asarray(-1e30, scores.dtype))
        w = jax.nn.softmax(scores.astype(jnp.float32), -1).astype(v.dtype)
        return jnp.einsum("bhqk,bhkd->bhqd", w, v)

    return apply_jfn("sparse_attention", jfn, query, key, value, offset,
                     columns)


# ---- in-place functional aliases (reference exports them from
# nn/functional: relu_, elu_, tanh_, softmax_) ----

def _assign_inplace(x, opname, fn):
    """Same tape discipline as Tensor's installed `*_` methods: the
    recorded node's input must be a PRE-mutation snapshot, never x
    itself (see ops/__init__._snapshot_for_inplace)."""
    from ...ops import _snapshot_for_inplace

    x = ensure_tensor(x)
    old = _snapshot_for_inplace(x, opname)
    out = fn(old)
    x._inplace_version += 1
    x._value = out._value
    x._grad_node = out._grad_node
    x._out_index = out._out_index
    x.stop_gradient = out.stop_gradient
    return x


def relu_(x, name=None):
    from ...ops.activation import relu

    return _assign_inplace(x, "relu", relu)


def elu_(x, alpha=1.0, name=None):
    from ...ops.activation import elu

    return _assign_inplace(x, "elu", lambda t: elu(t, alpha))


def tanh_(x, name=None):
    return ensure_tensor(x).tanh_()


def softmax_(x, axis=-1, dtype=None, name=None):
    from ...ops.activation import softmax

    return _assign_inplace(x, "softmax", lambda t: softmax(t, axis=axis))
