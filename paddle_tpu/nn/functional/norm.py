"""Normalization functionals.

(Reference: paddle/phi/kernels/gpu/batch_norm_kernel.cu, layer_norm_kernel.cu,
group_norm_kernel.cu — cuDNN/hand-rolled CUDA there; here pure jnp, which XLA
fuses into neighbouring ops on TPU. Running-stat updates are host-side
buffer assignments, matching eager semantics.)
"""
import jax
import jax.numpy as jnp

from ...ops._helpers import apply_jfn, ensure_tensor

__all__ = [
    "batch_norm",
    "layer_norm",
    "group_norm",
    "instance_norm",
    "local_response_norm",
    "normalize",
]


def batch_norm(x, running_mean, running_var, weight=None, bias=None,
               training=False, momentum=0.9, epsilon=1e-5,
               data_format="NCHW", use_global_stats=None, name=None):
    x = ensure_tensor(x)
    channel_last = data_format in ("NHWC", "NLC", "NDHWC")

    def feat_shape(xv):
        s = [1] * xv.ndim
        s[-1 if channel_last else 1] = -1
        return tuple(s)

    if use_global_stats is None:
        use_global_stats = not training

    if not use_global_stats:
        axes_of = lambda xv: tuple(
            i for i in range(xv.ndim) if i != (xv.ndim - 1 if channel_last else 1)
        )

        def jfn(xv, *rest):
            # statistics accumulate in f32 regardless of the activation
            # dtype; output stays in the INPUT dtype so bf16 activations
            # never round-trip through materialized f32 copies (profiled:
            # the old black-list upcast cost ResNet-50 ~2ms/step of pure
            # cast traffic around every BN)
            axes = axes_of(xv)
            xf = xv.astype(jnp.float32)
            mean = xf.mean(axis=axes)
            var = xf.var(axis=axes)
            fs = feat_shape(xv)
            scale = jax.lax.rsqrt(var.reshape(fs) + epsilon)
            shift = mean.reshape(fs)
            i = 0
            if weight is not None:
                scale = scale * rest[i].astype(jnp.float32).reshape(fs)
                i += 1
            offset = -shift * scale
            if bias is not None:
                offset = offset + rest[i].astype(jnp.float32).reshape(fs)
            out = (xf * scale + offset).astype(xv.dtype)
            return out, mean, var

        args = [x] + ([weight] if weight is not None else []) + (
            [bias] if bias is not None else []
        )
        out, batch_mean, batch_var = apply_jfn("batch_norm", jfn, *args)
        # eager-mode running-stat update (buffers are host state, not traced)
        if training and running_mean is not None:
            rm = ensure_tensor(running_mean)
            rv = ensure_tensor(running_var)
            rm._value = rm._value * momentum + batch_mean._value * (1 - momentum)
            rv._value = rv._value * momentum + batch_var._value * (1 - momentum)
        return out

    rm = ensure_tensor(running_mean)
    rv = ensure_tensor(running_var)

    def jfn(xv, mv, vv, *rest):
        fs = feat_shape(xv)
        out = (xv - mv.reshape(fs)) / jnp.sqrt(vv.reshape(fs) + epsilon)
        i = 0
        if weight is not None:
            out = out * rest[i].reshape(fs)
            i += 1
        if bias is not None:
            out = out + rest[i].reshape(fs)
        return out

    args = [x, rm, rv] + ([weight] if weight is not None else []) + (
        [bias] if bias is not None else []
    )
    return apply_jfn("batch_norm_infer", jfn, *args)


def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-5,
               name=None):
    x = ensure_tensor(x)
    if isinstance(normalized_shape, int):
        normalized_shape = (normalized_shape,)
    n = len(tuple(normalized_shape))

    def jfn(xv, *rest):
        axes = tuple(range(xv.ndim - n, xv.ndim))
        mean = xv.mean(axis=axes, keepdims=True)
        var = xv.var(axis=axes, keepdims=True)
        out = (xv - mean) / jnp.sqrt(var + epsilon)
        i = 0
        if weight is not None:
            out = out * rest[i]
            i += 1
        if bias is not None:
            out = out + rest[i]
        return out

    args = [x] + ([weight] if weight is not None else []) + (
        [bias] if bias is not None else []
    )
    return apply_jfn("layer_norm", jfn, *args)


def group_norm(x, num_groups, epsilon=1e-5, weight=None, bias=None,
               data_format="NCHW", name=None):
    x = ensure_tensor(x)
    channel_last = data_format == "NHWC"

    def jfn(xv, *rest):
        if channel_last:
            xv = jnp.moveaxis(xv, -1, 1)
        N, C = xv.shape[0], xv.shape[1]
        g = xv.reshape((N, num_groups, C // num_groups) + xv.shape[2:])
        axes = tuple(range(2, g.ndim))
        mean = g.mean(axis=axes, keepdims=True)
        var = g.var(axis=axes, keepdims=True)
        out = ((g - mean) / jnp.sqrt(var + epsilon)).reshape(xv.shape)
        fs = (1, C) + (1,) * (xv.ndim - 2)
        i = 0
        if weight is not None:
            out = out * rest[i].reshape(fs)
            i += 1
        if bias is not None:
            out = out + rest[i].reshape(fs)
        if channel_last:
            out = jnp.moveaxis(out, 1, -1)
        return out

    args = [x] + ([weight] if weight is not None else []) + (
        [bias] if bias is not None else []
    )
    return apply_jfn("group_norm", jfn, *args)


def instance_norm(x, running_mean=None, running_var=None, weight=None,
                  bias=None, use_input_stats=True, momentum=0.9, eps=1e-5,
                  data_format="NCHW", name=None):
    x = ensure_tensor(x)

    def jfn(xv, *rest):
        axes = tuple(range(2, xv.ndim))
        mean = xv.mean(axis=axes, keepdims=True)
        var = xv.var(axis=axes, keepdims=True)
        out = (xv - mean) / jnp.sqrt(var + eps)
        fs = (1, xv.shape[1]) + (1,) * (xv.ndim - 2)
        i = 0
        if weight is not None:
            out = out * rest[i].reshape(fs)
            i += 1
        if bias is not None:
            out = out + rest[i].reshape(fs)
        return out

    args = [x] + ([weight] if weight is not None else []) + (
        [bias] if bias is not None else []
    )
    return apply_jfn("instance_norm", jfn, *args)


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0,
                        data_format="NCHW", name=None):
    x = ensure_tensor(x)

    def jfn(xv):
        sq = xv * xv
        ch_axis = 1 if data_format.startswith("NC") else xv.ndim - 1
        C = xv.shape[ch_axis]
        pad_lo = (size - 1) // 2
        pad_hi = size - 1 - pad_lo
        pads = [(0, 0)] * xv.ndim
        pads[ch_axis] = (pad_lo, pad_hi)
        sq = jnp.pad(sq, pads)
        acc = jnp.zeros_like(xv)
        for i in range(size):
            idx = [slice(None)] * xv.ndim
            idx[ch_axis] = slice(i, i + C)
            acc = acc + sq[tuple(idx)]
        return xv / jnp.power(k + alpha * acc, beta)

    return apply_jfn("local_response_norm", jfn, x)


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    x = ensure_tensor(x)

    def jfn(xv):
        if p == 2:
            n = jnp.sqrt(jnp.sum(xv * xv, axis=axis, keepdims=True))
        else:
            n = jnp.sum(jnp.abs(xv) ** p, axis=axis, keepdims=True) ** (1.0 / p)
        return xv / jnp.maximum(n, epsilon)

    return apply_jfn("normalize", jfn, x)
