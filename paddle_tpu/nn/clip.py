"""Gradient clipping (reference: python/paddle/fluid/clip.py —
ClipGradByValue, ClipGradByNorm, ClipGradByGlobalNorm)."""
import jax.numpy as jnp

from ..tensor_core import Tensor

__all__ = ["ClipGradBase", "ClipGradByValue", "ClipGradByNorm",
           "ClipGradByGlobalNorm", "clip_grad_norm_", "clip_grad_value_"]


class ClipGradBase:
    def __call__(self, params_grads):
        return self._dygraph_clip(params_grads)

    def _dygraph_clip(self, params_grads):
        raise NotImplementedError

    def clip_tree(self, flat_params, flat_grads, need_clip=None):
        """Pure flat-list clip for jitted train steps (same math as the
        eager path, over raw jax arrays)."""
        raise NotImplementedError(
            f"{type(self).__name__} has no pure tree-path implementation"
        )


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -float(max)

    def _dygraph_clip(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                out.append((p, g))
                continue
            out.append((p, Tensor(jnp.clip(g._value, self.min, self.max))))
        return out

    def clip_tree(self, flat_params, flat_grads, need_clip=None):
        need_clip = need_clip or [True] * len(flat_grads)
        return [
            jnp.clip(g, self.min, self.max) if (g is not None and nc) else g
            for g, nc in zip(flat_grads, need_clip)
        ]


class ClipGradByNorm(ClipGradBase):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def _dygraph_clip(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                out.append((p, g))
                continue
            norm = jnp.sqrt(jnp.sum(g._value.astype(jnp.float32) ** 2))
            scale = jnp.minimum(self.clip_norm / jnp.maximum(norm, 1e-12), 1.0)
            out.append((p, Tensor((g._value * scale).astype(g._value.dtype))))
        return out

    def clip_tree(self, flat_params, flat_grads, need_clip=None):
        need_clip = need_clip or [True] * len(flat_grads)
        out = []
        for g, nc in zip(flat_grads, need_clip):
            if g is None or not nc:
                out.append(g)
                continue
            norm = jnp.sqrt(jnp.sum(g.astype(jnp.float32) ** 2))
            scale = jnp.minimum(
                self.clip_norm / jnp.maximum(norm, 1e-12), 1.0)
            out.append((g * scale).astype(g.dtype))
        return out


class ClipGradByGlobalNorm(ClipGradBase):
    """Global-norm clip. Under SPMD the sum-of-squares is computed on sharded
    grads and XLA inserts the cross-device psum — no manual allreduce
    (reference needs HybridParallelOptimizer's mp/pp-aware clip)."""

    def __init__(self, clip_norm, group_name="default_group",
                 auto_skip_clip=False):
        self.clip_norm = float(clip_norm)

    def _dygraph_clip(self, params_grads):
        sq = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                continue
            sq.append(jnp.sum(g._value.astype(jnp.float32) ** 2))
        if not sq:
            return params_grads
        global_norm = jnp.sqrt(sum(sq))
        scale = self.clip_norm / jnp.maximum(global_norm, self.clip_norm)
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                out.append((p, g))
                continue
            out.append((p, Tensor((g._value * scale).astype(g._value.dtype))))
        return out

    def clip_tree(self, flat_params, flat_grads, need_clip=None):
        need_clip = need_clip or [True] * len(flat_grads)
        sq = [
            jnp.sum(g.astype(jnp.float32) ** 2)
            for g, nc in zip(flat_grads, need_clip)
            if g is not None and nc
        ]
        if not sq:
            return flat_grads
        global_norm = jnp.sqrt(sum(sq))
        scale = self.clip_norm / jnp.maximum(global_norm, self.clip_norm)
        return [
            (g * scale).astype(g.dtype) if (g is not None and nc) else g
            for g, nc in zip(flat_grads, need_clip)
        ]


def clip_grad_norm_(parameters, max_norm, norm_type=2.0,
                    error_if_nonfinite=False):
    if isinstance(parameters, Tensor):
        parameters = [parameters]
    grads = [p.grad for p in parameters if p.grad is not None]
    if not grads:
        return Tensor(jnp.zeros([]))
    if norm_type == float("inf"):
        total = jnp.max(jnp.stack([jnp.max(jnp.abs(g._value)) for g in grads]))
    else:
        total = jnp.sum(
            jnp.stack([jnp.sum(jnp.abs(g._value) ** norm_type) for g in grads])
        ) ** (1.0 / norm_type)
    scale = jnp.minimum(max_norm / (total + 1e-6), 1.0)
    for p in parameters:
        if p.grad is not None:
            p.grad._value = (p.grad._value * scale).astype(p.grad._value.dtype)
    return Tensor(total)


def clip_grad_value_(parameters, clip_value):
    if isinstance(parameters, Tensor):
        parameters = [parameters]
    for p in parameters:
        if p.grad is not None:
            p.grad._value = jnp.clip(p.grad._value, -clip_value, clip_value)
