"""Optimizer base + the optimizer family.

TPU-native re-design of the reference's optimizers
(reference: python/paddle/optimizer/optimizer.py:91 `Optimizer`, and the phi
sgd/adam/... kernels under paddle/phi/kernels/gpu/). Each optimizer defines
a pure per-parameter update `_update(p, g, state, lr)` returning (new_p,
new_state); `step()` applies it eagerly, and jitted train steps can call
`apply_gradients_tree` — the same math over a whole pytree in one compiled
program (how TPU runs want it: one fused update, no per-param kernel
launches).
"""
import jax
import jax.numpy as jnp
import numpy as np

from ..autograd import engine
from ..tensor_core import Parameter, Tensor
from . import lr as lr_mod

__all__ = [
    "Optimizer", "SGD", "Momentum", "Adam", "AdamW", "Adamax", "Adagrad",
    "Adadelta", "RMSProp", "Lamb",
]


def _acc_dtype(v):
    return jnp.float32 if v.dtype in (jnp.bfloat16, jnp.float16) \
        else v.dtype


def _acc_zeros(p):
    """Accumulator buffer for one param. Low-precision (bf16/fp16) params
    get FLOAT32 accumulators — the mixed-precision recipe: (1-beta2)*g^2
    underflows in bf16 and small updates round away; params stay in
    their own dtype (the update math promotes to f32 and casts back)."""
    v = p._value
    return jnp.zeros(v.shape, _acc_dtype(v))


def _upcast_grad(pv, gv):
    """Gradients of low-precision params are upcast BEFORE the moment
    math: g*g and (1-beta)*g must be computed in the accumulator dtype,
    not quantized/underflowed in bf16 first."""
    dt = _acc_dtype(pv)
    return gv if gv.dtype == dt else gv.astype(dt)


class Optimizer:
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        if parameters is None:
            raise ValueError(
                "parameters is required in eager mode (pass model.parameters())"
            )
        self._param_groups = []
        params = list(parameters)
        if params and isinstance(params[0], dict):
            for g in params:
                group = dict(g)
                group["params"] = list(group["params"])
                self._param_groups.append(group)
        else:
            self._param_groups.append({"params": params})
        self._learning_rate = learning_rate
        self._weight_decay = weight_decay
        self._grad_clip = grad_clip
        self._states = {}  # param name -> dict of accumulator arrays
        self._step_count = 0
        # States are keyed by param name; aliased names would silently share
        # accumulators, so de-alias defensively (Tensor.__deepcopy__ already
        # assigns fresh names to copies).
        seen = set()
        for p in self._parameter_list:
            if p.name in seen:
                i = 1
                while f"{p.name}.dedup{i}" in seen:
                    i += 1
                p.name = f"{p.name}.dedup{i}"
            seen.add(p.name)

    # ---- lr ----
    def get_lr(self):
        if isinstance(self._learning_rate, lr_mod.LRScheduler):
            return float(self._learning_rate())
        return float(self._learning_rate)

    def set_lr(self, value):
        if isinstance(self._learning_rate, lr_mod.LRScheduler):
            raise RuntimeError(
                "cannot set_lr when learning rate is an LRScheduler"
            )
        self._learning_rate = float(value)

    @property
    def _parameter_list(self):
        return [p for g in self._param_groups for p in g["params"]]

    # ---- the update protocol ----
    def _init_state(self, p):
        """Return the fresh accumulator dict for one parameter."""
        return {}

    def _update(self, pv, gv, state, lr, wd=0.0, param=None):
        """Pure update: (param value, grad value, state dict, lr) →
        (new param value, new state dict). `param` is the owning Parameter
        when called eagerly (None on the jit/pytree path)."""
        raise NotImplementedError

    def _state_for(self, p):
        if p.name not in self._states:
            self._states[p.name] = self._init_state(p)
        return self._states[p.name]

    def _weight_decay_coeff(self, p, group):
        # per-parameter regularizer takes precedence over optimizer-level
        reg = getattr(p, "regularizer", None)
        if reg is not None and hasattr(reg, "_coeff"):
            return float(reg._coeff)
        wd = group.get("weight_decay", self._weight_decay)
        if wd is None:
            return 0.0
        if hasattr(wd, "_coeff"):  # L2Decay regularizer object
            wd = wd._coeff
        return float(wd)

    def step(self):
        self._step_count += 1
        with engine.no_grad_guard():
            for group in self._param_groups:
                params_grads = [
                    (p, p.grad) for p in group["params"] if p.grad is not None
                    and not p.stop_gradient
                ]
                if self._grad_clip is not None:
                    params_grads = self._grad_clip(params_grads)
                lr = group.get("learning_rate", None)
                lr = self.get_lr() if lr is None else (
                    float(lr()) if callable(lr) else float(lr)
                )
                for p, g in params_grads:
                    if g is None:
                        continue
                    state = self._state_for(p)
                    plr = lr * p.optimize_attr.get("learning_rate", 1.0)
                    wd = self._weight_decay_coeff(p, group)
                    if wd and not self._decoupled_wd():
                        gv = g._value + wd * p._value
                    else:
                        gv = g._value
                    new_p, new_state = self._update(
                        p._value, _upcast_grad(p._value, gv), state, plr,
                        wd=wd if self._decoupled_wd() else 0.0, param=p)
                    p._value = new_p.astype(p._value.dtype)
                    self._states[p.name] = new_state

    def _decoupled_wd(self):
        return False

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        loss.backward()
        self.step()
        return [], []

    def clear_grad(self, set_to_zero=False):
        for p in self._parameter_list:
            p.grad = None

    clear_gradients = clear_grad

    # ---- functional/jit path ----
    def _tree_meta(self, param_objs):
        """Per-leaf (group_lr, lr_scale, wd_coeff) — static trace constants
        mirroring what eager `step()` reads per parameter."""
        group_by_id = {}
        for g in self._param_groups:
            for q in g["params"]:
                group_by_id[id(q)] = g
        metas = []
        for p in param_objs:
            grp = group_by_id.get(id(p), self._param_groups[0])
            glr = grp.get("learning_rate", None)
            if callable(glr):
                # the compiled step bakes per-group lr as a constant; a
                # schedule would be silently frozen — fail loudly instead
                raise NotImplementedError(
                    "per-group callable learning_rate is not supported in "
                    "the compiled (tree) optimizer path; use a single "
                    "LRScheduler as the optimizer learning_rate"
                )
            if glr is not None:
                glr = float(glr)
            scale = float(p.optimize_attr.get("learning_rate", 1.0)) \
                if getattr(p, "optimize_attr", None) else 1.0
            metas.append((glr, scale, self._weight_decay_coeff(p, grp)))
        return metas

    def apply_gradients_tree(self, params_tree, grads_tree, states_tree, lr,
                             param_objs=None):
        """Pure pytree update for use inside jitted train steps.

        Returns (new_params_tree, new_states_tree). `states_tree` must come
        from `init_states_tree`. When `param_objs` (the Parameter objects
        matching the leaves, in order) is given, per-group learning rates,
        per-param lr scaling/regularizers and AdamW's apply_decay_param_fun
        are honored exactly as in eager `step()`; grad_clip is applied as a
        pure transform either way.
        """
        flat_p, treedef = jax.tree_util.tree_flatten(params_tree)
        flat_g = treedef.flatten_up_to(grads_tree)
        flat_s = states_tree
        if self._grad_clip is not None:
            need = None
            if param_objs is not None:
                need = [getattr(p, "need_clip", True) for p in param_objs]
            flat_g = self._grad_clip.clip_tree(flat_p, flat_g, need)
        if param_objs is not None:
            metas = self._tree_meta(param_objs)
        else:
            wd_global = 0.0 if self._weight_decay is None else (
                self._weight_decay._coeff
                if hasattr(self._weight_decay, "_coeff")
                else float(self._weight_decay)
            )
            metas = [(None, 1.0, wd_global)] * len(flat_p)
        new_p, new_s = [], []
        pobjs = param_objs if param_objs is not None else [None] * len(flat_p)
        for pv, gv, sv, (glr, lr_scale, wd), pobj in zip(
                flat_p, flat_g, flat_s, metas, pobjs):
            plr = (lr if glr is None else glr) * lr_scale
            if wd and not self._decoupled_wd():
                gv = gv + wd * pv
            gv = _upcast_grad(pv, gv)
            # pass the Parameter for python-level metadata checks (name
            # exclusions in Lamb/LarsMomentum) — jit-safe, never traced
            np_, ns_ = self._update(pv, gv, sv, plr,
                                    wd=wd if self._decoupled_wd() else 0.0,
                                    param=pobj)
            new_p.append(np_.astype(pv.dtype))
            new_s.append(ns_)
        return jax.tree_util.tree_unflatten(treedef, new_p), new_s

    def init_states_tree(self, params_tree):
        flat_p, _ = jax.tree_util.tree_flatten(params_tree)

        class _P:  # adapter so _init_state sees ._value
            def __init__(self, v):
                self._value = v

        return [self._init_state(_P(v)) for v in flat_p]

    # ---- checkpointing ----
    def state_dict(self):
        out = {}
        for pname, state in self._states.items():
            for k, v in state.items():
                out[f"{pname}_{k}"] = Tensor(v) if not isinstance(v, Tensor) else v
        out["@step"] = self._step_count
        if isinstance(self._learning_rate, lr_mod.LRScheduler):
            out["LR_Scheduler"] = self._learning_rate.state_dict()
        return out

    def set_state_dict(self, state_dict):
        if "@step" in state_dict:
            self._step_count = int(state_dict["@step"])
        if "LR_Scheduler" in state_dict and isinstance(
            self._learning_rate, lr_mod.LRScheduler
        ):
            self._learning_rate.set_state_dict(state_dict["LR_Scheduler"])
        for p in self._parameter_list:
            state = self._states.setdefault(p.name, self._init_state(p))
            for k in list(state):
                key = f"{p.name}_{k}"
                if key in state_dict:
                    v = state_dict[key]
                    state[k] = v._value if isinstance(v, Tensor) else jnp.asarray(v)

    set_dict = set_state_dict


class SGD(Optimizer):
    def __init__(self, learning_rate=0.001, parameters=None,
                 weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)

    def _update(self, pv, gv, state, lr, wd=0.0, param=None):
        return pv - lr * gv, state


class Momentum(Optimizer):
    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._momentum = momentum
        self._nesterov = use_nesterov

    def _init_state(self, p):
        return {"velocity": _acc_zeros(p)}

    def _update(self, pv, gv, state, lr, wd=0.0, param=None):
        v = self._momentum * state["velocity"] + gv
        if self._nesterov:
            new_p = pv - lr * (gv + self._momentum * v)
        else:
            new_p = pv - lr * v
        return new_p, {"velocity": v}


class Adam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, lazy_mode=False, multi_precision=False,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon

    def _init_state(self, p):
        return {
            "moment1": _acc_zeros(p),
            "moment2": _acc_zeros(p),
            "beta1_pow": jnp.ones([], jnp.float32),
            "beta2_pow": jnp.ones([], jnp.float32),
        }

    def _update(self, pv, gv, state, lr, wd=0.0, param=None):
        b1, b2, eps = self._beta1, self._beta2, self._epsilon
        b1p = state["beta1_pow"] * b1
        b2p = state["beta2_pow"] * b2
        m = b1 * state["moment1"] + (1 - b1) * gv
        v = b2 * state["moment2"] + (1 - b2) * gv * gv
        if wd:
            pv = pv * (1.0 - lr * wd)
        mh = m / (1 - b1p)
        vh = v / (1 - b2p)
        new_p = pv - lr * mh / (jnp.sqrt(vh) + eps)
        return new_p, {"moment1": m, "moment2": v, "beta1_pow": b1p,
                       "beta2_pow": b2p}


class AdamW(Adam):
    """Decoupled weight decay (reference: python/paddle/optimizer/adamw.py)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=0.01,
                 lr_ratio=None, apply_decay_param_fun=None, grad_clip=None,
                 lazy_mode=False, multi_precision=False, name=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         weight_decay, grad_clip)
        self._apply_decay_param_fun = apply_decay_param_fun

    def _decoupled_wd(self):
        return True

    def _weight_decay_coeff(self, p, group):
        if self._apply_decay_param_fun is not None and \
                not self._apply_decay_param_fun(p.name):
            return 0.0
        return super()._weight_decay_coeff(p, group)


class Adamax(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _init_state(self, p):
        return {
            "moment": _acc_zeros(p),
            "inf_norm": _acc_zeros(p),
            "beta1_pow": jnp.ones([], jnp.float32),
        }

    def _update(self, pv, gv, state, lr, wd=0.0, param=None):
        b1, b2, eps = self._beta1, self._beta2, self._epsilon
        b1p = state["beta1_pow"] * b1
        m = b1 * state["moment"] + (1 - b1) * gv
        u = jnp.maximum(b2 * state["inf_norm"], jnp.abs(gv) + eps)
        new_p = pv - (lr / (1 - b1p)) * (m / u)
        return new_p, {"moment": m, "inf_norm": u, "beta1_pow": b1p}


class Adagrad(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, parameters=None,
                 weight_decay=None, grad_clip=None, name=None,
                 initial_accumulator_value=0.0):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._epsilon = epsilon
        self._init_acc = initial_accumulator_value

    def _init_state(self, p):
        return {"moment": jnp.full(p._value.shape, self._init_acc,
                                   _acc_dtype(p._value))}

    def _update(self, pv, gv, state, lr, wd=0.0, param=None):
        m = state["moment"] + gv * gv
        new_p = pv - lr * gv / (jnp.sqrt(m) + self._epsilon)
        return new_p, {"moment": m}


class Adadelta(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95,
                 parameters=None, weight_decay=None, grad_clip=None,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._epsilon, self._rho = epsilon, rho

    def _init_state(self, p):
        return {
            "avg_squared_grad": _acc_zeros(p),
            "avg_squared_update": _acc_zeros(p),
        }

    def _update(self, pv, gv, state, lr, wd=0.0, param=None):
        rho, eps = self._rho, self._epsilon
        asg = rho * state["avg_squared_grad"] + (1 - rho) * gv * gv
        upd = gv * jnp.sqrt(state["avg_squared_update"] + eps) / jnp.sqrt(
            asg + eps)
        asu = rho * state["avg_squared_update"] + (1 - rho) * upd * upd
        return pv - lr * upd, {"avg_squared_grad": asg,
                               "avg_squared_update": asu}


class RMSProp(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._rho, self._epsilon = rho, epsilon
        self._momentum, self._centered = momentum, centered

    def _init_state(self, p):
        s = {
            "mean_square": _acc_zeros(p),
            "momentum": _acc_zeros(p),
        }
        if self._centered:
            s["mean_grad"] = _acc_zeros(p)
        return s

    def _update(self, pv, gv, state, lr, wd=0.0, param=None):
        rho, eps = self._rho, self._epsilon
        ms = rho * state["mean_square"] + (1 - rho) * gv * gv
        if self._centered:
            mg = rho * state["mean_grad"] + (1 - rho) * gv
            denom = jnp.sqrt(ms - mg * mg + eps)
        else:
            denom = jnp.sqrt(ms + eps)
        mom = self._momentum * state["momentum"] + lr * gv / denom
        new_state = {"mean_square": ms, "momentum": mom}
        if self._centered:
            new_state["mean_grad"] = mg
        return pv - mom, new_state


class Lamb(Optimizer):
    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9,
                 beta2=0.999, epsilon=1e-6, parameters=None, grad_clip=None,
                 exclude_from_weight_decay_fn=None, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon
        self._lamb_wd = lamb_weight_decay
        self._exclude_fn = exclude_from_weight_decay_fn

    def _init_state(self, p):
        return {
            "moment1": _acc_zeros(p),
            "moment2": _acc_zeros(p),
            "beta1_pow": jnp.ones([], jnp.float32),
            "beta2_pow": jnp.ones([], jnp.float32),
        }

    def _update(self, pv, gv, state, lr, wd=0.0, param=None):
        b1, b2, eps = self._beta1, self._beta2, self._epsilon
        b1p = state["beta1_pow"] * b1
        b2p = state["beta2_pow"] * b2
        m = b1 * state["moment1"] + (1 - b1) * gv
        v = b2 * state["moment2"] + (1 - b2) * gv * gv
        mh = m / (1 - b1p)
        vh = v / (1 - b2p)
        r = mh / (jnp.sqrt(vh) + eps)
        lamb_wd = self._lamb_wd
        if param is not None and self._exclude_fn is not None and \
                self._exclude_fn(param):
            lamb_wd = 0.0
        upd = r + lamb_wd * pv
        w_norm = jnp.linalg.norm(pv.astype(jnp.float32))
        u_norm = jnp.linalg.norm(upd.astype(jnp.float32))
        ratio = jnp.where((w_norm > 0) & (u_norm > 0), w_norm / u_norm, 1.0)
        return pv - lr * ratio * upd, {
            "moment1": m, "moment2": v, "beta1_pow": b1p, "beta2_pow": b2p
        }


class LarsMomentum(Optimizer):
    """LARS: layer-wise adaptive rate scaling over momentum (reference:
    fluid LarsMomentumOptimizer / fleet/meta_optimizers/lars_optimizer.py;
    operators/optimizers/lars_momentum_op). Per-layer trust ratio
    local_lr = lr·coeff·‖p‖ / (‖g‖ + wd·‖p‖ + eps) keeps huge-batch
    ResNet training stable.

    Weight decay: `lars_weight_decay` is the op's own decay term; a
    per-parameter regularizer additionally folds into the gradient
    BEFORE the op (matching fluid's append_regularization_ops running
    ahead of lars_momentum_op) — configure one or the other, not both.
    `exclude_from_weight_decay` name-tags work in both eager and jit
    paths (the Parameter is threaded through apply_gradients_tree)."""

    def __init__(self, learning_rate=0.001, momentum=0.9, lars_coeff=0.001,
                 lars_weight_decay=0.0005, parameters=None, grad_clip=None,
                 exclude_from_weight_decay=None, epsilon=1e-9, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip)
        self._momentum = momentum
        self._coeff = lars_coeff
        self._lars_wd = lars_weight_decay
        self._eps = epsilon
        self._exclude = list(exclude_from_weight_decay or [])

    def _init_state(self, p):
        return {"velocity": _acc_zeros(p)}

    def _update(self, pv, gv, state, lr, wd=0.0, param=None):
        lars_wd = self._lars_wd
        if param is not None and any(
                tag in (param.name or "") for tag in self._exclude):
            lars_wd = 0.0
        p32 = pv.astype(jnp.float32)
        g32 = gv.astype(jnp.float32)
        p_norm = jnp.linalg.norm(p32)
        g_norm = jnp.linalg.norm(g32)
        # zero-norm fallback keeps the coeff scale: falling back to the
        # RAW lr hands exactly the zero-init parameters (biases) an
        # unscaled large-batch learning rate — with momentum they
        # oscillate and diverge at the big lr LARS exists to enable
        # (‖b‖ grew monotonically at lr=0.5 on the tier-1 toy). lr·coeff
        # is the trust-ratio's own scale at ‖p‖/‖g‖ = 1; once ‖p‖ > 0
        # the standard ratio takes over.
        local_lr = jnp.where(
            (p_norm > 0) & (g_norm > 0),
            lr * self._coeff * p_norm
            / (g_norm + lars_wd * p_norm + self._eps),
            lr * self._coeff)
        v = self._momentum * state["velocity"] + local_lr * (
            g32 + lars_wd * p32)
        return (p32 - v).astype(pv.dtype), {"velocity": v}
