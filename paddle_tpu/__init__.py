"""paddle_tpu — a TPU-native deep-learning framework.

Brand-new framework with the capabilities of PaddlePaddle (reference snapshot
at /root/reference), designed for TPU: jax/XLA is the compute path, the eager
API is a tape over jax.vjp, `to_static` is whole-graph jax.jit capture, and
distribution is jax.sharding over device meshes (SPMD) rather than
NCCL-style message passing. See SURVEY.md for the capability map.
"""
__version__ = "0.1.0"

from . import core
from .core import dtype as _dtype_mod
from .core.dtype import (  # noqa: F401
    bfloat16,
    bool_ as bool,  # noqa: A001  (paddle.bool)
    complex64,
    complex128,
    float16,
    float32,
    float64,
    get_default_dtype,
    int8,
    int16,
    int32,
    int64,
    set_default_dtype,
    uint8,
)
from .core.place import (  # noqa: F401
    CPUPlace,
    CUDAPinnedPlace,
    CUDAPlace,
    CustomPlace,
    IPUPlace,
    MLUPlace,
    NPUPlace,
    TPUPlace,
    XPUPlace,
    device_count,
    get_cudnn_version,
    get_device,
    is_compiled_with_cinn,
    is_compiled_with_cuda,
    is_compiled_with_ipu,
    is_compiled_with_mlu,
    is_compiled_with_npu,
    is_compiled_with_rocm,
    is_compiled_with_tpu,
    is_compiled_with_xpu,
    set_device,
)
from .core.flags import get_flags, set_flags  # noqa: F401
from .core.rng import get_rng_state_tracker, seed  # noqa: F401
from .tensor_core import Parameter, Tensor  # noqa: F401
from .regularizer import L1Decay, L2Decay  # noqa: F401

from . import ops  # installs Tensor methods; must precede api re-export
from .ops import *  # noqa: F401,F403
from .ops import creation as _creation
from .autograd import enable_grad, no_grad  # noqa: F401
from .autograd.engine import grad, is_grad_enabled  # noqa: F401
from .autograd import backward as _autograd_backward  # noqa: F401

from . import autograd  # noqa: F401

# Subpackages below are imported lazily-but-eagerly as they land; each import
# line is appended when the subsystem is built (nn, optimizer, io, amp, jit,
# static, distributed, vision, hapi, profiler, ...).
import importlib as _importlib

for _sub in (
    "observability",  # first: jit/distributed/inference register metrics
    "nn",
    "optimizer",
    "metric",
    "io",
    "amp",
    "framework",
    "jit",
    "static",
    "distributed",
    "vision",
    "text",
    "device",
    "profiler",
    "incubate",
    "hapi",
    "linalg",
    "rec",
    "distribution",
    "audio",
    "inference",
    "native",
    "sparse",
    "quantization",
    "geometric",
    "fft",
    "signal",
    "utils",
    "onnx",
    "analysis",
):
    try:
        globals()[_sub] = _importlib.import_module("." + _sub, __name__)
    except ImportError:
        pass

if "framework" in globals() and hasattr(globals()["framework"], "io_state"):
    from .framework.io_state import load, save  # noqa: F401
if "nn" in globals():
    ParamAttr = globals()["nn"].ParamAttr
if "hapi" in globals() and hasattr(globals()["hapi"], "model"):
    from .hapi.model import Model  # noqa: F401
    from .hapi import callbacks  # noqa: F401
    from .hapi.dynamic_flops import flops  # noqa: F401
if "distributed" in globals():
    DataParallel = globals()["distributed"].DataParallel
from . import hub  # noqa: F401
from . import compat  # noqa: F401
from . import cost_model  # noqa: F401
from . import dataset  # noqa: F401
from . import reader  # noqa: F401
from . import sysconfig  # noqa: F401
from . import version  # noqa: F401

# paddle.dtype: the concrete dtype class (jnp dtypes are numpy dtypes), so
# `isinstance(x.dtype, paddle.dtype)` works as in the reference.
dtype = type(float32)


class LazyGuard:
    """Parameter-init laziness guard (reference: fluid/lazy_init.py).
    Host-side init on jax is cheap and functional; the guard is a no-op
    context kept for API parity."""

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


def batch(reader, batch_size, drop_last=False):
    """Legacy reader batching decorator (reference: python/paddle/batch.py)."""

    def batched():
        buf = []
        for item in reader():
            buf.append(item)
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf

    return batched

# paddle.disable_static / enable_static are no-ops: eager IS the default and
# static capture happens through paddle_tpu.jit.to_static (jax.jit).
_static_mode = False


def disable_static(place=None):
    global _static_mode
    _static_mode = False


def enable_static():
    global _static_mode
    _static_mode = True


def in_dynamic_mode():
    return not _static_mode


def is_grad_enabled_():
    from .autograd.engine import is_grad_enabled as f

    return f()


def summary(net, input_size=None, dtypes=None):
    from .hapi.summary import summary as _summary

    return _summary(net, input_size, dtypes)
