"""paddle_tpu.profiler — performance tracing + step timing.

TPU-native re-design of the reference profiler
(reference: python/paddle/profiler/profiler.py:310 `Profiler`,
make_scheduler:136, export_chrome_tracing:228, RecordEvent
profiler/utils.py:33, step timer profiler/timer.py:1; C++ host/device
event collection paddle/fluid/platform/profiler/).

The reference collects host + CUDA events through its own profiler
runtime. On TPU the device-side story is XLA's: `jax.profiler`
(xprof/perfetto) captures host activity, HLO op time on the chip, and
HBM/ICI traffic. This module wraps it in the reference's API shape:

    prof = Profiler(scheduler=(2, 5), on_trace_ready=export_chrome_tracing("./log"))
    prof.start()
    for batch in loader:
        train_step(batch)
        prof.step()
    prof.stop()
    prof.summary()

plus `RecordEvent` for user-scoped annotations and a `benchmark()` step
timer (reader cost / batch cost / ips), usable standalone via
timer_only=True.
"""
import os
import time

import jax

from ..observability import metrics as _obs

__all__ = ["Profiler", "ProfilerState", "ProfilerTarget", "make_scheduler",
           "export_chrome_tracing", "RecordEvent", "benchmark"]

# the step timer mirrors every tick into the shared telemetry registry,
# so hapi Model.fit (ProgBarLogger), raw loops around TrainStep, and a
# /metrics scrape all report THE SAME reader-cost/batch-cost/ips numbers
# (docs/OBSERVABILITY.md)
_BATCH_COST = _obs.histogram("pt_step_batch_cost_seconds",
                             "per-step wall time (armed step timer)")
_READER_COST = _obs.histogram("pt_step_reader_cost_seconds",
                              "dataloader fetch time per batch")
_SAMPLES_TOTAL = _obs.counter("pt_step_samples_total",
                              "samples consumed by timed steps")


class ProfilerState:
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


class ProfilerTarget:
    CPU = 0
    GPU = 1
    CUSTOM_DEVICE = 2
    TPU = 3


def make_scheduler(*, closed, ready, record, repeat=1, skip_first=0):
    """Step-state schedule mirroring the reference's make_scheduler
    (profiler.py:136): skip_first, then cycles of closed→ready→record."""
    cycle = closed + ready + record

    def schedule(step):
        if step < skip_first:
            return ProfilerState.CLOSED
        s = step - skip_first
        if repeat and s >= cycle * repeat:
            return ProfilerState.CLOSED
        pos = s % cycle
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == cycle - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD

    return schedule


def export_chrome_tracing(dir_name, worker_name=None):
    """on_trace_ready factory: traces land under `dir_name` (the
    jax.profiler/xprof dump contains perfetto/chrome-trace artifacts)."""

    def handler(prof):
        prof._trace_dir = dir_name

    handler._dir = dir_name
    return handler


class RecordEvent:
    """User-scoped annotation visible on the host timeline
    (reference profiler/utils.py:33 RecordEvent → here a
    jax.profiler.TraceAnnotation)."""

    def __init__(self, name, event_type=None):
        self.name = name
        self._ann = None

    def begin(self):
        self._ann = jax.profiler.TraceAnnotation(self.name)
        self._ann.__enter__()

    def end(self):
        if self._ann is not None:
            self._ann.__exit__(None, None, None)
            self._ann = None

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()


class _StepTimer:
    """reader/batch cost + ips tracker (reference profiler/timer.py).
    `enable()` arms the global meter; an armed meter is fed by
    jit.TrainStep automatically (one tick + sample count per compiled
    step), so `benchmark().summary()` gives ips with zero changes to the
    training loop."""

    def __init__(self):
        self.enabled = False
        self.reset()

    def reset(self):
        self.step_times = []
        self.reader_costs = []
        self.samples = 0
        self._t_last = None
        self.auto_fed = False   # True once an instrumented step ticked

    def enable(self):
        self.enabled = True
        self.reset()

    def disable(self):
        self.enabled = False

    def auto_step(self, num_samples=None, auto=True, dt=None):
        """Tick from an instrumented step (TrainStep). Steps chain
        through donated buffers, so wall deltas converge to true step
        time once the dispatch pipeline fills. auto=False ticks without
        claiming the auto-fed flag — for a HOST-side driver (hapi's
        ProgBarLogger on an eager loop) that must stand down the moment
        a compiled step starts feeding the meter itself. `dt` is an
        externally measured step wall (observability.steptrace's
        anchor→opt_publish total): when the phase plane is on, the
        instrumented steps pass it so this meter, hapi's bar, and
        pt_train_phase_seconds cannot disagree about step cost."""
        if auto:
            self.auto_fed = True
        self.step(dt=dt)
        if num_samples:
            self.samples += int(num_samples)
            _SAMPLES_TOTAL.inc(int(num_samples))

    def summary(self):
        s = self.stats()
        if not s:
            return "no steps recorded"
        line = (f"avg batch cost {s['avg_batch_cost_s'] * 1e3:.2f} ms, "
                f"{s['steps_per_sec']:.2f} steps/s")
        if self.samples and self.step_times:
            ips = self.samples / sum(self.step_times)
            line += f", {ips:,.1f} ips"
        return line

    def before_reader(self):
        self._t_reader = time.perf_counter()

    def after_reader(self):
        dt = time.perf_counter() - getattr(self, "_t_reader",
                                           time.perf_counter())
        self.reader_costs.append(dt)
        _READER_COST.observe(dt)

    def step(self, dt=None):
        """One step tick. `dt=None` measures the wall delta since the
        last tick (the self-clocked path); an explicit dt records the
        caller's measurement instead (steptrace routing, auto_step)."""
        now = time.perf_counter()
        if dt is not None:
            dt = float(dt)
            self.step_times.append(dt)
            _BATCH_COST.observe(dt)
        elif self._t_last is not None:
            dt = now - self._t_last
            self.step_times.append(dt)
            _BATCH_COST.observe(dt)
        self._t_last = now

    def stats(self, batch_size=None):
        if not self.step_times:
            return {}
        n = len(self.step_times)
        avg = sum(self.step_times) / n
        out = {"steps": n, "avg_batch_cost_s": avg,
               "steps_per_sec": 1.0 / avg if avg else float("inf")}
        if batch_size:
            out["ips"] = batch_size / avg
        if self.reader_costs:
            out["avg_reader_cost_s"] = (
                sum(self.reader_costs) / len(self.reader_costs))
        return out


_benchmark = _StepTimer()


def benchmark():
    """Global step timer (reference profiler/utils.py benchmark())."""
    return _benchmark


class Profiler:
    """Reference-shaped profiler driving jax.profiler underneath.

    scheduler: None (record from start() to stop()), an (on, off) batch
    tuple, or a make_scheduler callable. on_trace_ready: see
    export_chrome_tracing. timer_only=True skips tracing and only times
    steps."""

    def __init__(self, targets=None, scheduler=None, on_trace_ready=None,
                 timer_only=False, log_dir=None):
        if isinstance(scheduler, (tuple, list)):
            on, off = scheduler
            scheduler = make_scheduler(closed=on, ready=0, record=off - on,
                                       repeat=1)
        self._scheduler = scheduler
        self._on_trace_ready = on_trace_ready
        self.timer_only = timer_only
        self._trace_dir = log_dir or getattr(on_trace_ready, "_dir", None) \
            or "./profiler_log"
        self._step_no = 0
        self._tracing = False
        self.timer = _StepTimer()

    # -- tracing control --
    def _trace_on(self):
        if self.timer_only or self._tracing:
            return
        os.makedirs(self._trace_dir, exist_ok=True)
        jax.profiler.start_trace(self._trace_dir)
        self._tracing = True

    def _trace_off(self):
        if not self._tracing:
            return
        jax.profiler.stop_trace()
        self._tracing = False
        if self._on_trace_ready is not None:
            self._on_trace_ready(self)

    def _apply_state(self):
        if self._scheduler is None:
            self._trace_on()
            return
        st = self._scheduler(self._step_no)
        if st in (ProfilerState.RECORD, ProfilerState.RECORD_AND_RETURN):
            self._trace_on()
        else:
            self._trace_off()

    # -- reference API --
    def start(self):
        self.timer.reset()
        self.timer.step()  # arm the first interval
        self._apply_state()

    def stop(self):
        self._trace_off()

    def step(self, num_samples=None):
        self.timer.step()
        self._num_samples = num_samples
        self._step_no += 1
        self._apply_state()

    def step_info(self, unit=None):
        s = self.timer.stats(batch_size=getattr(self, "_num_samples", None))
        if not s:
            return "no steps recorded"
        ips = s.get("ips")
        return (f"batch_cost: {s['avg_batch_cost_s']:.5f} s "
                f"steps/s: {s['steps_per_sec']:.2f}"
                + (f" ips: {ips:.1f}" if ips else ""))

    def statistic_data(self):
        """Parsed per-op statistics from the captured trace (reference
        profiler_statistic.py StatisticData), or None when no trace was
        recorded (timer_only / nothing captured yet)."""
        if self.timer_only:
            return None
        from . import statistic

        collected = statistic.collect(self._trace_dir)
        if collected is None:
            return None
        return statistic.build_tables(collected)

    def summary(self, sorted_by=None, op_detail=True, thread_sep=False,
                time_unit="ms", views=None, max_rows=30):
        """Print the step timer line plus the reference-style per-op
        time/count tables parsed from the trace (reference:
        profiler_statistic.py op summary; device lanes carry executed
        HLO ops, host lanes carry python/runtime + RecordEvent spans)."""
        print(self.step_info())
        data = self.statistic_data()
        if data is None:
            if not self.timer_only:
                print(f"trace artifacts (xprof/perfetto): "
                      f"{self._trace_dir} (no parsed trace found)")
            return None
        from . import statistic

        order = {None: "total", SortedKeys.OpTotal: "total",
                 SortedKeys.OpAvg: "avg", SortedKeys.OpMax: "max",
                 "total": "total", "avg": "avg", "max": "max",
                 "calls": "calls"}.get(sorted_by, "total")
        print(statistic.render(data, sorted_by=order, max_rows=max_rows))
        return data

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()


class SortedKeys:
    """Sort orders for summary tables (reference:
    python/paddle/profiler/profiler.py SortedKeys)."""

    CPUTotal = 0
    CPUAvg = 1
    CPUMax = 2
    CPUMin = 3
    GPUTotal = 4
    GPUAvg = 5
    GPUMax = 6
    GPUMin = 7
    # aliases used by summary(): device==accelerator lanes, host==CPU
    OpTotal = 0
    OpAvg = 1
    OpMax = 2


class SummaryView:
    """Summary table views (reference: profiler.py SummaryView)."""

    DeviceView = 0
    OverView = 1
    ModelView = 2
    DistributedView = 3
    KernelView = 4
    OperatorView = 5
    MemoryView = 6
    MemoryManipulationView = 7
    UDFView = 8


class TracerEventType:
    """Event categories (reference: profiler/profiler_statistic.py)."""

    Operator = 0
    Dataloader = 1
    ProfileStep = 2
    CudaRuntime = 3
    Kernel = 4
    Memcpy = 5
    Memset = 6
    UserDefined = 7
    OperatorInner = 8
    Forward = 9
    Backward = 10
    Optimization = 11
    Communication = 12
    PythonOp = 13
    PythonUserDefined = 14


def export_protobuf(dir_name, worker_name=None):
    """on_trace_ready exporter writing the raw xplane protobuf dump
    (jax's profiler already persists .xplane.pb under the log dir)."""

    def handler(prof):
        return dir_name

    return handler


def load_profiler_result(file_name):
    """Load an exported trace for postprocessing. The jax/xprof trace is
    the artifact; return the path handle (statistics tables are produced
    by xprof tooling, not re-parsed here)."""
    import os

    if not os.path.exists(file_name):
        raise FileNotFoundError(file_name)
    return file_name


__all__ += ["SortedKeys", "SummaryView", "TracerEventType",
            "export_protobuf", "load_profiler_result"]
