"""Profiler statistics tables.

TPU-native counterpart of the reference's summary machinery (reference:
python/paddle/profiler/profiler_statistic.py — per-op/kernel time and
count tables rendered after a profiling window). The raw data source is
the trace jax.profiler/xprof writes (a gzipped chrome trace containing
host AND device lanes: on TPU each executed HLO op is an event on the
device lane; TraceAnnotation spans appear on the host lanes), so the
tables cover exactly what the reference's host+CUPTI collectors cover.

`collect(trace_dir)` loads the newest trace under the dump directory;
`build_tables(events)` aggregates into:

* overview — wall span and busy time per lane category,
* op summary — per event name: calls, total/avg/max/min ms, % of its
  category's busy time (reference op summary table),

and `render(tables)` formats them in the reference's table style.
"""
import glob
import gzip
import json
import os

__all__ = ["collect", "build_tables", "render", "SummaryData"]


def _newest_trace(trace_dir):
    pats = [os.path.join(trace_dir, "plugins", "profile", "*",
                         "*.trace.json.gz"),
            os.path.join(trace_dir, "**", "*.trace.json.gz")]
    hits = []
    for p in pats:
        hits.extend(glob.glob(p, recursive=True))
    if not hits:
        return None
    return max(hits, key=os.path.getmtime)


def collect(trace_dir):
    """Load trace events from the newest dump under `trace_dir`.
    Returns (events, process_names, thread_names) or None."""
    path = _newest_trace(trace_dir)
    if path is None:
        return None
    with gzip.open(path, "rt") as f:
        data = json.load(f)
    events = data.get("traceEvents", data if isinstance(data, list) else [])
    proc_names, thread_names = {}, {}
    for ev in events:
        if ev.get("ph") == "M":
            if ev.get("name") == "process_name":
                proc_names[ev.get("pid")] = ev["args"].get("name", "")
            elif ev.get("name") == "thread_name":
                thread_names[(ev.get("pid"), ev.get("tid"))] = \
                    ev["args"].get("name", "")
    return events, proc_names, thread_names


def _category(pid, tid, proc_names, thread_names):
    pname = (proc_names.get(pid) or "").lower()
    tname = (thread_names.get((pid, tid)) or "").lower()
    dev_markers = ("tpu", "device", "xla", "/device", "accelerator")
    if any(m in pname for m in dev_markers):
        return "device"
    if any(m in tname for m in dev_markers):
        return "device"
    return "host"


class SummaryData:
    def __init__(self, overview, op_table):
        self.overview = overview    # {category: {busy_us, span_us}}
        self.op_table = op_table    # {category: {name: row-dict}}

    def rows(self, category="device", sorted_by="total"):
        key = {"total": "total_us", "calls": "calls", "avg": "avg_us",
               "max": "max_us", "name": "name"}[sorted_by]
        rows = list(self.op_table.get(category, {}).values())
        rows.sort(key=lambda r: r[key], reverse=key != "name")
        return rows


def build_tables(collected):
    events, proc_names, thread_names = collected
    overview = {}
    ops = {}
    for ev in events:
        if ev.get("ph") != "X":  # complete events carry durations
            continue
        dur = float(ev.get("dur", 0.0))
        name = ev.get("name", "")
        if not name:
            continue
        cat = _category(ev.get("pid"), ev.get("tid"), proc_names,
                        thread_names)
        ov = overview.setdefault(cat, {"busy_us": 0.0, "first": None,
                                       "last": None})
        ts = float(ev.get("ts", 0.0))
        ov["busy_us"] += dur
        ov["first"] = ts if ov["first"] is None else min(ov["first"], ts)
        ov["last"] = (ts + dur if ov["last"] is None
                      else max(ov["last"], ts + dur))
        row = ops.setdefault(cat, {}).setdefault(
            name, {"name": name, "calls": 0, "total_us": 0.0,
                   "max_us": 0.0, "min_us": float("inf")})
        row["calls"] += 1
        row["total_us"] += dur
        row["max_us"] = max(row["max_us"], dur)
        row["min_us"] = min(row["min_us"], dur)
    for cat, table in ops.items():
        busy = max(overview[cat]["busy_us"], 1e-9)
        for row in table.values():
            row["avg_us"] = row["total_us"] / row["calls"]
            row["ratio"] = row["total_us"] / busy
    for cat, ov in overview.items():
        ov["span_us"] = (ov["last"] - ov["first"]) if ov["first"] is not \
            None else 0.0
        ov.pop("first", None)
        ov.pop("last", None)
    return SummaryData(overview, ops)


def _fmt_us(us):
    if us >= 1e6:
        return f"{us / 1e6:.3f} s"
    if us >= 1e3:
        return f"{us / 1e3:.3f} ms"
    return f"{us:.1f} us"


def render(data, sorted_by="total", max_rows=30, categories=None):
    """Reference-style text tables (profiler_statistic.py layout)."""
    lines = []
    cats = categories or [c for c in ("device", "host")
                          if c in data.op_table]
    bar = "-" * 78
    lines.append(bar)
    lines.append("Overview Summary")
    lines.append(bar)
    for cat, ov in sorted(data.overview.items()):
        lines.append(f"{cat:<10} span {_fmt_us(ov['span_us']):>12}   "
                     f"busy {_fmt_us(ov['busy_us']):>12}")
    for cat in cats:
        rows = data.rows(category=cat, sorted_by=sorted_by)
        if not rows:
            continue
        lines.append(bar)
        lines.append(f"{cat.capitalize()} Op Summary "
                     f"(sorted by {sorted_by})")
        lines.append(bar)
        lines.append(f"{'Name':<34}{'Calls':>7}{'Total':>12}{'Avg':>12}"
                     f"{'Max':>12}{'Ratio':>8}")
        for row in rows[:max_rows]:
            nm = row["name"]
            nm = nm if len(nm) <= 33 else nm[:30] + "..."
            lines.append(
                f"{nm:<34}{row['calls']:>7}"
                f"{_fmt_us(row['total_us']):>12}"
                f"{_fmt_us(row['avg_us']):>12}"
                f"{_fmt_us(row['max_us']):>12}"
                f"{row['ratio'] * 100:>7.1f}%")
        if len(rows) > max_rows:
            lines.append(f"... {len(rows) - max_rows} more rows")
    lines.append(bar)
    return "\n".join(lines)
