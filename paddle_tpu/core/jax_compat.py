"""jax version compatibility shims.

The codebase targets the modern jax surface (``jax.shard_map`` with the
``check_vma`` kwarg).  Older jax (< 0.6, e.g. the 0.4.x line) keeps
shard_map at ``jax.experimental.shard_map.shard_map`` with the kwarg
spelled ``check_rep``.  This module bridges the gap once, at package
import time, so every call site can use the modern spelling:

- exports :func:`shard_map` with the modern signature, and
- installs it as ``jax.shard_map`` when the attribute is missing, so
  existing ``jax.shard_map(...)`` / ``from jax import shard_map`` call
  sites work unchanged on old jax, and
- installs ``jax.lax.axis_size`` (added in jax 0.6) as the classic
  ``psum(1, axis_name)`` idiom, which old jax constant-folds to the
  static mesh-axis size under shard_map.
"""
import contextlib
import inspect

import jax

__all__ = ["shard_map", "no_persistent_cache"]


@contextlib.contextmanager
def no_persistent_cache():
    """Compile WITHOUT the persistent (on-disk) compilation cache.

    On jax 0.4.x CPU a DONATING executable loaded from the persistent
    cache can carry a mismatched input/output aliasing map and silently
    corrupt its donated outputs (observed as flaky ~1e-2 divergence on
    the first update after a checkpoint restore). Train-step compiles
    wrap themselves in this guard; everything else keeps the cache."""
    old = jax.config.jax_enable_compilation_cache
    jax.config.update("jax_enable_compilation_cache", False)
    try:
        yield
    finally:
        jax.config.update("jax_enable_compilation_cache", old)

if not hasattr(jax.lax, "axis_size"):
    def _axis_size(axis_name):
        return jax.lax.psum(1, axis_name)

    jax.lax.axis_size = _axis_size

# jax.export (stable since 0.4.30-ish, but absent from this jaxlib
# build): the implementation module ships as jax._src.export._export
# with the identical export()/deserialize()/Exported.call surface —
# alias it so jit.save / inference.Predictor work unchanged.
if not hasattr(jax, "export"):
    try:
        from jax._src.export import _export as _export_mod

        jax.export = _export_mod
    except ImportError:
        pass

if hasattr(jax, "shard_map"):
    shard_map = jax.shard_map
else:
    from jax.experimental.shard_map import shard_map as _shard_map

    _HAS_CHECK_VMA = "check_vma" in inspect.signature(_shard_map).parameters

    def shard_map(f, mesh=None, in_specs=None, out_specs=None,
                  check_vma=None, **kwargs):
        if check_vma is not None:
            kwargs["check_vma" if _HAS_CHECK_VMA else "check_rep"] = (
                check_vma)
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, **kwargs)

    jax.shard_map = shard_map
