"""Stateful RNG over jax.random.

The reference has a global stateful generator (paddle/fluid/framework/generator.cc)
plus the model-parallel RNGStatesTracker
(python/paddle/distributed/fleet/meta_parallel/parallel_layers/random.py).
TPU-native design: a named-stream key tracker over jax PRNG keys. Eager ops
split a fresh subkey per call; traced code should take keys explicitly (the
framework's jitted train steps thread a per-step seed).
"""
import contextlib
import threading

import jax
import numpy as np


class Generator:
    """Splittable stateful PRNG stream."""

    def __init__(self, seed=0):
        self._seed = int(seed)
        self._count = 0
        self._lock = threading.Lock()

    def manual_seed(self, seed):
        self._seed = int(seed)
        self._count = 0
        return self

    def seed(self):
        return self._seed

    def next_key(self):
        """A fresh PRNGKey. Deterministic in (seed, call index)."""
        with self._lock:
            c = self._count
            self._count += 1
        return jax.random.fold_in(jax.random.PRNGKey(self._seed), c)

    def get_state(self):
        return (self._seed, self._count)

    def set_state(self, state):
        self._seed, self._count = int(state[0]), int(state[1])


class RNGStatesTracker:
    """Named RNG streams — used for TP dropout determinism.

    Mirrors the semantics of the reference's RNGStatesTracker
    (fleet/layers/mpu/random.py): 'global' stream shared across
    model-parallel ranks, 'local' streams offset per rank.
    """

    def __init__(self):
        self.states = {}

    def add(self, name, seed):
        if name in self.states:
            raise ValueError(f"rng state {name!r} already exists")
        self.states[name] = Generator(seed)

    def get(self, name):
        return self.states[name]

    def reset(self):
        self.states = {}

    def rng_state(self, name="global"):
        import contextlib

        @contextlib.contextmanager
        def _ctx():
            global _default_generator
            old = _default_generator
            _default_generator = self.states[name]
            try:
                yield
            finally:
                _default_generator = old

        return _ctx()


class _TraceKeyState(threading.local):
    """Per-thread injected base key for traced code.

    Jitted train steps install a per-step key here so that `next_key()`
    calls made while tracing derive from a traced value instead of baking
    a host-side constant into the compiled program (which would replay the
    same dropout mask every step)."""

    def __init__(self):
        self.base = None
        self.count = 0


_trace_state = _TraceKeyState()


@contextlib.contextmanager
def trace_key_scope(base_key):
    """Within this scope, next_key() folds a trace-local counter into
    `base_key` (typically fold_in(seed_key, step)) instead of consuming the
    stateful generator."""
    old = (_trace_state.base, _trace_state.count)
    _trace_state.base, _trace_state.count = base_key, 0
    try:
        yield
    finally:
        _trace_state.base, _trace_state.count = old


_default_generator = Generator(np.random.randint(0, 2**31 - 1))
_tracker = RNGStatesTracker()


def default_generator():
    return _default_generator


def get_rng_state_tracker():
    return _tracker


def seed(s):
    """paddle.seed equivalent."""
    _default_generator.manual_seed(s)
    return _default_generator


def next_key():
    if _trace_state.base is not None:
        c = _trace_state.count
        _trace_state.count += 1
        return jax.random.fold_in(_trace_state.base, c)
    return _default_generator.next_key()
