"""Dtype system.

TPU-native replacement for the reference's VarType/phi DataType enum
(reference: paddle/fluid/framework/framework.proto:117 `VarType`,
paddle/phi/common/data_type.h). We expose numpy dtype objects directly so that
`x.dtype == paddle_tpu.float32` works and interop with jax/numpy is free.
"""
import numpy as np
import jax.numpy as jnp

# Canonical dtype constants (np.dtype instances — hashable, comparable).
bool_ = np.dtype("bool")
int8 = np.dtype("int8")
int16 = np.dtype("int16")
int32 = np.dtype("int32")
int64 = np.dtype("int64")
uint8 = np.dtype("uint8")
uint16 = np.dtype("uint16")
uint32 = np.dtype("uint32")
uint64 = np.dtype("uint64")
float16 = np.dtype("float16")
bfloat16 = jnp.bfloat16.dtype  # np.dtype(ml_dtypes.bfloat16)
float32 = np.dtype("float32")
float64 = np.dtype("float64")
complex64 = np.dtype("complex64")
complex128 = np.dtype("complex128")

_NAME_TO_DTYPE = {
    "bool": bool_,
    "int8": int8,
    "int16": int16,
    "int32": int32,
    "int64": int64,
    "uint8": uint8,
    "uint16": uint16,
    "uint32": uint32,
    "uint64": uint64,
    "float16": float16,
    "fp16": float16,
    "bfloat16": bfloat16,
    "bf16": bfloat16,
    "float32": float32,
    "fp32": float32,
    "float64": float64,
    "fp64": float64,
    "complex64": complex64,
    "complex128": complex128,
}

_FLOATING = {float16, bfloat16, float32, float64}
_INTEGER = {int8, int16, int32, int64, uint8, uint16, uint32, uint64}
_COMPLEX = {complex64, complex128}

# Process-wide default dtype (paddle.set_default_dtype /
# python/paddle/framework/framework.py in the reference).
_default_dtype = float32


def set_default_dtype(d):
    global _default_dtype
    _default_dtype = convert_dtype(d)


def get_default_dtype():
    return _default_dtype


def _narrow_if_no_x64(d):
    """Without jax x64, 64-bit dtypes silently narrow (TPU-native behavior;
    avoids per-op UserWarning spam when user code asks for paddle's int64)."""
    import jax

    if jax.config.jax_enable_x64:
        return d
    return {int64: int32, uint64: uint32, float64: float32,
            complex128: complex64}.get(d, d)


def convert_dtype(dtype):
    """Normalize str/np.dtype/jnp type → canonical np.dtype."""
    if dtype is None:
        return None
    if isinstance(dtype, str):
        try:
            return _narrow_if_no_x64(_NAME_TO_DTYPE[dtype])
        except KeyError:
            raise ValueError(f"Unknown dtype name: {dtype!r}")
    if isinstance(dtype, np.dtype):
        return _narrow_if_no_x64(dtype)
    # jnp.float32 style (type objects) and python builtins
    if dtype is bool:
        return bool_
    if dtype is int:
        return int64
    if dtype is float:
        return _default_dtype
    try:
        return np.dtype(dtype)
    except TypeError:
        raise ValueError(f"Cannot convert {dtype!r} to a dtype")


def is_floating_point(dtype):
    return convert_dtype(dtype) in _FLOATING


def is_integer(dtype):
    return convert_dtype(dtype) in _INTEGER


def is_complex(dtype):
    return convert_dtype(dtype) in _COMPLEX
