"""Runtime flag registry.

TPU-native equivalent of the reference's gflags-based
`PADDLE_DEFINE_EXPORTED_*` registry (reference:
paddle/fluid/platform/flags.cc:24 `GetExportedFlagInfoMap`, python
`paddle.set_flags`). Flags are plain python values, seedable from `FLAGS_*`
environment variables, settable at runtime via set_flags().
"""
import os
import threading

_lock = threading.Lock()
_registry = {}
_hooks = {}


def on_flag_change(name, fn):
    """Register fn(new_value) to run whenever `name` is set via
    set_flags (the reference's flag-callback pattern in flags.cc)."""
    _hooks.setdefault(name, []).append(fn)


class _FlagInfo:
    __slots__ = ("name", "default", "value", "doc", "type")

    def __init__(self, name, default, doc):
        self.name = name
        self.default = default
        self.value = default
        self.doc = doc
        self.type = type(default)


def _coerce(ty, raw):
    if ty is bool:
        if isinstance(raw, str):
            return raw.lower() in ("1", "true", "yes", "on")
        return bool(raw)
    return ty(raw)


def define_flag(name, default, doc=""):
    with _lock:
        if name in _registry:
            return _registry[name].value
        info = _FlagInfo(name, default, doc)
        env = os.environ.get("FLAGS_" + name)
        if env is not None:
            info.value = _coerce(info.type, env)
        _registry[name] = info
        return info.value


def get_flags(names=None):
    if names is None:
        names = list(_registry)
    if isinstance(names, str):
        names = [names]
    return {n: _registry[n].value for n in names if n in _registry}


def set_flags(flags):
    changed = []
    with _lock:
        for name, value in flags.items():
            name = name[len("FLAGS_"):] if name.startswith("FLAGS_") else name
            if name not in _registry:
                _registry[name] = _FlagInfo(name, value, "")
                changed.append((name, value))
            else:
                info = _registry[name]
                info.value = _coerce(info.type, value)
                changed.append((name, info.value))
    for name, value in changed:
        for fn in _hooks.get(name, ()):
            fn(value)


def get_flag(name):
    return _registry[name].value if name in _registry else None


# Core flags (subset of reference's 74; grown as subsystems land).
define_flag("check_nan_inf", False, "scan op outputs for NaN/Inf (debug)")


def _sync_debug_nans(v):
    """check_nan_inf covers compiled programs too: jax_debug_nans re-runs
    a jitted computation op-by-op on a NaN so the failing primitive is
    attributed (the in-jit analog of the eager per-op scan)."""
    try:
        import jax

        jax.config.update("jax_debug_nans", bool(v))
    except Exception:  # ptlint: disable=PTL804 (knob probe; jax absent or knob unknown)
        pass


on_flag_change("check_nan_inf", _sync_debug_nans)
# the env var (FLAGS_check_nan_inf=1) seeds the value without firing
# hooks — sync the jit-level check once at import
if get_flag("check_nan_inf"):
    _sync_debug_nans(True)
define_flag("allocator_strategy", "xla", "memory handled by XLA/PJRT on TPU")
define_flag("eager_delete_tensor_gb", 0.0, "no-op: XLA owns buffers")
define_flag("use_pallas_kernels", True, "use pallas kernels for hot ops on TPU")
define_flag("log_level", 0, "verbose log level (VLOG equivalent)")
