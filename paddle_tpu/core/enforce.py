"""Error enforcement helpers.

Equivalent of the reference's PADDLE_ENFORCE macro family
(reference: paddle/phi/core/enforce.h). Python-level: raise typed errors with
a clear message; no C++ stack dance needed.
"""


class EnforceNotMet(RuntimeError):
    pass


class InvalidArgumentError(ValueError):
    pass


class NotFoundError(KeyError):
    pass


class UnimplementedError(NotImplementedError):
    pass


class OutOfRangeError(IndexError):
    pass


def enforce(cond, msg="", exc=EnforceNotMet):
    if not cond:
        raise exc(msg)


def enforce_eq(a, b, msg=""):
    if a != b:
        raise InvalidArgumentError(f"{msg} (expected {a!r} == {b!r})")


def enforce_gt(a, b, msg=""):
    if not a > b:
        raise InvalidArgumentError(f"{msg} (expected {a!r} > {b!r})")


def enforce_shape_match(s1, s2, msg=""):
    if tuple(s1) != tuple(s2):
        raise InvalidArgumentError(f"{msg} (shape {tuple(s1)} vs {tuple(s2)})")
