from . import jax_compat  # noqa: F401  (must run before jax.shard_map use)
from . import dtype, enforce, flags, place, rng  # noqa: F401
from .dtype import (  # noqa: F401
    bfloat16,
    bool_,
    complex64,
    complex128,
    convert_dtype,
    float16,
    float32,
    float64,
    get_default_dtype,
    int8,
    int16,
    int32,
    int64,
    set_default_dtype,
    uint8,
)
from .place import (  # noqa: F401
    CPUPlace,
    CUDAPlace,
    Place,
    TPUPlace,
    get_device,
    get_place,
    set_device,
)
