"""Device / Place model.

TPU-native replacement for the reference's `Place` hierarchy
(reference: paddle/phi/common/place.h, python `paddle.set_device` in
python/paddle/device/__init__.py). A Place maps onto a jax.Device; there is no
driver-level device management here — PJRT owns that.
"""
import jax


class Place:
    """Base place. Compares by (kind, device_id)."""

    kind = "unknown"

    def __init__(self, device_id=0):
        self.device_id = int(device_id)

    def get_device_id(self):
        return self.device_id

    def __eq__(self, other):
        return (
            isinstance(other, Place)
            and self.kind == other.kind
            and self.device_id == other.device_id
        )

    def __hash__(self):
        return hash((self.kind, self.device_id))

    def __repr__(self):
        return f"Place({self.kind}:{self.device_id})"

    def jax_device(self):
        devs = [d for d in jax.devices() if d.platform == self._platform()]
        if not devs:
            # fall back to whatever the default backend exposes
            devs = jax.devices()
        return devs[min(self.device_id, len(devs) - 1)]

    def _platform(self):
        return {"tpu": "tpu", "cpu": "cpu", "gpu": "gpu"}.get(self.kind, "cpu")


class CPUPlace(Place):
    kind = "cpu"

    def __init__(self):
        super().__init__(0)


class TPUPlace(Place):
    kind = "tpu"


class CUDAPlace(Place):
    """Accepted for API compatibility; maps to the default accelerator."""

    kind = "gpu"


class CUDAPinnedPlace(CPUPlace):
    pass


class XPUPlace(TPUPlace):
    """Accepted for API compatibility; maps to the default accelerator."""


class NPUPlace(TPUPlace):
    """Accepted for API compatibility; maps to the default accelerator."""


class MLUPlace(TPUPlace):
    """Accepted for API compatibility; maps to the default accelerator."""


class IPUPlace(TPUPlace):
    """Accepted for API compatibility; maps to the default accelerator."""


class CustomPlace(Place):
    """Custom-device place (reference: phi::CustomPlace). Accepts a device
    type string; any PJRT-visible platform matches, else default backend."""

    def __init__(self, device_type, device_id=0):
        super().__init__(device_id)
        self.kind = str(device_type)

    def _platform(self):
        return self.kind


_current_place = None


def _default_place():
    try:
        plat = jax.default_backend()
    except Exception:
        plat = "cpu"
    if plat == "tpu":
        return TPUPlace(0)
    if plat == "gpu":
        return CUDAPlace(0)
    return CPUPlace()


def set_device(device):
    """paddle.set_device — accepts 'cpu', 'tpu', 'tpu:0', 'gpu:0'."""
    global _current_place
    if isinstance(device, Place):
        _current_place = device
        return _current_place
    name, _, idx = device.partition(":")
    idx = int(idx) if idx else 0
    if name == "cpu":
        _current_place = CPUPlace()
    elif name in ("tpu", "xpu", "npu"):
        _current_place = TPUPlace(idx)
    elif name in ("gpu", "cuda"):
        _current_place = CUDAPlace(idx)
    else:
        raise ValueError(f"Unknown device {device!r}")
    return _current_place


def get_device():
    p = get_place()
    return f"{p.kind}:{p.device_id}"


def get_place():
    global _current_place
    if _current_place is None:
        _current_place = _default_place()
    return _current_place


def is_compiled_with_cuda():
    return False


def is_compiled_with_tpu():
    return True


def is_compiled_with_cinn():
    return False


def is_compiled_with_rocm():
    return False


def is_compiled_with_xpu():
    return False


def is_compiled_with_npu():
    return False


def is_compiled_with_mlu():
    return False


def is_compiled_with_ipu():
    return False


def get_cudnn_version():
    return None


def device_count():
    return len(jax.devices())
