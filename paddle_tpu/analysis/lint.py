"""jit-safety AST linter — the source half of `paddle_tpu.analysis`.

Every hard bug this repo shipped and then root-caused is a statically
detectable misuse of the JAX/XLA programming model: donation silently
dropped and reused buffers (PR 1/2), per-instance recompiles from
non-argument rng keys (PR 1), host-sync `float(loss)` on a hot path,
the mixed int8/raw wire-format deadlock shape (PR 4). This module is
the same idea as PaddlePaddle's static-graph IR validity passes
(SURVEY layer 3/4a), run at the SOURCE level: find the misuse before a
TPU run does.

Design:

* **stdlib-only.** No jax import — `tools/ptlint.py` loads this module
  standalone, so the CI gate lints the whole tree in a few seconds
  (python startup + ast.parse, no backend init). The jaxpr/HLO half
  (donation coverage, dtype promotions) lives in `step_analysis.py`
  and needs a live step to trace.

* **Traced-context detection.** A function is "traced" when the module
  shows it entering a jax trace: decorated with / passed to `jax.jit`,
  `pjit`, `grad`, `value_and_grad`, `vmap`, `pmap`, `checkpoint`,
  `shard_map`, a `lax` control-flow combinator, `to_static`, or named
  as a `TrainStep` loss_fn. Nested defs inherit the context.
  `to_static` functions run under AutoGraph (`jit/autograph.py`
  rewrites tensor if/for into `lax.cond`/`scan`), so the
  tracer-control-flow rules are skipped there — only raw-trace
  contexts get them.

* **Two-level taint.** Inside a traced function, parameters and
  anything derived from them are `tainted` (may hold tracers);
  expressions that are *definitely* jax arrays (results of
  `jnp.*`/`lax.*`/`jax.random.*` calls, arithmetic on them, …) are
  additionally `array`. Host-sync rules fire on `tainted` (a
  `float()` of anything trace-derived is a bug); control-flow rules
  fire only on `array` (iterating a python list OF tracers is fine —
  iterating a tracer is not). Static accessors (`.shape`, `.dtype`,
  `len()`, …) launder taint: branching on shapes is legal and
  idiomatic.

Suppressions: a trailing `# ptlint: disable=PTL101` (comma-separated
ids or slugs, or `all`) on the offending line — or on the enclosing
`def` line to waive a whole function — and `# ptlint: skip-file`
anywhere in the file. Suppressed findings are counted but not
reported; the CLI's JSON output carries both numbers.
"""
import ast
import dataclasses
import fnmatch
import os
import re
import types

__all__ = ["PTLINT_VERSION", "SPMD_ANALYSIS_VERSION",
           "LOCK_ANALYSIS_VERSION", "RULES", "Rule",
           "Finding", "lint_source", "lint_file", "lint_paths",
           "iter_python_files", "build_lock_graph", "lock_graph_report"]

PTLINT_VERSION = "1.3.0"
# version of the jaxpr-level SPMD pass suite (analysis/spmd_analysis.py).
# Declared HERE so the stdlib-only loaders (tools/ptlint.py, bench.py's
# supervisor-side stamp) can report it without importing jax.
SPMD_ANALYSIS_VERSION = "1.0.0"
# version of the tree-wide lock-acquisition-graph pass (PTL801 and the
# fleet_lock_order.json golden) — stdlib-only, lives in this module
LOCK_ANALYSIS_VERSION = "1.0.0"


@dataclasses.dataclass(frozen=True)
class Rule:
    id: str
    name: str
    summary: str
    # the real, shipped-and-root-caused bug this rule would have caught
    # (or the bug class it fences off) — docs/ANALYSIS.md renders this
    caught: str


RULES = {r.id: r for r in [
    Rule("PTL101", "host-sync-in-trace",
         "float()/int()/bool()/.item()/.numpy()/.tolist() on a traced "
         "value inside a traced function",
         "host-sync float(loss) on the training hot path; under jit "
         "this is a ConcretizationTypeError at best, a silent "
         "per-step device sync at worst"),
    Rule("PTL102", "numpy-on-tracer",
         "np.* call applied to a traced value inside a traced function",
         "np.asarray(tracer) falls out of the XLA program — it either "
         "crashes the trace or bakes a trace-time constant"),
    Rule("PTL103", "tracer-branch",
         "python if/while/assert on a jax array value inside a "
         "raw-traced function (no AutoGraph)",
         "branching on a tracer crashes the trace; the fix is "
         "lax.cond/jnp.where, or @to_static which rewrites it"),
    Rule("PTL104", "tracer-loop",
         "python for over a jax array value inside a raw-traced "
         "function (no AutoGraph)",
         "iterating a tracer unrolls (or crashes) the trace; use "
         "lax.scan/fori_loop, or @to_static"),
    Rule("PTL105", "print-in-trace",
         "print() of a traced value inside a traced function",
         "print under trace fires once at trace time with an abstract "
         "value, not per step — use jax.debug.print"),
    Rule("PTL201", "donated-reuse",
         "a buffer passed at a donated argument position is read "
         "again after the donating call",
         "the PR-2 class: a donated-then-reused pytree reads freed "
         "HBM — jax errors on CPU but silently corrupts under some "
         "backends/caches"),
    Rule("PTL202", "mixed-weak-arg",
         "the same jitted callable takes a python scalar literal AND "
         "a non-literal at the same argument position",
         "a weak-typed python scalar and a committed array hash to "
         "different jit signatures — two executables for one step "
         "(the PR-1 retrace-churn class)"),
    Rule("PTL203", "impure-time",
         "time.time()/perf_counter() etc. inside a traced function",
         "wall-clock reads freeze to a trace-time constant — the "
         "telemetry that motivated PR 3 measures OUTSIDE the program"),
    Rule("PTL204", "impure-random",
         "python random.* / np.random.* inside a traced function",
         "host RNG bakes one draw into the compiled program (the "
         "same-mask-every-step dropout bug PR 1 fixed by threading "
         "the key as an argument)"),
    Rule("PTL301", "int8-dot-no-preferred",
         "dot_general/dot/matmul/einsum on int8-family operands "
         "(astype(int8) or the packed-nibble int4 unpack) without "
         "preferred_element_type",
         "int8×int8 accumulating in int8 overflows silently; the "
         "quantized runtime (PR 4, int4 in PR 12) requires "
         "preferred_element_type=int32 — the MXU-native contract; "
         "unpack_int4 codes are int8 on the wire into the dot"),
    Rule("PTL401", "rank-divergent-collective",
         "a collective call (direct, or through any call depth) "
         "inside a branch conditioned on the process rank",
         "the PR-4 wire-format deadlock shape: one rank entering a "
         "collective its peers skip (or entering a different one) "
         "hangs the pod; interprocedural since ISSUE-11 — a helper "
         "that reaches a collective is as divergent as the "
         "collective itself"),
    Rule("PTL501", "aliasing-escape",
         "a value aliasing caller-owned storage (np.asarray/"
         "jnp.asarray/frombuffer/memoryview of an argument) escapes "
         "into an attribute or shared container that outlives the "
         "call",
         "the set_state_dict class: a restore path stored VIEWS of "
         "the caller's arrays, so a later in-place update (or a "
         "donating executable consuming the origin) silently "
         "corrupted the caller's copy — the zero-copy aliasing "
         "family behind years of 'platform-bug' flakes, fenced at "
         "runtime since PR 11 and now a static fail; np.array / "
         "jnp.array(copy=True) / .copy() are the documented fixes "
         "and launder the taint"),
    Rule("PTL502", "host-view-into-jit",
         "a host view of caller storage (asarray/frombuffer of an "
         "argument) handed to a recorded jitted callable without a "
         "defensive copy",
         "the make_array_from_callback root cause: a zero-copy host "
         "view entering a compiled step can be aliased by the "
         "runtime — donation frees the caller's buffer, and async "
         "dispatch races any caller-side mutation of the view; copy "
         "first (np.array / jnp.array(copy=True))"),
    Rule("PTL601", "concat-into-partial-shard-map-spec",
         "a jnp.concatenate/stack-derived value enters shard_map "
         "through a partial in_spec (a PartitionSpec leaving mesh "
         "axes unmentioned)",
         "the PR-6 hybrid-pp NaN: jax-0.4.37's spmd partitioner "
         "mis-shards a concatenate result entering shard_map "
         "through a partial in_spec — values arrive SUMMED over "
         "the unmentioned axes (labels doubled at pp=2 -> OOB "
         "vocab ids -> take_along_axis NaN-fill). jnp.pad "
         "partitions correctly and is the pinned-safe rewrite "
         "(test_label_shift_survives_partial_shard_spec)"),
    Rule("PTL701", "shared-dict-iter",
         "iteration over a shared dict attribute of a "
         "thread-shared class outside a list()/sorted()/dict() "
         "snapshot or the class lock",
         "the PR-7 scrape race: the /metrics HTTP thread iterating "
         "scheduler/pool dicts while the engine thread "
         "inserts/deletes -> intermittent RuntimeError 500s — "
         "fixed by hand in PR 7's fifth review pass, mechanized "
         "here"),
    Rule("PTL702", "unlocked-rmw",
         "read-modify-write of shared state outside the lock, in "
         "a class that declares one",
         "a lock-owning class whose `+=` runs unlocked loses "
         "increments under concurrency — the shared-counter race "
         "class the observability registry's per-thread cells "
         "(PR 3) exist to avoid"),
    Rule("PTL703", "defaultdict-read-materializes",
         "Load-context subscript of a defaultdict attribute in a "
         "thread-shared class — a read that INSERTS races every "
         "concurrent snapshot; use .get()",
         "the PR-7 phantom-meter bug: _order_key reading the "
         "tenant fair-queuing defaultdict materialized a 0.0 "
         "meter per merely-waiting tenant (mutation on the read "
         "path), fixed to .get in review pass 2"),
    Rule("PTL801", "lock-order-cycle",
         "the per-class lock-acquisition graph (with self.<lock>: "
         "nesting, direct and through self/cls helper calls and "
         "cross-class method calls, transitively) contains a cycle — "
         "or a non-reentrant Lock is re-acquired on a path that "
         "already holds it",
         "the PR-13 wedged-replica flap shape: two threads taking "
         "the same pair of locks in opposite order wedge both "
         "forever with zero CPU — the blessed fleet-wide order is "
         "pinned in tests/golden/fleet_lock_order.json the same way "
         "the dp2.tp2.pp2 collective schedule is pinned"),
    Rule("PTL802", "blocking-call-under-lock",
         "a blocking call (time.sleep, file open, socket "
         "send/recv/accept/connect, thread/process join, "
         "Future.result, Event.wait, block_until_ready) runs while a "
         "declared class lock is held — directly or through any "
         "helper-call depth in the module",
         "a lock held across a disk/network/device wait serializes "
         "every other thread behind I/O — the anomaly journal held "
         "its lock across open()+write, so one slow disk stalled "
         "every thread that journaled; the fixes are the kv_tier "
         "idioms: snapshot-then-release, or a bounded-queue hand-off "
         "to a worker thread"),
    Rule("PTL803", "callback-under-lock",
         "a caller-supplied callback (an attribute assigned verbatim "
         "from a constructor/method parameter, or a function "
         "parameter called directly) is invoked while a class lock "
         "is held",
         "the re-entrancy shape: spill_fn / event sinks / registered "
         "state providers are arbitrary caller code — invoked under "
         "the lock they can call back into the class and self-"
         "deadlock (non-reentrant Lock), or wedge on a second lock; "
         "snapshot the callback and its arguments, release, THEN "
         "invoke"),
    Rule("PTL804", "silent-except-pass",
         "a bare `except:` / `except Exception:` whose handler body "
         "is only pass/continue — a swallowed failure with no "
         "journal, counter, or log",
         "the PR-15 class: the kwarg-collision dump path failed "
         "silently for three releases because its guard was `except "
         "Exception: pass` — best-effort code must leave a trace "
         "(resilience.record(...), a pt_* counter, or a log call in "
         "the handler makes it legal)"),
]}

_SLUG_TO_ID = {r.name: r.id for r in RULES.values()}

# ----------------------------------------------------------------- tables

# transforms whose function argument enters a jax trace:
# component name -> positions of traced callables in the call args
_TRACING_CALL_ARGS = {
    "jit": (0,), "pjit": (0,), "vmap": (0,), "pmap": (0,),
    "grad": (0,), "value_and_grad": (0,), "checkpoint": (0,),
    "remat": (0,), "shard_map": (0,), "custom_vjp": (0,),
    "scan": (0,), "while_loop": (0, 1), "fori_loop": (2,),
    "cond": (1, 2), "associative_scan": (0,),
}
# decorator component names that make the decorated def traced
_TRACING_DECORATORS = {"jit", "pjit", "vmap", "pmap", "grad",
                       "value_and_grad", "checkpoint", "remat",
                       "shard_map", "custom_vjp"}
# AutoGraph-covered entries (tensor control flow is REWRITTEN, so the
# tracer-control-flow rules don't apply)
_AUTOGRAPH_NAMES = {"to_static"}
# TrainStep-family constructors: positional arg 1 / kwarg loss_fn is
# traced (raw trace, no autograph)
_TRAINSTEP_NAMES = {"TrainStep", "DistributedTrainStep",
                    "SparseTrainStep"}

# attribute reads that LAUNDER taint — static at trace time
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "nbytes",
                 "itemsize", "weak_type", "sharding", "device",
                 "aval", "name"}
# calls whose result is static regardless of argument taint
_STATIC_FUNCS = {"len", "isinstance", "issubclass", "type", "hasattr",
                 "callable", "id", "repr", "str", "format", "dir",
                 "vars", "globals", "locals"}
# roots whose calls produce jax arrays
_ARRAY_ROOTS = {"jnp", "lax", "jsp"}
# jnp/jax functions that return HOST values (static metadata), not arrays
_STATIC_ARRAY_FUNCS = {"issubdtype", "isdtype", "result_type",
                       "promote_types", "iinfo", "finfo", "dtype",
                       "shape", "ndim", "size", "broadcast_shapes",
                       "eval_shape", "tree_structure", "make_jaxpr"}
_ARRAY_DOTTED_PREFIXES = ("jax.numpy.", "jax.lax.", "jax.random.",
                          "jax.nn.", "jax.scipy.")

_DOT_FUNCS = {"dot_general", "dot", "matmul", "einsum", "tensordot"}

_COLLECTIVE_FUNCS = {
    "all_reduce", "all_reduce_np", "all_gather", "all_gather_np",
    "all_gather_bytes", "all_gather_obj", "broadcast", "broadcast_np",
    "barrier", "reduce_scatter", "all_to_all", "psum", "psum_scatter",
    "pmean", "pmax", "pmin", "ppermute", "pshuffle",
    "fused_allreduce_gradients", "allreduce", "allgather",
}
_RANK_NAMES = {"rank", "local_rank", "world_rank", "global_rank",
               "proc_id", "proc_index", "process_index", "pid"}
_RANK_CALLS = {"get_rank", "process_index", "get_world_rank",
               "local_rank", "get_local_rank"}

_TIME_FUNCS = {"time", "perf_counter", "monotonic", "process_time",
               "clock_gettime", "time_ns", "perf_counter_ns",
               "monotonic_ns"}

_SYNC_METHODS = {"item", "tolist", "numpy", "block_until_ready"}
_SYNC_BUILTINS = {"float", "int", "bool", "complex"}

# ---- PTL601 (sharding hazard) tables ----
# the concatenate family the jax-0.4.37 partitioner mis-shards into a
# partial-spec shard_map input (jnp.pad is the pinned-safe rewrite and
# deliberately NOT listed — flagging the documented fix idiom would
# turn the regression-pinned safe shape into a permanent suppression)
_CONCAT_FUNCS = {"concatenate", "stack", "hstack", "vstack",
                 "column_stack", "row_stack"}
_SHARD_MAP_NAMES = {"shard_map"}

# ---- PTL7xx (host concurrency) tables ----
# classes opt in to the race fence with `# ptlint: thread-shared` on
# the class line (the serving/fleet scrape contract), or implicitly by
# owning a threading lock (declared lock discipline)
_THREAD_SHARED_RE = re.compile(r"#\s*ptlint:\s*thread-shared")
_LOCK_FACTORIES = {"Lock", "RLock"}
_DICT_FACTORIES = {"dict", "defaultdict", "OrderedDict", "Counter"}
# wrappers that materialize a dict view in one C-level pass (no
# bytecode boundary another thread could interleave a resize into)
_LAZY_ITER_WRAPPERS = {"enumerate", "zip", "map", "filter", "iter",
                       "reversed", "chain"}

# ---- PTL5xx (aliasing/donation escape) tables ----
# zero-copy constructors: their result ALIASES the argument's storage
# whenever numpy/jax can avoid the copy. np.array / jnp.array(copy=True)
# / .copy() are the documented fixes — they simply don't match, so
# correct code launders the taint by construction.
_ALIAS_VIEW_FUNCS = {"asarray", "frombuffer"}
# ndarray methods that return views of views — aliasing survives them
_VIEW_METHODS = {"view", "reshape", "ravel", "transpose", "squeeze",
                 "swapaxes"}
# container mutators through which an alias escapes into shared state
_CONTAINER_STORES = {"append", "add", "insert", "setdefault", "extend"}

# ---- PTL8xx (lock discipline) tables ----
# attribute calls that block the calling thread (socket, future,
# queue, subprocess and device waits). `.join` is handled separately
# in _blocking_call with a strict signature guard so str.join and
# os.path.join never match.
_BLOCKING_METHODS = {"result", "recv", "recvfrom", "accept", "connect",
                     "sendall", "send", "communicate",
                     "block_until_ready", "wait"}
# method names too generic to resolve a cross-class lock edge by bare
# name (half the tree defines a close()/get()/put()) — the lock graph
# follows a bare-name call only when exactly ONE lock-owning class
# defines it AND the name is specific enough to mean that class
_GENERIC_METHODS = frozenset({
    "get", "put", "pop", "add", "append", "remove", "clear", "update",
    "close", "start", "stop", "run", "join", "wait", "notify", "send",
    "recv", "read", "write", "flush", "reset", "register", "submit",
    "shutdown", "metrics", "snapshot", "load", "save", "set", "step",
    "call", "apply", "copy", "result", "next", "keys", "values",
    "items", "acquire", "release", "drain", "tick", "poll", "open",
    "cancel",   # Future.cancel — dogfood FP: resolved to FleetRouter
})


@dataclasses.dataclass
class _ClassInfo:
    """Concurrency contract of one class, prescanned from its body."""
    name: str
    shared: bool = False          # thread-shared marker or owns a lock
    dict_attrs: frozenset = frozenset()        # self attrs holding dicts
    defaultdict_attrs: frozenset = frozenset()
    lock_attrs: frozenset = frozenset()        # self attrs holding locks
    # self attrs assigned VERBATIM from a method parameter — the
    # caller-supplied-callback shape (spill_fn, event sinks) PTL803
    # fences when invoked under a lock
    callback_attrs: frozenset = frozenset()


@dataclasses.dataclass
class Finding:
    rule: str
    name: str
    path: str
    line: int
    col: int
    message: str
    func: str = ""

    def as_dict(self):
        return dataclasses.asdict(self)

    def format(self):
        loc = f"{self.path}:{self.line}:{self.col}"
        where = f" [in {self.func}]" if self.func else ""
        return f"{loc} {self.rule} {self.name}: {self.message}{where}"


# ------------------------------------------------------------- utilities

def _dotted(node):
    """'a.b.c' for Attribute/Name chains, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _component(node):
    """Last attribute component of a callable expression."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _root(node):
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        node = node.value
    if isinstance(node, ast.Name):
        return node.id
    return None


def _target_key(node):
    """Trackable key for a call target / assign target: bare name or a
    self/cls attribute chain."""
    if isinstance(node, ast.Name):
        return node.id
    d = _dotted(node)
    if d and (d.startswith("self.") or d.startswith("cls.")):
        return d
    return None


# helpers whose RESULT is int8-family code data (the packed-nibble
# path: unpack_int4 yields sign-extended int8 codes, pack_int4 packed
# bytes — both overflow an int8-accumulating dot exactly like a plain
# astype(int8)). dequantize_kv_int4 returns FLOAT and is deliberately
# absent.
_INT4_CODE_FUNCS = ("unpack_int4", "pack_int4", "quantize_kv_rows_int4")


_FLOAT_DTYPE_NAMES = ("float32", "float16", "bfloat16", "float64",
                      "float_", "double")


def _is_float_cast(node):
    """`<expr>.astype(<float dtype>)` — the dequant idiom. A float
    cast LAUNDERS the int8 carrier property: `codes.astype(f32) *
    scale` is exactly how every dequant-on-gather path leaves the
    int8 domain, and flagging the float einsum downstream of it was
    the first dogfood FP of the int4 rule extension."""
    if not (isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "astype" and node.args):
        return False
    arg = node.args[0]
    for n in ast.walk(arg):
        if isinstance(n, ast.Constant) and n.value in _FLOAT_DTYPE_NAMES:
            return True
        if isinstance(n, ast.Attribute) and n.attr in _FLOAT_DTYPE_NAMES:
            return True
        if isinstance(n, ast.Name) and n.id in _FLOAT_DTYPE_NAMES:
            return True
    return False


def _mentions_int8(node, int8_names):
    """Does this expression visibly carry int8-family data?
    (astype(jnp.int8), np.int8 casts, the packed-nibble int4 helpers —
    unpack_int4 codes are int8 on the wire into the dot — and names
    locally assigned from such expressions). Float casts prune their
    subtree (`_is_float_cast`): a dequantized value is not a carrier."""

    def carrier(n):
        if _is_float_cast(n):
            return False
        if isinstance(n, ast.Constant) and n.value in ("int8", "int4"):
            return True
        if isinstance(n, ast.Attribute) and n.attr in (
                "int8", "uint8", "int4", "uint4"):
            return True
        if isinstance(n, ast.Call):
            comp = _component(n.func)
            if comp in _INT4_CODE_FUNCS:
                return True
        if isinstance(n, ast.Name) and n.id in int8_names:
            return True
        return any(carrier(c) for c in ast.iter_child_nodes(n))

    return carrier(node)


def _walk_shallow(stmts):
    """ast.walk that does NOT descend into nested function/class
    scopes — sub-linters prescan their own bodies (keeps the module
    pass linear; nested re-walks made it quadratic)."""
    stack = list(stmts)
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if not isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef,
                                      ast.ClassDef, ast.Lambda)):
                stack.append(child)


def _is_rankish(test):
    for n in ast.walk(test):
        if isinstance(n, ast.Name) and n.id in _RANK_NAMES:
            return True
        if isinstance(n, ast.Attribute) and n.attr in _RANK_NAMES:
            return True
        if isinstance(n, ast.Call):
            c = _component(n.func)
            if c in _RANK_CALLS:
                return True
    return False


def _blocking_call(node):
    """Short description when `node` is a Call that blocks the
    calling thread, else None (the PTL802 table lookup)."""
    comp = _component(node.func)
    if isinstance(node.func, ast.Name):
        if comp == "open":
            return "open()"
        if comp == "sleep":
            return "sleep()"
    root = _root(node.func)
    if comp == "sleep" and root in ("time", "_time"):
        return "time.sleep()"
    if isinstance(node.func, ast.Attribute):
        if comp in _BLOCKING_METHODS:
            return f".{comp}()"
        if comp == "join":
            # strict: thread/process/queue join only — zero args, a
            # single numeric timeout, or a timeout= kwarg. str.join
            # (`",".join(parts)`) and os.path.join always take a
            # non-numeric argument and never match.
            if not node.args and not node.keywords:
                return ".join()"
            if len(node.args) == 1 and not node.keywords and \
                    isinstance(node.args[0], ast.Constant) and \
                    isinstance(node.args[0].value, (int, float)) and \
                    not isinstance(node.args[0].value, bool):
                return ".join(timeout)"
            if any(kw.arg == "timeout" for kw in node.keywords):
                return ".join(timeout=...)"
    return None


def _broad_handler_type(t):
    """None (bare except) or Exception/BaseException, incl. tuples."""
    if t is None:
        return True
    if isinstance(t, (ast.Tuple, ast.List)):
        return any(_broad_handler_type(e) for e in t.elts)
    return _component(t) in ("Exception", "BaseException")


def _silent_handler(h):
    """PTL804 shape: a broad handler whose body swallows the failure
    without a trace — only pass/continue/break/docstring statements.
    ANY call in the handler (journal, counter, log, re-raise helper)
    makes it legal."""
    if not _broad_handler_type(h.type):
        return False
    for stmt in h.body:
        if isinstance(stmt, (ast.Pass, ast.Continue, ast.Break)):
            continue
        if isinstance(stmt, ast.Expr) and \
                isinstance(stmt.value, ast.Constant):
            continue
        return False
    return True


# ------------------------------------------------- module-level discovery

class _TracedDiscovery(ast.NodeVisitor):
    """Collect names of functions that enter a jax trace anywhere in
    the module, and whether they run under AutoGraph."""

    def __init__(self):
        self.raw = set()        # raw-traced function names
        self.autograph = set()  # AutoGraph-covered traced names

    def _add_callable_node(self, node, autograph):
        if isinstance(node, ast.Name):
            (self.autograph if autograph else self.raw).add(node.id)
        elif isinstance(node, (ast.List, ast.Tuple)):
            for elt in node.elts:
                self._add_callable_node(elt, autograph)
        # Lambda bodies are handled where the Call is visited (the
        # linter walks Lambda args of tracing calls directly)

    def visit_Call(self, node):
        comp = _component(node.func)
        if comp in _TRACING_CALL_ARGS:
            for pos in _TRACING_CALL_ARGS[comp]:
                if pos < len(node.args):
                    self._add_callable_node(node.args[pos], False)
        elif comp == "switch" and len(node.args) >= 2:
            self._add_callable_node(node.args[1], False)
        elif comp in _AUTOGRAPH_NAMES and node.args:
            self._add_callable_node(node.args[0], True)
        elif comp in _TRAINSTEP_NAMES:
            if len(node.args) >= 2:
                self._add_callable_node(node.args[1], False)
            for kw in node.keywords:
                if kw.arg == "loss_fn":
                    self._add_callable_node(kw.value, False)
        self.generic_visit(node)


def _decorated_context(fn_node):
    """(traced, autograph) from this def's decorator list."""
    for dec in fn_node.decorator_list:
        target = dec.func if isinstance(dec, ast.Call) else dec
        comp = _component(target)
        if comp in _TRACING_DECORATORS:
            return True, False
        if comp in _AUTOGRAPH_NAMES:
            return True, True
    return False, False


# -------------------------------------------------------------- the pass

class _FunctionLinter:
    """One function scope: taint tracking + all rule checks.

    `traced` turns on the trace-context rules (PTL1xx/2xx impurity);
    the host-level rules (PTL201/202/301/401) run in every scope —
    donation misuse and rank-divergent collectives live in host code.
    """

    def __init__(self, module, fn_node, traced, autograph, func_name,
                 cls_info=None):
        self.m = module                     # _ModuleLint
        self.fn = fn_node
        self.traced = traced
        self.autograph = autograph
        self.func_name = func_name
        self.cls_info = cls_info            # _ClassInfo of enclosing class
        self._lock_depth = 0                # inside `with self.<lock>:`
        self.tainted = set()
        self.array = set()
        self.int8_names = set()
        self.concat_names = set()   # names derived from jnp.concatenate
        # PTL501/502 state: names whose value ALIASES caller-owned
        # storage (asarray/frombuffer/memoryview of a parameter, and
        # views thereof) — flow-sensitive like concat_names
        self.alias_names = set()
        # this function's own parameter names (PTL803: a parameter
        # called directly under a lock is caller-supplied code)
        self.param_names = set()
        # PTL601 state: key -> in_specs AST node of a shard_map wrapper
        self.shard_wraps = {}
        # PTL201 state: key -> donated positions (from jax.jit assigns
        # seen in this scope, merged over the module's self-attr map)
        self.jitted = dict(module.jitted_attrs)
        self.consumed = {}         # key -> (line, end_line) of donation
        # store-tracking stacks for loop bodies (PTL201 loop-carried
        # donation: donated inside the body + never reassigned there =
        # iteration 2 reuses a freed buffer)
        self._loop_stores = []
        # PTL202 state: (callee key, position) -> {"literal","other"}
        self.arg_kinds = {}
        self.rank_if_depth = 0

    # ---- taint queries ------------------------------------------------

    def _is_tainted(self, node):
        return self._level(node) >= 1

    def _is_array(self, node):
        return self._level(node) >= 2

    def _level(self, node):
        """0 = clean, 1 = tainted (may derive from tracers), 2 = array
        (definitely a jax array value)."""
        if isinstance(node, ast.Name):
            if node.id in self.array:
                return 2
            return 1 if node.id in self.tainted else 0
        if isinstance(node, ast.Attribute):
            if node.attr in _STATIC_ATTRS:
                return 0
            base = self._level(node.value)
            return base
        if isinstance(node, ast.Subscript):
            return self._level(node.value)
        if isinstance(node, ast.Call):
            return self._call_level(node)
        if isinstance(node, (ast.BinOp,)):
            return max(self._level(node.left), self._level(node.right))
        if isinstance(node, ast.UnaryOp):
            return self._level(node.operand)
        if isinstance(node, ast.Compare):
            lv = max([self._level(node.left)]
                     + [self._level(c) for c in node.comparators])
            return lv
        if isinstance(node, ast.BoolOp):
            return max(self._level(v) for v in node.values)
        if isinstance(node, ast.IfExp):
            return max(self._level(node.body), self._level(node.orelse))
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            if not node.elts:
                return 0
            # containers carry taint but are not themselves arrays
            return min(1, max(self._level(e) for e in node.elts))
        if isinstance(node, ast.Dict):
            vals = [v for v in node.values if v is not None]
            if not vals:
                return 0
            return min(1, max(self._level(v) for v in vals))
        if isinstance(node, ast.Starred):
            return self._level(node.value)
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.GeneratorExp)):
            lv = max([self._level(g.iter) for g in node.generators]
                     + [0])
            return min(1, max(lv, 1) if lv else 0)
        if isinstance(node, ast.Await):
            return self._level(node.value)
        if isinstance(node, ast.NamedExpr):
            return self._level(node.value)
        return 0

    def _call_level(self, node):
        comp = _component(node.func)
        if comp in _STATIC_FUNCS or comp in _STATIC_ARRAY_FUNCS:
            return 0
        root = _root(node.func)
        dotted = _dotted(node.func) or ""
        args_lv = max(
            [self._level(a) for a in node.args]
            + [self._level(kw.value) for kw in node.keywords]
            + [0])
        # jnp./lax./jax.random. calls produce arrays; carve out the
        # jax callables that DON'T — transform factories return
        # functions, tree utilities return containers
        if root == "jax":
            if comp in _TRACING_CALL_ARGS or comp == "switch":
                return 0                      # factory → a callable
            if dotted.startswith(("jax.tree_util.", "jax.tree.")):
                return min(1, args_lv)        # pytree container
        if root in _ARRAY_ROOTS or \
                dotted.startswith(_ARRAY_DOTTED_PREFIXES) or \
                (root == "jax" and "." in dotted):
            return 2
        # method on an array value keeps array-ness (x.sum(), x.astype)
        if isinstance(node.func, ast.Attribute) and \
                self._level(node.func.value) == 2:
            return 2
        # any other call: taints if anything flowing in is tainted
        func_lv = self._level(node.func) if \
            isinstance(node.func, ast.Attribute) else 0
        return min(1, max(args_lv, func_lv))

    # ---- findings -----------------------------------------------------

    def _emit(self, rule_id, node, message):
        self.m.emit(rule_id, node, message, self.func_name,
                    def_line=self.fn.lineno if self.fn is not None
                    else None)

    # ---- statement walk ----------------------------------------------

    def run(self):
        if self.fn is None:           # module scope
            body = self.m.tree.body
        else:
            body = self.fn.body
            self._seed_params()
        self._prescan_int8(body)
        self._prescan_jitted(body)
        self._prescan_shard_map(body)
        for stmt in body:
            self._visit(stmt)

    def _seed_params(self):
        a = self.fn.args
        names = [p.arg for p in (a.posonlyargs + a.args + a.kwonlyargs)]
        if a.vararg:
            names.append(a.vararg.arg)
        if a.kwarg:
            names.append(a.kwarg.arg)
        for i, n in enumerate(names):
            if i == 0 and n in ("self", "cls"):
                continue
            self.tainted.add(n)
            self.param_names.add(n)

    def _prescan_int8(self, body):
        for n in _walk_shallow(body):
            if isinstance(n, ast.Assign) and \
                    _mentions_int8(n.value, self.int8_names):
                for t in n.targets:
                    if isinstance(t, ast.Name):
                        self.int8_names.add(t.id)

    def _prescan_jitted(self, body):
        """Record `<key> = jax.jit(fn, donate_argnums=...)` assignments
        (key = name or self.attr) for PTL201/PTL202."""
        for n in _walk_shallow(body):
            if not (isinstance(n, ast.Assign)
                    and isinstance(n.value, ast.Call)):
                continue
            comp = _component(n.value.func)
            if comp not in ("jit", "pjit"):
                continue
            donated = ()
            for kw in n.value.keywords:
                if kw.arg == "donate_argnums":
                    donated = self._literal_ints(kw.value)
            for t in n.targets:
                key = _target_key(t)
                if key:
                    self.jitted[key] = donated

    def _mentions_concat(self, node):
        """Does this expression carry a concatenate-family result?
        Flow-sensitive via _assign_target (a clean reassignment clears
        the taint), and `jnp.pad(...)` LAUNDERS: its result partitions
        correctly whatever fed it — pad is the documented fix idiom,
        so the rule must not chase taint through it."""
        if isinstance(node, ast.Call):
            comp = _component(node.func)
            root = _root(node.func)
            arrayish = root in ("jnp", "lax", "np", "numpy", "jax",
                                "jsp")
            if arrayish and comp in _CONCAT_FUNCS:
                return True
            if arrayish and comp == "pad":
                return False
        if isinstance(node, ast.Name):
            return node.id in self.concat_names
        return any(self._mentions_concat(c)
                   for c in ast.iter_child_nodes(node))

    def _prescan_shard_map(self, body):
        """Record `<key> = jax.shard_map(fn, ..., in_specs=...)`
        wrappers for the PTL601 partial-spec check at call sites."""
        for n in _walk_shallow(body):
            if not (isinstance(n, ast.Assign)
                    and isinstance(n.value, ast.Call)):
                continue
            if _component(n.value.func) not in _SHARD_MAP_NAMES:
                continue
            in_specs = None
            for kw in n.value.keywords:
                if kw.arg == "in_specs":
                    in_specs = kw.value
            for t in n.targets:
                key = _target_key(t)
                if key and in_specs is not None:
                    self.shard_wraps[key] = in_specs

    @staticmethod
    def _spec_at(in_specs, pos):
        """The in_specs entry feeding argument `pos` (a single spec
        broadcasts over every argument)."""
        if isinstance(in_specs, (ast.Tuple, ast.List)):
            return in_specs.elts[pos] if pos < len(in_specs.elts) \
                else None
        return in_specs

    @staticmethod
    def _is_partial_pspec(spec):
        """A P(...)/PartitionSpec(...) literal that leaves mesh axes
        unmentioned: an explicit None entry, or no axis names at all.
        Non-literal specs are unknown — never flagged."""
        if not isinstance(spec, ast.Call):
            return False
        if _component(spec.func) not in ("P", "PartitionSpec"):
            return False
        if not spec.args:
            return True
        return any(isinstance(a, ast.Constant) and a.value is None
                   for a in spec.args)

    @staticmethod
    def _literal_ints(node):
        if isinstance(node, ast.Constant) and isinstance(node.value, int):
            return (node.value,)
        if isinstance(node, (ast.Tuple, ast.List)):
            out = []
            for e in node.elts:
                if isinstance(e, ast.Constant) and \
                        isinstance(e.value, int):
                    out.append(e.value)
            return tuple(out)
        return ()

    # -- statements --

    def _visit(self, node):
        meth = getattr(self, "_visit_" + type(node).__name__, None)
        if meth is not None:
            meth(node)
        else:
            self._generic(node)

    def _generic(self, node):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.expr):
                self._expr(child)
            else:
                self._visit(child)

    def _visit_FunctionDef(self, node):
        # nested def: traced context (and taint env) flows in; a nested
        # def inside a host fn is traced only if discovery marked it
        name = node.name
        traced = self.traced or name in self.m.raw_traced \
            or name in self.m.autograph_traced
        autograph = (self.autograph if self.traced
                     else name in self.m.autograph_traced)
        dec_traced, dec_autograph = _decorated_context(node)
        traced = traced or dec_traced
        autograph = autograph or dec_autograph
        sub = _FunctionLinter(self.m, node, traced, autograph,
                              f"{self.func_name}.{name}" if
                              self.func_name else name,
                              cls_info=self.cls_info)
        sub.tainted |= self.tainted
        sub.array |= self.array
        sub.int8_names |= self.int8_names
        sub.concat_names |= self.concat_names
        sub.alias_names |= self.alias_names
        sub.param_names |= self.param_names   # closures capture them
        sub.jitted.update(self.jitted)
        sub.shard_wraps.update(self.shard_wraps)
        sub._lock_depth = self._lock_depth
        sub.run()

    _visit_AsyncFunctionDef = _visit_FunctionDef

    def _visit_ClassDef(self, node):
        for stmt in node.body:
            self._visit(stmt)

    def _visit_Assign(self, node):
        self._expr(node.value)
        lv = self._level(node.value)
        for t in node.targets:
            self._assign_target(t, lv, node.value)

    def _visit_AnnAssign(self, node):
        if node.value is not None:
            self._expr(node.value)
            self._assign_target(node.target, self._level(node.value),
                                node.value)

    def _visit_AugAssign(self, node):
        self._expr(node.value)
        lv = max(self._level(node.value), self._level(node.target))
        self._check_unlocked_rmw(node)
        self._assign_target(node.target, lv, node.value)

    def _check_unlocked_rmw(self, node):
        """PTL702: `self.X += ...` / `self.X[k] += ...` outside the
        lock, in a class that DECLARES one — the declared lock names
        the multi-writer contract; an unlocked read-modify-write
        loses updates."""
        info = self.cls_info
        if info is None or not info.lock_attrs or self._lock_depth or \
                (self.fn is not None and self.fn.name == "__init__"):
            return
        t = node.target
        if isinstance(t, ast.Subscript):
            t = t.value
        key = _target_key(t)
        if not key or not key.startswith("self."):
            return
        if key[len("self."):] in info.lock_attrs:
            return
        self._emit(
            "PTL702", node,
            f"read-modify-write of '{key}' outside the lock "
            f"{info.name} declares — a concurrent writer loses this "
            "update; hold the lock or route through the telemetry "
            "registry's per-thread counters")

    def _assign_target(self, t, lv, value):
        if isinstance(t, ast.Name):
            self.tainted.discard(t.id)
            self.array.discard(t.id)
            if lv >= 1:
                self.tainted.add(t.id)
            if lv >= 2:
                self.array.add(t.id)
            self._record_store(t.id)
            if _mentions_int8(value, self.int8_names):
                self.int8_names.add(t.id)
            else:
                # flow-sensitive like PTL601's concat taint: a clean
                # reassignment launders — the dequant idiom
                # `ks = ks.astype(f32) * scale` leaves the int8 domain
                # and the float math downstream must not keep flagging
                # (the prescan still covers use-before-assign order)
                self.int8_names.discard(t.id)
            # flow-sensitive (unlike the int8 prescan): a clean
            # reassignment launders — `x = jnp.zeros(...)` after a
            # concatenate must not keep flagging x
            if self._mentions_concat(value):
                self.concat_names.add(t.id)
            else:
                self.concat_names.discard(t.id)
            # same shape for the aliasing taint: np.array(...) /
            # .copy() reassignments launder
            if value is not None and self._is_alias(value):
                self.alias_names.add(t.id)
            else:
                self.alias_names.discard(t.id)
        elif isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                inner = e.value if isinstance(e, ast.Starred) else e
                # element-of-container: array-ness survives unpacking
                self._assign_target(inner, lv and max(lv, 1), value)
        elif isinstance(t, (ast.Attribute, ast.Subscript)):
            key = _target_key(t)
            if key:
                self._record_store(key)
            self._check_alias_escape(t, value)

    def _record_store(self, key):
        self.consumed.pop(key, None)
        for stores in self._loop_stores:
            stores.add(key)

    # ---- PTL5xx: aliasing / donation escape ---------------------------

    def _is_alias(self, node):
        """Does this expression's value ALIAS caller-owned storage?
        Sources are the zero-copy constructors applied to a
        parameter-derived value; aliasing survives view methods and
        container literals but NOT arbitrary calls — np.array /
        jnp.array(copy=True) / .copy() launder by construction."""
        if isinstance(node, ast.Name):
            return node.id in self.alias_names
        if isinstance(node, ast.Call):
            comp = _component(node.func)
            root = _root(node.func)
            if comp in _ALIAS_VIEW_FUNCS and \
                    root in ("np", "numpy", "jnp", "jax"):
                return bool(node.args) and \
                    (self._is_tainted(node.args[0])
                     or self._is_alias(node.args[0]))
            if comp == "memoryview" and isinstance(node.func, ast.Name):
                return bool(node.args) and \
                    (self._is_tainted(node.args[0])
                     or self._is_alias(node.args[0]))
            if isinstance(node.func, ast.Attribute) and \
                    comp in _VIEW_METHODS:
                return self._is_alias(node.func.value)
            return False
        if isinstance(node, (ast.List, ast.Tuple, ast.Set)):
            return any(self._is_alias(e) for e in node.elts)
        if isinstance(node, ast.Dict):
            return any(v is not None and self._is_alias(v)
                       for v in node.values)
        if isinstance(node, ast.IfExp):
            return self._is_alias(node.body) or \
                self._is_alias(node.orelse)
        if isinstance(node, ast.BoolOp):
            return any(self._is_alias(v) for v in node.values)
        if isinstance(node, ast.Starred):
            return self._is_alias(node.value)
        return False

    def _check_alias_escape(self, t, value):
        """PTL501 (attribute form): an alias of caller storage stored
        into a self/cls attribute — or a subscript of one — outlives
        the call. `__init__` is NOT exempt: constructors are exactly
        where set_state_dict-style restore paths capture views."""
        node = t.value if isinstance(t, ast.Subscript) else t
        key = _target_key(node)
        if not key or not key.startswith(("self.", "cls.")):
            return
        if value is None or not self._is_alias(value):
            return
        self._emit(
            "PTL501", t,
            f"storing a zero-copy view of caller-owned storage into "
            f"'{key}' — a later in-place update (or a donating "
            "executable consuming the origin) corrupts the caller's "
            "copy; take ownership with np.array / "
            "jnp.array(copy=True) / .copy()")

    # ---- PTL8xx: lock-discipline gating -------------------------------

    def _lock_fence_active(self):
        """PTL802/803 fire while a declared class lock is held,
        outside __init__ (a constructor's lock cannot be contended
        yet)."""
        return (self._lock_depth > 0 and self.cls_info is not None
                and not (self.fn is not None
                         and self.fn.name == "__init__"))

    def _visit_If(self, node):
        self._expr(node.test)
        if self.traced and not self.autograph and \
                self._is_array(node.test):
            self._emit("PTL103", node.test,
                       "branching on a jax array value inside a "
                       "traced function — use lax.cond/jnp.where")
        rankish = _is_rankish(node.test)
        if rankish:
            self.rank_if_depth += 1
        # branch-aware donation state: a buffer donated on ONE path is
        # only consumed afterwards if EVERY path donated it (the else
        # branch of `if fast: out = g(buf)` may legally read buf)
        saved = dict(self.consumed)
        for stmt in node.body:
            self._visit(stmt)
        after_body = self.consumed
        self.consumed = dict(saved)
        for stmt in node.orelse:
            self._visit(stmt)
        after_else = self.consumed
        self.consumed = {k: v for k, v in after_body.items()
                         if k in after_else}
        if rankish:
            self.rank_if_depth -= 1

    def _visit_With(self, node):
        for item in node.items:
            self._expr(item.context_expr)
            if item.optional_vars is not None:
                self._assign_target(item.optional_vars,
                                    self._level(item.context_expr),
                                    item.context_expr)
        locked = any(self._is_lock_expr(item.context_expr)
                     for item in node.items)
        if locked:
            self._lock_depth += 1
        for stmt in node.body:
            self._visit(stmt)
        if locked:
            self._lock_depth -= 1

    _visit_AsyncWith = _visit_With

    def _is_lock_expr(self, expr):
        if self.cls_info is None:
            return False
        key = _target_key(expr)
        return bool(key) and key.startswith("self.") and \
            key[len("self."):] in self.cls_info.lock_attrs

    def _visit_Try(self, node):
        for stmt in node.body:
            self._visit(stmt)
        for h in node.handlers:
            if _silent_handler(h):
                # PTL804: everywhere, not just thread-shared classes —
                # a swallowed failure is invisible in ANY plane
                what = "bare except" if h.type is None else \
                    f"except {_dotted(h.type) or '(broad tuple)'}"
                self._emit(
                    "PTL804", h,
                    f"{what} swallows the failure with no trace — "
                    "narrow the exception type, or leave a record "
                    "(resilience.record(...), a pt_* counter, or a "
                    "log call) in the handler")
            if h.type is not None:
                self._expr(h.type)
            for stmt in h.body:
                self._visit(stmt)
        for stmt in node.orelse:
            self._visit(stmt)
        for stmt in node.finalbody:
            self._visit(stmt)

    _visit_TryStar = _visit_Try

    # ---- PTL7xx: host-concurrency race fence -------------------------

    def _race_fence_active(self):
        """The PTL7xx rules run in thread-shared classes (marker or
        declared lock), outside __init__ (no concurrency during
        construction) and outside the class lock."""
        return (self.cls_info is not None and self.cls_info.shared
                and not self._lock_depth
                and not (self.fn is not None
                         and self.fn.name == "__init__"))

    def _shared_dict_view(self, expr):
        """Attr name when `expr` is an UNSNAPSHOTTED view of a shared
        dict attribute: `self.X` / `self.X.items()/values()/keys()`,
        possibly under a lazy wrapper (enumerate/zip/...). Snapshot
        wrappers (list/sorted/dict/...) produce a Call that simply
        doesn't match — safe by construction."""
        while isinstance(expr, ast.Call) and \
                isinstance(expr.func, ast.Name) and \
                expr.func.id in _LAZY_ITER_WRAPPERS and expr.args:
            expr = expr.args[0]
        if isinstance(expr, ast.Call) and \
                isinstance(expr.func, ast.Attribute) and \
                expr.func.attr in ("items", "values", "keys"):
            expr = expr.func.value
        key = _target_key(expr)
        if key and key.startswith("self.") and \
                key[len("self."):] in self.cls_info.dict_attrs:
            return key
        return None

    def _check_shared_iter(self, iter_expr, report_node):
        if not self._race_fence_active():
            return
        key = self._shared_dict_view(iter_expr)
        if key is None:
            return
        self._emit(
            "PTL701", report_node,
            f"iterating '{key}' (a shared dict of thread-shared class "
            f"{self.cls_info.name}) without a list()/sorted() snapshot "
            "or the class lock — a concurrent insert/delete raises "
            "RuntimeError mid-iteration (the /metrics scrape race)")

    def _visit_While(self, node):
        self._expr(node.test)
        if self.traced and not self.autograph and \
                self._is_array(node.test):
            self._emit("PTL103", node.test,
                       "while-loop condition on a jax array value "
                       "inside a traced function — use lax.while_loop")
        self._loop_body(node.body)
        for stmt in node.orelse:   # runs once, after the loop
            self._visit(stmt)

    def _loop_body(self, stmts, _frame_pushed=False):
        """Visit a loop body with loop-carried donation detection: a
        buffer donated inside the body and never reassigned there is
        reused FREED on iteration 2 (the PR-2 class, loop form)."""
        if not _frame_pushed:
            self._loop_stores.append(set())
        pre = set(self.consumed)
        for stmt in stmts:
            self._visit(stmt)
        stores = self._loop_stores.pop()
        for key, (line, _end) in list(self.consumed.items()):
            if key not in pre and key not in stores:
                self._emit(
                    "PTL201",
                    types.SimpleNamespace(lineno=line, col_offset=0),
                    f"'{key}' is donated to a jitted call inside this "
                    "loop and never reassigned in the body — the next "
                    "iteration passes a freed buffer")
                del self.consumed[key]

    def _visit_For(self, node):
        self._expr(node.iter)
        self._check_shared_iter(node.iter, node.iter)
        if self.traced and not self.autograph and \
                self._is_array(node.iter):
            self._emit("PTL104", node.iter,
                       "iterating a jax array value inside a traced "
                       "function unrolls the trace — use "
                       "lax.scan/fori_loop")
        # the loop target is REASSIGNED by the loop itself each
        # iteration — record its store inside the loop-store frame so
        # `for w in ws: step(w, c)` (fresh buffer per pass) stays
        # silent; the orelse runs ONCE after the loop, outside the
        # per-iteration donation check
        self._loop_stores.append(set())
        self._assign_target(node.target,
                            min(1, self._level(node.iter)), node.iter)
        self._loop_body(node.body, _frame_pushed=True)
        for stmt in node.orelse:
            self._visit(stmt)

    def _visit_Assert(self, node):
        self._expr(node.test)
        if self.traced and not self.autograph and \
                self._is_array(node.test):
            self._emit("PTL103", node.test,
                       "assert on a jax array value inside a traced "
                       "function — use checkify or a host-side check")

    def _visit_Return(self, node):
        if node.value is not None:
            self._expr(node.value)

    def _visit_Expr(self, node):
        self._expr(node.value)

    # -- expressions --

    def _expr(self, node):
        for n in ast.walk(node):
            if isinstance(n, ast.Call):
                self._check_call(n)
            elif isinstance(n, ast.IfExp):
                if self.traced and not self.autograph and \
                        self._is_array(n.test):
                    self._emit("PTL103", n.test,
                               "conditional expression on a jax array "
                               "value inside a traced function — use "
                               "jnp.where")
            elif isinstance(n, ast.Name) and \
                    isinstance(n.ctx, ast.Load):
                self._check_reuse(n.id, n)
            elif isinstance(n, ast.Attribute) and \
                    isinstance(n.ctx, ast.Load):
                key = _target_key(n)
                if key:
                    self._check_reuse(key, n)
            elif isinstance(n, (ast.ListComp, ast.SetComp, ast.DictComp,
                                ast.GeneratorExp)):
                for gen in n.generators:
                    self._check_shared_iter(gen.iter, gen.iter)
            elif isinstance(n, ast.YieldFrom):
                self._check_shared_iter(n.value, n.value)
            elif isinstance(n, ast.Subscript) and \
                    isinstance(n.ctx, ast.Load):
                self._check_defaultdict_read(n)
            elif isinstance(n, ast.Lambda):
                self._lambda(n)

    def _check_defaultdict_read(self, node):
        """PTL703: a Load-context subscript of a defaultdict attr in a
        thread-shared class INSERTS on miss — mutation on the read
        path, racing every concurrent snapshot (the PR-7 phantom-meter
        bug). Writes (Store/AugAssign targets) are the owner's
        intentional materialization and stay legal."""
        if not self._race_fence_active():
            return
        key = _target_key(node.value)
        if not key or not key.startswith("self.") or \
                key[len("self."):] not in \
                self.cls_info.defaultdict_attrs:
            return
        self._emit(
            "PTL703", node,
            f"reading '{key}[...]' materializes a default entry in a "
            f"thread-shared defaultdict of {self.cls_info.name} — a "
            "mutation on the read path; use .get() with an explicit "
            "default")

    def _check_reuse(self, key, node):
        entry = self.consumed.get(key)
        if entry is None:
            return
        call_line, call_end = entry
        if node.lineno <= call_end:   # a read inside the call itself
            return
        self._emit(
            "PTL201", node,
            f"'{key}' was donated to a jitted call on line "
            f"{call_line} and read again — donated buffers are freed "
            "by XLA")
        del self.consumed[key]        # one finding per misuse

    def _lambda(self, node):
        # a lambda in a TRACED scope runs at trace time (sort keys,
        # comprehension filters, ...) — lint it with the OUTER taint
        # env, but do NOT force-taint its own params: what flows into
        # them depends on the call site (`sorted(dims, key=lambda d:
        # int(d))` over laundered shape data is legal). Lambdas whose
        # params ARE tracers — those handed straight to a tracing
        # transform — get param taint via _check_call below.
        if not self.traced:
            return
        self._lint_lambda(node, taint_params=False)

    def _lint_lambda(self, node, taint_params=True):
        sub = _FunctionLinter(self.m, None, True, self.autograph,
                              f"{self.func_name}.<lambda>",
                              cls_info=self.cls_info)
        sub._lock_depth = self._lock_depth
        sub.tainted = set(self.tainted)
        sub.array = set(self.array)
        if taint_params:
            a = node.args
            for p in a.posonlyargs + a.args + a.kwonlyargs:
                sub.tainted.add(p.arg)
        sub.int8_names = set(self.int8_names)
        sub.concat_names = set(self.concat_names)
        sub.alias_names = set(self.alias_names)
        sub.param_names = set(self.param_names)
        sub.jitted = dict(self.jitted)
        sub.shard_wraps = dict(self.shard_wraps)
        # ast.walk in _expr yields the body node itself first, so a
        # bare-Call body is checked along with everything nested in it
        sub._expr(node.body)

    def _consume(self, arg, lineno, end_lineno):
        """Mark a donated argument (name, self-attr, or a container of
        them) as consumed for PTL201. `end_lineno` bounds the donating
        call itself — its own argument reads are not reuse."""
        if isinstance(arg, (ast.Tuple, ast.List)):
            for e in arg.elts:
                self._consume(e, lineno, end_lineno)
            return
        akey = _target_key(arg)
        if akey:
            self.consumed[akey] = (lineno, end_lineno)

    def _check_call(self, node):
        comp = _component(node.func)
        dotted = _dotted(node.func) or ""
        root = _root(node.func)

        # a lambda handed straight to a tracing transform enters the
        # trace no matter what scope the call sits in
        if comp in _TRACING_CALL_ARGS:
            for pos in _TRACING_CALL_ARGS[comp]:
                if pos < len(node.args) and \
                        isinstance(node.args[pos], ast.Lambda):
                    self._lint_lambda(node.args[pos])

        # ---- trace-context rules ----
        if self.traced:
            if comp in _SYNC_BUILTINS and isinstance(node.func, ast.Name) \
                    and len(node.args) == 1 and \
                    self._is_tainted(node.args[0]):
                self._emit("PTL101", node,
                           f"{comp}() of a traced value forces a host "
                           "sync / fails under trace — keep it in the "
                           "program or read it outside the step")
            if comp in _SYNC_METHODS and \
                    isinstance(node.func, ast.Attribute) and \
                    self._is_tainted(node.func.value):
                self._emit("PTL101", node,
                           f".{comp}() on a traced value forces a "
                           "host sync / fails under trace")
            if root in ("np", "numpy") and \
                    not dotted.startswith(("np.random.",
                                           "numpy.random.")) and \
                    any(self._is_tainted(a) for a in node.args):
                self._emit("PTL102", node,
                           f"{dotted}() pulls a traced value out of "
                           "the XLA program — use jnp instead")
            if comp == "print" and isinstance(node.func, ast.Name) and \
                    any(self._is_tainted(a) for a in node.args):
                self._emit("PTL105", node,
                           "print() of a traced value fires once at "
                           "trace time — use jax.debug.print")
            if (root in ("time", "_time") and comp in _TIME_FUNCS):
                self._emit("PTL203", node,
                           f"{dotted}() inside a traced function "
                           "freezes to a trace-time constant — "
                           "measure outside the compiled step")
            if root == "random" or \
                    dotted.startswith(("np.random.", "numpy.random.")):
                self._emit("PTL204", node,
                           f"{dotted}() draws host randomness at "
                           "trace time — thread a jax.random key "
                           "through the program instead")

        # ---- host-level rules ----
        if comp in _DOT_FUNCS and root in ("jnp", "lax", "jax"):
            # preferred_element_type may ride positionally on the lax
            # API: dot_general(lhs, rhs, dnums, precision, PREF) /
            # dot(lhs, rhs, precision, PREF)
            has_pref = any(kw.arg == "preferred_element_type"
                           for kw in node.keywords) or \
                (comp == "dot_general" and len(node.args) >= 5) or \
                (comp == "dot" and len(node.args) >= 4)
            if not has_pref and any(
                    _mentions_int8(a, self.int8_names)
                    for a in node.args):
                self._emit("PTL301", node,
                           f"{dotted}() on int8 operands without "
                           "preferred_element_type accumulates in "
                           "int8 and overflows — pass "
                           "preferred_element_type=jnp.int32")

        if comp in _COLLECTIVE_FUNCS and self.rank_if_depth > 0:
            self._emit("PTL401", node,
                       f"collective {comp}() under a rank-conditioned "
                       "branch — peers that skip (or reorder) it "
                       "deadlock the pod")
        elif self.rank_if_depth > 0 and \
                comp in self.m.collective_reach and \
                (isinstance(node.func, ast.Name) or
                 (isinstance(node.func, ast.Attribute) and
                  isinstance(node.func.value, ast.Name) and
                  node.func.value.id in ("self", "cls"))):
            # interprocedural: a helper that (transitively) reaches a
            # collective is as divergent as the collective itself.
            # Matching is by bare def name, so only plain-name and
            # direct self/cls method calls qualify — an unrelated
            # object's same-named method (`self.log_file.flush()`)
            # must not inherit another class's reachability
            via = self.m.collective_reach[comp]
            self._emit("PTL401", node,
                       f"{comp}() reaches collective {via}() (through "
                       "its call chain) under a rank-conditioned "
                       "branch — peers that skip it deadlock the pod")

        # ---- PTL802/803: work under a held class lock ----
        if self._lock_fence_active():
            desc = _blocking_call(node)
            if desc is not None:
                self._emit(
                    "PTL802", node,
                    f"blocking call {desc} while a "
                    f"{self.cls_info.name} lock is held — every other "
                    "thread queues behind this wait; snapshot state, "
                    "release the lock, then block (or hand off "
                    "through a bounded queue as kv_tier does)")
            elif comp in self.m.blocking_reach and \
                    (isinstance(node.func, ast.Name) or
                     (isinstance(node.func, ast.Attribute) and
                      isinstance(node.func.value, ast.Name) and
                      node.func.value.id in ("self", "cls"))):
                # interprocedural, same bare-name matching caveats as
                # PTL401's collective closure
                via = self.m.blocking_reach[comp]
                self._emit(
                    "PTL802", node,
                    f"{comp}() reaches blocking call {via} (through "
                    f"its call chain) while a {self.cls_info.name} "
                    "lock is held — snapshot, release, then block")
            cb = None
            if isinstance(node.func, ast.Attribute) and \
                    isinstance(node.func.value, ast.Name) and \
                    node.func.value.id in ("self", "cls") and \
                    node.func.attr in self.cls_info.callback_attrs:
                cb = f"self.{node.func.attr}"
            elif isinstance(node.func, ast.Name) and \
                    node.func.id in self.param_names:
                cb = node.func.id
            if cb is not None:
                self._emit(
                    "PTL803", node,
                    f"invoking caller-supplied callback '{cb}' while "
                    f"a {self.cls_info.name} lock is held — arbitrary "
                    "caller code can re-enter the class and "
                    "self-deadlock; snapshot the callback and its "
                    "arguments, release, THEN invoke")

        # PTL501 (container form): an alias escaping into a shared
        # container through a mutator — self.pages.append(view)
        if isinstance(node.func, ast.Attribute) and \
                node.func.attr in _CONTAINER_STORES:
            rnode = node.func.value
            if isinstance(rnode, ast.Subscript):
                rnode = rnode.value
            rkey = _target_key(rnode)
            if rkey and rkey.startswith(("self.", "cls.")) and \
                    any(self._is_alias(a) for a in node.args):
                self._emit(
                    "PTL501", node,
                    f"'{rkey}.{node.func.attr}(...)' stores a "
                    "zero-copy view of caller-owned storage in a "
                    "container that outlives the call — take "
                    "ownership with np.array / jnp.array(copy=True) "
                    "/ .copy()")

        # PTL601: a concatenate-family result entering shard_map
        # through a partial in_spec (the PR-6 partitioner bug shape)
        in_specs = None
        if isinstance(node.func, ast.Call) and \
                _component(node.func.func) in _SHARD_MAP_NAMES:
            for kw in node.func.keywords:
                if kw.arg == "in_specs":
                    in_specs = kw.value
        else:
            fkey = _target_key(node.func)
            if fkey and fkey in self.shard_wraps:
                in_specs = self.shard_wraps[fkey]
        if in_specs is not None:
            for pos, a in enumerate(node.args):
                if not self._mentions_concat(a):
                    continue
                spec = self._spec_at(in_specs, pos)
                if spec is not None and self._is_partial_pspec(spec):
                    self._emit(
                        "PTL601", node,
                        "jnp.concatenate-derived value enters "
                        f"shard_map at position {pos} through a "
                        "partial in_spec — jax-0.4.37's partitioner "
                        "delivers it SUMMED over the unmentioned mesh "
                        "axes (the PR-6 hybrid-pp NaN); rewrite with "
                        "jnp.pad or mention every mesh axis in the "
                        "spec")
            # keyword-passed operands can't be mapped to a spec
            # position statically — flag when ANY spec is partial
            # (conservative: the PR-6 shape must not hide behind a
            # kwarg)
            specs = (in_specs.elts
                     if isinstance(in_specs, (ast.Tuple, ast.List))
                     else [in_specs])
            if any(self._is_partial_pspec(s) for s in specs):
                for kw in node.keywords:
                    if self._mentions_concat(kw.value):
                        self._emit(
                            "PTL601", node,
                            "jnp.concatenate-derived value enters "
                            f"shard_map via keyword '{kw.arg}' and at "
                            "least one in_spec is partial — the PR-6 "
                            "partitioner mis-shard shape; rewrite "
                            "with jnp.pad or mention every mesh axis")

        # PTL201/202: calls THROUGH a recorded jitted callable
        key = _target_key(node.func)
        if key and key in self.jitted:
            donated = self.jitted[key]
            # PTL502: a host view of caller storage entering the
            # compiled step — donation frees the caller's buffer and
            # async dispatch races caller-side mutation of the view
            for a in node.args:
                if self._is_alias(a):
                    self._emit(
                        "PTL502", node,
                        f"zero-copy host view handed to jitted "
                        f"'{key}' without a defensive copy — the "
                        "runtime may alias (or donation may free) "
                        "the caller's buffer; copy first with "
                        "np.array / jnp.array(copy=True)")
            starred = any(isinstance(a, ast.Starred) for a in node.args)
            if not starred:
                end = getattr(node, "end_lineno", node.lineno)
                for pos in donated:
                    if pos < len(node.args):
                        self._consume(node.args[pos], node.lineno, end)
                for pos, a in enumerate(node.args):
                    kind = ("literal" if isinstance(a, ast.Constant)
                            and isinstance(a.value, (int, float))
                            and not isinstance(a.value, bool)
                            else "other")
                    seen = self.arg_kinds.setdefault((key, pos), set())
                    if kind == "literal" and "other" in seen or \
                            kind == "other" and "literal" in seen:
                        self._emit(
                            "PTL202", node,
                            f"jitted '{key}' takes a python scalar "
                            f"literal and a non-literal at position "
                            f"{pos} across call sites — weak vs "
                            "committed types compile two executables; "
                            "pass jnp.asarray(..., dtype=...) "
                            "consistently")
                        seen.clear()
                    seen.add(kind)


class _ModuleLint:
    """One source file: discovery + per-function passes + suppression."""

    def __init__(self, path, source):
        self.path = path
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        disc = _TracedDiscovery()
        disc.visit(self.tree)
        self.raw_traced = disc.raw
        self.autograph_traced = disc.autograph
        self.findings = []
        self.suppressed = 0
        # class-scope `self._x = jax.jit(...)` assignments are visible
        # to every method of the module (the TrainStep idiom assigns in
        # _build and calls in __call__)
        self.jitted_attrs = {}
        for n in ast.walk(self.tree):
            if isinstance(n, ast.Assign) and \
                    isinstance(n.value, ast.Call) and \
                    _component(n.value.func) in ("jit", "pjit"):
                donated = ()
                for kw in n.value.keywords:
                    if kw.arg == "donate_argnums":
                        donated = _FunctionLinter._literal_ints(kw.value)
                for t in n.targets:
                    key = _target_key(t)
                    if key and key.startswith(("self.", "cls.")):
                        self.jitted_attrs[key] = donated
        self.collective_reach = self._collective_reach()
        self.blocking_reach = self._blocking_reach()

    def _collective_reach(self):
        """PTL401 interprocedural closure: function name -> the
        collective it (transitively) reaches through calls to other
        module functions. Direct calls only per body (nested defs lint
        their own scope); bare-name matching covers both module
        functions and methods."""
        direct, calls = {}, {}
        for n in ast.walk(self.tree):
            if not isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            called = set()
            for sub in _walk_shallow(n.body):
                if isinstance(sub, ast.Call):
                    comp = _component(sub.func)
                    if comp in _COLLECTIVE_FUNCS:
                        direct.setdefault(n.name, comp)
                    elif comp:
                        called.add(comp)
            # UNION when defs share a name (overloads/methods across
            # classes) — overwriting would make reach depend on
            # definition order
            calls.setdefault(n.name, set()).update(called)
        reach = dict(direct)
        changed = True
        while changed:
            changed = False
            for fn, called in calls.items():
                if fn in reach:
                    continue
                for c in called:
                    if c in reach:
                        reach[fn] = reach[c]
                        changed = True
                        break
        return reach

    def _blocking_reach(self):
        """PTL802 interprocedural closure: function name -> the
        blocking call it (transitively) reaches — the PTL401 shape
        applied to lock discipline. Same bare-name matching, same
        union-on-shared-names caveats."""
        direct, calls = {}, {}
        for n in ast.walk(self.tree):
            if not isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            called = set()
            for sub in _walk_shallow(n.body):
                if isinstance(sub, ast.Call):
                    desc = _blocking_call(sub)
                    if desc is not None:
                        direct.setdefault(n.name, desc)
                    comp = _component(sub.func)
                    if comp:
                        called.add(comp)
            calls.setdefault(n.name, set()).update(called)
        reach = dict(direct)
        changed = True
        while changed:
            changed = False
            for fn, called in calls.items():
                if fn in reach:
                    continue
                for c in called:
                    if c in reach:
                        reach[fn] = reach[c]
                        changed = True
                        break
        return reach

    def _suppressions(self, lineno):
        if lineno is None or lineno < 1 or lineno > len(self.lines):
            return set()
        m = re.search(r"#\s*ptlint:\s*disable=([\w,\- ]+)",
                      self.lines[lineno - 1])
        if not m:
            return set()
        out = set()
        for tok in m.group(1).split(","):
            tok = tok.strip()
            if not tok:
                continue
            out.add(_SLUG_TO_ID.get(tok, tok))
        return out

    def emit(self, rule_id, node, message, func_name, def_line=None):
        rule = RULES[rule_id]
        line = getattr(node, "lineno", 1)
        sup = self._suppressions(line) | self._suppressions(def_line)
        if rule_id in sup or "all" in sup:
            self.suppressed += 1
            return
        self.findings.append(Finding(
            rule=rule_id, name=rule.name, path=self.path, line=line,
            col=getattr(node, "col_offset", 0), message=message,
            func=func_name))

    def run(self):
        if re.search(r"#\s*ptlint:\s*skip-file", self.source):
            return self
        for node in self.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                self._run_def(node, prefix="")
        # module top-level statements (int8 dots / collectives at
        # import time)
        top = _FunctionLinter(self, None, False, False, "<module>")
        top._prescan_int8(self.tree.body)
        top._prescan_jitted(self.tree.body)
        for stmt in self.tree.body:
            if not isinstance(stmt, (ast.FunctionDef,
                                     ast.AsyncFunctionDef,
                                     ast.ClassDef)):
                top._visit(stmt)
        # PTL801: per-module lock-order pass (cross-FILE cycles are
        # caught by the tree-wide build_lock_graph / golden gate)
        for _path, line, func, msg in _lock_findings(
                _scan_lock_classes(self.tree, self.path)):
            self.emit("PTL801",
                      types.SimpleNamespace(lineno=line, col_offset=0),
                      msg, func)
        # lambdas are visited both in their enclosing expression walk
        # and as sub-scopes — dedup identical findings
        seen, unique = set(), []
        for f in self.findings:
            k = (f.rule, f.line, f.col, f.message)
            if k not in seen:
                seen.add(k)
                unique.append(f)
        self.findings = unique
        self.findings.sort(key=lambda f: (f.line, f.col, f.rule))
        return self

    def _scan_class(self, node):
        """Build the class's concurrency contract (_ClassInfo): the
        thread-shared marker on the class line, declared locks, and
        which self attributes hold dicts / defaultdicts."""
        line = (self.lines[node.lineno - 1]
                if 0 < node.lineno <= len(self.lines) else "")
        marked = bool(_THREAD_SHARED_RE.search(line))
        dict_attrs, dd_attrs, lock_attrs = set(), set(), set()
        for n in ast.walk(node):
            # AnnAssign too: `self.q: dict = {}` must not silently
            # switch the whole race fence off for an annotated class
            if isinstance(n, ast.AnnAssign):
                if n.value is None:
                    continue
                targets = [n.target]
            elif isinstance(n, ast.Assign):
                targets = n.targets
            else:
                continue
            for t in targets:
                key = _target_key(t)
                if not key or not key.startswith("self."):
                    continue
                attr = key[len("self."):]
                if "." in attr:
                    continue
                v = n.value
                if isinstance(v, (ast.Dict, ast.DictComp)):
                    dict_attrs.add(attr)
                elif isinstance(v, ast.Call):
                    comp = _component(v.func)
                    if comp in _DICT_FACTORIES:
                        dict_attrs.add(attr)
                        if comp == "defaultdict":
                            dd_attrs.add(attr)
                    elif comp in _LOCK_FACTORIES:
                        lock_attrs.add(attr)
        # PTL803 input: self attributes assigned VERBATIM from a
        # method parameter — the caller-supplied-callback shape
        callback_attrs = set()
        for meth in node.body:
            if not isinstance(meth, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            a = meth.args
            params = {p.arg for p in
                      (a.posonlyargs + a.args + a.kwonlyargs)}
            params.discard("self")
            params.discard("cls")
            for n in _walk_shallow(meth.body):
                if not isinstance(n, ast.Assign) or \
                        not (isinstance(n.value, ast.Name)
                             and n.value.id in params):
                    continue
                for t in n.targets:
                    key = _target_key(t)
                    if key and key.startswith("self.") and \
                            "." not in key[len("self."):]:
                        callback_attrs.add(key[len("self."):])
        return _ClassInfo(name=node.name,
                          shared=marked or bool(lock_attrs),
                          dict_attrs=frozenset(dict_attrs),
                          defaultdict_attrs=frozenset(dd_attrs),
                          lock_attrs=frozenset(lock_attrs),
                          callback_attrs=frozenset(callback_attrs))

    def _run_def(self, node, prefix, cls_info=None):
        if isinstance(node, ast.ClassDef):
            cprefix = f"{prefix}{node.name}."
            info = self._scan_class(node)
            for child in node.body:
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef,
                                      ast.ClassDef)):
                    self._run_def(child, cprefix, cls_info=info)
            return
        name = node.name
        traced = name in self.raw_traced or name in self.autograph_traced
        autograph = name in self.autograph_traced
        dec_traced, dec_autograph = _decorated_context(node)
        traced = traced or dec_traced
        autograph = autograph or dec_autograph
        _FunctionLinter(self, node, traced, autograph,
                        prefix + name, cls_info=cls_info).run()


# --------------------------------------------------------------- frontend

def lint_source(source, path="<string>"):
    """Lint one source string. Returns (findings, suppressed_count)."""
    try:
        ml = _ModuleLint(path, source).run()
    except SyntaxError as e:
        return [Finding(rule="PTL000", name="syntax-error", path=path,
                        line=e.lineno or 1, col=e.offset or 0,
                        message=f"cannot parse: {e.msg}")], 0
    return ml.findings, ml.suppressed


def lint_file(path):
    with open(path, "r", encoding="utf-8", errors="replace") as f:
        return lint_source(f.read(), path)


_DEFAULT_EXCLUDE = ("__pycache__", ".git", ".jax_cache")


def iter_python_files(paths, exclude=_DEFAULT_EXCLUDE):
    """Expand files / directories / globs into .py files, sorted."""
    out = []
    for p in paths:
        if os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = [d for d in dirnames
                               if d not in exclude]
                for fn in filenames:
                    if fn.endswith(".py"):
                        out.append(os.path.join(dirpath, fn))
        elif os.path.isfile(p):
            out.append(p)
        else:
            import glob as _glob

            out.extend(f for f in _glob.glob(p, recursive=True)
                       if f.endswith(".py"))
    return sorted(set(out))


def lint_paths(paths, select=None, ignore=None):
    """Lint files/dirs/globs.

    Returns dict: {"findings": [Finding], "suppressed": int,
    "files": int, "version": PTLINT_VERSION}. `select`/`ignore` filter
    by rule id or slug (fnmatch patterns allowed, e.g. 'PTL1*').
    """
    def _norm(pats):
        return [_SLUG_TO_ID.get(p, p) for p in pats or ()]

    select = _norm(select)
    ignore = _norm(ignore)

    def keep(f):
        if select and not any(fnmatch.fnmatch(f.rule, p)
                              for p in select):
            return False
        if ignore and any(fnmatch.fnmatch(f.rule, p) for p in ignore):
            return False
        return True

    findings, suppressed, nfiles = [], 0, 0
    for path in iter_python_files(paths):
        nfiles += 1
        fs, sup = lint_file(path)
        findings.extend(f for f in fs if keep(f))
        suppressed += sup
    return {"findings": findings, "suppressed": suppressed,
            "files": nfiles, "version": PTLINT_VERSION}


# ------------------------------------------- lock-acquisition graph (801)
#
# The PTL801 pass proper: every lock-owning class contributes nodes
# ("Class.lockattr") and its methods contribute edges — a with-nesting
# inside one method, or a call made while a lock is held that
# (transitively, through self/cls helpers and uniquely-resolvable
# cross-class methods) acquires another lock. A cycle in this graph is
# a deadlock two threads can walk into from opposite ends; the blessed
# acyclic edge set is pinned in tests/golden/fleet_lock_order.json.

@dataclasses.dataclass
class _LockMethod:
    acquires: set = dataclasses.field(default_factory=set)
    # (outer_attr, inner_attr, line): with-nesting inside this method
    nested: list = dataclasses.field(default_factory=list)
    # (held_attr, callee, selfish, line): calls made under a held lock
    under: list = dataclasses.field(default_factory=list)
    # (callee, selfish): every named call (for the acquires* closure)
    calls: set = dataclasses.field(default_factory=set)


@dataclasses.dataclass
class _LockClass:
    name: str
    path: str
    locks: dict      # attr -> factory name ("Lock" | "RLock")
    methods: dict    # method name -> _LockMethod


class _LockMethodScan(ast.NodeVisitor):
    """Per-method scan: which locks it acquires (`with self.<lock>:`),
    same-method nesting pairs, and every call made while a lock is
    held. Nested defs/lambdas don't run at definition time — skipped
    (they lint in their own right through _FunctionLinter)."""

    def __init__(self, lock_attrs):
        self.lock_attrs = lock_attrs
        self.held = []
        self.out = _LockMethod()

    def visit_FunctionDef(self, node):   # do not descend
        pass

    visit_AsyncFunctionDef = visit_FunctionDef
    visit_Lambda = visit_FunctionDef
    visit_ClassDef = visit_FunctionDef

    def _acquired(self, expr):
        key = _target_key(expr)
        if key and key.startswith("self.") and \
                key[len("self."):] in self.lock_attrs:
            return key[len("self."):]
        return None

    def visit_With(self, node):
        pushed = 0
        for item in node.items:
            self.visit(item.context_expr)   # calls inside the expr
            attr = self._acquired(item.context_expr)
            if attr is not None:
                self.out.acquires.add(attr)
                for h in self.held:
                    self.out.nested.append((h, attr, node.lineno))
                self.held.append(attr)
                pushed += 1
        for stmt in node.body:
            self.visit(stmt)
        for _ in range(pushed):
            self.held.pop()

    visit_AsyncWith = visit_With

    def visit_Call(self, node):
        comp = _component(node.func)
        if comp:
            selfish = (isinstance(node.func, ast.Attribute)
                       and isinstance(node.func.value, ast.Name)
                       and node.func.value.id in ("self", "cls"))
            self.out.calls.add((comp, selfish))
            for h in self.held:
                self.out.under.append((h, comp, selfish, node.lineno))
        self.generic_visit(node)


def _class_lock_attrs(cnode):
    """attr -> factory name, from Assign/AnnAssign anywhere in the
    class body except nested classes (their locks are their own)."""
    locks = {}
    stack = list(cnode.body)
    while stack:
        n = stack.pop()
        if isinstance(n, ast.ClassDef):
            continue
        for child in ast.iter_child_nodes(n):
            stack.append(child)
        if isinstance(n, ast.AnnAssign):
            targets, v = [n.target], n.value
        elif isinstance(n, ast.Assign):
            targets, v = n.targets, n.value
        else:
            continue
        if not isinstance(v, ast.Call):
            continue
        comp = _component(v.func)
        if comp not in _LOCK_FACTORIES:
            continue
        for t in targets:
            key = _target_key(t)
            if key and key.startswith("self.") and \
                    "." not in key[len("self."):]:
                locks[key[len("self."):]] = comp
    return locks


def _scan_lock_classes(tree, path):
    """Every lock-owning class in the tree: its declared locks plus a
    per-method acquisition scan — the PTL801 graph input."""
    out = []
    for cnode in ast.walk(tree):
        if not isinstance(cnode, ast.ClassDef):
            continue
        locks = _class_lock_attrs(cnode)
        if not locks:
            continue
        methods = {}
        for meth in cnode.body:
            if not isinstance(meth, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            scan = _LockMethodScan(frozenset(locks))
            for stmt in meth.body:
                scan.visit(stmt)
            methods[meth.name] = scan.out
        out.append(_LockClass(name=cnode.name, path=path,
                              locks=locks, methods=methods))
    return out


def _lock_graph(classes):
    """The acquisition graph: {(src, dst): [(path, line,
    'Class.method'), ...]} over lock nodes 'Class.attr', after a
    global fixpoint computing acquires*(class, method) through self
    calls and uniquely-resolvable cross-class calls."""
    by_method = {}
    for c in classes:
        for m in c.methods:
            by_method.setdefault(m, []).append(c)

    def resolve(cls, callee, selfish):
        if selfish:
            return [(cls, callee)] if callee in cls.methods else []
        # cross-class by bare name: only when exactly ONE other
        # lock-owning class defines it and the name isn't generic —
        # `self.log_file.flush()` must not inherit another class's
        # acquisitions
        if callee in _GENERIC_METHODS:
            return []
        owners = [c for c in by_method.get(callee, ()) if c is not cls]
        return [(owners[0], callee)] if len(owners) == 1 else []

    acq = {}
    for c in classes:
        for mname, m in c.methods.items():
            acq[(id(c), mname)] = {f"{c.name}.{a}" for a in m.acquires}
    changed = True
    while changed:
        changed = False
        for c in classes:
            for mname, m in c.methods.items():
                cur = acq[(id(c), mname)]
                for callee, selfish in m.calls:
                    for tc, tm in resolve(c, callee, selfish):
                        for lock_node in acq.get((id(tc), tm), ()):
                            if lock_node not in cur:
                                cur.add(lock_node)
                                changed = True
    edges = {}
    for c in classes:
        for mname, m in c.methods.items():
            where = f"{c.name}.{mname}"
            for outer, inner, line in m.nested:
                edges.setdefault(
                    (f"{c.name}.{outer}", f"{c.name}.{inner}"),
                    []).append((c.path, line, where))
            for held, callee, selfish, line in m.under:
                src = f"{c.name}.{held}"
                for tc, tm in resolve(c, callee, selfish):
                    for dst in acq[(id(tc), tm)]:
                        edges.setdefault((src, dst), []).append(
                            (c.path, line, where))
    return edges, acq


def _sccs(graph):
    """Tarjan's strongly-connected components, iterative."""
    index, low, onstack = {}, {}, set()
    stack, out, counter = [], [], [0]
    for start in sorted(graph):
        if start in index:
            continue
        index[start] = low[start] = counter[0]
        counter[0] += 1
        stack.append(start)
        onstack.add(start)
        work = [(start, iter(sorted(graph[start])))]
        while work:
            node, it = work[-1]
            advanced = False
            for nxt in it:
                if nxt not in index:
                    index[nxt] = low[nxt] = counter[0]
                    counter[0] += 1
                    stack.append(nxt)
                    onstack.add(nxt)
                    work.append((nxt, iter(sorted(graph.get(nxt,
                                                            ())))))
                    advanced = True
                    break
                if nxt in onstack:
                    low[node] = min(low[node], index[nxt])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                scc = []
                while True:
                    w = stack.pop()
                    onstack.discard(w)
                    scc.append(w)
                    if w == node:
                        break
                out.append(scc)
    return out


def _lock_findings(classes):
    """PTL801 findings from a class set: (path, line, func, message)
    per lock-order cycle (ONE per SCC, anchored at its smallest-line
    site) and per non-reentrant self-re-acquisition."""
    edges, _acq = _lock_graph(classes)
    factory = {}
    for c in classes:
        for a, fac in c.locks.items():
            factory.setdefault(f"{c.name}.{a}", fac)
    out = []
    for (src, dst), sites in sorted(edges.items()):
        if src != dst or factory.get(src) == "RLock":
            continue
        path, line, where = min(sites, key=lambda s: s[1])
        out.append((path, line, where,
                    f"non-reentrant Lock '{src}' is re-acquired on a "
                    "path that already holds it — the thread wedges "
                    "against itself; split the locked region (or make "
                    "the re-entry explicit with RLock)"))
    graph = {}
    for (src, dst), _sites in edges.items():
        if src != dst:
            graph.setdefault(src, set()).add(dst)
            graph.setdefault(dst, set())
    for scc in _sccs(graph):
        if len(scc) < 2:
            continue
        sites = [site
                 for (s, d), ss in edges.items()
                 if s in scc and d in scc and s != d
                 for site in ss]
        path, line, where = min(sites, key=lambda s: s[1])
        order = " -> ".join(sorted(scc))
        out.append((path, line, where,
                    f"lock-order cycle {order} — two threads entering "
                    "it from opposite ends wedge forever with zero "
                    "CPU (the wedged-replica flap); pick ONE global "
                    "order and pin it in "
                    "tests/golden/fleet_lock_order.json"))
    out.sort(key=lambda t: (t[0], t[1]))
    return out


def build_lock_graph(paths):
    """Parse every .py under `paths` (stdlib-only, no imports
    executed) and return (classes, edges, findings) for the tree-wide
    lock-order pass. Cross-file edges resolve here — the per-module
    PTL801 pass only sees cycles within one file."""
    classes = []
    for path in iter_python_files(paths):
        with open(path, "r", encoding="utf-8", errors="replace") as f:
            src = f.read()
        if re.search(r"#\s*ptlint:\s*skip-file", src):
            continue
        try:
            tree = ast.parse(src, filename=path)
        except SyntaxError:
            continue
        classes.extend(_scan_lock_classes(tree, path))
    edges, _acq = _lock_graph(classes)
    findings = [
        Finding(rule="PTL801", name=RULES["PTL801"].name, path=p,
                line=line, col=0, message=msg, func=func)
        for p, line, func, msg in _lock_findings(classes)]
    return classes, edges, findings


def lock_graph_report(paths):
    """JSON-able tree-wide lock report — the source of truth for
    tests/golden/fleet_lock_order.json and bench.py's `locks` stamp."""
    classes, edges, findings = build_lock_graph(paths)
    edge_sites = {}
    for (s, d), ss in edges.items():
        edge_sites[f"{s} -> {d}"] = [
            {"path": p, "line": line, "func": fn}
            for p, line, fn in sorted(ss)]
    return {
        "version": LOCK_ANALYSIS_VERSION,
        "classes": len(classes),
        "locks": sum(len(c.locks) for c in classes),
        "edges": sorted(f"{s} -> {d}" for (s, d) in edges),
        "edge_sites": edge_sites,
        "findings": [f.as_dict() for f in findings],
    }
