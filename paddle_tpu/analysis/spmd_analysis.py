"""SPMD safety analysis — collective schedules, rank divergence, and
declared-vs-live sharding, at the jaxpr level.

Every distributed bug this project shipped was found by a human after
a flaky multi-host failure: the PR-6 partial-spec concatenate
mis-shard (NaN'd hybrid-pp), the PR-4 rank-conditioned collective
deadlock shape, the PR-7 scrape races. The AST half of those fences
lives in `lint.py` (PTL601/PTL7xx); this module is the compiled half —
what only exists after tracing:

* **Collective-schedule extraction** (`extract_schedule`): walk the
  jaxpr of a `HybridTrainStep`/`DistributedTrainStep`/shard_map body
  and emit the ORDERED schedule of collectives — op kind, mesh axes,
  reduce op, payload bytes per execution, execution count (scan trip
  multipliers folded in). Two uses: (a) the tier-1 hybrid3d schedule
  is pinned as a golden in tests, so an accidental extra all-gather
  fails CI before a pod ever sees it; (b) `per_axis_bytes` is the
  measured baseline ROADMAP item 2's quantized in-XLA all-reduce
  (EQuARX) must beat.

* **Rank-invariance** (`rank_divergence`): trace the same step builder
  under different host ranks and diff the schedules. A divergence IS
  the PR-4 deadlock class — one rank compiles a collective its peers
  don't — caught at trace time instead of wedging a pod (PTL603).

* **Rank-conditioned collectives in-program** (PTL604, found during
  the walk): a collective under a `lax.cond` whose predicate derives
  from `axis_index` over an axis the collective itself reduces —
  members of one axis group take different branches, so some enter the
  collective and some don't. A predicate over a DIFFERENT axis is
  legal (every member of the collective's own group branches the same
  way — the 1F1B head-stage loss is the shipped example) and stays
  silent.

* **Declared-PSpec vs live placement** (`check_placement`, PTL602):
  each parameter's `_pspec` annotation vs the sharding its live value
  actually has. Drift here is the PR-6 LocalSGD bug class — a host
  path re-placed averaged params and silently flipped the step to a
  second executable.

Byte accounting semantics: `count` multiplies scan trip lengths;
`lax.cond` branches are BOTH counted (the compiled program's upper
bound — at most one executes per rank per trip); `while_loop` bodies
count one trip (length unknowable statically — flagged in context as
`while[?]`).

CLI: `tools/ptlint.py --spmd` runs these passes on the tier-1
dp2.tp2.pp2 reference step and dumps the machine-readable schedule;
the stdlib-only AST gate stays jax-free and ~4 s.
"""
import dataclasses
import types as _types

import numpy as np

import jax

from .lint import (Finding, SPMD_ANALYSIS_VERSION,
                   LOCK_ANALYSIS_VERSION, build_lock_graph,
                   lock_graph_report)

__all__ = ["SPMD_ANALYSIS_VERSION", "SPMD_RULES", "Collective",
           "CollectiveSchedule", "collectives_of_jaxpr",
           "extract_schedule", "schedule_diff", "rank_divergence",
           "check_placement", "spmd_report", "reference_report",
           "LOCK_ANALYSIS_VERSION", "build_lock_graph",
           "lock_graph_report", "lock_order_diff"]

# the jaxpr-level SPMD finding ids (the AST linter owns PTL6xx's
# source-visible shapes; these need a trace)
SPMD_RULES = {
    "PTL602": "pspec-placement-drift",
    "PTL603": "rank-divergent-schedule",
    "PTL604": "rank-conditioned-collective",
}

# collective primitive -> reduce op (None = data movement, no reduce)
_COLLECTIVES = {
    "psum": "add", "pmax": "max", "pmin": "min",
    "psum_scatter": "add",
    "ppermute": None, "pbroadcast": None, "all_gather": None,
    "all_to_all": None, "pgather": None,
}


@dataclasses.dataclass(frozen=True)
class Collective:
    """One collective in the compiled program."""
    op: str          # primitive name (psum, ppermute, all_gather, ...)
    axes: tuple      # mesh axis names it communicates over
    reduce: object   # "add"/"max"/"min", or None for pure movement
    bytes: int       # payload bytes per execution (per-shard avals)
    count: int       # executions per step (scan trips folded in)
    context: str     # program path, e.g. "/shard_map/scan[15]"

    def key(self):
        """Identity WITHOUT context — rank-divergence and the golden
        compare care about what communicates, not sub-jaxpr naming."""
        return (self.op, self.axes, self.reduce, self.bytes, self.count)

    def as_dict(self):
        return dataclasses.asdict(self)


@dataclasses.dataclass
class CollectiveSchedule:
    ops: list                  # [Collective] in program order
    findings: list             # PTL604 from the walk

    @property
    def per_axis_bytes(self):
        """axis -> total payload bytes per step (bytes x count summed
        over every collective touching the axis; cond branches both
        counted — the compiled upper bound)."""
        out = {}
        for c in self.ops:
            for ax in c.axes:
                out[ax] = out.get(ax, 0) + c.bytes * c.count
        return dict(sorted(out.items()))

    @property
    def per_axis_counts(self):
        out = {}
        for c in self.ops:
            for ax in c.axes:
                out[ax] = out.get(ax, 0) + c.count
        return dict(sorted(out.items()))

    def keys(self):
        return [c.key() for c in self.ops]

    def identical(self, other):
        return self.keys() == other.keys()

    def as_dict(self):
        return {"version": SPMD_ANALYSIS_VERSION,
                "n_collectives": len(self.ops),
                "executions": sum(c.count for c in self.ops),
                "per_axis_bytes": self.per_axis_bytes,
                "per_axis_counts": self.per_axis_counts,
                "ops": [c.as_dict() for c in self.ops],
                "findings": [f.as_dict() for f in self.findings]}

    def summary(self):
        axes = ", ".join(f"{a}: {b / 1e6:.3f} MB"
                         for a, b in self.per_axis_bytes.items())
        return (f"{len(self.ops)} collectives "
                f"({sum(c.count for c in self.ops)} executions) — "
                f"{axes or 'no communication'}")


# --------------------------------------------------------------- walker

def _axes_of(eqn):
    p = eqn.params
    ax = p.get("axes", p.get("axis_name", p.get("axis", ())))
    if isinstance(ax, (str, int)):
        ax = (ax,)
    return tuple(str(a) for a in ax)


def _payload_bytes(eqn):
    total = 0
    for v in eqn.invars:
        aval = getattr(v, "aval", None)
        if aval is None or not hasattr(aval, "shape"):
            continue
        try:
            total += int(np.prod(aval.shape, dtype=np.int64)) * \
                np.dtype(aval.dtype).itemsize
        except (TypeError, ValueError):
            pass       # extended dtypes (PRNG keys) — no collective use
    return total


def _is_var(v):
    return not hasattr(v, "val")     # jax Literal carries .val


class _Walker:
    """Ordered jaxpr walk with scan-trip multipliers and rank-origin
    taint (which mesh axes a value's `axis_index` ancestry covers)."""

    def __init__(self):
        self.ops = []
        self.findings = []

    def walk(self, jaxpr, mult=1, ctx="", taint=None):
        taint = {} if taint is None else taint   # Var -> frozenset(axes)
        for eqn in jaxpr.eqns:
            name = eqn.primitive.name
            in_taint = frozenset().union(
                *(taint.get(v, frozenset()) for v in eqn.invars
                  if _is_var(v))) if eqn.invars else frozenset()
            if name == "axis_index":
                ax = _axes_of(eqn)
                for ov in eqn.outvars:
                    taint[ov] = in_taint | set(ax)
                continue
            if name in _COLLECTIVES:
                self.ops.append(Collective(
                    op=name, axes=_axes_of(eqn),
                    reduce=_COLLECTIVES[name],
                    bytes=_payload_bytes(eqn), count=mult,
                    context=ctx or "/"))
            if in_taint:
                for ov in eqn.outvars:
                    taint[ov] = in_taint
            # ---- sub-jaxprs ----
            if name == "scan":
                length = int(eqn.params.get("length", 1) or 1)
                self._enter(eqn.params["jaxpr"], eqn, taint,
                            mult * length, f"{ctx}/scan[{length}]")
            elif name == "cond":
                self._cond(eqn, mult, ctx, taint)
            elif name == "while":
                for key in ("cond_jaxpr", "body_jaxpr"):
                    sub = eqn.params.get(key)
                    if sub is not None:
                        self._enter(sub, eqn, taint, mult,
                                    f"{ctx}/while[?]")
            elif name == "pjit":
                label = eqn.params.get("name") or "pjit"
                self._enter(eqn.params["jaxpr"], eqn, taint,
                            mult, f"{ctx}/{label}")
            else:
                for key in sorted(eqn.params):
                    v = eqn.params[key]
                    for sub in (v if isinstance(v, (list, tuple))
                                else (v,)):
                        if hasattr(sub, "eqns") or (
                                hasattr(sub, "jaxpr")
                                and hasattr(sub.jaxpr, "eqns")):
                            self._enter(sub, eqn, taint, mult,
                                        f"{ctx}/{name}")
        return taint

    def _enter(self, sub, eqn, taint, mult, ctx):
        jx = sub.jaxpr if hasattr(sub, "jaxpr") else sub
        outer_invars = eqn.invars
        inner = {}
        if len(jx.invars) == len(outer_invars):
            for ov, iv in zip(outer_invars, jx.invars):
                if _is_var(ov):
                    t = taint.get(ov)
                    if t:
                        inner[iv] = t
        else:
            # arity mismatch (pruned/const-hoisted): conservative union
            u = frozenset().union(
                *(taint.get(v, frozenset()) for v in outer_invars
                  if _is_var(v))) if outer_invars else frozenset()
            if u:
                inner = {v: u for v in jx.invars}
        inner = self.walk(jx, mult, ctx, inner)
        # taint flows back OUT: an axis_index computed INSIDE a
        # pjit/scan must taint the outer result, or a rank-derived
        # cond predicate behind any sub-jaxpr boundary goes invisible
        if len(jx.outvars) == len(eqn.outvars):
            for iv, ov in zip(jx.outvars, eqn.outvars):
                t = inner.get(iv) if _is_var(iv) else None
                if t:
                    taint[ov] = taint.get(ov, frozenset()) | t

    def _cond(self, eqn, mult, ctx, taint):
        pred = eqn.invars[0]
        pred_axes = (taint.get(pred, frozenset())
                     if _is_var(pred) else frozenset())
        branches = eqn.params.get("branches", ())
        walkers = []
        for i, br in enumerate(branches):
            w = _Walker()
            # branch operands are eqn.invars[1:] (invars[0] is the
            # predicate); branch outvar taint flows back to the cond's
            # outvars through _enter's out-mapping (the shared taint
            # dict is written in place — unions across branches)
            shim = _types.SimpleNamespace(invars=eqn.invars[1:],
                                          outvars=eqn.outvars)
            w._enter(br, shim, taint, mult, f"{ctx}/cond[{i}]")
            walkers.append(w)
            self.ops.extend(w.ops)
            self.findings.extend(w.findings)
        if pred_axes and walkers:
            # deadlock shape: members of a predicate axis group take
            # different branches, so a collective OVER that axis runs
            # on some members and not others. Filter each branch's
            # sub-schedule to the predicate axes and demand identity.
            def filt(w):
                return [c.key() for c in w.ops
                        if set(c.axes) & pred_axes]

            base = filt(walkers[0])
            for i, w in enumerate(walkers[1:], start=1):
                if filt(w) != base:
                    self.findings.append(Finding(
                        rule="PTL604",
                        name=SPMD_RULES["PTL604"],
                        path=f"<jaxpr{ctx or '/'}>", line=0, col=0,
                        message=(
                            "collective over axes "
                            f"{sorted(pred_axes)} inside a lax.cond "
                            "whose predicate derives from axis_index "
                            "over the same axes — branch "
                            f"0 and branch {i} schedule different "
                            "collectives, so members of one axis "
                            "group diverge (the PR-4 deadlock shape, "
                            "in-program form)"),
                        func="cond"))
                    break


def collectives_of_jaxpr(closed):
    """CollectiveSchedule of a (Closed)Jaxpr — the walk behind
    `extract_schedule`, usable on a jaxpr you already hold."""
    w = _Walker()
    jx = closed.jaxpr if hasattr(closed, "jaxpr") else closed
    w.walk(jx)
    return CollectiveSchedule(ops=w.ops, findings=w.findings)


# ------------------------------------------------------------ frontends

def _trace_step(step, batch):
    """ClosedJaxpr of a TrainStep-family step (the SAME `_step_args`
    layout the runtime dispatches with — see step_analysis)."""
    from ..tensor_core import Tensor
    import jax.numpy as jnp

    if type(step).__name__ == "SparseTrainStep":
        raise TypeError(
            "extract_schedule does not support SparseTrainStep "
            "(per-step rows/inv operands); analyze a dense step")
    if step._compiled is None:
        step._build()
    if not batch:
        raise ValueError(
            "extract_schedule(TrainStep) needs one example batch: "
            "extract_schedule(step, x, y)")
    batch_vals = [b._value if isinstance(b, Tensor) else jnp.asarray(b)
                  for b in batch]
    return step._compiled.trace(*step._step_args(batch_vals)).jaxpr


def extract_schedule(step, *args):
    """Ordered per-mesh-axis collective schedule of a live step.

    Accepts a `jit.TrainStep` (incl. `HybridTrainStep` /
    `DistributedTrainStep`) plus one example batch, any `jax.jit`-
    wrapped callable plus example args (ShapeDtypeStructs work), or a
    (Closed)Jaxpr directly. Nothing is executed — the walk is pure
    trace inspection.
    """
    from ..jit import TrainStep
    from ..distributed.parallel_step import DistributedTrainStep

    if isinstance(step, TrainStep):
        closed = _trace_step(step, args)
    elif isinstance(step, DistributedTrainStep):
        from ..tensor_core import Tensor
        import jax.numpy as jnp

        if not args:
            raise ValueError(
                "extract_schedule(DistributedTrainStep) needs one "
                "example batch")
        batch_vals = [b._value if isinstance(b, Tensor)
                      else jnp.asarray(b) for b in args]
        if step._compiled is None:
            step._build(batch_vals)
        if not hasattr(step._compiled, "trace"):
            raise TypeError(
                "extract_schedule: AOT-restored DistributedTrainStep "
                "is shape-frozen — extract before restore")
        closed = step._compiled.trace(
            *step._step_args(batch_vals)).jaxpr
    elif hasattr(step, "trace") and hasattr(step, "lower"):
        closed = step.trace(*args).jaxpr
    elif hasattr(step, "eqns") or hasattr(step, "jaxpr"):
        closed = step
    else:
        raise TypeError(
            f"extract_schedule: unsupported subject "
            f"{type(step).__name__} — expected jit.TrainStep, a "
            "jax.jit-wrapped callable, or a jaxpr")
    return collectives_of_jaxpr(closed)


def schedule_diff(a, b, label_a="a", label_b="b"):
    """Human-readable divergences between two schedules: the first
    op-stream mismatch plus per-axis byte deltas. Empty = identical
    (the rank-invariance pass passes)."""
    out = []
    ka, kb = a.keys(), b.keys()
    for i, (x, y) in enumerate(zip(ka, kb)):
        if x != y:
            out.append(f"op[{i}]: {label_a}={x} vs {label_b}={y}")
            break
    if len(ka) != len(kb):
        out.append(f"length: {label_a}={len(ka)} vs "
                   f"{label_b}={len(kb)} collectives")
    ba, bb = a.per_axis_bytes, b.per_axis_bytes
    for ax in sorted(set(ba) | set(bb)):
        if ba.get(ax, 0) != bb.get(ax, 0):
            out.append(f"axis '{ax}': {label_a}={ba.get(ax, 0)} vs "
                       f"{label_b}={bb.get(ax, 0)} bytes")
    return out


def rank_divergence(schedules):
    """PTL603 findings from rank-parameterized schedules
    (`{rank: CollectiveSchedule}` — trace the same builder once per
    rank). Any divergence is the deadlock class: one rank compiles a
    collective its peers don't."""
    findings = []
    ranks = sorted(schedules)
    if len(ranks) < 2:
        return findings
    base = schedules[ranks[0]]
    for r in ranks[1:]:
        diff = schedule_diff(base, schedules[r],
                             f"rank{ranks[0]}", f"rank{r}")
        if diff:
            findings.append(Finding(
                rule="PTL603", name=SPMD_RULES["PTL603"],
                path="<rank-traces>", line=0, col=0,
                message=("collective schedule diverges across ranks "
                         f"({'; '.join(diff[:3])}) — at a multi-host "
                         "run this wedges the pod at the first "
                         "mismatched collective"),
                func=f"rank{r}"))
    return findings


# ------------------------------------------------------------ placement

def check_placement(step):
    """PTL602: declared `_pspec` vs the LIVE sharding of each
    parameter. Drift means a host path re-placed a buffer (the PR-6
    LocalSGD bug class): the next dispatch reshards silently or
    compiles a second executable."""
    params = getattr(step, "_param_objs", None)
    if params is None:
        raise TypeError(
            "check_placement expects a built jit.TrainStep-family "
            "step (needs its parameter objects)")
    from ..distributed.parallel_step import sharding_of

    findings = []
    for i, p in enumerate(params):
        spec = getattr(p, "_pspec", None)
        val = getattr(p, "_value", None)
        if spec is None or val is None or \
                not hasattr(val, "sharding"):
            continue
        try:
            expected = sharding_of(val, spec)
            actual = val.sharding
            same = actual.is_equivalent_to(expected, val.ndim)
        except Exception:  # ptlint: disable=PTL804 (degenerate mesh / non-addressable; entry skipped)
            continue
        if not same:
            name = getattr(p, "name", "") or f"param{i}"
            findings.append(Finding(
                rule="PTL602", name=SPMD_RULES["PTL602"],
                path="<placement>", line=0, col=0,
                message=(f"parameter '{name}' declares PSpec "
                         f"{spec} but its live value is placed as "
                         f"{actual} — a host path re-placed it "
                         "(the LocalSGD drift class); the next "
                         "dispatch pays a silent reshard or a second "
                         "executable"),
                func=name))
    return findings


# -------------------------------------------------------------- surface

def spmd_report(step, *batch):
    """One-call SPMD report (bench / CLI surface): schedule dump +
    placement check + all jaxpr-level findings."""
    sched = extract_schedule(step, *batch)
    findings = list(sched.findings)
    try:
        findings.extend(check_placement(step))
    except TypeError:
        pass                   # raw jitfn/jaxpr: no parameters to check
    d = sched.as_dict()
    d["findings"] = [f.as_dict() for f in findings]
    d["num_findings"] = len(findings)
    return d


def reference_report():
    """`ptlint --spmd`'s subject: the tier-1-size GPT over the
    dp2.tp2.pp2 hybrid mesh — the same geometry the golden-schedule
    test pins. Needs 8 devices (the CLI forces 8 virtual CPU devices
    before importing jax)."""
    import paddle_tpu as paddle
    from ..distributed import hybrid3d, mesh as mesh_mod
    from ..text.models.gpt import GPTConfig

    gpt_cfg = GPTConfig(vocab_size=256, hidden_size=32, num_layers=4,
                        num_heads=4, max_seq_len=32)
    cfg3d = hybrid3d.Hybrid3DConfig(dp=2, tp=2, pp=2)
    mesh_mod.reset_mesh()
    hybrid3d.init_hybrid_mesh(cfg3d)
    try:
        paddle.seed(0)
        model = hybrid3d.build_gpt3d(gpt_cfg, cfg3d)
        opt = paddle.optimizer.AdamW(1e-3,
                                     parameters=model.parameters())
        step = hybrid3d.HybridTrainStep(
            model, lambda mm, i: mm.loss(i), opt, config=cfg3d)
        ids = np.random.default_rng(1).integers(0, 256, (8, 16))
        rep = spmd_report(step, ids)
        rep["config"] = cfg3d.describe()
        return rep
    finally:
        mesh_mod.reset_mesh()


# ------------------------------------------------- lock-order export

def lock_order_diff(report, golden):
    """Divergences between a live `lock_graph_report()` and the pinned
    golden (`tests/golden/fleet_lock_order.json`) — the lock-graph
    twin of `schedule_diff`. Empty = the fleet still acquires locks in
    the blessed order.

    A NEW edge is not automatically a bug — cross-class lock nesting
    that stays acyclic is legal — but it IS a contract change: the
    golden pins the blessed edge set the same way the dp2.tp2.pp2
    collective schedule is pinned, so the author of a new edge must
    look at the cycle report and re-bless the golden consciously.
    Findings (actual cycles) are always divergences.
    """
    out = []
    live = set(report.get("edges", []))
    pinned = set(golden.get("edges", []))
    for e in sorted(live - pinned):
        out.append(f"new lock-order edge not in golden: {e}")
    for e in sorted(pinned - live):
        out.append(f"golden edge no longer acquired: {e}")
    gv = golden.get("version")
    if gv is not None and gv != report.get("version"):
        out.append(f"lock-analysis version drift: live="
                   f"{report.get('version')} vs golden={gv}")
    for f in report.get("findings", []):
        out.append(f"lock-order finding: {f}")
    return out
