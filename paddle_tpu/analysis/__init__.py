"""paddle_tpu.analysis — jit-safety static analysis.

The TPU-native analog of the reference's static-graph IR validity
passes (SURVEY layer 3/4a): PaddlePaddle verifies a ProgramDesc before
the executor runs it; this framework has no graph IR to verify — the
program IS python that traces — so correctness checking happens at the
two layers that exist here:

* **Source level** (`lint`): an AST linter with framework-specific
  rules — host syncs inside traced code, python control flow on
  tracers, donated-buffer reuse, weak-type retrace hazards, int8 dots
  without `preferred_element_type`, rank-divergent collective
  ordering. `tools/ptlint.py` is the CLI/CI gate; the tier-1 suite
  pins the shipped tree at zero findings.

* **Concurrency & aliasing level** (`lint`, lock/alias passes): the
  same AST pass also builds a per-class lock-acquisition graph across
  the tree (PTL801 lock-order cycles — a static deadlock detector
  with `tests/golden/fleet_lock_order.json` pinning the blessed
  cross-class edge set), lints blocking calls and caller-supplied
  callbacks under a held lock (PTL802/803), silent exception
  swallowing (PTL804), and zero-copy aliasing escapes into long-lived
  state (PTL501/502). `build_lock_graph` / `lock_graph_report` export
  the graph for CI and `tools/ptlint.py --locks`.

* **jaxpr/HLO level** (`step_analysis`): `analyze_step()` traces a
  live `jit.TrainStep` / `inference.LLMEngine` and reports donation
  coverage (did the compiled executable really alias the donated
  buffers — the PR-2 persistent-cache bug, caught mechanically),
  silent dtype promotions, host callbacks in the step body, and
  weak-type retrace hazards with a diffable input signature.

Rule catalogue with the real shipped-bug each rule would have caught:
docs/ANALYSIS.md.
"""
from .lint import (  # noqa: F401
    PTLINT_VERSION, SPMD_ANALYSIS_VERSION, LOCK_ANALYSIS_VERSION,
    RULES, Rule, Finding,
    lint_source, lint_file, lint_paths, iter_python_files,
    build_lock_graph, lock_graph_report)
from .step_analysis import (  # noqa: F401
    ANALYSIS_RULES, StepReport, analyze_step, analyze_jit,
    donation_coverage, signature_diff)
from .spmd_analysis import (  # noqa: F401
    SPMD_RULES, Collective, CollectiveSchedule, collectives_of_jaxpr,
    extract_schedule, schedule_diff, rank_divergence, check_placement,
    spmd_report)

__all__ = [
    "PTLINT_VERSION", "SPMD_ANALYSIS_VERSION", "LOCK_ANALYSIS_VERSION",
    "RULES", "Rule", "Finding",
    "lint_source", "lint_file", "lint_paths", "iter_python_files",
    "build_lock_graph", "lock_graph_report",
    "ANALYSIS_RULES", "StepReport", "analyze_step", "analyze_jit",
    "donation_coverage", "signature_diff",
    "SPMD_RULES", "Collective", "CollectiveSchedule",
    "collectives_of_jaxpr", "extract_schedule", "schedule_diff",
    "rank_divergence", "check_placement", "spmd_report",
]
