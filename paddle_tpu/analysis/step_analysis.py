"""jaxpr/HLO-level step analysis — the compiled half of
`paddle_tpu.analysis`.

`analyze_step()` traces a live training/serving step and reports what
the SOURCE linter cannot see, because it only exists after lowering:

* **Donation coverage** — which donated buffers actually aliased an
  output in the compiled executable. This catches the PR-2 bug
  mechanically: on jax 0.4.x a persistent-cache-served donating
  executable can silently drop (or mismatch) its input/output aliasing
  map — bit-correct results, 25% slower serving, and a step-corruption
  hazard. The check compiles through the SAME cache path the runtime
  uses, so a poisoned cache entry is visible here.

* **Dtype promotions** — every `convert_element_type` in the program,
  with the silent upcasts (bf16→f32, f16→f32, f32→f64) split out and
  anything landing in f64 flagged: a TPU-targeted step has no business
  computing in f64 (MXU has no f64; on CPU-x64 it doubles scalar
  traffic).

* **Host callbacks / transfers** — `*_callback`, infeed/outfeed
  primitives in the step body. A compiled hot-path step should have
  none; each one is a per-step device↔host round trip.

* **Retrace hazards** — weak-typed inputs (python scalars riding as
  jit arguments hash differently from committed arrays — one stray
  `jnp.asarray` at a call site makes a second executable) and the full
  input signature, with `signature_diff()` to name what forced a
  recompile between two traces.

Accepts a `jit.TrainStep`, an `inference.LLMEngine` / `LLMServer`
(the `_CompiledPagedStep` is analyzed with the engine's live
geometry), or any `jax.jit`-wrapped callable plus example args.
"""
import dataclasses
import re
from collections import Counter

import numpy as np

import jax
import jax.numpy as jnp

from .lint import Finding

__all__ = ["StepReport", "analyze_step", "analyze_jit",
           "donation_coverage", "signature_diff", "ANALYSIS_RULES"]

# analyzer finding ids (the AST linter owns PTL1xx-4xx; the step
# analyzer owns PTL5xx — same Finding shape, same suppression story in
# reports)
ANALYSIS_RULES = {
    "PTL511": "donation-dropped",
    "PTL512": "f64-in-program",
    "PTL513": "host-callback-in-step",
}

_HOST_CALL_PRIMS = ("callback", "infeed", "outfeed")


@dataclasses.dataclass
class StepReport:
    kind: str
    # {"expected": n, "aliased": n, "held": bool, "dropped": [labels]}
    donation: dict
    # every convert_element_type, keyed "src->dst"
    conversions: dict
    # the silent-upcast subset (bf16->f32, f16->f32, f32->f64, ...)
    promotions: dict
    # primitives that leave the device mid-step
    host_calls: dict
    # labels of weak-typed inputs (python scalars in the signature)
    weak_type_args: list
    # ((shape, dtype, weak_type), ...) per flat input — diffable
    signature: tuple
    findings: list
    # collective summary from the same trace (spmd_analysis walk):
    # {"n_collectives", "executions", "per_axis_bytes",
    # "per_axis_counts"} — {} when the program has no collectives
    collectives: dict = dataclasses.field(default_factory=dict)

    def ok(self):
        return not self.findings

    def as_dict(self):
        d = dataclasses.asdict(self)
        d["findings"] = [f.as_dict() for f in self.findings]
        return d


# ------------------------------------------------------------ jaxpr walk

def _walk_jaxpr(jaxpr, conversions, host_calls):
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if any(tok in name for tok in _HOST_CALL_PRIMS):
            host_calls[name] += 1
        if name == "convert_element_type" and eqn.invars and \
                hasattr(eqn.invars[0], "aval"):
            src = eqn.invars[0].aval.dtype
            dst = eqn.outvars[0].aval.dtype
            conversions[f"{src}->{dst}"] += 1
        for v in eqn.params.values():
            for sub in (v if isinstance(v, (list, tuple)) else (v,)):
                if hasattr(sub, "eqns"):
                    _walk_jaxpr(sub, conversions, host_calls)
                elif hasattr(sub, "jaxpr") and \
                        hasattr(sub.jaxpr, "eqns"):
                    _walk_jaxpr(sub.jaxpr, conversions, host_calls)


_UPCASTS = {("bfloat16", "float32"), ("float16", "float32"),
            ("float16", "bfloat16"), ("float32", "float64"),
            ("bfloat16", "float64"), ("float16", "float64")}


def _split_promotions(conversions):
    promos = {}
    for key, n in conversions.items():
        src, dst = key.split("->")
        if (src, dst) in _UPCASTS:
            promos[key] = n
    return promos


# ------------------------------------------------------------- donation

def _flat_labels(args, names=None):
    """One label per flat leaf of the positional args tuple."""
    labels = []
    for i, a in enumerate(args):
        leaves_paths = jax.tree_util.tree_flatten_with_path(a)[0]
        base = (names[i] if names and i < len(names) and names[i]
                else f"arg{i}")
        for path, _ in leaves_paths:
            suffix = jax.tree_util.keystr(path)
            labels.append(base + suffix if suffix else base)
    return labels


def _donated_flat_indices(args, donate_argnums):
    idx, out = 0, []
    for i, a in enumerate(args):
        n = len(jax.tree_util.tree_leaves(a))
        if i in donate_argnums:
            out.extend(range(idx, idx + n))
        idx += n
    return out


def _aliased_param_indices(compiled):
    """Flat parameter indices that alias an output, parsed from the
    optimized-HLO module header (`input_output_alias={ {o}: (i, {},
    may-alias), ... }`)."""
    try:
        txt = compiled.as_text()
    except Exception:
        return None                       # backend can't render: unknown
    start = txt.find("input_output_alias={")
    if start == -1:
        # no alias map at all — either nothing was donated or XLA
        # dropped every alias
        return []
    i = start + len("input_output_alias=")
    depth, j = 0, i
    for j in range(i, len(txt)):
        if txt[j] == "{":
            depth += 1
        elif txt[j] == "}":
            depth -= 1
            if depth == 0:
                break
    body = txt[i:j + 1]
    # entries look like `{out}: (param, {tuple_path}, may-alias)` —
    # the param index is the first integer after each `: (`
    return sorted({int(g) for g in
                   re.findall(r":\s*\(\s*(\d+)\s*,", body)})


def donation_coverage(jitfn, args, donate_argnums, names=None,
                      lowered=None):
    """Compile (through the live cache path) and report which donated
    leaves actually aliased. Returns {"expected", "aliased", "held",
    "dropped"} — `held` means every donated buffer aliased an output,
    i.e. the in-place update actually happened.

    An empty `donate_argnums` short-circuits without lowering; a
    caller that already holds a Lowered for these args can pass it to
    skip the re-trace."""
    expected_idx = _donated_flat_indices(args, tuple(donate_argnums))
    if not expected_idx:
        return {"expected": 0, "aliased": 0, "held": True,
                "dropped": []}
    if lowered is None:
        lowered = jitfn.lower(*args)
    aliased_params = _aliased_param_indices(lowered.compile())
    if aliased_params is None:
        return {"expected": len(expected_idx), "aliased": -1,
                "held": False, "dropped": ["<unreadable executable>"]}
    # HLO parameter numbering skips UNUSED flat args (jit prunes them
    # under the default keep_unused=False) — map param j back to its
    # flat arg index through kept_var_idx before comparing, else one
    # unused leaf ahead of a donated one shifts every index and the
    # probe cries wolf. A donated-but-pruned leaf stays "dropped":
    # XLA never aliased it, the caller's buffer is consumed for
    # nothing.
    kept = None
    try:
        kept = sorted(lowered._lowering.compile_args["kept_var_idx"])
    except (AttributeError, KeyError, TypeError):
        pass                      # older jax: numbering is already flat
    if kept is not None:
        aliased_flat = {kept[j] for j in aliased_params
                        if j < len(kept)}
    else:
        aliased_flat = set(aliased_params)
    labels = _flat_labels(args, names)
    dropped = [labels[i] if i < len(labels) else f"flat[{i}]"
               for i in expected_idx if i not in aliased_flat]
    return {"expected": len(expected_idx),
            "aliased": len(aliased_flat & set(expected_idx)),
            "held": not dropped,
            "dropped": dropped}


# ------------------------------------------------------------ signatures

def _signature(in_avals):
    return tuple((tuple(a.shape), str(a.dtype),
                  bool(getattr(a, "weak_type", False)))
                 for a in in_avals)


def signature_diff(sig_a, sig_b):
    """Human-readable list of what changed between two step signatures
    — each entry is one retrace cause (shape churn, dtype flip, or a
    weak↔committed scalar flip)."""
    out = []
    if len(sig_a) != len(sig_b):
        out.append(f"arity {len(sig_a)} -> {len(sig_b)}")
    for i, (a, b) in enumerate(zip(sig_a, sig_b)):
        if a == b:
            continue
        sa, da, wa = a
        sb, db, wb = b
        if sa != sb:
            out.append(f"flat[{i}] shape {sa} -> {sb}")
        if da != db:
            out.append(f"flat[{i}] dtype {da} -> {db}")
        if wa != wb:
            out.append(f"flat[{i}] weak_type {wa} -> {wb} "
                       "(python scalar vs committed array)")
    return out


# ------------------------------------------------------------- analyzers

def analyze_jit(jitfn, args, donate_argnums=(), kind="jit", names=None,
                check_donation=True):
    """Analyze one jit-wrapped callable with example args (abstract
    `jax.ShapeDtypeStruct`s work — nothing is executed)."""
    traced = jitfn.trace(*args)
    closed = traced.jaxpr
    conversions, host_calls = Counter(), Counter()
    _walk_jaxpr(closed.jaxpr, conversions, host_calls)
    conversions = dict(conversions)
    promotions = _split_promotions(conversions)
    labels = _flat_labels(args, names)
    weak = [labels[i] if i < len(labels) else f"flat[{i}]"
            for i, a in enumerate(closed.in_avals)
            if getattr(a, "weak_type", False)]
    sig = _signature(closed.in_avals)

    if check_donation and donate_argnums:
        # traced.lower() reuses the trace above — one trace, not two
        donation = donation_coverage(jitfn, args, donate_argnums,
                                     names=names,
                                     lowered=traced.lower())
    else:
        donation = {"expected": 0, "aliased": 0, "held": True,
                    "dropped": []}

    findings = []

    def f(rule, msg):
        findings.append(Finding(
            rule=rule, name=ANALYSIS_RULES[rule], path=f"<{kind}>",
            line=0, col=0, message=msg, func=kind))

    if not donation["held"]:
        f("PTL511",
          f"donation dropped for {len(donation['dropped'])} of "
          f"{donation['expected']} donated buffers "
          f"({', '.join(donation['dropped'][:4])}"
          f"{'…' if len(donation['dropped']) > 4 else ''}) — the "
          "compiled executable copies instead of updating in place "
          "(the PR-2 persistent-cache aliasing bug shape)")
    f64 = {k: n for k, n in conversions.items()
           if k.endswith("->float64")}
    if f64:
        f("PTL512",
          f"program promotes into float64 ({f64}) — TPU has no f64 "
          "MXU path; pin dtypes (weak python scalars under x64 are "
          "the usual source)")
    if host_calls:
        f("PTL513",
          f"host callbacks inside the step body ({dict(host_calls)}) "
          "— each is a per-step device-host round trip")

    # collective schedule off the SAME trace (no second lowering):
    # summary stats ride the report, and a rank-conditioned collective
    # (PTL604) found during the walk is a finding like any other
    from .spmd_analysis import collectives_of_jaxpr

    sched = collectives_of_jaxpr(closed)
    findings.extend(sched.findings)
    collectives = {}
    if sched.ops:
        collectives = {"n_collectives": len(sched.ops),
                       "executions": sum(c.count for c in sched.ops),
                       "per_axis_bytes": sched.per_axis_bytes,
                       "per_axis_counts": sched.per_axis_counts}

    return StepReport(kind=kind, donation=donation,
                      conversions=conversions, promotions=promotions,
                      host_calls=dict(host_calls),
                      weak_type_args=weak, signature=sig,
                      findings=findings, collectives=collectives)


def _analyze_trainstep(step, batch, check_donation):
    from ..tensor_core import Tensor

    if type(step).__name__ == "SparseTrainStep":
        # its compiled signature carries per-step rows/inv operands
        # (distributed/ps.py) — the 7-arg TrainStep layout below would
        # trace with the wrong arity
        raise TypeError(
            "analyze_step does not support SparseTrainStep: its "
            "compiled signature carries per-step rows/inv operands — "
            "analyze a dense TrainStep of the same model instead")
    if step._compiled is None:
        step._build()
    if not batch:
        raise ValueError(
            "analyze_step(TrainStep) needs one example batch: "
            "analyze_step(step, x, y)")
    batch_vals = [b._value if isinstance(b, Tensor) else jnp.asarray(b)
                  for b in batch]
    # the step's own signature helper — ONE layout definition shared
    # with lower() and compile_stats(check_donation=True)
    return analyze_jit(step._compiled, step._step_args(batch_vals),
                       donate_argnums=step._donate_argnums,
                       kind="TrainStep", names=step._STEP_ARG_NAMES,
                       check_donation=check_donation)


def _analyze_dist_trainstep(step, batch, check_donation):
    from ..tensor_core import Tensor

    if not batch:
        raise ValueError(
            "analyze_step(DistributedTrainStep) needs one example "
            "batch: analyze_step(step, x, y)")
    batch_vals = [b._value if isinstance(b, Tensor) else jnp.asarray(b)
                  for b in batch]
    if step._compiled is None:
        step._build(batch_vals)
    if not hasattr(step._compiled, "trace"):
        raise TypeError(
            "analyze_step: this DistributedTrainStep was checkpoint-"
            "restored onto an AOT executable (shape-frozen, compiled "
            "outside the persistent cache) — analyze it before "
            "restore, or rebuild")
    # the step's OWN layout helpers (parallel_step._step_args /
    # _donate_argnums / _STEP_ARG_NAMES) — one definition shared with
    # __call__, so probe-vs-runtime drift can't defeat the guard
    return analyze_jit(step._compiled, step._step_args(batch_vals),
                       donate_argnums=step._donate_argnums,
                       kind="DistributedTrainStep",
                       names=step._STEP_ARG_NAMES,
                       check_donation=check_donation)


def _paged_step_args(engine):
    """The engine's compiled-step example args, from its live geometry
    and pools (nothing is executed — donation is safe to analyze). The
    kv_state pytree is (pools, scale planes, PRNG key) — the sampling
    key rides the donated state so reseeding never recompiles."""
    from ..distributed import mesh as mesh_mod

    T = engine.token_budget
    i32 = np.int32
    sf = engine._step_fn
    sharding = mesh_mod.named_sharding()
    # sid / sample_idx are device-COMMITTED at runtime (the engine's
    # staging cache) — match, or the probe itself would trace a second
    # signature
    return (
        [p._value for p in sf._params],
        np.zeros((T,), i32), np.zeros((T,), i32),
        jax.device_put(np.zeros((T,), i32), sharding),
        np.zeros((T,), i32), engine._page_tables, np.zeros((T,), i32),
        jax.device_put(np.zeros((engine.num_slots,), i32), sharding),
        (engine._kv, engine._kv_scales, engine._key),
    )


_PAGED_NAMES = ("weights", "tok", "pos", "slot_id", "write_idx",
                "page_tables", "kv_len", "sample_idx", "kv_state")


def _fused_step_args(engine):
    """Example args of the fused k-step decode executable
    (`_CompiledFusedStep`): per-SLOT frontier state + the same donated
    kv_state pytree as the single-tick step."""
    S = engine.num_slots
    i32 = np.int32
    sf = engine._ensure_fused()
    # grammar args must MATCH the live dispatch (committed device
    # tables on a token_strs engine, all-None otherwise), or the
    # probe itself would trace a second signature
    gst, gtrans, gmask = engine._grammar_args(())
    return (
        [p._value for p in sf._params],
        np.zeros((S,), i32), np.zeros((S,), i32), np.ones((S,), i32),
        np.zeros((S,), bool), np.full((S,), -1, i32),
        np.zeros((S,), np.float32), np.ones((S,), np.float32),
        np.zeros((S,), i32), gst, gtrans, gmask,
        engine._page_tables,
        (engine._kv, engine._kv_scales, engine._key),
    )


_FUSED_NAMES = ("weights", "tok0", "pos0", "rem", "fin0", "eos",
                "temps", "top_ps", "streams", "gstate0", "gtrans",
                "gmask", "page_tables", "kv_state")


def _verify_step_args(engine):
    """Example args of the speculative-verify executable
    (`speculative._CompiledVerifyStep`): per-SLOT frontier state plus
    the [S, k] draft-proposal matrix, and the same donated kv_state
    pytree as every other decode executable."""
    spec = engine._spec
    if spec is None:
        raise TypeError(
            "analyze_step(which='verify') needs a speculative engine — "
            "configure LLMEngineConfig(draft_model=..., spec_k=...)")
    S = engine.num_slots
    i32 = np.int32
    gst, gtrans, gmask = engine._grammar_args(())
    return (
        [p._value for p in spec._verify_fn._params],
        np.zeros((S,), i32), np.zeros((S,), i32),
        np.zeros((S, spec.k), i32), np.ones((S,), i32),
        np.ones((S,), i32), np.zeros((S,), bool),
        np.full((S,), -1, i32), np.zeros((S,), np.float32),
        np.ones((S,), np.float32), np.zeros((S,), i32),
        gst, gtrans, gmask,
        engine._page_tables,
        (engine._kv, engine._kv_scales, engine._key),
    )


_VERIFY_NAMES = ("weights", "tok0", "pos0", "drafts", "width", "rem",
                 "fin0", "eos", "temps", "top_ps", "streams",
                 "gstate0", "gtrans", "gmask",
                 "page_tables", "kv_state")


def _propose_step_args(engine):
    """Example args of the draft propose executable
    (`speculative._CompiledProposeStep`) — donates the DRAFT pool
    pytree + the shared PRNG key."""
    spec = engine._spec
    if spec is None:
        raise TypeError(
            "analyze_step(which='propose') needs a speculative engine "
            "— configure LLMEngineConfig(draft_model=..., spec_k=...)")
    S = engine.num_slots
    i32 = np.int32
    return (
        [p._value for p in spec._propose_fn._params],
        np.zeros((S,), i32), np.zeros((S,), i32),
        np.ones((S,), i32), np.zeros((S,), bool),
        np.full((S,), -1, i32), np.zeros((S,), np.float32),
        np.ones((S,), np.float32), np.zeros((S,), i32),
        np.zeros((S,), i32), np.zeros((S,), i32),
        engine._page_tables,
        (spec._kv, spec._kv_scales, engine._key),
    )


_PROPOSE_NAMES = ("weights", "tok0", "pos0", "rem", "fin0", "eos",
                  "temps", "top_ps", "streams", "lag", "frontier",
                  "page_tables", "kv_state")


def _analyze_engine(engine, check_donation, which="paged"):
    if which == "verify":
        # the speculative CI contract (tests/test_speculative.py):
        # zero host callbacks (PTL513) in the one-dispatch ragged
        # verify and full donation of the big pools + scales + PRNG
        # key pytree (gauge pt_step_donation_held{step="spec_verify"})
        args = _verify_step_args(engine)
        return analyze_jit(engine._spec._verify_fn._jit, args,
                           donate_argnums=(15,), kind="SpecVerify",
                           names=_VERIFY_NAMES,
                           check_donation=check_donation)
    if which == "propose":
        # the DRAFT side of the speculative contract: the propose
        # scan donates the draft pool pytree — a silent aliasing drop
        # there would copy the whole draft pool every window
        args = _propose_step_args(engine)
        return analyze_jit(engine._spec._propose_fn._jit, args,
                           donate_argnums=(12,), kind="SpecPropose",
                           names=_PROPOSE_NAMES,
                           check_donation=check_donation)
    if which == "fused":
        # the fused-window CI contract (tests/test_fused_decode.py):
        # zero host callbacks (PTL513) in the k-step scan and full
        # donation of the pools + scales + PRNG key pytree
        args = _fused_step_args(engine)
        return analyze_jit(engine._fused_fn._jit, args,
                           donate_argnums=(13,), kind="FusedDecode",
                           names=_FUSED_NAMES,
                           check_donation=check_donation)
    args = _paged_step_args(engine)
    return analyze_jit(engine._step_fn._jit, args, donate_argnums=(8,),
                       kind="PagedDecode", names=_PAGED_NAMES,
                       check_donation=check_donation)


def analyze_step(step, *batch, check_donation=True, which="paged"):
    """Analyze a live step object. Dispatches on type:

    * `jit.TrainStep` (incl. `HybridTrainStep`) or
      `distributed.DistributedTrainStep` — pass one example batch:
      `analyze_step(step, x, y)`
    * `inference.LLMEngine` / `LLMServer` — no batch needed (the
      compiled decode step has fixed geometry). `which="fused"`
      analyzes the fused k-step decode executable instead of the
      single-tick step (building it if the engine hasn't yet);
      `which="verify"` analyzes the speculative-decoding ragged verify
      executable and `which="propose"` the draft propose scan (both
      require a draft_model-configured engine).
    * anything `jax.jit`-wrapped — `analyze_step(jitted, *args)`
      (donation not inferred; use `analyze_jit` to pass
      `donate_argnums`)

    THREADING: analyzing a TrainStep/engine re-traces its pure step,
    and the trace body temporarily swaps the model's live parameter
    values for tracers — run it from the thread that owns the step (a
    serving tick on another thread mid-trace would dispatch tracers).
    """
    # late imports: analysis must not drag serving into train-only use
    try:
        from ..inference.llm_engine import LLMEngine, LLMServer
    except Exception:           # pragma: no cover - circular-import guard
        LLMEngine = LLMServer = ()
    from ..jit import TrainStep
    from ..distributed.parallel_step import DistributedTrainStep

    if isinstance(step, TrainStep):
        return _analyze_trainstep(step, batch, check_donation)
    if isinstance(step, DistributedTrainStep):
        return _analyze_dist_trainstep(step, batch, check_donation)
    if LLMServer and isinstance(step, LLMServer):
        return _analyze_engine(step.engine, check_donation, which=which)
    if LLMEngine and isinstance(step, LLMEngine):
        return _analyze_engine(step, check_donation, which=which)
    if hasattr(step, "trace") and hasattr(step, "lower"):
        return analyze_jit(step, batch, kind="jit",
                           check_donation=check_donation)
    raise TypeError(
        f"analyze_step: unsupported step type {type(step).__name__} — "
        "expected jit.TrainStep, inference.LLMEngine/LLMServer, or a "
        "jax.jit-wrapped callable")
