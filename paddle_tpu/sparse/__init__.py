"""paddle_tpu.sparse — sparse tensors (COO/CSR) and their op corpus.

TPU-native re-design of the reference sparse API (reference:
python/paddle/incubate/sparse/ — creation.py sparse_coo_tensor:68,
unary.py/binary.py op corpus; C++ SparseCooTensor
paddle/phi/core/sparse_coo_tensor.h, sparse kernels
paddle/phi/kernels/sparse/).

Representation: `jax.experimental.sparse.BCOO` under a paddle-shaped
`SparseCooTensor` wrapper whose VALUES are a framework Tensor — unary
ops and sparse·dense matmul funnel through the autograd tape, so
gradients flow into sparse values exactly like dense code. CSR keeps
its compressed rows for the API but computes as COO (on TPU both lower
to gather/scatter + dot_general; there is no separate CSR kernel zoo to
mirror).
"""
import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import sparse as jsparse

from ..ops._helpers import apply_jfn, ensure_tensor, value_of
from ..tensor_core import Tensor

__all__ = [
    "SparseCooTensor", "SparseCsrTensor", "sparse_coo_tensor",
    "sparse_csr_tensor", "is_sparse", "coalesce",
    "sin", "tan", "asin", "atan", "sinh", "tanh", "asinh", "atanh",
    "sqrt", "square", "log1p", "abs", "pow", "cast", "neg", "deg2rad",
    "rad2deg", "expm1",
    "add", "subtract", "multiply", "divide",
    "matmul", "masked_matmul", "mv", "addmm", "to_dense",
]


class SparseCooTensor:
    """COO sparse tensor: indices [ndim, nnz] + values [nnz] (+ dense
    trailing dims)."""

    def __init__(self, indices, values, shape, coalesced=False):
        self.indices_ = ensure_tensor(indices)
        self.values_ = values if isinstance(values, Tensor) \
            else ensure_tensor(values)
        self.shape = list(int(s) for s in shape)
        self._coalesced = coalesced

    # -- paddle Tensor-ish surface --
    def indices(self):
        return self.indices_

    def values(self):
        return self.values_

    @property
    def nnz(self):
        return int(value_of(self.values_).shape[0])

    @property
    def dtype(self):
        return self.values_.dtype

    @property
    def stop_gradient(self):
        return self.values_.stop_gradient

    def is_sparse(self):
        return True

    def is_sparse_coo(self):
        return True

    def is_sparse_csr(self):
        return False

    def _bcoo(self, vvals=None):
        idx = jnp.swapaxes(value_of(self.indices_), 0, 1)  # [nnz, ndim]
        v = vvals if vvals is not None else value_of(self.values_)
        return jsparse.BCOO((v, idx), shape=tuple(self.shape))

    def to_dense(self):
        idx_t = self.indices_
        shape = tuple(self.shape)

        def jfn(v):
            idx = jnp.swapaxes(value_of(idx_t), 0, 1)
            return jsparse.BCOO((v, idx), shape=shape).todense()

        return apply_jfn("sparse_to_dense", jfn, self.values_)

    def numpy(self):
        return np.asarray(value_of(self.to_dense()))

    def __repr__(self):
        return (f"SparseCooTensor(shape={self.shape}, nnz={self.nnz}, "
                f"dtype={self.dtype})")

    def backward(self, *a, **k):
        return self.values_.backward(*a, **k)

    @property
    def grad(self):
        return self.values_.grad


class SparseCsrTensor(SparseCooTensor):
    """CSR view: keeps crows/cols for the API, computes as COO."""

    def __init__(self, crows, cols, values, shape):
        crows_v = np.asarray(value_of(ensure_tensor(crows)))
        cols_v = np.asarray(value_of(ensure_tensor(cols)))
        rows = np.repeat(np.arange(len(crows_v) - 1),
                         np.diff(crows_v))
        indices = np.stack([rows, cols_v])
        super().__init__(indices, values, shape, coalesced=True)
        self.crows_ = ensure_tensor(crows)
        self.cols_ = ensure_tensor(cols)

    def crows(self):
        return self.crows_

    def cols(self):
        return self.cols_

    def is_sparse_coo(self):
        return False

    def is_sparse_csr(self):
        return True


def sparse_coo_tensor(indices, values, shape=None, dtype=None, place=None,
                      stop_gradient=True):
    """reference creation.py:68."""
    idx = ensure_tensor(indices)
    vals = ensure_tensor(values, dtype=dtype)
    if not stop_gradient:
        vals.stop_gradient = False
    if shape is None:
        iv = np.asarray(value_of(idx))
        shape = list(iv.max(axis=1) + 1)
    return SparseCooTensor(idx, vals, shape)


def sparse_csr_tensor(crows, cols, values, shape, dtype=None, place=None,
                      stop_gradient=True):
    vals = ensure_tensor(values, dtype=dtype)
    if not stop_gradient:
        vals.stop_gradient = False
    return SparseCsrTensor(crows, cols, vals, shape)


def is_sparse(x):
    return isinstance(x, SparseCooTensor)


def coalesce(x):
    """Merge duplicate indices (reference coalesce op)."""
    b = x._bcoo().sum_duplicates()
    return SparseCooTensor(jnp.swapaxes(b.indices, 0, 1), Tensor(b.data),
                           x.shape, coalesced=True)


def to_dense(x):
    return x.to_dense()


# ----------------------------------------------------- unary (on values)

def _unary(name, fn):
    def op(x, name_=None):
        out_vals = apply_jfn(f"sparse_{name}", fn, x.values_)
        return SparseCooTensor(x.indices_, out_vals, x.shape,
                               x._coalesced)

    op.__name__ = name
    return op


sin = _unary("sin", jnp.sin)
tan = _unary("tan", jnp.tan)
asin = _unary("asin", jnp.arcsin)
atan = _unary("atan", jnp.arctan)
sinh = _unary("sinh", jnp.sinh)
tanh = _unary("tanh", jnp.tanh)
asinh = _unary("asinh", jnp.arcsinh)
atanh = _unary("atanh", jnp.arctanh)
sqrt = _unary("sqrt", jnp.sqrt)
square = _unary("square", jnp.square)
log1p = _unary("log1p", jnp.log1p)
abs = _unary("abs", jnp.abs)
neg = _unary("neg", jnp.negative)
expm1 = _unary("expm1", jnp.expm1)
deg2rad = _unary("deg2rad", jnp.deg2rad)
rad2deg = _unary("rad2deg", jnp.rad2deg)


def pow(x, factor, name=None):
    out_vals = apply_jfn("sparse_pow", lambda v: jnp.power(v, factor),
                         x.values_)
    return SparseCooTensor(x.indices_, out_vals, x.shape, x._coalesced)


def cast(x, index_dtype=None, value_dtype=None, name=None):
    vals = x.values_
    if value_dtype is not None:
        from ..ops.manipulation import cast as dense_cast

        vals = dense_cast(vals, value_dtype)
    idx = x.indices_
    if index_dtype is not None:
        from ..ops.manipulation import cast as dense_cast

        idx = dense_cast(idx, index_dtype)
    return SparseCooTensor(idx, vals, x.shape, x._coalesced)


# ------------------------------------------------------------ binary

def _ewise(name, fn, same_pattern_only=False):
    def op(x, y, name_=None):
        if is_sparse(x) and is_sparse(y):
            xi = np.asarray(value_of(x.indices_))
            yi = np.asarray(value_of(y.indices_))
            if xi.shape == yi.shape and (xi == yi).all():
                # same pattern: elementwise on values, tape-differentiable
                out = apply_jfn(f"sparse_{name}", fn, x.values_, y.values_)
                return SparseCooTensor(x.indices_, out, x.shape)
            if same_pattern_only:
                # e.g. divide: implicit zeros would produce inf/nan
                raise ValueError(
                    f"sparse.{name} requires matching sparsity patterns "
                    "(an implicit zero makes the result undefined)")
            # mismatched patterns: merge via dense (sparse-sparse union
            # has data-dependent nnz — not a jit-able shape on TPU)
            dense = apply_jfn(f"sparse_{name}", fn, x.to_dense(),
                              y.to_dense())
            return _dense_to_coo(dense)
        raise TypeError(f"sparse.{name} expects two sparse tensors")

    op.__name__ = name
    return op


def _dense_to_coo(dense):
    """Dense Tensor → COO. The index pattern comes from the host values
    (stop-grad), but the VALUES are a tape gather from the dense input,
    so gradients keep flowing."""
    v = np.asarray(value_of(dense))
    idx = np.stack(np.nonzero(v)) if v.any() else \
        np.zeros((v.ndim, 0), np.int64)
    idx_tuple = tuple(jnp.asarray(row) for row in idx)
    vals = apply_jfn("sparse_gather_coo", lambda d: d[idx_tuple], dense)
    return SparseCooTensor(idx, vals, list(v.shape))


add = _ewise("add", jnp.add)
subtract = _ewise("subtract", jnp.subtract)
multiply = _ewise("multiply", jnp.multiply)
divide = _ewise("divide", jnp.divide, same_pattern_only=True)


# ------------------------------------------------------------ matmul

def matmul(x, y, name=None):
    """sparse @ dense → dense (reference sparse matmul; lowers to
    bcoo_dot_general = gather + MXU dot)."""
    if not is_sparse(x):
        raise TypeError("sparse.matmul expects a sparse lhs")
    y = ensure_tensor(y)
    idx_t = x.indices_
    shape = tuple(x.shape)

    def jfn(v, d):
        idx = jnp.swapaxes(value_of(idx_t), 0, 1)
        return jsparse.BCOO((v, idx), shape=shape) @ d

    return apply_jfn("sparse_matmul", jfn, x.values_, y)


def mv(x, vec, name=None):
    return matmul(x, vec)


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    from ..ops.math import add as dense_add

    return dense_add(ensure_tensor(input) * beta, matmul(x, y) * alpha)


def masked_matmul(x, y, mask, name=None):
    """(x @ y) sampled at mask's sparsity (reference masked_matmul /
    SDDMM). x, y dense; mask sparse: computes only the nnz entries."""
    x = ensure_tensor(x)
    y = ensure_tensor(y)
    idx_t = mask.indices_

    def jfn(xv, yv):
        idx = value_of(idx_t)
        rows, cols = idx[0], idx[1]
        return (xv[rows] * jnp.swapaxes(yv, 0, 1)[cols]).sum(-1)

    vals = apply_jfn("sparse_masked_matmul", jfn, x, y)
    return SparseCooTensor(mask.indices_, vals, mask.shape)


relu = _unary("relu", lambda v: jnp.maximum(v, 0))


def softmax(x, axis=-1, name=None):
    """Row-wise softmax over stored values of a 2-D sparse matrix
    (reference: incubate/sparse/nn/functional/activation.py softmax —
    only the nnz entries participate, matching the CSR kernel)."""
    import jax

    if axis not in (-1, 1):
        raise ValueError("sparse softmax supports the last axis only")
    rows = value_of(x.indices_)[0].astype(jnp.int32)
    n_rows = int(x.shape[0])

    def jfn(v):
        rowmax = jax.ops.segment_max(v, rows, num_segments=n_rows)
        rowmax = jnp.where(jnp.isfinite(rowmax), rowmax, 0.0)
        e = jnp.exp(v - rowmax[rows])
        denom = jax.ops.segment_sum(e, rows, num_segments=n_rows)
        return e / denom[rows]

    out_vals = apply_jfn("sparse_softmax", jfn, x.values_)
    return SparseCooTensor(x.indices_, out_vals, x.shape, x._coalesced)


def is_same_shape(x, y):
    """Shape equality across sparse/dense operands
    (reference: incubate/sparse/binary.py is_same_shape)."""
    return list(x.shape) == list(y.shape)


from . import nn  # noqa: E402,F401

__all__ += ["relu", "softmax", "is_same_shape", "nn"]
