"""paddle.sparse.nn namespace (reference: python/paddle/incubate/sparse/nn):
activation layers operating on sparse tensors (values-wise)."""
from ..nn.layer.layers import Layer
from . import relu as _relu_fn
from . import SparseCooTensor

__all__ = ["ReLU", "Softmax"]


class ReLU(Layer):
    def __init__(self, name=None):
        super().__init__()

    def forward(self, x):
        return _relu_fn(x)


class Softmax(Layer):
    """Softmax over the last dense axis of a sparse CSR/COO matrix's rows
    (reference: incubate/sparse/nn/layer/activation.py Softmax): computed
    over the stored values per row."""

    def __init__(self, axis=-1, name=None):
        super().__init__()

    def forward(self, x):
        from . import softmax as _softmax_fn

        return _softmax_fn(x)
