"""Quantized RUNTIME — int8 as a throughput format, not a file format.

The QAT/PTQ stack in `paddle_tpu.quantization` trains and calibrates
models INTO int8; this module is the other half: running the live system
ON int8 where the bits buy bandwidth (the MXU has a native int8 path and
every serving byte is HBM- or wire-bound):

* **Int8 weight-only serving** (`quantize_model_int8`): every Linear in
  a loaded model is swapped for `Int8WeightOnlyLinear` — per-channel
  int8 weights held as BUFFERS (so `state_dict()` carries them and the
  engine's compiled decode executable threads int8 weight buffers as jit
  arguments), activations quantized dynamically per row inside the op,
  and the matmul runs `lax.dot_general(..., preferred_element_type=
  int32)` with the dequant folded into the epilogue. No calibration
  pass: weight-only + dynamic activation scales is calibration-free.

* **Int8 KV-cache codecs** (`quantize_kv_rows` / `dequantize_kv`): the
  per-(token, head) absmax quantization used by the paged KV pool
  (inference/llm_engine.py `kv_dtype="int8"`): each written row carries
  its own scale, so incremental page writes never re-scale earlier
  tokens (scales live in page-shaped planes alongside the pool).

* **Int8 wire codec** (`encode_int8_wire` / `decode_int8_wire`): the
  EQuARX-style (PAPERS.md) all-reduce/p2p payload format — per-block
  absmax scales + int8 payload, ~4× fewer bytes than fp32. Opt-in via
  `PT_QUANT_ALLREDUCE=1`; distributed/xproc.py applies it to the
  coordination-KV collective fallback and the socket p2p transport.

Env knobs (docs/QUANTIZATION.md):
  PT_KV_DTYPE        default kv-cache dtype for LLMEngine
                     (float32 | bfloat16 | int8; unset = model dtype)
  PT_QUANT_ALLREDUCE 1 = int8-with-scale wire codec for eager
                     collectives / float p2p payloads
"""
import os
import struct

import numpy as np

import jax.numpy as jnp
from jax import lax

from .. import nn
from ..ops._helpers import apply_jfn, ensure_tensor

__all__ = [
    "Int8WeightOnlyLinear", "Int4WeightOnlyLinear", "quantize_model_int8",
    "quantize_model_int4", "resolve_kv_dtype", "kv_scale_shape",
    "quantize_kv_rows", "dequantize_kv", "pack_int4", "unpack_int4",
    "quantize_kv_rows_int4", "dequantize_kv_int4",
    "quant_allreduce_enabled", "wire_eligible", "encode_int8_wire",
    "decode_int8_wire", "WIRE_MAGIC",
]

QMAX = 127.0
QMAX4 = 7.0


# ------------------------------------------------------------- int4 pack

def pack_int4(codes, axis=0):
    """int8 codes in [-8, 7] → packed bytes, HALF the size along `axis`
    (which must be even-sized). Split-halves layout: byte j holds code
    j (low nibble) and code j + size/2 (high nibble), so unpacking is a
    cheap CONCATENATE of the two de-nibbled halves — never an
    interleave reshape (the Pallas paged-attention kernel unpacks in
    VMEM, where a lane-dim interleave would not lower)."""
    codes = jnp.asarray(codes)
    n = codes.shape[axis]
    if n % 2:
        raise ValueError(f"pack_int4: axis {axis} size {n} is odd")
    lo, hi = jnp.split(codes, 2, axis=axis)
    lo_u = lo.astype(jnp.uint8) & jnp.uint8(0x0F)
    hi_u = (hi.astype(jnp.uint8) & jnp.uint8(0x0F)) << 4
    return (lo_u | hi_u).astype(jnp.int8)


def unpack_int4(packed, axis=0):
    """Inverse of `pack_int4`: packed int8 bytes → sign-extended int8
    codes, double the size along `axis`. Pure shift/mask arithmetic in
    int32 (the `(x ^ 8) - 8` sign-extension), so it lowers identically
    under XLA and inside Pallas kernels."""
    p = jnp.asarray(packed).astype(jnp.int32) & 0xFF
    lo = (((p & 0xF) ^ 8) - 8).astype(jnp.int8)
    hi = ((((p >> 4) & 0xF) ^ 8) - 8).astype(jnp.int8)
    return jnp.concatenate([lo, hi], axis=axis)


# ---------------------------------------------------------------- weights

class Int8WeightOnlyLinear(nn.Layer):
    """Serving-time Linear over per-channel int8 weights.

    Built from an existing (fp) linear layer at model-load time. The
    int8 weight and its per-out-channel dequant step are registered as
    persistable BUFFERS — they appear in `state_dict()`, so compiled
    steps that thread `state_dict().values()` as jit arguments (the
    `_CompiledPagedStep` / TrainStep pattern) carry int8 buffers in the
    executable instead of fp32 weights. The fp weight is dropped.

    Forward = dynamic per-row activation quant → int8×int8 matmul with
    int32 accumulation (`preferred_element_type` — the MXU-native path)
    → dequant in the epilogue by (activation step × weight step).
    Inference-only: serving runs under no_grad; there is no fake-quant
    STE here (that is the QAT stack's job)."""

    def __init__(self, linear, post_shard=None):
        super().__init__()
        from . import quantize_weight_int8
        from ..tensor_core import Tensor

        w = linear.weight  # [in, out] (paddle layout)
        q, scale = quantize_weight_int8(w, axis=1)  # scale [1, out]
        self.in_features = int(w.shape[0])
        self.out_features = int(w.shape[1])
        self.register_buffer("weight_q", Tensor(jnp.asarray(q)))
        self.register_buffer("w_step", Tensor(
            jnp.asarray(np.asarray(scale, np.float32) / QMAX)))
        self.bias = getattr(linear, "bias", None)
        # activation-layout epilogue of the layer this wrapper replaced
        # (Column/RowParallelLinear apply a shard_activation hint);
        # identity off-mesh
        self._post_shard = post_shard

    def forward(self, x):
        x = ensure_tensor(x)

        def jfn(v, wq, wstep, *b):
            f = v.astype(jnp.float32)
            a_step = jnp.maximum(
                jnp.max(jnp.abs(f), axis=-1, keepdims=True), 1e-8) / QMAX
            qv = jnp.clip(jnp.round(f / a_step), -QMAX, QMAX).astype(
                jnp.int8)
            acc = lax.dot_general(
                qv, wq, (((f.ndim - 1,), (0,)), ((), ())),
                preferred_element_type=jnp.int32)
            out = acc.astype(jnp.float32) * a_step * wstep
            if b:
                out = out + b[0].astype(jnp.float32)
            return out.astype(v.dtype)

        args = (x, self.weight_q, self.w_step)
        if self.bias is not None:
            args = args + (self.bias,)
        out = apply_jfn("int8_weight_only_matmul", jfn, *args)
        if self._post_shard is not None:
            out = self._post_shard(out)
        return out

    def extra_repr(self):
        return (f"in={self.in_features}, out={self.out_features}, "
                f"weight=int8 per-channel")


class Int4WeightOnlyLinear(nn.Layer):
    """Serving-time Linear over per-channel PACKED int4 weights — the
    lower-bit sibling of `Int8WeightOnlyLinear` (half the weight bytes
    again: two nibbles per byte along the in-dim, split-halves layout).

    At 4 bits (15 levels) plain absmax wastes most of the grid on one
    outlier, so the MSE clip search (`quantize_weight_int8(bits=4,
    search_mse=True)` — documented in PR 4 as "the knob that matters at
    int4") is ALWAYS on. Forward: unpack nibbles → sign-extended int8
    codes → the same dynamic per-row activation quant →
    `dot_general(int8, int8, preferred_element_type=int32)` → dequant
    epilogue. The unpack is shift/mask arithmetic the compiler fuses
    into the matmul's operand read; HBM (and `state_dict()` /
    checkpoint bytes) stay packed.

    in_features must be even (nibble pairing); `quantize_model_int4`
    leaves odd layers unquantized. TP note: the packed in-dim interleaves
    rows j and j+in/2 into one byte, so row/column mesh sharding of the
    packed buffer would split activation rows non-contiguously — int4
    buffers stay REPLICATED (use int8 for TP-sharded weight-stationary
    serving)."""

    def __init__(self, linear, post_shard=None):
        super().__init__()
        from . import quantize_weight_int8
        from ..tensor_core import Tensor

        w = linear.weight  # [in, out] (paddle layout)
        self.in_features = int(w.shape[0])
        self.out_features = int(w.shape[1])
        if self.in_features % 2:
            raise ValueError(
                f"Int4WeightOnlyLinear: in_features "
                f"{self.in_features} is odd — nibble packing pairs "
                "in-dim rows (quantize_model_int4 skips such layers)")
        q, scale = quantize_weight_int8(w, axis=1, bits=4,
                                        search_mse=True)  # scale [1, out]
        self.register_buffer("weight_q",
                             Tensor(pack_int4(jnp.asarray(q), axis=0)))
        self.register_buffer("w_step", Tensor(
            jnp.asarray(np.asarray(scale, np.float32) / QMAX4)))
        self.bias = getattr(linear, "bias", None)
        self._post_shard = post_shard

    def forward(self, x):
        x = ensure_tensor(x)

        def jfn(v, wq_packed, wstep, *b):
            wq = unpack_int4(wq_packed, axis=0)     # [in, out] int8
            f = v.astype(jnp.float32)
            a_step = jnp.maximum(
                jnp.max(jnp.abs(f), axis=-1, keepdims=True), 1e-8) / QMAX
            qv = jnp.clip(jnp.round(f / a_step), -QMAX, QMAX).astype(
                jnp.int8)
            acc = lax.dot_general(
                qv, wq, (((f.ndim - 1,), (0,)), ((), ())),
                preferred_element_type=jnp.int32)
            out = acc.astype(jnp.float32) * a_step * wstep
            if b:
                out = out + b[0].astype(jnp.float32)
            return out.astype(v.dtype)

        args = (x, self.weight_q, self.w_step)
        if self.bias is not None:
            args = args + (self.bias,)
        out = apply_jfn("int4_weight_only_matmul", jfn, *args)
        if self._post_shard is not None:
            out = self._post_shard(out)
        return out

    def extra_repr(self):
        return (f"in={self.in_features}, out={self.out_features}, "
                f"weight=int4 packed per-channel (MSE clip)")


def _linear_classes():
    from .. import nn
    from ..distributed.fleet.meta_parallel.mp_layers import (
        ColumnParallelLinear, RowParallelLinear)

    return nn.Linear, ColumnParallelLinear, RowParallelLinear


def _post_shard_for(sub):
    """Reproduce the activation-sharding epilogue of the parallel-linear
    classes so a quantized model keeps the same layout hints on a mesh
    (all of them collapse to the identity off-mesh)."""
    from ..distributed.fleet.meta_parallel.mp_layers import (
        ColumnParallelLinear, shard_activation)

    if isinstance(sub, ColumnParallelLinear) and not sub.gather_output:
        return lambda out: shard_activation(
            out, *(["dp"] + [None] * (out.ndim - 2) + ["mp"]))
    return lambda out: shard_activation(
        out, *(["dp"] + [None] * (out.ndim - 1)))


def quantize_model_int8(model, skip=(), tp_shard=True):
    """Swap every Linear-family sublayer for `Int8WeightOnlyLinear`,
    in place, at model-load time. Embeddings (and the tied vocab head
    that reads the embedding weight) stay in the float dtype — the
    gather needs the float table anyway and the head wants full logit
    precision.

    skip: attribute-name substrings to leave unquantized
    (e.g. ``skip=("lm_head",)``).
    tp_shard: on a mesh with 'mp' > 1, shard the int8 weight + scale
    buffers over the tp axis (weight-stationary: ColumnParallelLinear
    ancestry → column placement, RowParallelLinear → row, plain Linear
    → whichever dim divides; distributed.hybrid3d.tp rules). False
    keeps the buffers replicated.

    Returns a report dict: layers swapped, fp bytes before, int8 bytes
    after (weights only), and — when sharding applied — a
    ``tp_placements`` {path: 'column'|'row'|None} map.
    """
    from . import QuantizedLinear
    from ..distributed import mesh as mesh_mod
    from ..distributed.fleet.meta_parallel.mp_layers import (
        ColumnParallelLinear, RowParallelLinear)

    linear_types = _linear_classes()
    report = {"layers": 0, "weight_bytes_fp": 0, "weight_bytes_int8": 0}
    swapped = []  # (path, wrapped, tp kind)

    def swap(layer, prefix=""):
        for name, sub in list(layer.named_children()):
            path = f"{prefix}.{name}" if prefix else name
            if isinstance(sub, (Int8WeightOnlyLinear, Int4WeightOnlyLinear,
                                QuantizedLinear)):
                continue  # already quantized (runtime or QAT stack)
            if isinstance(sub, linear_types) and not any(
                    s in path for s in skip):
                w = sub.weight._value
                wrapped = Int8WeightOnlyLinear(
                    sub, post_shard=_post_shard_for(sub))
                report["layers"] += 1
                report["weight_bytes_fp"] += int(
                    w.size * w.dtype.itemsize)
                report["weight_bytes_int8"] += int(
                    wrapped.weight_q._value.nbytes
                    + wrapped.w_step._value.nbytes)
                kind = "auto"
                if isinstance(sub, ColumnParallelLinear):
                    kind = "column"
                elif isinstance(sub, RowParallelLinear):
                    kind = "row"
                swapped.append((path, wrapped, kind))
                setattr(layer, name, wrapped)
            else:
                swap(sub, path)

    swap(model)
    if tp_shard and mesh_mod.axis_size("mp") > 1:
        from ..distributed.hybrid3d.tp import shard_int8_linear

        placements = {}
        for path, wrapped, kind in swapped:
            placements[path] = shard_int8_linear(wrapped, kind)
        report["tp_placements"] = placements
    model.eval()
    return report


def quantize_model_int4(model, skip=()):
    """`quantize_model_int8`'s packed-int4 sibling: swap every
    Linear-family sublayer for `Int4WeightOnlyLinear` in place (MSE
    clip search per out-channel — load-bearing at 4 bits). Layers with
    an ODD in_features cannot nibble-pair and are left unquantized
    (counted in the report as `skipped_odd`). Buffers stay REPLICATED
    on a mesh (see the class TP note); embeddings/tied head stay float
    as in the int8 path.

    Returns {layers, skipped_odd, weight_bytes_fp, weight_bytes_int4}.
    """
    from . import QuantizedLinear

    linear_types = _linear_classes()
    report = {"layers": 0, "skipped_odd": 0,
              "weight_bytes_fp": 0, "weight_bytes_int4": 0}

    def swap(layer, prefix=""):
        for name, sub in list(layer.named_children()):
            path = f"{prefix}.{name}" if prefix else name
            if isinstance(sub, (Int4WeightOnlyLinear,
                                Int8WeightOnlyLinear, QuantizedLinear)):
                continue
            if isinstance(sub, linear_types) and not any(
                    s in path for s in skip):
                w = sub.weight._value
                if int(w.shape[0]) % 2:
                    report["skipped_odd"] += 1
                    continue
                wrapped = Int4WeightOnlyLinear(
                    sub, post_shard=_post_shard_for(sub))
                report["layers"] += 1
                report["weight_bytes_fp"] += int(
                    w.size * w.dtype.itemsize)
                report["weight_bytes_int4"] += int(
                    wrapped.weight_q._value.nbytes
                    + wrapped.w_step._value.nbytes)
                setattr(layer, name, wrapped)
            else:
                swap(sub, path)

    swap(model)
    model.eval()
    return report


# ---------------------------------------------------------------- kv cache

_KV_DTYPES = {
    "float32": jnp.float32, "fp32": jnp.float32,
    "bfloat16": jnp.bfloat16, "bf16": jnp.bfloat16,
    "int8": jnp.int8,
}


def resolve_kv_dtype(requested, compute_dtype):
    """(requested | $PT_KV_DTYPE | model compute dtype) → (storage jnp
    dtype, quantized bits). `requested` may be a string name or a
    dtype. `bits` is 0 for float pools, 8 for int8, 4 for packed int4
    (storage dtype int8, head_dim HALVED in the pool — two nibbles per
    byte; truthiness keeps every existing `if quantized:` site
    working)."""
    req = requested
    if req is None:
        req = os.environ.get("PT_KV_DTYPE", "").strip() or None
    if req is None:
        dt = jnp.dtype(compute_dtype)
        return dt, 0
    if isinstance(req, str):
        key = req.lower()
        if key in ("int4", "i4"):
            return jnp.dtype(jnp.int8), 4
        if key not in _KV_DTYPES:
            raise ValueError(
                f"unknown kv_dtype {req!r}: expected one of "
                f"{sorted(set(_KV_DTYPES) | {'int4'})}")
        dt = jnp.dtype(_KV_DTYPES[key])
    else:
        dt = jnp.dtype(req)
    return dt, 8 if dt == jnp.dtype(jnp.int8) else 0


def kv_scale_shape(num_pages, page_size, num_heads):
    """Shape of the per-page scale plane stored alongside an int8 pool:
    one fp32 scale per (page, row, head) — each written token row is
    quantized ONCE with its own scale, so incremental page writes never
    invalidate earlier rows (a single per-page scalar would)."""
    return (num_pages, page_size, num_heads)


def quantize_kv_rows(x):
    """[T, H, D] float → (int8 values [T, H, D], fp32 scales [T, H]).

    Per-(token, head) absmax: dequant error ≤ absmax/254 per element,
    and the scale plane costs 4/D of the int8 payload (~6% at D=64)."""
    f = x.astype(jnp.float32)
    scale = jnp.maximum(jnp.max(jnp.abs(f), axis=-1), 1e-8) / QMAX
    q = jnp.clip(jnp.round(f / scale[..., None]), -QMAX, QMAX).astype(
        jnp.int8)
    return q, scale


def dequantize_kv(q, scale):
    """Inverse of `quantize_kv_rows` (broadcasts a trailing dim onto
    the scales)."""
    return q.astype(jnp.float32) * scale[..., None]


def quantize_kv_rows_int4(x):
    """[T, H, D] float → (packed int4 values [T, H, D/2], fp32 scales
    [T, H]). Per-(token, head) absmax against qmax 7 (15 levels);
    dequant error ≤ absmax/14 per element — measurably coarser than
    int8, which is why the engine acceptance pins greedy token-match
    ≥ 0.95 rather than int8's 0.98. Packed split-halves along head_dim
    (`pack_int4`), so the pool's last dim is D/2 and the existing
    per-row scale planes carry the dequant exactly as for int8."""
    f = x.astype(jnp.float32)
    scale = jnp.maximum(jnp.max(jnp.abs(f), axis=-1), 1e-8) / QMAX4
    q = jnp.clip(jnp.round(f / scale[..., None]), -QMAX4, QMAX4).astype(
        jnp.int8)
    return pack_int4(q, axis=-1), scale


def dequantize_kv_int4(packed, scale):
    """Inverse of `quantize_kv_rows_int4` → [T, H, D] float32."""
    return unpack_int4(packed, axis=-1).astype(jnp.float32) \
        * scale[..., None]


# ---------------------------------------------------------------- wire

WIRE_MAGIC = b"PTQ8"
_WIRE_VERSION = 1
_WIRE_DTYPES = {0: np.float32, 1: np.float64}
_WIRE_CODES = {np.dtype(np.float32): 0, np.dtype(np.float64): 1}
_WIRE_HDR = struct.Struct("<4sBBHIQ")  # magic, ver, dtype, ndim, block, size


def quant_allreduce_enabled():
    return os.environ.get("PT_QUANT_ALLREDUCE", "0").strip().lower() in (
        "1", "true", "yes", "on")


def wire_eligible(arr, min_size=512):
    """Only fp32/fp64 payloads above a size floor ride the codec: tiny
    arrays (barriers, scalar telemetry) would pay header overhead for
    nothing, and int/bool payloads (ids, tokens) must stay exact.

    Deliberately DATA-INDEPENDENT (dtype + size only): inside a
    collective every rank must take the same encode path, and a
    value-dependent probe (e.g. isfinite) would let one rank's NaN grad
    publish a raw frame while its peers publish PTQ8 frames — a
    mixed-format crash mid-collective. Non-finite values are instead
    handled inside `encode_int8_wire`: they decode back as NaN blocks,
    so the NaN signal survives for downstream grad guards on every rank
    identically. Also keeps eligibility O(1) on the DP-sync hot path."""
    return arr.dtype in (np.float32, np.float64) and arr.size >= min_size


def encode_int8_wire(arr, block=2048):
    """float array → self-describing int8-with-scale frame.

    Layout: header | shape (u32 each) | per-block fp32 scales | int8
    payload. Scales are per-`block`-element absmax/127, so the relative
    error is bounded by each block's own dynamic range — the property
    that makes a quantized GRADIENT all-reduce converge (EQuARX): big
    layers can't crush small layers' scale. ~4× smaller than fp32."""
    a = np.ascontiguousarray(arr)
    code = _WIRE_CODES[np.dtype(a.dtype)]
    flat = a.reshape(-1).astype(np.float32)
    n = flat.size
    nblocks = -(-n // block) if n else 0
    pad = nblocks * block - n
    padded = np.pad(flat, (0, pad)).reshape(nblocks, block)
    # a non-finite value makes its block's scale NaN/inf, which decodes
    # the WHOLE block to NaN — the poison signal survives the wire for
    # every rank identically (see wire_eligible: eligibility must stay
    # data-independent, so crashing here is not an option either)
    scales = np.maximum(np.abs(padded).max(axis=1), 1e-12) / QMAX
    with np.errstate(invalid="ignore", over="ignore"):
        ratio = np.nan_to_num(padded / scales[:, None],
                              nan=0.0, posinf=QMAX, neginf=-QMAX)
    q = np.clip(np.round(ratio), -QMAX, QMAX).astype(np.int8)
    head = _WIRE_HDR.pack(WIRE_MAGIC, _WIRE_VERSION, code, a.ndim,
                          block, n)
    shape = np.asarray(a.shape, np.uint32).tobytes()
    return head + shape + scales.astype(np.float32).tobytes() + \
        q.reshape(-1)[:n].tobytes()


def decode_int8_wire(buf):
    """Inverse of `encode_int8_wire` → np array in the original float
    dtype."""
    magic, ver, code, ndim, block, n = _WIRE_HDR.unpack_from(buf, 0)
    if magic != WIRE_MAGIC or ver != _WIRE_VERSION:
        raise ValueError("not a PTQ8 int8 wire frame")
    off = _WIRE_HDR.size
    shape = tuple(np.frombuffer(buf, np.uint32, ndim, off))
    off += 4 * ndim
    nblocks = -(-n // block) if n else 0
    scales = np.frombuffer(buf, np.float32, nblocks, off)
    off += 4 * nblocks
    q = np.frombuffer(buf, np.int8, n, off).astype(np.float32)
    pad = nblocks * block - n
    with np.errstate(invalid="ignore"):  # poison blocks: 0 × inf → NaN
        vals = (np.pad(q, (0, pad)).reshape(nblocks, block)
                * scales[:, None]).reshape(-1)[:n]
    return vals.astype(_WIRE_DTYPES[code]).reshape(shape)


def is_quant_wire(buf):
    return bytes(buf[:4]) == WIRE_MAGIC
