"""paddle_tpu.quantization — QAT + post-training quantization.

TPU-native re-design of the reference slim quantization stack
(reference: python/paddle/fluid/contrib/slim/quantization/
imperative/qat.py ImperativeQuantAware:80, post_training_quantization.py
PostTrainingQuantization:122, fake-quant ops
paddle/fluid/operators/fake_quantize_op.cc).

- `fake_quant(x, scale, bits)`: symmetric quant-dequant with a
  straight-through estimator, written as the jit-friendly identity
  `x + stop_grad(qdq(x) − x)` — no custom VJP registration needed
  (reference FakeQuantizeMovingAverageAbsMax kernel + its STE grad).
- `QuantizedLinear`: weights fake-quantized per-channel, activations by
  a moving-average absmax observer — the QAT compute pattern.
- `ImperativeQuantAware.quantize(model)`: swaps Linear sublayers
  in-place, `convert` freezes observers.
- `PostTrainingQuantization`: calibrates observers over sample data and
  returns a model whose Linears run a REAL int8×int8→int32 matmul
  (`lax.dot_general` with preferred_element_type) and rescale — the MXU
  has a native int8 path, so PTQ here is a throughput feature, not just
  a file-size one.
"""
import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from .. import nn
from ..ops._helpers import apply_jfn, ensure_tensor, value_of
from ..tensor_core import Tensor

__all__ = ["fake_quant", "QuantizedLinear", "ImperativeQuantAware",
           "PostTrainingQuantization", "quantize_weight_int8", "runtime"]


def fake_quant(x, scale, bits=8, name=None):
    """Symmetric quant-dequant with STE gradient."""
    qmax = float(2 ** (bits - 1) - 1)
    s = value_of(ensure_tensor(scale))

    def jfn(v):
        sc = jnp.maximum(s, 1e-8) / qmax
        q = jnp.clip(jnp.round(v / sc), -qmax, qmax) * sc
        return v + lax.stop_gradient(q - v)

    return apply_jfn("fake_quantize_dequantize", jfn, x)


def _search_scale_mse(vals, absmax, bits=8, fracs=None):
    """Scalar absmax refinement: pick the clip scale minimizing
    quant-dequant MSE over `vals`. Anchored at the TRUE absmax (f=1.0
    is in the sweep, so the result can never be worse than absmax —
    and at 8 bits it usually IS absmax: clipping a real outlier costs
    more squared error than the finer grid buys). The wide log-spaced
    range is for lower bit widths, where clipping starts to pay."""
    qmax = float(2 ** (bits - 1) - 1)
    if fracs is None:
        fracs = np.geomspace(0.05, 1.0, 40)
    vals = np.asarray(vals, np.float64).reshape(-1)
    best_s, best_e = float(absmax), np.inf
    for f in fracs:
        s = max(float(absmax) * float(f), 1e-8)
        step = s / qmax
        qd = np.clip(np.round(vals / step), -qmax, qmax) * step
        e = float(np.mean((qd - vals) ** 2))
        if e < best_e:
            best_e, best_s = e, s
    return best_s


def _search_scale_mse_per_channel(wv, scale0, red, bits=8, fracs=None):
    """Vectorized per-channel variant of `_search_scale_mse`: one MSE
    sweep over clip fractions, argmin kept independently per channel."""
    qmax = float(2 ** (bits - 1) - 1)
    if fracs is None:
        fracs = np.geomspace(0.05, 1.0, 40)
    best_s = np.asarray(scale0, np.float64).copy()
    best_e = np.full(best_s.shape, np.inf)
    w64 = np.asarray(wv, np.float64)
    for f in fracs:
        s = np.maximum(scale0 * float(f), 1e-8)
        step = s / qmax
        qd = np.clip(np.round(w64 / step), -qmax, qmax) * step
        e = ((qd - w64) ** 2).mean(axis=red, keepdims=True)
        sel = e < best_e
        best_e = np.where(sel, e, best_e)
        best_s = np.where(sel, s, best_s)
    return best_s


def quantize_weight_int8(w, axis=None, search_mse=False, bits=8):
    """→ (int8 array of [-qmax, qmax] codes, float32 scale —
    per-channel ndarray (keepdims shape) when `axis` is given,
    np.float32 scalar otherwise).

    search_mse=True refines each scale by the MSE clip search instead
    of plain absmax (what `QuantizedLinear.freeze` uses). `bits` sets
    the code width (qmax = 2^(bits-1) − 1): at 8 bits the search
    nearly always lands on absmax (the never-worse safety net); at 4
    bits (15 levels) clipping real outliers buys grid resolution and
    the search becomes LOAD-BEARING — `runtime.Int4WeightOnlyLinear`
    always runs it."""
    qmax = float(2 ** (bits - 1) - 1)
    wv = np.asarray(value_of(ensure_tensor(w)))
    if axis is None:
        scale = np.abs(wv).max() or 1e-8
        if search_mse:
            scale = _search_scale_mse(wv, scale, bits=bits)
        q = np.clip(np.round(wv / scale * qmax), -qmax, qmax).astype(
            np.int8)
        return q, np.float32(scale)
    red = tuple(d for d in range(wv.ndim) if d != axis)
    scale = np.maximum(np.abs(wv).max(axis=red, keepdims=True), 1e-8)
    if search_mse:
        scale = _search_scale_mse_per_channel(wv, scale, red, bits=bits)
    q = np.clip(np.round(wv / scale * qmax), -qmax, qmax).astype(np.int8)
    # the per-channel keepdims shape must SURVIVE: np.float32(arr)
    # collapses size-1 arrays to a 0-d scalar on older numpy, silently
    # turning per-channel dequant into per-tensor (regression-tested)
    return q, np.asarray(scale, dtype=np.float32)


class _AbsMaxObserver:
    """Moving-average absmax (reference
    FakeQuantizeMovingAverageAbsMax), plus the TRUE absmax and a
    bounded |x| sample buffer: the decayed average UNDERESTIMATES the
    range whenever calibration batches vary (silent clipping at freeze
    — the old tier-1 PTQ failure), so freeze-time scales anchor at the
    real absmax and MSE-refine over what calibration actually saw."""

    _PER_UPDATE = 2048
    _CAP = 32768

    def __init__(self, momentum=0.9):
        self.momentum = momentum
        self.scale = None
        self.absmax = 0.0
        self._samples = []
        self._kept = 0

    def update(self, v):
        cur = float(jnp.abs(v).max())
        self.absmax = max(self.absmax, cur)
        self.scale = cur if self.scale is None else (
            self.momentum * self.scale + (1 - self.momentum) * cur)
        if self._kept < self._CAP:
            a = np.abs(np.asarray(v)).reshape(-1)
            if a.size > self._PER_UPDATE:  # deterministic stride thinning
                a = a[:: -(-a.size // self._PER_UPDATE)]
            self._samples.append(a.astype(np.float32))
            self._kept += a.size
        return self.scale

    def searched_scale(self, bits=8):
        """MSE-searched clip scale over the calibration samples; falls
        back to the moving-average scale when nothing was retained."""
        if not self._samples:
            return self.scale
        vals = np.concatenate(self._samples)
        return _search_scale_mse(vals, max(self.absmax, 1e-8), bits=bits)


class QuantizedLinear(nn.Layer):
    """QAT Linear: per-channel weight fake-quant + activation observer
    fake-quant. After `freeze()` (or via PostTrainingQuantization) the
    forward switches to a true int8 matmul."""

    def __init__(self, linear, bits=8, act_momentum=0.9):
        super().__init__()
        self.inner = linear
        self.bits = bits
        self.observer = _AbsMaxObserver(act_momentum)
        self._frozen = False
        self._wq = None

    def forward(self, x):
        x = ensure_tensor(x)
        if self._frozen:
            return self._int8_forward(x)
        if self.training:
            self.observer.update(value_of(x))
        a_scale = self.observer.scale or float(
            jnp.abs(value_of(x)).max())
        xq = fake_quant(x, a_scale, self.bits)
        w = self.inner.weight
        w_scale = jnp.abs(value_of(w)).max(axis=0)  # per-out-channel
        wq = fake_quant(w, w_scale, self.bits)
        out = xq @ wq
        if self.inner.bias is not None:
            out = out + self.inner.bias
        return out

    def freeze(self):
        """Bake int8 weights; forward becomes int8×int8→int32·scale."""
        if self.observer.scale is None:
            raise RuntimeError(
                "QuantizedLinear.freeze(): the activation observer was "
                "never updated — run calibration (train-mode forwards or "
                "PostTrainingQuantization.calibrate) before freezing")
        q, w_scale = quantize_weight_int8(self.inner.weight, axis=1,
                                          search_mse=True)
        self._wq = jnp.asarray(q)
        self._w_scale = jnp.asarray(w_scale / 127.0)  # [1, out]
        self._a_scale = jnp.float32(
            self.observer.searched_scale(self.bits) / 127.0)
        self._frozen = True
        return self

    def _int8_forward(self, x):
        wq, w_s, a_s = self._wq, self._w_scale, self._a_scale
        bias = self.inner.bias

        def jfn(v, *b):
            q = jnp.clip(jnp.round(v / a_s), -127, 127).astype(jnp.int8)
            acc = lax.dot_general(
                q, wq, (((q.ndim - 1,), (0,)), ((), ())),
                preferred_element_type=jnp.int32)
            out = acc.astype(jnp.float32) * (a_s * w_s)
            if b:
                out = out + b[0]
            return out.astype(v.dtype)

        args = (x,) + ((bias,) if bias is not None else ())
        return apply_jfn("quantized_matmul_int8", jfn, *args)


class ImperativeQuantAware:
    """reference imperative/qat.py:80 — swap quantizable sublayers."""

    def __init__(self, bits=8, **kwargs):
        self.bits = bits

    def quantize(self, model):
        self._swap(model)
        return model

    def _swap(self, layer):
        for name, sub in list(layer.named_children()):
            if isinstance(sub, nn.Linear):
                setattr(layer, name, QuantizedLinear(sub, self.bits))
            else:
                self._swap(sub)

    def convert(self, model):
        for sub in model.sublayers(include_self=True):
            if isinstance(sub, QuantizedLinear):
                sub.freeze()
        return model

    save_quantized_model = staticmethod(
        lambda model, path, input_spec=None: __import__(
            "paddle_tpu").jit.save(model, path, input_spec=input_spec))


class PostTrainingQuantization:
    """reference post_training_quantization.py:122 — calibrate then
    freeze to int8."""

    def __init__(self, model, bits=8):
        self.model = ImperativeQuantAware(bits).quantize(model)

    def calibrate(self, data_iter, steps=None):
        self.model.eval()
        for q in self.model.sublayers(include_self=True):
            if isinstance(q, QuantizedLinear):
                q.train()  # observers on
        from ..autograd import no_grad

        with no_grad():
            for i, batch in enumerate(data_iter):
                if steps is not None and i >= steps:
                    break
                xs = batch if isinstance(batch, (tuple, list)) else (batch,)
                self.model(*xs)
        return self

    def quantize(self):
        self.model.eval()
        for q in self.model.sublayers(include_self=True):
            if isinstance(q, QuantizedLinear):
                q.freeze()
        return self.model


from . import runtime  # noqa: E402,F401  (the serving/wire half)
