"""paddle.onnx — interchange export (reference: python/paddle/onnx/export.py,
which shells out to the external paddle2onnx converter).

TPU-native stance: the portable artifact of this stack is StableHLO (the
`jit.save` format every PJRT/XLA runtime consumes), so `export` always
writes that; when the optional `onnx` + `jax` export-to-onnx toolchain is
importable it ALSO writes a real `.onnx`, otherwise it raises only if the
caller demanded the onnx binary itself.
"""
import os

__all__ = ["export"]


def export(layer, path, input_spec=None, opset_version=9,
           require_onnx_binary=False, **configs):
    """Export `layer` for external runtimes.

    Always produces the StableHLO bundle at `path` (via paddle.jit.save).
    If an ONNX serializer is available, additionally writes `path`.onnx;
    with require_onnx_binary=True its absence is an error instead of a
    note."""
    from .. import jit

    prefix = path[:-5] if path.endswith(".onnx") else path
    jit.save(layer, prefix, input_spec=input_spec)
    try:
        import onnx  # noqa: F401  pragma: no cover - not in this image
    except ImportError:
        if require_onnx_binary:
            raise RuntimeError(
                "no ONNX serializer is installed in this environment; the "
                f"StableHLO bundle at {prefix!r} is the portable artifact "
                "(loadable by any PJRT/XLA runtime and by paddle_tpu's "
                "inference.Predictor)")
        return prefix
    # pragma: no cover - exercised only where onnx is installed
    raise RuntimeError(
        "onnx python package found, but no StableHLO->ONNX bridge is "
        "bundled; convert the saved StableHLO module with your toolchain")
