"""Continuous-batching LLM serving engine with a paged KV cache.

The serving half of the framework the way `jit.TrainStep` is the
training half. The static-batch path (`GPTGenerationMixin.generate` +
the shape-bucketed `InferenceServer`) cannot admit a new request into a
running decode batch, so every mixed-length workload pays worst-case
padding and head-of-line blocking. This engine fixes both, TPU-style
(PAPERS.md "Ragged Paged Attention"; the capability the reference ships
as its analysis_predictor/serving stack):

* **Paged KV cache** — the cache is a pool of fixed-size pages
  [num_pages, page_size, heads, head_dim] per layer with per-sequence
  page tables. Pages are allocated as a sequence grows and freed the
  step it finishes, so HBM scales with LIVE TOKENS instead of
  batch × max_seq_len (padding-waste model: docs/PERF_NOTES.md
  "Serving"). Physical page 0 is a reserved trash page: padding-token
  writes land there and are never attended. The pool dtype is
  configurable (`kv_dtype` / PT_KV_DTYPE): "int8" runs the QUANTIZED
  pool — each written row carries a per-(token, head) fp32 scale in
  page-shaped scale planes, attention dequantizes on gather, and page
  bytes drop ~4× vs fp32 (~2× vs bf16), which is more live sequences
  per HBM byte (quantization runtime, docs/QUANTIZATION.md).

* **Continuous scheduler** — every step admits queued prompts into free
  decode slots, chunks their prefill into the running batch (a FLAT
  token budget: each step carries one decode token per running sequence
  plus as many prefill tokens as fit), samples at each sequence
  frontier, and evicts on EOS or token budget. When the pool runs dry
  the youngest sequence is preempted back to the queue (pages freed;
  greedy decode makes the re-run deterministic).

* **ONE compiled decode executable** — every scheduler tick calls the
  same fixed-shape program (`_CompiledPagedStep` over
  `GPTGenerationMixin._paged_decode_core`: token_budget flat tokens,
  num_slots page tables, the pools), so steady-state serving never
  recompiles. Built the `jit.TrainStep` way: weights thread through as
  jit ARGUMENTS (not baked constants — persistent-cache friendly) and
  the KV pools are DONATED, so the page writes are in-place HBM updates
  instead of per-step pool copies. The attention inside is
  `F.paged_attention` — jnp reference on CPU, the Pallas ragged kernel
  on real TPU.

Surface:

    server = inference.LLMServer(model)        # GPTForCausalLM
    with server:
        fut = server.submit(prompt_ids, max_new_tokens=64,
                            eos_token_id=50256)
        tokens = fut.result()   # np.int64 [prompt + generated]

Greedy decode is token-for-token identical to `generate()` (pinned by
tests/test_llm_engine.py); eos semantics follow the shared contract
(the emitted eos is kept, nothing after it).
"""
import collections
import itertools
import queue
import time as _time
from concurrent.futures import Future

import numpy as np

import jax
import jax.numpy as jnp

from ..observability import metrics as _obs
from ..observability.tracing import trace_span as _trace_span
from .serving import _FutureQueueServer

__all__ = ["PagePool", "PoolExhausted", "LLMEngineConfig", "LLMEngine",
           "LLMServer"]

# serving telemetry (docs/OBSERVABILITY.md). Counters/histograms are
# process-global (engines in one process share them; `LLMServer.metrics()`
# reads this registry — the bench's attribution source). Gauges carry
# the most recent scheduler tick's view.
_REQS_TOTAL = _obs.counter("pt_llm_requests_total", "requests accepted")
_FINISHED_TOTAL = _obs.counter("pt_llm_finished_total",
                               "requests finished (eos or budget)")
_PREEMPTIONS_TOTAL = _obs.counter(
    "pt_llm_preemptions_total", "sequences preempted on a dry page pool")
_STEPS_TOTAL = _obs.counter("pt_llm_steps_total", "scheduler ticks")
_ABORTS_TOTAL = _obs.counter("pt_llm_aborts_total",
                             "abort_all events (device-error path)")
_TOKENS_TOTAL = _obs.counter(
    "pt_llm_tokens_total",
    "flat tokens through the compiled step: one decode token per "
    "sampling frontier, the rest chunked prefill",
    labelnames=("phase",))
_QUEUE_DEPTH = _obs.gauge("pt_llm_queue_depth", "requests waiting")
_LIVE_SLOTS = _obs.gauge("pt_llm_live_slots", "sequences decoding")
_SLOT_OCC = _obs.gauge("pt_llm_slot_occupancy",
                       "live slots / num_slots, last tick")
_PAGE_OCC = _obs.gauge("pt_llm_kv_page_occupancy",
                       "live KV pages / allocable pages")
_PAGE_FRAG = _obs.gauge(
    "pt_llm_kv_fragmentation",
    "internal fragmentation: 1 - written tokens / live page capacity")
_ADMIT_SECONDS = _obs.histogram("pt_llm_admission_seconds",
                                "submit -> first decode-slot admission")
_TTFT_SECONDS = _obs.histogram("pt_llm_ttft_seconds",
                               "submit -> first generated token")
_REQ_TOK_RATE = _obs.histogram(
    "pt_llm_request_tokens_per_sec",
    "per-request generated tok/s (admission -> finish)",
    buckets=(1, 2, 5, 10, 20, 50, 100, 200, 500, 1000, 2000, 5000,
             10000))
_KV_POOL_BYTES = _obs.gauge(
    "pt_kv_pool_bytes",
    "resident KV page-pool bytes (pools + int8 scale planes), by the "
    "pool dtype (quantized runtime: docs/QUANTIZATION.md)",
    labelnames=("dtype",))
# shared with jit.TrainStep's probe — ONE definition (the registry
# would raise on a labelnames divergence between two copies)
from ..jit import _DONATION_HELD


class PoolExhausted(RuntimeError):
    """No free KV pages (the scheduler preempts and retries on this)."""


class PagePool:
    """Fixed-size KV-page allocator. Physical page 0 is reserved as the
    trash page (padding-token writes), so pages 1..num_pages-1 are
    allocable. Strict double-free/leak checking — the invariants the
    soak test pins."""

    def __init__(self, num_pages, page_size):
        if num_pages < 2:
            raise ValueError("need at least 2 pages (page 0 is trash)")
        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        # LIFO free stack, seeded so the first allocs hand out 1, 2, ...
        self._free = list(range(self.num_pages - 1, 0, -1))
        self._live = set()

    @property
    def num_free(self):
        return len(self._free)

    @property
    def num_live(self):
        return len(self._live)

    def alloc(self):
        if not self._free:
            raise PoolExhausted(
                f"all {self.num_pages - 1} KV pages in use")
        p = self._free.pop()
        self._live.add(p)
        return p

    def free(self, pages):
        for p in pages:
            if p not in self._live:
                raise RuntimeError(
                    f"double free of KV page {p} (live: "
                    f"{len(self._live)})")
            self._live.remove(p)
            self._free.append(p)

    def assert_consistent(self):
        total = len(self._free) + len(self._live)
        if total != self.num_pages - 1:
            raise RuntimeError(
                f"page leak: {len(self._free)} free + "
                f"{len(self._live)} live != {self.num_pages - 1}")


class LLMEngineConfig:
    """Engine sizing. Defaults are safe (worst-case pool: no
    preemption); shrink `num_pages` to trade HBM for occasional
    preemption under load.

    num_slots     max concurrently-decoding sequences (the compiled
                  step's batch geometry)
    page_size     tokens per KV page
    num_pages     pool size incl. the trash page; default
                  num_slots * ceil(max_model_len / page_size) + 1
    max_model_len per-sequence token cap; default model max_seq_len
    token_budget  flat tokens per step (>= num_slots); the surplus over
                  the decode tokens is the chunked-prefill bandwidth.
                  Default num_slots + max(num_slots, 8).
    kv_dtype      pool dtype: "float32" | "bfloat16" | "int8" (the
                  quantized runtime — int8 pools carry per-row scale
                  planes and dequantize on gather). Default: the
                  PT_KV_DTYPE env var, else the model compute dtype.
    """

    def __init__(self, num_slots=4, page_size=16, num_pages=None,
                 max_model_len=None, token_budget=None, kv_dtype=None):
        self.num_slots = int(num_slots)
        self.page_size = int(page_size)
        self.num_pages = num_pages
        self.max_model_len = max_model_len
        self.token_budget = token_budget
        self.kv_dtype = kv_dtype
        if self.num_slots < 1:
            raise ValueError("num_slots must be >= 1")
        if self.page_size < 1:
            raise ValueError("page_size must be >= 1")

    @staticmethod
    def kv_bytes_per_page(model_config, page_size, kv_dtype=None):
        """Bytes ONE page costs across every layer's k+v pool, scale
        planes included — the unit of the capacity math below."""
        from ..quantization import runtime as _qrt

        dt, quantized = _qrt.resolve_kv_dtype(kv_dtype, jnp.float32)
        nh = model_config.num_heads
        hd = model_config.hidden_size // nh
        per_row = nh * hd * jnp.dtype(dt).itemsize
        if quantized:
            per_row += nh * 4  # fp32 scale per (row, head)
        return 2 * model_config.num_layers * page_size * per_row

    @classmethod
    def for_pool_budget(cls, model_config, budget_bytes, page_size=16,
                        kv_dtype=None, **kw):
        """Size `num_pages` to a page-pool BYTE budget — the equal-bytes
        capacity comparison the quantized-KV acceptance pins (int8 pools
        admit ~4× the pages of fp32 at the same budget)."""
        per_page = cls.kv_bytes_per_page(model_config, page_size,
                                         kv_dtype)
        num_pages = max(2, int(budget_bytes) // per_page + 1)  # + trash
        return cls(page_size=page_size, num_pages=num_pages,
                   kv_dtype=kv_dtype, **kw)


class _CompiledPagedStep:
    """The engine's ONE decode executable, built the `jit.TrainStep`
    way: a pure function over (param_vals, step arrays, kv pools) under
    `jax.jit`. Weights ride as ARGUMENTS (structurally-equal engines
    share one correct persistent-cache entry — the same reasoning as
    TrainStep's base-key-as-argument note), and the kv-pool pytree is
    DONATED so the paged cache writes update HBM in place instead of
    copying every pool every tick."""

    def __init__(self, model):
        self._params = list(model.state_dict().values())

        def pure(param_vals, tok, pos, sid, widx, pt, klen, smp,
                 kv_state):
            from ..autograd import engine as eng
            from ..tensor_core import Tensor

            def t(v):
                return Tensor(v, stop_gradient=True)

            # kv_state = (pools, scale planes) — scales empty for float
            # pools; ONE donated pytree so int8 pools and their scales
            # update in place together
            kv_vals, kv_scales = kv_state
            originals = [p._value for p in self._params]
            for p, v in zip(self._params, param_vals):
                p._value = v
            try:
                with eng.no_grad_guard():
                    out = model._paged_decode_core(
                        t(tok), t(pos), t(sid), t(widx), t(pt), t(klen),
                        t(smp), [t(v) for v in kv_vals],
                        kv_scales=(
                            [t(s) for s in kv_scales] if kv_scales
                            else None))
            finally:
                for p, v in zip(self._params, originals):
                    p._value = v
            logits, *new_kv = out
            n = len(kv_vals)
            return logits._value, ([x._value for x in new_kv[:n]],
                                   [x._value for x in new_kv[n:]])

        self._jit = jax.jit(pure, donate_argnums=(8,))
        self._warm = False

    def __call__(self, tok, pos, sid, widx, pt, klen, smp, kv_state):
        args = ([p._value for p in self._params], tok, pos, sid, widx,
                pt, klen, smp, kv_state)
        if self._warm:
            return self._jit(*args)
        # FIRST call compiles OUTSIDE the persistent cache: a
        # cache-loaded donating executable on jax 0.4.x drops (or worse,
        # mismatches) its aliasing map — measured 25% slower serving
        # from the silent donation loss alone (docs/RESILIENCE.md; same
        # guard as the restored-TrainStep path). Guard the compile only:
        # the flag is process-global, so flipping it every tick from the
        # serving thread would race other threads' compiles.
        from ..core.jax_compat import no_persistent_cache

        with no_persistent_cache():
            out = self._jit(*args)
        self._warm = True
        return out

    def cache_size(self):
        n = getattr(self._jit, "_cache_size", None)
        return int(n()) if callable(n) else -1


class _Request:
    _ids = itertools.count()

    def __init__(self, tokens, max_new_tokens, eos_token_id, future):
        self.rid = next(_Request._ids)
        self.tokens = [int(t) for t in tokens]  # prompt, grows as decoded
        self.prompt_len = len(self.tokens)
        self.max_new = int(max_new_tokens)
        self.eos = eos_token_id
        self.future = future if future is not None else Future()
        self.target = None        # total-token cap, set at add_request
        self.slot = None
        self.pages = []           # physical page ids, logical order
        self.n_prefilled = 0      # kv-written tokens (reset on preempt)
        self.admit_seq = None     # admission order (preemption picks max)
        self.preemptions = 0
        # telemetry stamps (admission latency / TTFT / per-request rate)
        self.t_submit = _time.perf_counter()
        self.t_first_admit = None

    @property
    def num_generated(self):
        return len(self.tokens) - self.prompt_len

    def result_array(self):
        return np.asarray(self.tokens, np.int64)


class LLMEngine:
    """Scheduler + paged-KV state around ONE compiled ragged decode step
    (module docstring has the design). Drive it directly —

        eng = LLMEngine(model)
        req = eng.add_request(prompt_ids, max_new_tokens=32)
        while eng.has_work():
            eng.step()
        tokens = req.future.result()

    — or through `LLMServer` for the threaded future/queue surface."""

    def __init__(self, model, config=None):
        model.eval()
        self.model = model
        mcfg = model.config
        cfg = config or LLMEngineConfig()
        self.num_slots = cfg.num_slots
        self.page_size = cfg.page_size
        self.max_model_len = int(cfg.max_model_len or mcfg.max_seq_len)
        if self.max_model_len > mcfg.max_seq_len:
            raise ValueError(
                f"max_model_len {self.max_model_len} exceeds the "
                f"model's max_seq_len {mcfg.max_seq_len}")
        self.pages_per_seq = -(-self.max_model_len // self.page_size)
        self.token_budget = int(
            cfg.token_budget
            or self.num_slots + max(self.num_slots, 8))
        if self.token_budget < self.num_slots:
            raise ValueError(
                f"token_budget {self.token_budget} < num_slots "
                f"{self.num_slots}: every running sequence needs one "
                "decode token per step")
        num_pages = int(cfg.num_pages
                        or self.num_slots * self.pages_per_seq + 1)
        self.pool = PagePool(num_pages, self.page_size)

        nh = mcfg.num_heads
        hd = mcfg.hidden_size // nh
        # pool in the configured kv_dtype (default: the model's compute
        # dtype — decode is HBM-bound, same reasoning as generate()'s
        # cache dtype; "int8" quantizes each written row per (token,
        # head) with fp32 scale planes alongside — quantization runtime,
        # docs/QUANTIZATION.md). The zero pools are COMMITTED with the
        # same replicated NamedSharding the step executable's outputs
        # carry (the TP layers' sharding constraints stamp the global
        # mesh on every output) — a placement mismatch between step 0's
        # pools and every later step's would cost a second
        # dispatch-cache entry (the zero-recompile probe would read 2
        # executables, not 1)
        from ..distributed import mesh as mesh_mod
        from ..quantization import runtime as _qrt

        compute_dt = model.gpt.wte.weight._value.dtype
        cache_dt, self.kv_quantized = _qrt.resolve_kv_dtype(
            cfg.kv_dtype, compute_dt)
        self.kv_dtype = str(jnp.dtype(cache_dt))
        sharding = mesh_mod.named_sharding()  # replicated on the mesh

        def _fresh_pools():
            pools = [
                jax.device_put(
                    jnp.zeros((num_pages, self.page_size, nh, hd),
                              cache_dt), sharding)
                for _ in range(2 * mcfg.num_layers)]
            scales = []
            if self.kv_quantized:
                sshape = _qrt.kv_scale_shape(num_pages, self.page_size,
                                             nh)
                scales = [
                    jax.device_put(jnp.zeros(sshape, jnp.float32),
                                   sharding)
                    for _ in range(2 * mcfg.num_layers)]
            return pools, scales

        self._fresh_pools = _fresh_pools
        self._kv, self._kv_scales = _fresh_pools()
        _KV_POOL_BYTES.labels(dtype=self.kv_dtype).set(self.pool_bytes())
        self._page_tables = np.zeros(
            (self.num_slots, self.pages_per_seq), np.int32)
        self._slots = [None] * self.num_slots
        self.waiting = collections.deque()
        self._admit_counter = itertools.count()
        self._step_fn = _CompiledPagedStep(model)
        self.stats = {"steps": 0, "tokens_in": 0, "generated": 0,
                      "finished": 0, "preemptions": 0,
                      "occupancy_sum": 0.0}

    # ---- client side ----

    def add_request(self, prompt, max_new_tokens=32, eos_token_id=None,
                    future=None):
        toks = np.asarray(prompt).reshape(-1)
        if toks.size == 0:
            raise ValueError("empty prompt")
        if toks.size > self.max_model_len:
            raise ValueError(
                f"prompt length {toks.size} exceeds max_model_len "
                f"{self.max_model_len}")
        if -(-int(toks.size) // self.page_size) > self.pool.num_pages - 1:
            raise ValueError(
                f"prompt needs more KV pages than the pool holds "
                f"({self.pool.num_pages - 1})")
        req = _Request(toks, max_new_tokens, eos_token_id, future)
        req.target = min(req.prompt_len + req.max_new, self.max_model_len)
        _REQS_TOTAL.inc()
        if req.target <= req.prompt_len:
            # zero budget (same contract as generate()): prompt echoes back
            if not req.future.cancelled():
                req.future.set_result(req.result_array())
            return req
        self.waiting.append(req)
        _QUEUE_DEPTH.set(len(self.waiting))
        return req

    def has_work(self):
        return bool(self.waiting) or any(
            r is not None for r in self._slots)

    @property
    def mean_occupancy(self):
        s = self.stats["steps"]
        return self.stats["occupancy_sum"] / s if s else 0.0

    def compile_stats(self, check_donation=False):
        """Executable count of the decode step (the jit dispatch-cache
        size) — the zero-recompile-after-warmup probe the engine test
        asserts on.

        `check_donation=True` additionally re-lowers the decode step
        through the live compile-cache path and reports whether the
        donated kv pools (and int8 scale planes) actually aliased
        outputs in the executable — donation silently dropping is the
        measured-25%-slower PR-2 serving bug (docs/RESILIENCE.md).
        Adds a `"donation"` key: {"expected", "aliased", "held",
        "dropped"}.

        THREADING: the donation probe re-TRACES the decode step, and
        the trace body temporarily swaps the model's live parameter
        values for tracers — call it from the thread that owns the
        engine (direct-drive callers; or around, never during, an
        `LLMServer` loop tick). The plain `check_donation=False` form
        is read-only and always safe.
        """
        out = {"executables": self._step_fn.cache_size()}
        if not check_donation:
            return out
        from .. import analysis

        rep = analysis.analyze_step(self, check_donation=True)
        out["donation"] = rep.donation
        _DONATION_HELD.labels(step="paged_decode").set(
            1.0 if rep.donation["held"] else 0.0)
        return out

    def pool_bytes(self):
        """Resident KV pool bytes across layers — int8 scale planes
        included (they are part of the cache's true footprint)."""
        return int(sum(int(a.nbytes) for a in self._kv)
                   + sum(int(s.nbytes) for s in self._kv_scales))

    def kv_fragmentation(self):
        """Internal fragmentation of the live KV pages: 1 − written
        tokens / (live pages × page_size). High values mean many
        sequences holding mostly-empty tail pages (page_size too big
        for the workload)."""
        cap = self.pool.num_live * self.page_size
        if not cap:
            return 0.0
        used = sum(r.n_prefilled for r in self._slots if r is not None)
        return max(0.0, 1.0 - used / cap)

    def metrics(self):
        """Live engine view + the process-global serving counters from
        the telemetry registry (docs/OBSERVABILITY.md) — what
        `LLMServer.metrics()` and the bench's llm_serve arm report."""
        live = sum(r is not None for r in self._slots)
        return {
            "queue_depth": len(self.waiting),
            "live_slots": live,
            "num_slots": self.num_slots,
            "kv_dtype": self.kv_dtype,
            "kv_pool_bytes": self.pool_bytes(),
            "slot_occupancy": live / self.num_slots,
            "mean_slot_occupancy": self.mean_occupancy,
            "kv_page_occupancy":
                self.pool.num_live / (self.pool.num_pages - 1),
            "kv_fragmentation": self.kv_fragmentation(),
            "requests": int(_REQS_TOTAL.value),
            "finished": int(_FINISHED_TOTAL.value),
            "preemptions": int(_PREEMPTIONS_TOTAL.value),
            "steps": int(_STEPS_TOTAL.value),
            "aborts": int(_ABORTS_TOTAL.value),
            "prefill_tokens":
                int(_TOKENS_TOTAL.labels(phase="prefill").value),
            "decode_tokens":
                int(_TOKENS_TOTAL.labels(phase="decode").value),
            "admission_p50_s": _ADMIT_SECONDS.quantile(0.5),
            "admission_p99_s": _ADMIT_SECONDS.quantile(0.99),
            "ttft_p50_s": _TTFT_SECONDS.quantile(0.5),
            "ttft_p99_s": _TTFT_SECONDS.quantile(0.99),
            "request_tok_per_s_p50": _REQ_TOK_RATE.quantile(0.5),
            "executables": self._step_fn.cache_size(),
        }

    def abort_all(self, exc):
        """Fail every live and queued request (device-error path),
        release all pages, and re-zero the pools — a step that died
        mid-donation leaves the old kv buffers deleted, so the engine
        must not reuse them."""
        for slot, req in enumerate(self._slots):
            if req is not None:
                self._release(slot, req)
                if not req.future.done():
                    req.future.set_exception(exc)
        while self.waiting:
            req = self.waiting.popleft()
            if not req.future.done():
                req.future.set_exception(exc)
        self._kv, self._kv_scales = self._fresh_pools()
        _ABORTS_TOTAL.inc()
        _QUEUE_DEPTH.set(0)
        _LIVE_SLOTS.set(0)
        _SLOT_OCC.set(0.0)

    # ---- scheduler ----

    def _release(self, slot, req):
        self.pool.free(req.pages)
        req.pages = []
        req.n_prefilled = 0
        req.slot = None
        self._page_tables[slot, :] = 0
        self._slots[slot] = None

    def _finish(self, slot, req):
        self._release(slot, req)
        self.stats["finished"] += 1
        _FINISHED_TOTAL.inc()
        if req.t_first_admit is not None and req.num_generated:
            dt = _time.perf_counter() - req.t_first_admit
            if dt > 0:
                _REQ_TOK_RATE.observe(req.num_generated / dt)
        # a client may have cancel()ed while the request was in flight —
        # set_result would raise InvalidStateError and the server loop
        # would read that as a device error and abort EVERYONE
        if not req.future.cancelled():
            req.future.set_result(req.result_array())

    def _preempt_one(self, keep_req):
        """Free the youngest running sequence (≠ keep_req) back to the
        queue front. Returns False when there is no victim."""
        victim, vslot = None, None
        for slot, req in enumerate(self._slots):
            if req is None or req is keep_req:
                continue
            if victim is None or req.admit_seq > victim.admit_seq:
                victim, vslot = req, slot
        if victim is None:
            return False
        # keep the already-generated tokens: greedy re-decode of
        # prompt+generated reproduces the same continuation, so a
        # preempted request stays deterministic
        self._release(vslot, victim)
        victim.preemptions += 1
        self.stats["preemptions"] += 1
        _PREEMPTIONS_TOTAL.inc()
        self.waiting.appendleft(victim)
        return True

    def _admit(self):
        while self.waiting and None in self._slots:
            req = self.waiting[0]
            need = -(-len(req.tokens) // self.page_size)
            if self.pool.num_free < need:
                break  # FIFO: don't let a short prompt jump the queue
            self.waiting.popleft()
            slot = self._slots.index(None)
            req.slot = slot
            req.admit_seq = next(self._admit_counter)
            self._slots[slot] = req
            if req.t_first_admit is None:
                req.t_first_admit = _time.perf_counter()
                _ADMIT_SECONDS.observe(req.t_first_admit - req.t_submit)

    def _active(self):
        """Running sequences in admission order (deterministic plan)."""
        return sorted(
            ((slot, req) for slot, req in enumerate(self._slots)
             if req is not None),
            key=lambda it: it[1].admit_seq)

    def _plan(self):
        """Allot this step's flat token budget: one frontier token per
        running sequence first, then chunked prefill FIFO. Allocates the
        pages the planned tokens will write; a dry pool preempts the
        youngest sequence and replans."""
        while True:
            active = self._active()
            if not active:
                return None
            alloc = {}
            budget = self.token_budget - len(active)
            for slot, req in active:
                remaining = len(req.tokens) - req.n_prefilled
                take = 1 + min(remaining - 1, budget)
                budget -= take - 1
                alloc[slot] = take
            ok = True
            for slot, req in active:
                last = req.n_prefilled + alloc[slot] - 1
                try:
                    while last // self.page_size >= len(req.pages):
                        page = self.pool.alloc()
                        self._page_tables[slot, len(req.pages)] = page
                        req.pages.append(page)
                except PoolExhausted:
                    if not self._preempt_one(req):
                        # lone sequence outgrew the pool: unservable
                        self._release(slot, req)
                        if not req.future.done():
                            req.future.set_exception(PoolExhausted(
                                f"request {req.rid} needs more KV pages "
                                f"than the pool holds"))
                    ok = False
                    break
            if ok:
                return [(slot, req, alloc[slot]) for slot, req in active]

    def step(self):
        """One scheduler tick: admit → plan → ONE compiled decode step →
        sample frontiers → evict finished. Returns the list of requests
        finished this tick."""
        self._admit()
        plan = self._plan()
        if plan is None:
            return []

        T = self.token_budget
        tok = np.zeros((T,), np.int32)
        pos = np.zeros((T,), np.int32)
        sid = np.zeros((T,), np.int32)
        widx = np.zeros((T,), np.int32)   # 0 → trash page, row 0
        klen = np.zeros((T,), np.int32)   # 0 → padding token
        # per-SLOT sampling frontier: the vocab head only runs on these
        # gathered rows (stale slots point at row 0; logits ignored)
        sample_idx = np.zeros((self.num_slots,), np.int32)
        sample_slots = []
        i = 0
        for slot, req, take in plan:
            for k in range(take):
                p = req.n_prefilled + k
                tok[i] = req.tokens[p]
                pos[i] = p
                sid[i] = slot
                widx[i] = (req.pages[p // self.page_size]
                           * self.page_size + p % self.page_size)
                klen[i] = p + 1
                if p == len(req.tokens) - 1:
                    sample_idx[slot] = i
                    sample_slots.append(slot)
                i += 1

        try:
            with _trace_span("llm_engine.step", tokens=i,
                             live=len(plan)):
                logits, (self._kv, self._kv_scales) = self._step_fn(
                    tok, pos, sid, widx, self._page_tables, klen,
                    sample_idx, (self._kv, self._kv_scales))
        except Exception as e:
            # the donated pools may already be consumed by the failed
            # dispatch — fail the in-flight work and re-zero so a
            # direct-drive caller's engine stays serviceable (the server
            # loop's own abort_all then finds nothing left to do)
            self.abort_all(e)
            raise

        self.stats["steps"] += 1
        self.stats["tokens_in"] += i
        self.stats["occupancy_sum"] += len(plan) / self.num_slots
        _STEPS_TOTAL.inc()
        # the flat-budget split: one decode token per sampling frontier,
        # everything else is (chunked or preemption-replay) prefill
        _TOKENS_TOTAL.labels(phase="decode").inc(len(sample_slots))
        _TOKENS_TOTAL.labels(phase="prefill").inc(i - len(sample_slots))
        _QUEUE_DEPTH.set(len(self.waiting))
        _LIVE_SLOTS.set(len(plan))
        _SLOT_OCC.set(len(plan) / self.num_slots)
        _PAGE_OCC.set(self.pool.num_live / (self.pool.num_pages - 1))

        nxt = []
        if sample_slots:
            rows = jnp.asarray(sample_slots, jnp.int32)
            lv = jnp.take(logits[0], rows, axis=0).astype(jnp.float32)
            # greedy frontier sampling — same pick as generate()'s
            # default path, so outputs stay token-identical
            nxt = np.asarray(jnp.argmax(lv, axis=-1))

        for slot, req, take in plan:
            req.n_prefilled += take
        _PAGE_FRAG.set(self.kv_fragmentation())
        finished = []
        now = _time.perf_counter()
        for slot, tok_id in zip(sample_slots, nxt):
            req = self._slots[slot]
            t = int(tok_id)
            req.tokens.append(t)
            self.stats["generated"] += 1
            if req.num_generated == 1:      # replays don't re-count
                _TTFT_SECONDS.observe(now - req.t_submit)
            if ((req.eos is not None and t == req.eos)
                    or len(req.tokens) >= req.target):
                self._finish(slot, req)
                finished.append(req)
        return finished


class LLMServer(_FutureQueueServer):
    """Continuous-batching text-generation server: the future/queue
    surface of `InferenceServer` over an `LLMEngine` (module docstring
    has the usage). One background thread owns the engine; `submit` is
    thread-safe."""

    _thread_name = "llm-engine"

    def __init__(self, model, config=None):
        super().__init__()
        self._engine = LLMEngine(model, config)
        self.stats = self._engine.stats  # shared view + request counts
        self.stats.setdefault("requests", 0)
        self._http = None

    @property
    def engine(self):
        return self._engine

    def metrics(self):
        """Engine telemetry snapshot (registry-sourced; see
        LLMEngine.metrics). Thread-safe: reads only."""
        return self._engine.metrics()

    def start_metrics_http(self, port=0, host="127.0.0.1"):
        """Optional stdlib-only pull endpoint: GET /metrics serves the
        process registry in Prometheus text format, /metrics.json the
        full snapshot with this engine's view under "extra". port=0
        picks a free port; returns the handle (`.url`, `.port`).
        Stopped automatically with the server."""
        if self._http is None:
            from ..observability import start_http_server

            self._http = start_http_server(port=port, host=host,
                                           extra_json=self.metrics)
        return self._http

    def stop(self):
        super().stop()
        if self._http is not None:
            self._http.stop()
            self._http = None

    def submit(self, prompt, max_new_tokens=32, eos_token_id=None):
        """Enqueue one prompt (1-D int token ids). Returns a Future
        resolving to np.int64 [prompt + generated] (eos kept, nothing
        after it)."""
        fut = Future()
        self._enqueue((np.asarray(prompt).reshape(-1),
                       int(max_new_tokens), eos_token_id, fut))
        return fut

    def generate(self, prompt, max_new_tokens=32, eos_token_id=None):
        return self.submit(prompt, max_new_tokens, eos_token_id).result()

    def _ingest(self, payload):
        prompt, max_new, eos, fut = payload
        try:
            self._engine.add_request(prompt, max_new, eos, future=fut)
            self.stats["requests"] += 1
        except Exception as e:  # bad request must not kill the loop
            if not fut.done():
                fut.set_exception(e)

    def _loop(self):
        eng = self._engine
        while self._running or not self._q.empty() or eng.has_work():
            try:
                while True:
                    self._ingest(self._q.get_nowait())
            except queue.Empty:
                pass
            if not eng.has_work():
                # idle: block briefly for the next submission
                try:
                    self._ingest(self._q.get(timeout=0.05))
                except queue.Empty:
                    continue
            try:
                eng.step()
            except Exception as e:  # defensive: never die silently
                eng.abort_all(e)
